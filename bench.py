#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line for the driver.

Workload (BASELINE.md north star): SPADE on a BMS-WebView-2-shaped database
at minsup=0.1%.  The real BMS-WebView-2 file is unreachable (zero-egress
sandbox), so a seeded synthetic DB with the documented shape (77.5k
sequences, 3.3k item alphabet, ~4.6 itemsets/sequence) stands in; point
BENCH_DATASET at a real SPMF file to override.

Metric: patterns/sec of the steady-state mine (second run, compiles warm).
vs_baseline: 10s-target ratio = 10.0 / steady wall-clock (>1 beats the
"<10s on v5e-8" north star; here a single chip).

Env knobs: BENCH_SCALE (default 1.0), BENCH_MINSUP (default 0.001),
BENCH_DATASET (SPMF file path), BENCH_PARITY=1 (also run the CPU oracle and
check byte-identical output; adds oracle wall-clock), BENCH_PALLAS=1 to
enable the Pallas pair-support kernel (default off until it is validated on
the target chip generation; a kernel failure falls back to the jnp path,
but a hang would stall the harness, so opt-in here).
"""

import json
import os
import socket
import sys
import time


def _tpu_reachable() -> bool:
    """The axon TPU tunnel relay listens on 8082; if it's gone, importing
    the axon backend hangs forever, so gate BEFORE the first backend init."""
    try:
        with socket.create_connection(("127.0.0.1", 8082), timeout=2.0):
            return True
    except OSError:
        return False


def main() -> None:
    want_tpu = os.environ.get("JAX_PLATFORMS", "").lower() not in ("cpu",)
    use_tpu = want_tpu and _tpu_reachable()
    import jax
    if not use_tpu:
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from spark_fsm_tpu.data.spmf import load_spmf
    from spark_fsm_tpu.data.synth import bms_webview2_like
    from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
    from spark_fsm_tpu.models.spade_tpu import SpadeTPU
    from spark_fsm_tpu.utils.canonical import patterns_text

    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    rel_minsup = float(os.environ.get("BENCH_MINSUP", "0.001"))
    dataset = os.environ.get("BENCH_DATASET")

    t0 = time.time()
    db = load_spmf(dataset) if dataset else bms_webview2_like(scale=scale)
    minsup = abs_minsup(rel_minsup, len(db))
    vdb = build_vertical(db, min_item_support=minsup)
    build_s = time.time() - t0

    platform = jax.devices()[0].platform
    use_pallas = "auto" if os.environ.get("BENCH_PALLAS") == "1" else False
    t0 = time.time()
    eng = SpadeTPU(vdb, minsup, use_pallas=use_pallas)
    res = eng.mine()
    cold_s = time.time() - t0

    eng.stats = {k: 0 for k in eng.stats}  # per-run stats for the steady pass
    t0 = time.time()
    res = eng.mine()
    steady_s = time.time() - t0

    patterns_per_sec = len(res) / steady_s if steady_s > 0 else 0.0
    out = {
        "metric": "patterns/sec (SPADE, BMS-WebView-2-shaped, minsup=0.1%)",
        "value": round(patterns_per_sec, 2),
        "unit": "patterns/sec",
        "vs_baseline": round(10.0 / steady_s, 3) if steady_s > 0 else 0.0,
        "patterns": len(res),
        "wall_s": round(steady_s, 3),
        "cold_wall_s": round(cold_s, 3),
        "vertical_build_s": round(build_s, 3),
        "sequences": vdb.n_sequences,
        "frequent_items": vdb.n_items,
        "platform": platform,
        "candidates": eng.stats["candidates"],
    }

    if os.environ.get("BENCH_PARITY") == "1":
        from spark_fsm_tpu.models.oracle import mine_spade
        t0 = time.time()
        oracle = mine_spade(db, minsup)
        out["oracle_wall_s"] = round(time.time() - t0, 3)
        out["parity"] = patterns_text(res) == patterns_text(oracle)

    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
