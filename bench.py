#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line for the driver.

Workload (BASELINE.md north star): SPADE on a BMS-WebView-2-shaped database
at minsup=0.1%.  The real BMS-WebView-2 file is unreachable (zero-egress
sandbox), so a seeded SYNTHETIC DB with the documented shape (77.5k
sequences, 3.3k item alphabet, ~4.6 itemsets/sequence) stands in; point
BENCH_DATASET at a real SPMF file to override.  The metric string names the
dataset truthfully either way.

Metric: patterns/sec of the steady-state mine — the MEDIAN of
BENCH_REPEATS warm passes (default 3; compiles cached from the cold run),
with `wall_min_s` and relative `wall_spread` reported so tunnel noise is
visible in the artifact.  vs_baseline: 10s-target ratio = 10.0 / median
steady wall-clock (>1 beats the "<10s on v5e-8" north star; here a
single chip).

Parity (the north star's other half) is checked by default against the CPU
oracle — `"parity": true` in the output attests a byte-identical pattern
set.  Set BENCH_PARITY=0 to skip (saves the oracle's ~30s wall-clock).

The Pallas pair-support kernel is ON by default ("auto": enabled on a real
TPU backend; validated on-chip v5e, exact parity, ~3x over the jnp gather
path).  Set BENCH_PALLAS=0 to force the jnp path.

If the TPU tunnel is down the harness retries for BENCH_TPU_WAIT seconds
(default 60) and then falls back to CPU LOUDLY: `"platform": "cpu"` plus a
`"tpu_fallback_reason"` field — a CPU number is not a TPU number.

Env knobs: BENCH_SCALE (default 1.0), BENCH_MINSUP (default 0.001),
BENCH_DATASET (SPMF file path), BENCH_PARITY=0, BENCH_PALLAS=0,
BENCH_REPEATS (steady passes, default 3), BENCH_TPU_WAIT (seconds).
"""

import json
import os
import statistics
import sys
import time

# importing the backend with the relay down hangs forever, so probe BEFORE
# backend init (utils/probe.py imports nothing heavy).
from spark_fsm_tpu.utils.probe import tpu_probe as _tpu_probe


def main() -> None:
    # fail a typo'd engine pin in milliseconds, not after ~15s of datagen
    want_engine = os.environ.get("BENCH_ENGINE", "auto")
    if want_engine not in ("auto", "classic", "queue"):
        print(f"bench: unknown BENCH_ENGINE={want_engine!r} "
              "(accepted: auto, classic, queue)", file=sys.stderr)
        sys.exit(2)

    from spark_fsm_tpu.utils.jitcache import enable_compile_cache
    enable_compile_cache()  # compiles persist across runs (cold-start win)
    fallback_reason = ""
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        fallback_reason = "JAX_PLATFORMS=cpu requested by caller"
    else:
        fallback_reason = _tpu_probe(float(os.environ.get("BENCH_TPU_WAIT", "60")))
    import jax
    if fallback_reason:
        print(f"bench: FALLING BACK TO CPU — {fallback_reason}", file=sys.stderr)
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from spark_fsm_tpu.data.spmf import load_spmf
    from spark_fsm_tpu.data.synth import bms_webview2_like
    from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
    from spark_fsm_tpu.models.spade_queue import QueueSpadeTPU, queue_eligible
    from spark_fsm_tpu.models.spade_tpu import SpadeTPU
    from spark_fsm_tpu.utils.canonical import patterns_text

    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    rel_minsup = float(os.environ.get("BENCH_MINSUP", "0.001"))
    dataset = os.environ.get("BENCH_DATASET")
    dataset_name = (os.path.basename(dataset) if dataset
                    else "synthetic BMS-WebView-2-shaped")

    t0 = time.time()
    db = load_spmf(dataset) if dataset else bms_webview2_like(scale=scale)
    datagen_s = time.time() - t0
    minsup = abs_minsup(rel_minsup, len(db))
    t0 = time.time()
    vdb = build_vertical(db, min_item_support=minsup)
    build_s = time.time() - t0

    platform = jax.devices()[0].platform
    use_pallas = False if os.environ.get("BENCH_PALLAS") == "0" else "auto"
    # Engine route mirrors the service default (mine_spade_tpu
    # fused="auto"): the sparse-frontier queue engine where eligible —
    # ONE readback for the whole mine vs one per DFS wave, the dominant
    # cost on this tunneled chip (docs/DESIGN.md wall anatomy) — with the
    # classic host-driven DFS as fallback.  BENCH_ENGINE=classic pins the
    # old path for comparison runs (non-canonical: routing IS the
    # default config).
    use_queue = (want_engine == "queue"
                 or (want_engine == "auto" and queue_eligible(vdb)))
    t0 = time.time()
    fused_fallback_s = None
    if use_queue:
        eng = QueueSpadeTPU(vdb, minsup, use_pallas=use_pallas)
        res = eng.mine()
        if res is None:  # cap overflow: route to classic like the service
            use_queue = False
            # the failed attempt's wall is recorded separately and the
            # cold timer restarts, so cold_wall_s is the REPORTED engine's
            # cold wall, not queue-attempt + classic conflated
            fused_fallback_s = time.time() - t0
            t0 = time.time()
    if not use_queue:
        eng = SpadeTPU(vdb, minsup, use_pallas=use_pallas)
        res = eng.mine()
    cold_s = time.time() - t0

    # Steady state, median of N passes: the shared host + TPU tunnel are
    # noisy (the same code has measured 0.82s and 1.17s hours apart), so a
    # single sample makes vs_baseline a roll of the dice.  The median is
    # the headline; min and relative spread ((max-min)/median) are reported
    # so a noisy session is visible in the artifact instead of silently
    # inflating or deflating the number.
    repeats = max(1, int(os.environ.get("BENCH_REPEATS", "3")))
    walls = []
    for _ in range(repeats):
        eng.stats = {k: 0 for k in eng.stats}  # per-pass stats
        t0 = time.time()
        res = eng.mine()
        walls.append(time.time() - t0)
    walls.sort()
    steady_s = statistics.median(walls)

    patterns_per_sec = len(res) / steady_s if steady_s > 0 else 0.0
    out = {
        "metric": f"patterns/sec (SPADE, {dataset_name}, minsup={rel_minsup:g})",
        "value": round(patterns_per_sec, 2),
        "unit": "patterns/sec",
        "vs_baseline": round(10.0 / steady_s, 3) if steady_s > 0 else 0.0,
        "patterns": len(res),
        "wall_s": round(steady_s, 3),
        "wall_min_s": round(walls[0], 3),
        "wall_spread": round((walls[-1] - walls[0]) / steady_s, 3)
        if steady_s > 0 else 0.0,
        "steady_repeats": repeats,
        "cold_wall_s": round(cold_s, 3),
        "datagen_s": round(datagen_s, 3),
        "vertical_build_s": round(build_s, 3),
        "sequences": vdb.n_sequences,
        "frequent_items": vdb.n_items,
        "platform": platform,
        "pallas": bool(eng.use_pallas),
        "engine": "queue" if use_queue else "classic",
        "candidates": eng.stats["candidates"],
    }
    if fused_fallback_s is not None:
        out["fused_overflow"] = True
        out["fused_fallback_s"] = round(fused_fallback_s, 3)
    if fallback_reason:
        out["tpu_fallback_reason"] = fallback_reason

    if os.environ.get("BENCH_PARITY") != "0":
        from spark_fsm_tpu.models.oracle import mine_spade
        t0 = time.time()
        oracle = mine_spade(db, minsup)
        out["oracle_wall_s"] = round(time.time() - t0, 3)
        out["parity"] = patterns_text(res) == patterns_text(oracle)

    # Only the canonical workload under default engine config, with the
    # parity half of the north star checked and passing, may overwrite the
    # headline entry — a BENCH_PALLAS=0 comparison run, a parity-skipped
    # quick run, or a parity FAILURE must never masquerade as the baseline.
    canonical = (scale == 1.0 and rel_minsup == 0.001 and not dataset
                 and os.environ.get("BENCH_PALLAS") != "0"
                 and os.environ.get("BENCH_ENGINE", "auto") == "auto"
                 and out.get("parity") is True)
    if canonical:
        _publish(out)
    else:
        print("bench: non-canonical run (scale/minsup/dataset/pallas "
              "override, or parity not attested) — not recorded in "
              "BASELINE.json.published", file=sys.stderr)
    print(json.dumps(out))


def _publish(out: dict) -> None:
    """Record the canonical-workload result in BASELINE.json.published
    (SURVEY.md sec 7 step 10).  Callers gate on the default config so a
    scaled-down smoke run can never clobber the headline number.

    The HEADLINE key (``tpu_single_chip``) holds the best-known run (by
    steady wall-clock) so existing consumers keep reading the headline;
    ``tpu_single_chip_latest`` holds the most recent run.  Both are kept
    because the sandbox host + TPU tunnel are shared and noisy — the same
    code measured 0.82s and 1.16s hours apart while the pure-CPU oracle
    swung 26s -> 43s — so "latest" alone under-reports the engine and
    "best" alone hides the variance.  New entries carry their timestamp
    (entries recorded before this scheme may lack one) and the oracle
    wall from the same session as a noise reference."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            base = json.load(f)
        pub = base.get("published") or {}
        key = "tpu_single_chip" if out["platform"] == "tpu" else "cpu_fallback"
        entry = dict(out, ts=round(time.time(), 1))
        prev_best = pub.get(key)
        pub[key + "_latest"] = entry
        if (not prev_best
                or entry["wall_s"] <= prev_best.get("wall_s", float("inf"))):
            pub[key] = entry
        base["published"] = pub
        tmp = path + ".tmp"  # atomic replace: a mid-write kill must not
        with open(tmp, "w") as f:  # truncate the committed baseline
            json.dump(base, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except Exception as e:  # never let bookkeeping kill the bench line
        print(f"bench: could not update BASELINE.json: {e}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
