"""SPAM vertical-bitmap miner — fixed-shape wave engine (ISSUE 15).

The second mining engine next to the SPADE family: same pattern
universe, same enumeration (the oracle's S/I equivalence classes), same
frontier-node shape and checkpoint format — a DIFFERENT evaluation
strategy.  Where the classic engine builds ragged per-node candidate
lists on the host and packs them into chunked launches, SPAM evaluates
every popped node against the WHOLE item axis in one fixed-shape
device pass (ops/spam_bitops.py): gather + s-extension shift-mask once
per node, AND against all item bitmaps, support = popcount of packed
per-sequence alive bits.  The host then reads only the lanes its
candidate lists name and prunes at the threshold.

Why both engines exist (the planner's crossover, service/planner.py):
on DENSE data — small alphabet, most items frequent in most sequences
— the per-node candidate lists approach the full alphabet anyway, so
the fixed-shape pass does the same work with no ragged packing, fewer
distinct compiled shapes, and launch counts independent of candidate
raggedness.  On SPARSE data the full item axis is mostly dead lanes
and the classic engine's candidate-list packing wins.  The "Data
Structure Perspective" thread (PAPERS.md) places the representation,
not the partitioning, as the dominant cost — this engine IS that
representation choice made routable per dataset.

Composition invariants (pinned by tests/test_spam.py):

- **Enumeration parity**: byte-identical output to the CPU oracle
  (``models/oracle.mine_spade``) and therefore to every SPADE engine.
- **Shared frontier format**: nodes are ``models/_common.FrontierNode``
  and ``frontier_fingerprint()`` matches ``SpadeTPU``'s exactly, so a
  checkpoint written mid-mine by either engine RESUMES under the other
  (the service may re-route an orphan through a different engine after
  a crash without losing progress).
- **Partition classes unchanged**: a pattern's class is its first item
  (the DFS root), precisely the classes parallel/partition.py already
  balances — the partitioned route seeds only owned roots and the
  slice union is exact, same as SPADE.
- **Threshold loop**: the wave loop prunes against ``self.threshold``,
  a monotone non-decreasing bound seeded at minsup — the same
  rising-threshold contract TSR's top-k loop drives, so the resident-
  frontier/launch-fusion eligibility reasoning carries over (waves ride
  ``fusion.dispatch_wave`` for the broker's accounting/fault surface;
  in minsup mode the threshold simply never rises).
"""

from __future__ import annotations

import functools
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import VerticalDB, build_vertical
from spark_fsm_tpu.models._common import (
    FrontierNode, SlotPool, auto_pool_bytes, decode_frontier, device_axes,
    encode_frontier, load_checkpoint, next_pow2, scatter_build_store)
from spark_fsm_tpu.ops import bitops_np as BN
from spark_fsm_tpu.ops import ragged_batch as RB
from spark_fsm_tpu.ops import spam_bitops as SB
from spark_fsm_tpu.parallel import multihost as MH
from spark_fsm_tpu.service import usage
from spark_fsm_tpu.utils import jobctl, obs, shapes
from spark_fsm_tpu.utils.canonical import Pattern, PatternResult, sort_patterns

Step = Tuple[int, bool]
_Node = FrontierNode


def spam_geometry(n_sequences: int, n_items: int, n_words: int, *,
                  mesh: Optional[Mesh] = None, node_batch: int = 64,
                  pipeline_depth: int = 2,
                  pool_bytes: Optional[int] = None,
                  shape_buckets: bool = False,
                  use_pallas: bool = False,
                  tile: int = SB.ITEM_TILE) -> dict:
    """Derived device geometry — the one sizing routine shared by the
    constructor and the shape-key record, same contract as
    ``classic_geometry``.  The extra constraint vs the classic engine:
    each in-flight wave holds a ``[2*nb, tile, S, W]`` AND intermediate,
    so the node batch is bounded by the pool budget divided by that
    live tile footprint, not only by slot arithmetic.  ``use_pallas``
    follows the classic engine's precedent: the fused kernel's sequence
    grid wants the per-shard axis padded to a whole number of s_blocks,
    so the geometry (and shape key) shift only when the kernel path is
    actually enabled."""
    n_shards = 1 if mesh is None else mesh.devices.size
    n_seq, s_block, _ = device_axes(
        n_sequences, n_items, n_words, mesh=mesh, use_pallas=use_pallas,
        shape_buckets=shape_buckets)
    if pool_bytes is None:
        pool_bytes = auto_pool_bytes(mesh)
    ni_pad = SB.pad_items(n_items, tile)
    slot_bytes = n_seq * n_words * 4
    spd = -(-slot_bytes // n_shards)  # per-device bytes of one store row
    budget_slots = max(64, min(int(pool_bytes) // max(slot_bytes, 1), 32768))
    d = max(1, min(int(pipeline_depth), max(1, budget_slots // 8)))
    # a quarter of the pool budget may live in wave intermediates,
    # split across the in-flight depth
    nb_wave = max(1, (int(pool_bytes) // 4) // max(1, 2 * tile * spd * d))
    nb = max(1, min(int(node_batch), nb_wave, budget_slots // (3 * (d + 2))))
    pool_slots = max(8, budget_slots - 2 * d * nb)
    total = ni_pad + pool_slots + 1
    if shape_buckets:
        floor_rows = ni_pad + 8 + 1
        total = next_pow2(total)
        budget_rows = ni_pad + 1 + budget_slots
        if total > budget_rows and total // 2 >= floor_rows:
            total //= 2
        pool_slots = total - ni_pad - 1
        nb = max(1, min(nb, pool_slots // (3 * (d + 2))))
    return {
        "n_seq": n_seq, "s_block": s_block, "ni_pad": ni_pad, "tile": tile,
        "node_batch": nb, "pipeline_depth": d, "pool_slots": pool_slots,
        "total_rows": total, "scratch": ni_pad + pool_slots,
        # sparse-candidate pair-launch chunk width (hybrid store): same
        # pow2 ladder as the materialize chunk so the shape registry's
        # spam-pair enumeration can mirror it exactly
        "chunk": min(2048, max(64, next_pow2(2 * nb))),
        "shape_key": shapes.key_spam(n_seq, n_words, total, nb, ni_pad),
    }


class SpamBitmapTPU:
    """Single- or multi-chip SPAM miner over the shared bitmap store.

    Args mirror :class:`models.spade_tpu.SpadeTPU` where shared;
    ``node_batch`` is deliberately smaller (default 64) because every
    node pays the full item axis.  The prep/materialize/recompute
    kernels are REUSED from the classic engine's jit cache
    (``spade_tpu._spade_fns``) — the two engines differ only in the
    support pass, so they must not compile two copies of everything
    else.
    """

    def __init__(
        self,
        vdb: VerticalDB,
        minsup_abs: int,
        *,
        mesh: Optional[Mesh] = None,
        node_batch: int = 64,
        pipeline_depth: int = 2,
        pool_bytes: Optional[int] = None,
        max_pattern_itemsets: Optional[int] = None,
        shape_buckets: bool = False,
        partition=None,
        representation: Optional[str] = None,
        density_crossover: Optional[float] = None,
        diffset_depth: Optional[int] = None,
        use_pallas="auto",
    ):
        from spark_fsm_tpu.models.spade_tpu import _spade_fns
        from spark_fsm_tpu.service import planner

        self.vdb = vdb
        self.minsup = int(minsup_abs)
        # the rising-threshold hook (see module docstring): prunes
        # compare against this, monotone non-decreasing, == minsup in
        # minsup mode
        self.threshold = int(minsup_abs)
        self.mesh = mesh
        self._partition = partition
        self._multiproc = MH.is_multihost(mesh)
        self._put = functools.partial(MH.host_to_device, mesh)
        self.max_pattern_itemsets = max_pattern_itemsets
        self._shape_buckets = bool(shape_buckets)

        n_items, n_seq, n_words = vdb.n_items, vdb.n_sequences, vdb.n_words
        # per-item representation plan (ISSUE 16): the planner's density
        # crossover splits the item axis into dense (wave lanes) and
        # sparse (pair-launch) halves, and picks the depth at which
        # supports flip to the dEclat diffset formulation; the call
        # lands the explaining planner.representation trace record
        self.rep_plan, self.diffset_depth = planner.choose_representation(
            vdb.item_supports, n_seq, pin=representation,
            crossover=density_crossover, diffset_depth=diffset_depth,
            engine="spam")
        self._hybrid = self.rep_plan.n_sparse > 0
        # same resolution idiom as SpadeTPU: "auto" means the kernel is
        # only worth compiling on real TPU backends; interpret mode makes
        # explicit use_pallas=True testable on CPU
        eligible = n_items > 0
        if use_pallas == "auto":
            self.use_pallas = eligible and jax.default_backend() == "tpu"
        else:
            self.use_pallas = bool(use_pallas) and eligible
        self._pallas_interpret = jax.default_backend() != "tpu"

        g = spam_geometry(
            n_seq, n_items, n_words, mesh=mesh, node_batch=node_batch,
            pipeline_depth=pipeline_depth, pool_bytes=pool_bytes,
            shape_buckets=self._shape_buckets, use_pallas=self.use_pallas)
        n_seq = g["n_seq"]
        self.n_items, self.n_seq, self.n_words = n_items, n_seq, n_words
        self.ni_pad = g["ni_pad"]
        self.node_batch = g["node_batch"]
        self.pipeline_depth = g["pipeline_depth"]
        self.pool_slots = g["pool_slots"]
        self.scratch = g["scratch"]
        total = g["total_rows"]

        # pool slots start at ni_pad, NOT n_items: rows n_items..ni_pad-1
        # are all-zero item pad rows the wave pass ANDs against — a pad
        # lane's support is exactly 0, never a live pattern bitmap's
        self.store = scatter_build_store(vdb, total, n_seq, n_words,
                                         mesh=mesh, put=self._put,
                                         bucket_tokens=self._shape_buckets,
                                         flat=True)
        self._pool = SlotPool(range(self.ni_pad,
                                    self.ni_pad + self.pool_slots))

        fns = _spade_fns(mesh, n_words)
        self._prep_fn = fns["prep"]
        self._materialize_fn = fns["materialize"]
        self._recompute_fn = fns["recompute"]

        # hybrid item split: dense items buy wave lanes in a compact
        # gathered block (the wave's item axis shrinks from ni_pad to
        # nd_pad); sparse items ride explicit pair launches instead.
        # On the pure-bitmap plan the wave runs over the store itself
        # and nd_pad == ni_pad — byte- and launch-identical geometry to
        # the unfused engine.
        rep = self.rep_plan.rep
        dense_idx = np.flatnonzero(rep[:n_items])
        self.n_dense = int(dense_idx.size)
        self._dense_col = np.full(max(n_items, 1), -1, np.int32)
        self._dense_col[dense_idx] = np.arange(self.n_dense, dtype=np.int32)
        if self._hybrid:
            self.nd_pad = SB.pad_items(self.n_dense) if self.n_dense else 0
        else:
            self.nd_pad = self.ni_pad
        if self._hybrid and self.n_dense:
            rows = np.full(self.nd_pad, -1, np.int32)
            rows[: self.n_dense] = dense_idx.astype(np.int32)
            self._dense_items = SB.gather_rows_fn(mesh)(
                self.store, self._put(rows))
        else:
            self._dense_items = None  # wave (if any) runs over the store
        self._wave_fn = (
            SB.wave_extend_prune_fn(
                mesh, n_words, self.nd_pad, g["tile"],
                use_pallas=self.use_pallas, s_block=g["s_block"],
                interpret=self._pallas_interpret)
            if self.nd_pad else None)
        self._pair_fn = SB.pair_prune_fn(mesh, n_words) if self._hybrid \
            else None
        # materialize + sparse pair-launch width: fixed-shape pow2
        # chunks like the classic engine
        self.chunk = g["chunk"]

        if self._hybrid:
            shape_key = shapes.key_spam_hybrid(
                n_seq, n_words, total, self.node_batch, self.ni_pad,
                self.nd_pad)
        else:
            shape_key = g["shape_key"]
        self.stats = {
            "engine": "spam",
            "candidates": 0, "evaluated_lanes": 0, "waves": 0,
            "kernel_launches": 0, "recomputed_nodes": 0,
            "reclaimed_slots": 0, "patterns": 0,
            "shape_key": shape_key,
            "representation": self.rep_plan.pin,
            "rep_dense": self.n_dense,
            "rep_idlist": int(self.rep_plan.n_sparse),
            "diffset_depth": int(self.diffset_depth),
            "diffset_nodes": 0, "pair_launches": 0, "wave_survivors": 0,
        }
        shapes.record(shape_key)

    # ------------------------------------------------------------ slot mgmt

    def _alloc(self) -> Optional[int]:
        return self._pool.alloc()

    def _free_slot(self, slot: Optional[int]) -> None:
        if slot is not None and slot >= self.ni_pad:
            self._pool.free(slot)

    # ------------------------------------------------------------- kernels

    def _prep(self, batch: List[_Node]):
        slots = np.zeros(self.node_batch, np.int32)
        for i, n in enumerate(batch):
            slots[i] = n.slot
        pt = self._prep_fn(self.store, self._put(slots))
        self.stats["kernel_launches"] += 1
        return pt

    def _materialize(self, prep, ref, item, iss, out_slot) -> None:
        n = len(ref)
        c = self.chunk
        for lo in range(0, n, c):
            hi = min(lo + c, n)
            pad = c - (hi - lo)
            r = self._put(np.pad(ref[lo:hi].astype(np.int32), (0, pad)))
            it = self._put(np.pad(item[lo:hi].astype(np.int32), (0, pad)))
            ss = self._put(np.pad(iss[lo:hi].astype(bool), (0, pad)))
            os_ = self._put(np.pad(out_slot[lo:hi].astype(np.int32),
                                   (0, pad), constant_values=self.scratch))
            self.store = self._materialize_fn(prep, self.store, r, it, ss,
                                              os_)
            self.stats["kernel_launches"] += 1

    def _ensure_slots(self, batch: List[_Node], stack: List[_Node]) -> None:
        missing = [n for n in batch if n.slot is None]
        if not missing:
            return
        self.stats["recomputed_nodes"] += len(missing)
        if len(self._pool) < len(missing):
            self._pool.reclaim(stack, len(missing),
                               lambda n: n.slot >= self.ni_pad)
            self.stats["reclaimed_slots"] = self._pool.reclaimed
        rc = max(16, self.node_batch)
        for lo in range(0, len(missing), rc):
            group = missing[lo: lo + rc]
            m = rc
            k = next_pow2(max(len(n.steps) for n in group))
            items = np.zeros((k, m), np.int32)
            iss = np.zeros((k, m), bool)
            valid = np.zeros((k, m), bool)
            slots = np.full(m, self.scratch, np.int32)
            for col, node in enumerate(group):
                slot = self._alloc()
                assert slot is not None, "slot pool exhausted beyond reclaim"
                node.slot = slot
                slots[col] = slot
                for row, (it, s) in enumerate(node.steps):
                    items[row, col], iss[row, col] = it, s
                    valid[row, col] = True
            self.store = self._recompute_fn(
                self.store, self._put(items), self._put(iss),
                self._put(valid), self._put(slots))
            self.stats["kernel_launches"] += 1

    # ---------------------------------------------------------------- mine

    def _pattern_of(self, steps: Sequence[Step]) -> Pattern:
        ids = self.vdb.item_ids
        pat: List[List[int]] = []
        for it, is_s in steps:
            if is_s:
                pat.append([int(ids[it])])
            else:
                pat[-1].append(int(ids[it]))
        return tuple(tuple(s) for s in pat)

    def _dispatch(self, stack: List[_Node]):
        """Pop a node batch and launch ONE fused extension-count-prune
        wave for the whole (nodes x dense items x {s,i}) grid, plus (on
        a hybrid plan) chunked pair launches for the sparse-item
        candidates; the async host copies start immediately.  Routed
        through the fusion broker's wave surface for its
        accounting/fault posture (an armed ``fusion.dispatch`` fault
        degrades to a direct dispatch, never loses the wave)."""
        from spark_fsm_tpu.service import fusion

        jobctl.check()  # launch-boundary safe point (cancel/deadline/fence)
        batch = [stack.pop() for _ in range(min(self.node_batch, len(stack)))]
        self._ensure_slots(batch, stack)
        prep = self._prep(batch)
        thr_dev = self._put(np.int32(self.threshold))
        # per-row dEclat flags: a node at or past the diffset depth has
        # BOTH its interleaved rows (plain 2b, transformed 2b+1) count
        # via support(parent) - |diffset| — exact identity, but the
        # accounting matters for drift calibration and the trace
        dd = self.diffset_depth
        ud_rows = np.zeros(2 * self.node_batch, bool)
        for b, node in enumerate(batch):
            if dd and len(node.steps) >= dd:
                ud_rows[2 * b] = ud_rows[2 * b + 1] = True
                self.stats["diffset_nodes"] += 1
        sup_dev = mask_dev = None
        if self._wave_fn is not None:
            items_arg = (self._dense_items if self._dense_items is not None
                         else self.store)
            sup_dev, mask_dev = fusion.dispatch_wave(
                "spam",
                lambda: self._wave_fn(prep, items_arg, thr_dev,
                                      self._put(ud_rows)),
                nodes=len(batch), items=self.nd_pad)
            self.stats["kernel_launches"] += 1
            self.stats["waves"] += 1
            self.stats["evaluated_lanes"] += 2 * self.node_batch * self.nd_pad
        # sparse half of the hybrid store: candidates whose item the
        # planner kept as an id-list never bought a wave lane — pack
        # them into fixed pow2-width pair launches (compiled once per
        # width, recorded in the shape registry like ragged chunks)
        pair_devs: List = []
        pair_pos = {}
        if self._hybrid:
            pref_l: List[int] = []
            item_l: List[int] = []
            ud_l: List[bool] = []
            for b, node in enumerate(batch):
                node_ud = bool(dd and len(node.steps) >= dd)
                if self._allow_s(node):
                    for i in node.s_list:
                        if self._dense_col[i] < 0:
                            pair_pos[(2 * b + 1, i)] = len(pref_l)
                            pref_l.append(2 * b + 1)
                            item_l.append(i)
                            ud_l.append(node_ud)
                for i in node.i_list:
                    if self._dense_col[i] < 0:
                        pair_pos[(2 * b, i)] = len(pref_l)
                        pref_l.append(2 * b)
                        item_l.append(i)
                        ud_l.append(node_ud)
            c = self.chunk
            for lo in range(0, len(pref_l), c):
                hi = min(lo + c, len(pref_l))
                w = max(64, next_pow2(hi - lo))
                pref = np.zeros(w, np.int32)
                pref[: hi - lo] = pref_l[lo:hi]
                item = np.full(w, -1, np.int32)
                item[: hi - lo] = item_l[lo:hi]
                ud = np.zeros(w, bool)
                ud[: hi - lo] = ud_l[lo:hi]
                d = fusion.dispatch_wave(
                    "spam",
                    lambda p=pref, it=item, u=ud: self._pair_fn(
                        prep, self.store, self._put(p), self._put(it),
                        thr_dev, self._put(u)),
                    nodes=len(batch), items=w)
                shapes.record(shapes.key_spam_pair(self.n_seq, self.n_words,
                                                   w))
                self.stats["kernel_launches"] += 1
                self.stats["pair_launches"] += 1
                self.stats["evaluated_lanes"] += w
                pair_devs.append(d)
        self.stats["candidates"] += sum(
            (len(n.s_list) if self._allow_s(n) else 0) + len(n.i_list)
            for n in batch)
        for dev in ([sup_dev, mask_dev] if sup_dev is not None
                    else []) + pair_devs:
            try:
                dev.copy_to_host_async()
            except (AttributeError, NotImplementedError):
                pass
        # dispatch-cost stamp for attribution at resolve time
        # (service/usage.py): launches + lane-traffic this inflight
        # entry bought, the cost model's estimate for them, and the
        # dispatch instant the resolve measures its wall from
        launches = (1 if sup_dev is not None else 0) + len(pair_devs)
        lanes = (2 * self.node_batch * self.nd_pad
                 if sup_dev is not None else 0)
        lanes += sum(self._pair_width(d) for d in pair_devs)
        est_s = RB.estimate_seconds(lanes, max(1, launches), self.n_seq,
                                    self.n_words) if launches else 0.0
        return (batch, prep, sup_dev, mask_dev, pair_devs, pair_pos,
                (launches, lanes, est_s, time.monotonic()))

    @staticmethod
    def _pair_width(dev) -> int:
        try:
            return int(dev.shape[-1])
        except Exception:
            return 0

    def _allow_s(self, node: _Node) -> bool:
        if self.max_pattern_itemsets is None:
            return True
        return sum(1 for _, s in node.steps
                   if s) < self.max_pattern_itemsets

    def _resolve(self, inflight, stack: List[_Node],
                 results: List[PatternResult]) -> None:
        (batch, prep, sup_dev, mask_dev, pair_devs, pair_pos,
         cost) = inflight
        sups = (np.asarray(sup_dev)  # [2*nb, nd_pad] dense-column lanes
                if sup_dev is not None else None)
        pair_sups = [np.asarray(d) for d in pair_devs]
        launches, lanes, est_s, t0 = cost
        if launches:
            measured_s = time.monotonic() - t0
            # spam residuals feed the spam FAMILY gauge only — the
            # global recalibration EWMA stays fed by its two
            # pre-existing surfaces (bench_smoke pins it byte-identical)
            obs.observe_costmodel_family("spam", est_s, measured_s)
            if usage.get() is not None:
                ctl = jobctl.current()
                if ctl is not None:
                    nbytes = (int(sups.nbytes) if sups is not None
                              else 0) + sum(int(a.nbytes)
                                            for a in pair_sups)
                    usage.deposit(ctl.uid, launches=launches,
                                  traffic_units=lanes,
                                  seconds_est=est_s,
                                  seconds_measured=measured_s,
                                  readback_bytes=nbytes)
        if mask_dev is not None:
            # survivor-mask accounting: the fused prune's packed alive
            # bits over the LIVE node rows (pad rows carry slot-0
            # garbage lanes the host never reads)
            m = np.asarray(mask_dev)[: 2 * len(batch)]
            self.stats["wave_survivors"] += int(BN.popcount(m).sum())
        col = self._dense_col
        c = self.chunk

        def sup_at(r: int, i: int) -> int:
            # fused-prune read contract: the value is the exact count
            # where >= threshold and exactly 0 otherwise, so the host's
            # >= thr comparison below is byte-identical to the unfused
            # engine's
            ci = col[i]
            if ci >= 0:
                return int(sups[r, ci])
            gi = pair_pos[(r, i)]
            return int(pair_sups[gi // c][gi % c])

        thr = self.threshold
        children: List[_Node] = []
        mat_ref: List[int] = []; mat_item: List[int] = []
        mat_iss: List[bool] = []; mat_child: List[int] = []
        for b, node in enumerate(batch):
            allow_s = self._allow_s(node)
            n_itemsets = sum(1 for _, s in node.steps if s)
            # host-side lane read: only the lanes the candidate lists
            # name — pad lanes and non-candidate items are never read
            s_items = ([i for i in node.s_list if sup_at(2 * b + 1, i) >= thr]
                       if allow_s else [])
            i_items = [i for i in node.i_list if sup_at(2 * b, i) >= thr]
            for it, is_s in ([(i, True) for i in s_items]
                             + [(i, False) for i in i_items]):
                sup = sup_at(2 * b + 1, it) if is_s else sup_at(2 * b, it)
                steps = node.steps + ((it, is_s),)
                results.append((self._pattern_of(steps), sup))
                src = s_items if is_s else i_items
                child_i = [j for j in src if j > it]
                child_itemsets = n_itemsets + (1 if is_s else 0)
                child_allow_s = (self.max_pattern_itemsets is None
                                 or child_itemsets
                                 < self.max_pattern_itemsets)
                if not ((s_items and child_allow_s) or child_i):
                    continue
                child = _Node(steps, None, s_items, child_i)
                slot = self._alloc()
                if slot is not None:
                    child.slot = slot
                    mat_ref.append(b); mat_item.append(it)
                    mat_iss.append(is_s); mat_child.append(slot)
                children.append(child)
        if mat_child:
            self._materialize(prep, np.array(mat_ref, np.int32),
                              np.array(mat_item, np.int32),
                              np.array(mat_iss, bool),
                              np.array(mat_child, np.int32))
        stack.extend(reversed(children))
        for node in batch:
            self._free_slot(node.slot)

    def frontier_fingerprint(self) -> dict:
        """Identical field-for-field to ``SpadeTPU.frontier_fingerprint``
        — deliberately: the two engines' checkpoints must resume each
        other (same projection, same enumeration, same node shape)."""
        ids = self.vdb.item_ids
        return {
            "minsup": self.minsup,
            "n_items": self.n_items,
            "n_sequences": self.vdb.n_sequences,
            "max_itemsets": self.max_pattern_itemsets,
            "item_ids_head": [int(i) for i in ids[:8]],
            "item_ids_sum": int(ids.astype(np.int64).sum()),
        }

    def frontier_state(self, stack: List[_Node],
                       results: List[PatternResult],
                       results_from: int = 0) -> dict:
        return encode_frontier(self.frontier_fingerprint(), stack, results,
                               results_from)

    def mine(self, *, resume: Optional[dict] = None,
             checkpoint_cb=None,
             checkpoint_every_s: float = 30.0) -> List[PatternResult]:
        stack: List[_Node] = []
        results: List[PatternResult]
        if resume is not None:
            results, stack = decode_frontier(
                resume, self.frontier_fingerprint(), _Node)
            self.stats["resumed_nodes"] = len(stack)
        else:
            results = []
            root_items = [i for i in range(self.n_items)
                          if int(self.vdb.item_supports[i]) >= self.minsup]
            seed = set(root_items)
            if self._partition is not None:
                plan, pidx = self._partition
                seed = set(plan.owned_slice(root_items,
                                            self.vdb.item_ids, pidx))
            for i in reversed(root_items):
                if i not in seed:
                    continue
                results.append((self._pattern_of(((i, True),)),
                                int(self.vdb.item_supports[i])))
                stack.append(_Node(((i, True),), i, root_items,
                                   [j for j in root_items if j > i]))

        ckpt_done = len(results) if resume is not None else 0
        last_ckpt = time.monotonic()
        inflight: deque = deque()
        while stack or inflight:
            while stack and len(inflight) < self.pipeline_depth:
                inflight.append(self._dispatch(stack))
            self._resolve(inflight.popleft(), stack, results)
            if (checkpoint_cb is not None
                    and time.monotonic() - last_ckpt >= checkpoint_every_s):
                while inflight:
                    self._resolve(inflight.popleft(), stack, results)
                checkpoint_cb(self.frontier_state(stack, results,
                                                  results_from=ckpt_done))
                ckpt_done = len(results)
                self.stats["checkpoints"] = \
                    self.stats.get("checkpoints", 0) + 1
                last_ckpt = time.monotonic()

        self.stats["patterns"] = len(results)
        return sort_patterns(results)


# ---------------------------------------------------------------------------
# CPU reference (the SPAM plugin's engine; numpy popcount formulation)
# ---------------------------------------------------------------------------


def mine_spam_cpu(db: SequenceDB, minsup_abs: int, *,
                  max_pattern_itemsets: Optional[int] = None,
                  stats_out: Optional[dict] = None,
                  representation: Optional[str] = None,
                  density_crossover: Optional[float] = None,
                  diffset_depth: Optional[int] = None) -> List[PatternResult]:
    """Host SPAM miner on the dense bitmaps with the same popcount
    support formulation (``bitops_np.support_popcount``) — the CPU leg
    of the SPAM plugin pair, byte-identical to ``oracle.mine_spade`` by
    the shared enumeration.  Carries the same hybrid-representation
    seams as the device engine (ISSUE 16): planner-routed per-item
    bitmap/id-list split (sparse candidates count via
    ``vertical.idlist_join_support``) and depth-selected dEclat diffset
    supports — all three paths are exact, so results stay byte-identical
    across any plan."""
    from spark_fsm_tpu.data.vertical import idlist_join_support
    from spark_fsm_tpu.service import planner

    vdb = build_vertical(db, min_item_support=minsup_abs)
    if vdb.n_items == 0:
        return []
    plan, dd = planner.choose_representation(
        vdb.item_supports, vdb.n_sequences, pin=representation,
        crossover=density_crossover, diffset_depth=diffset_depth,
        engine="spam-cpu")
    rep = plan.rep
    bm = vdb.bitmaps  # [n_items, S, W]
    n_items = vdb.n_items
    results: List[PatternResult] = []
    ids = vdb.item_ids

    def pattern_of(steps) -> Pattern:
        pat: List[List[int]] = []
        for it, is_s in steps:
            if is_s:
                pat.append([int(ids[it])])
            else:
                pat[-1].append(int(ids[it]))
        return tuple(tuple(s) for s in pat)

    root_items = [i for i in range(n_items)
                  if int(vdb.item_supports[i]) >= minsup_abs]
    stack: List[tuple] = []  # (steps, bitmap, s_list, i_list)
    for i in reversed(root_items):
        results.append((pattern_of(((i, True),)),
                        int(vdb.item_supports[i])))
        stack.append(((( i, True),), bm[i], root_items,
                      [j for j in root_items if j > i]))
    waves = candidates = diffset_nodes = 0

    def eval_cands(parent, cand, use_diff):
        """support(parent AND bm[i]) per candidate via the plan's
        per-item path: dense items as one bitmap block (direct popcount
        or the dEclat ``support(parent) - |diffset|`` spelling), sparse
        items via the id-list token join — three exact formulations of
        the same count."""
        sups = {}
        dense = [i for i in cand if rep[i]]
        if dense:
            joined = parent[None] & bm[dense]           # [n, S, W]
            if use_diff:
                block = BN.support_from_diffset(
                    BN.support_popcount(parent[None]),
                    BN.diffset_count(parent[None], joined))
            else:
                block = BN.support_popcount(joined)
            sups.update((i, int(s)) for i, s in zip(dense, block))
        for i in cand:
            if not rep[i]:
                sups[i] = idlist_join_support(parent, *vdb.idlist(i))
        return sups

    while stack:
        steps, b, s_list, i_list = stack.pop()
        n_itemsets = sum(1 for _, s in steps if s)
        allow_s = (max_pattern_itemsets is None
                   or n_itemsets < max_pattern_itemsets)
        trans = BN.sext_transform(b)
        waves += 1
        use_diff = bool(dd and len(steps) >= dd)
        if use_diff:
            diffset_nodes += 1
        s_items: List[int] = []
        s_sups = {}
        if allow_s and s_list:
            all_s = eval_cands(trans, s_list, use_diff)
            candidates += len(s_list)
            for i in s_list:
                if all_s[i] >= minsup_abs:
                    s_items.append(i)
                    s_sups[i] = all_s[i]
        i_items: List[int] = []
        i_sups = {}
        if i_list:
            all_i = eval_cands(b, i_list, use_diff)
            candidates += len(i_list)
            for i in i_list:
                if all_i[i] >= minsup_abs:
                    i_items.append(i)
                    i_sups[i] = all_i[i]
        children = []
        for it, is_s in ([(i, True) for i in s_items]
                         + [(i, False) for i in i_items]):
            sup = s_sups[it] if is_s else i_sups[it]
            child_steps = steps + ((it, is_s),)
            results.append((pattern_of(child_steps), sup))
            src = s_items if is_s else i_items
            child_i = [j for j in src if j > it]
            child_itemsets = n_itemsets + (1 if is_s else 0)
            child_allow_s = (max_pattern_itemsets is None
                             or child_itemsets < max_pattern_itemsets)
            if not ((s_items and child_allow_s) or child_i):
                continue
            cb = (BN.s_extend(b, bm[it]) if is_s
                  else BN.i_extend(b, bm[it]))
            children.append((child_steps, cb, s_items, child_i))
        stack.extend(reversed(children))
    if stats_out is not None:
        stats_out.update({"engine": "spam-cpu", "waves": waves,
                          "candidates": candidates,
                          "patterns": len(results),
                          "representation": plan.pin,
                          "rep_dense": plan.n_dense,
                          "rep_idlist": plan.n_sparse,
                          "diffset_depth": dd,
                          "diffset_nodes": diffset_nodes})
    return sort_patterns(results)


# ---------------------------------------------------------------------------
# Service entry points
# ---------------------------------------------------------------------------


def mine_spam_tpu(
    db: SequenceDB,
    minsup_abs: int,
    *,
    mesh: Optional[Mesh] = None,
    max_pattern_itemsets: Optional[int] = None,
    stats_out: Optional[dict] = None,
    checkpoint=None,
    partition_parts: int = 0,
    partition_classes: int = 64,
    **kwargs,
) -> List[PatternResult]:
    """DB -> vertical build -> SPAM wave mine; same wrapper contract as
    ``mine_spade_tpu`` (checkpoint load/save/every_s, optional
    equivalence-class partitioning)."""
    vdb = build_vertical(db, min_item_support=minsup_abs)
    if vdb.n_items == 0:
        return []
    if partition_parts and int(partition_parts) > 1:
        return _mine_spam_partitioned(
            vdb, minsup_abs, mesh=mesh, parts=int(partition_parts),
            classes=int(partition_classes),
            max_pattern_itemsets=max_pattern_itemsets,
            stats_out=stats_out, checkpoint=checkpoint, **kwargs)
    eng = SpamBitmapTPU(vdb, minsup_abs, mesh=mesh,
                        max_pattern_itemsets=max_pattern_itemsets, **kwargs)
    resume, save_cb, every_s = load_checkpoint(
        checkpoint, eng.frontier_fingerprint())
    results = eng.mine(resume=resume, checkpoint_cb=save_cb,
                       checkpoint_every_s=every_s)
    if stats_out is not None:
        stats_out.update(eng.stats)
    return results


def _mine_spam_partitioned(
    vdb: VerticalDB,
    minsup_abs: int,
    *,
    mesh: Optional[Mesh],
    parts: int,
    classes: int,
    max_pattern_itemsets: Optional[int],
    stats_out: Optional[dict],
    checkpoint,
    **kwargs,
) -> List[PatternResult]:
    """Equivalence-class partitioned SPAM: identical structure to the
    partitioned SPADE route — a pattern's class is its first item, so
    fixed-minsup slices are fully independent and the union is exact;
    composite checkpoints nest each slice's frontier in the shared
    ``frontier_state`` format (parallel/partition.py)."""
    from spark_fsm_tpu.models.spade_tpu import _SliceCheckpoint
    from spark_fsm_tpu.parallel import partition as PN

    plan = PN.plan_partitions(vdb.item_ids, vdb.item_supports, parts,
                              classes)
    meshes = PN.submeshes(mesh, parts)
    ids = vdb.item_ids
    fingerprint = {
        "minsup": int(minsup_abs),
        "n_items": int(vdb.n_items),
        "n_sequences": int(vdb.n_sequences),
        "max_itemsets": max_pattern_itemsets,
        "item_ids_head": [int(i) for i in ids[:8]],
        "item_ids_sum": int(ids.astype(np.int64).sum()),
        # NO engine marker — field-identical to the partitioned SPADE
        # fingerprint on purpose: the composite nests slice frontiers in
        # the shared format, so either engine resumes the other's
        # partitioned checkpoint too
        "partition": plan.fingerprint(),
    }
    resume, save_cb, every_s = load_checkpoint(checkpoint, fingerprint)
    stats: dict = {
        "engine": "spam",
        "partition_parts": int(parts),
        "partition_classes": int(classes),
        "partition_imbalance": round(plan.imbalance_ratio, 4),
    }
    PN.count_mine("spam")

    def mine_part(p, inner_mesh, resume_state, part_cb):
        part_stats: dict = {}
        ckpt = None
        if resume_state is not None or part_cb is not None:
            ckpt = _SliceCheckpoint(resume_state, part_cb, every_s)
        eng = SpamBitmapTPU(vdb, minsup_abs, mesh=inner_mesh,
                            max_pattern_itemsets=max_pattern_itemsets,
                            partition=(plan, p), **kwargs)
        p_resume, p_save, p_every = load_checkpoint(
            ckpt, eng.frontier_fingerprint())
        res = eng.mine(resume=p_resume, checkpoint_cb=p_save,
                       checkpoint_every_s=p_every)
        part_stats.update(eng.stats)
        PN.fold_numeric_stats(stats, part_stats)
        return PN.encode_patterns(res)

    rows = PN.mine_partitioned_slices(
        plan=plan, meshes=meshes, fingerprint=fingerprint,
        mine_part=mine_part, resume=resume, checkpoint_cb=save_cb,
        stats=stats)
    results = sort_patterns(PN.decode_patterns(rows))
    stats["patterns"] = len(results)
    if stats_out is not None:
        stats_out.update(stats)
    return results
