"""CPU oracle miners.

Two independent implementations guard correctness (SURVEY.md sec 4):

- ``brute_force_mine``: direct containment checks over the horizontal DB with
  unpruned candidate extension — slow, only for tiny fixtures, but shares no
  bitmap/join code with anything else.  Ground truth for the oracle itself.
- ``mine_spade``: the real CPU oracle — SPAM-style DFS over the vertical
  bitmap DB (SURVEY.md sec 2.3 steps 2-5) built on ops/bitops_np.py.  This is
  the "CPU SPADE" the north star's byte-identical parity is measured against,
  and its enumeration (shared S/I candidate lists per equivalence class,
  ascending item order) defines the canonical pattern universe the TPU engine
  must reproduce.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from spark_fsm_tpu.data.spmf import Sequence, SequenceDB
from spark_fsm_tpu.data.vertical import VerticalDB, build_vertical
from spark_fsm_tpu.ops import bitops_np as B
from spark_fsm_tpu.utils.canonical import Pattern, PatternResult, sort_patterns


# ---------------------------------------------------------------------------
# Brute force (independent ground truth for tiny DBs)
# ---------------------------------------------------------------------------

def contains(seq: Sequence, pattern: Pattern) -> bool:
    """True iff ``pattern`` occurs in ``seq`` (ordered itemset-subset match).

    Greedy leftmost matching is correct for plain containment: taking the
    earliest itemset that covers the next pattern element never removes later
    options.
    """
    p = 0
    for itemset in seq:
        if p == len(pattern):
            return True
        if set(pattern[p]).issubset(itemset):
            p += 1
    return p == len(pattern)


def brute_force_mine(
    db: SequenceDB,
    minsup_abs: int,
    max_pattern_itemsets: int = 6,
    max_itemset_size: int = 3,
) -> List[PatternResult]:
    """Level-wise mining with direct containment counting.

    Extends every frequent pattern with every frequent item (both s- and
    i-extension), relying only on the apriori property — no equivalence-class
    pruning — so its completeness is independent of the SPAM S/I-list logic.
    """
    items = sorted({i for seq in db for itemset in seq for i in itemset})

    def sup(pat: Pattern) -> int:
        return sum(1 for seq in db if contains(seq, pat))

    freq_items = [i for i in items if sup(((i,),)) >= minsup_abs]
    results: List[PatternResult] = []
    frontier: List[Pattern] = []
    for i in freq_items:
        pat: Pattern = ((i,),)
        results.append((pat, sup(pat)))
        frontier.append(pat)
    while frontier:
        nxt: List[Pattern] = []
        for pat in frontier:
            cands: List[Pattern] = []
            if len(pat) < max_pattern_itemsets:
                cands.extend(pat + ((i,),) for i in freq_items)
            last = pat[-1]
            if len(last) < max_itemset_size:
                cands.extend(
                    pat[:-1] + (tuple(sorted(last + (i,))),)
                    for i in freq_items if i > last[-1]
                )
            for c in cands:
                s = sup(c)
                if s >= minsup_abs:
                    results.append((c, s))
                    nxt.append(c)
        frontier = nxt
    return sort_patterns(results)


# ---------------------------------------------------------------------------
# CPU SPADE oracle (SPAM bitmap DFS)
# ---------------------------------------------------------------------------

def mine_spade_vertical(
    vdb: VerticalDB,
    minsup_abs: int,
    max_pattern_itemsets: Optional[int] = None,
) -> List[PatternResult]:
    """SPAM-style DFS over a prebuilt vertical DB.

    Equivalence-class candidate pruning per Ayres et al. 2002 (SURVEY.md
    sec 2.3 step 3): at each node with candidate lists (S, I), the frequent
    s-extension items S' become every child's S list; an s-child by item i
    gets I = {j in S' : j > i}; an i-child by item i gets I = {j in I' : j >
    i} where I' are the frequent i-extension items.
    """
    bm = vdb.bitmaps  # [n_items, n_seq, n_words]
    n_items = vdb.n_items
    ids = vdb.item_ids
    results: List[PatternResult] = []

    root_items = [i for i in range(n_items) if int(vdb.item_supports[i]) >= minsup_abs]

    # Stack-based DFS; node = (pattern, bitmap, s_list, i_list).
    stack: List[Tuple[Pattern, np.ndarray, List[int], List[int]]] = []
    for i in reversed(root_items):
        pat: Pattern = ((int(ids[i]),),)
        results.append((pat, int(vdb.item_supports[i])))
        stack.append((pat, bm[i], root_items, [j for j in root_items if j > i]))

    while stack:
        pat, bmp, s_list, i_list = stack.pop()
        if max_pattern_itemsets is not None and len(pat) >= max_pattern_itemsets and not i_list:
            continue
        s_ok: List[Tuple[int, np.ndarray, int]] = []
        allow_s = max_pattern_itemsets is None or len(pat) < max_pattern_itemsets
        if allow_s and s_list:
            trans = B.sext_transform(bmp)
            for i in s_list:
                nb = trans & bm[i]
                sup = int(B.support(nb))
                if sup >= minsup_abs:
                    s_ok.append((i, nb, sup))
        s_items = [i for i, _, _ in s_ok]
        i_ok: List[Tuple[int, np.ndarray, int]] = []
        for i in i_list:
            nb = bmp & bm[i]
            sup = int(B.support(nb))
            if sup >= minsup_abs:
                i_ok.append((i, nb, sup))
        i_items = [i for i, _, _ in i_ok]

        # Push in reverse so DFS visits ascending item order, s before i.
        for i, nb, sup in reversed(i_ok):
            child = pat[:-1] + (pat[-1] + (int(ids[i]),),)
            results.append((child, sup))
            stack.append((child, nb, s_items, [j for j in i_items if j > i]))
        for i, nb, sup in reversed(s_ok):
            child = pat + ((int(ids[i]),),)
            results.append((child, sup))
            stack.append((child, nb, s_items, [j for j in s_items if j > i]))
    return sort_patterns(results)


def mine_spade(
    db: SequenceDB,
    minsup_abs: int,
    max_pattern_itemsets: Optional[int] = None,
) -> List[PatternResult]:
    vdb = build_vertical(db, min_item_support=minsup_abs)
    if vdb.n_items == 0:
        return []
    return mine_spade_vertical(vdb, minsup_abs, max_pattern_itemsets)


# ---------------------------------------------------------------------------
# Constrained mining (maxgap / maxwindow), SURVEY.md sec 2.3 step 6
# ---------------------------------------------------------------------------

def contains_constrained(
    seq: Sequence,
    pattern: Pattern,
    maxgap: Optional[int] = None,
    maxwindow: Optional[int] = None,
) -> bool:
    """True iff ``pattern`` has an occurrence with consecutive itemset-
    position gaps <= maxgap and total span <= maxwindow.

    Exhaustive DFS over position assignments (greedy matching is NOT valid
    under constraints), so only for small fixtures.
    """
    sets = [set(s) for s in pattern]
    n = len(seq)

    def ok_at(p: int, j: int) -> bool:
        return sets[j].issubset(seq[p])

    def dfs(j: int, prev: int, start: int) -> bool:
        if j == len(sets):
            return True
        hi = n if maxgap is None else min(n, prev + maxgap + 1)
        for p in range(prev + 1, hi):
            if maxwindow is not None and p - start > maxwindow:
                break
            if ok_at(p, j) and dfs(j + 1, p, start):
                return True
        return False

    for p0 in range(n):
        if ok_at(p0, 0) and dfs(1, p0, p0):
            return True
    return False


def brute_force_mine_constrained(
    db: SequenceDB,
    minsup_abs: int,
    maxgap: Optional[int] = None,
    maxwindow: Optional[int] = None,
    max_pattern_itemsets: int = 5,
    max_itemset_size: int = 3,
) -> List[PatternResult]:
    """Level-wise constrained mining by direct (unpruned) counting.

    Note the candidate frontier must NOT prune on the constrained support:
    under maxgap a super-pattern can be frequent while a non-contiguous
    sub-pattern is not, so candidates extend patterns frequent under the
    UNCONSTRAINED count (apriori-safe superset) and constrained support
    only decides output membership.
    """
    items = sorted({i for seq in db for itemset in seq for i in itemset})

    def csup(pat: Pattern) -> int:
        return sum(1 for s in db if contains_constrained(s, pat, maxgap, maxwindow))

    def usup(pat: Pattern) -> int:
        return sum(1 for s in db if contains(s, pat))

    freq_items = [i for i in items if usup(((i,),)) >= minsup_abs]
    results: List[PatternResult] = []
    frontier: List[Pattern] = [((i,),) for i in freq_items]
    for pat in frontier:
        results.append((pat, csup(pat)))
    while frontier:
        nxt: List[Pattern] = []
        for pat in frontier:
            cands: List[Pattern] = []
            if len(pat) < max_pattern_itemsets:
                cands.extend(pat + ((i,),) for i in freq_items)
            last = pat[-1]
            if len(last) < max_itemset_size:
                cands.extend(
                    pat[:-1] + (tuple(sorted(last + (i,))),)
                    for i in freq_items if i > last[-1]
                )
            for c in cands:
                if usup(c) >= minsup_abs:
                    nxt.append(c)
                    s = csup(c)
                    if s >= minsup_abs:
                        results.append((c, s))
        frontier = nxt
    return sort_patterns([(p, s) for p, s in results if s >= minsup_abs])


def mine_cspade(
    db: SequenceDB,
    minsup_abs: int,
    maxgap: Optional[int] = None,
    maxwindow: Optional[int] = None,
    max_pattern_itemsets: Optional[int] = None,
) -> List[PatternResult]:
    """CPU oracle for constrained SPADE using the max-start state
    (ops/maxstart_np.py).

    Enumeration: under maxgap, s-candidates are ALL frequent root items
    (sibling S-list pruning is unsound there — cSPADE's F2-join
    observation); with no gap bound the sibling prune applies as usual.
    i-candidates always use sibling pruning, which stays valid
    (i-extension keeps every occurrence's positions).  The DFS prunes on
    the CONSTRAINED (gap- and window-checked) support: it is anti-monotone
    under prefix growth — a valid child occurrence contains a valid
    same-start prefix occurrence — so the prune is exact.
    """
    from spark_fsm_tpu.ops import maxstart_np as MS

    vdb = build_vertical(db, min_item_support=minsup_abs)
    if vdb.n_items == 0:
        return []
    bm = vdb.bitmaps
    ids = vdb.item_ids
    n_items = vdb.n_items
    results: List[PatternResult] = []

    root_items = [i for i in range(n_items) if int(vdb.item_supports[i]) >= minsup_abs]
    stack: List[Tuple[Pattern, np.ndarray, List[int], List[int]]] = []
    for i in reversed(root_items):
        pat: Pattern = ((int(ids[i]),),)
        results.append((pat, int(vdb.item_supports[i])))
        m0 = MS.root_state(bm[i])
        stack.append((pat, m0, root_items, [j for j in root_items if j > i]))

    while stack:
        pat, m, s_list, i_list = stack.pop()
        allow_s = max_pattern_itemsets is None or len(pat) < max_pattern_itemsets
        s_ok: List[Tuple[int, np.ndarray, int]] = []
        if allow_s:
            pm = MS.prev_max(m, maxgap)
            for i in s_list:
                occ = MS.expand_bits(bm[i])
                nm = np.where(occ & (pm >= 0), pm, MS.NONE16)
                # windowed support is anti-monotone under prefix growth (a
                # valid child occurrence contains a valid prefix occurrence
                # with the same start), so pruning on it is exact
                csup = int(MS.support(nm, maxwindow))
                if csup >= minsup_abs:
                    s_ok.append((i, nm, csup))
        i_ok: List[Tuple[int, np.ndarray, int]] = []
        for i in i_list:
            occ = MS.expand_bits(bm[i])
            nm = np.where(occ & (m >= 0), m, MS.NONE16)
            csup = int(MS.support(nm, maxwindow))
            if csup >= minsup_abs:
                i_ok.append((i, nm, csup))
        i_items = [i for i, _, _ in i_ok]
        s_items = [i for i, _, _ in s_ok]
        child_s = s_items if maxgap is None else root_items
        for i, nm, csup in reversed(i_ok):
            child = pat[:-1] + (pat[-1] + (int(ids[i]),),)
            results.append((child, csup))
            stack.append((child, nm, child_s, [j for j in i_items if j > i]))
        for i, nm, csup in reversed(s_ok):
            child = pat + ((int(ids[i]),),)
            results.append((child, csup))
            stack.append((child, nm, child_s, [j for j in s_items if j > i]))
    return sort_patterns(results)
