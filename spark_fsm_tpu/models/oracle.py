"""CPU oracle miners.

Two independent implementations guard correctness (SURVEY.md sec 4):

- ``brute_force_mine``: direct containment checks over the horizontal DB with
  unpruned candidate extension — slow, only for tiny fixtures, but shares no
  bitmap/join code with anything else.  Ground truth for the oracle itself.
- ``mine_spade``: the real CPU oracle — SPAM-style DFS over the vertical
  bitmap DB (SURVEY.md sec 2.3 steps 2-5) built on ops/bitops_np.py.  This is
  the "CPU SPADE" the north star's byte-identical parity is measured against,
  and its enumeration (shared S/I candidate lists per equivalence class,
  ascending item order) defines the canonical pattern universe the TPU engine
  must reproduce.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from spark_fsm_tpu.data.spmf import Sequence, SequenceDB
from spark_fsm_tpu.data.vertical import VerticalDB, build_vertical
from spark_fsm_tpu.ops import bitops_np as B
from spark_fsm_tpu.utils.canonical import Pattern, PatternResult, sort_patterns


# ---------------------------------------------------------------------------
# Brute force (independent ground truth for tiny DBs)
# ---------------------------------------------------------------------------

def contains(seq: Sequence, pattern: Pattern) -> bool:
    """True iff ``pattern`` occurs in ``seq`` (ordered itemset-subset match).

    Greedy leftmost matching is correct for plain containment: taking the
    earliest itemset that covers the next pattern element never removes later
    options.
    """
    p = 0
    for itemset in seq:
        if p == len(pattern):
            return True
        if set(pattern[p]).issubset(itemset):
            p += 1
    return p == len(pattern)


def brute_force_mine(
    db: SequenceDB,
    minsup_abs: int,
    max_pattern_itemsets: int = 6,
    max_itemset_size: int = 3,
) -> List[PatternResult]:
    """Level-wise mining with direct containment counting.

    Extends every frequent pattern with every frequent item (both s- and
    i-extension), relying only on the apriori property — no equivalence-class
    pruning — so its completeness is independent of the SPAM S/I-list logic.
    """
    items = sorted({i for seq in db for itemset in seq for i in itemset})

    def sup(pat: Pattern) -> int:
        return sum(1 for seq in db if contains(seq, pat))

    freq_items = [i for i in items if sup(((i,),)) >= minsup_abs]
    results: List[PatternResult] = []
    frontier: List[Pattern] = []
    for i in freq_items:
        pat: Pattern = ((i,),)
        results.append((pat, sup(pat)))
        frontier.append(pat)
    while frontier:
        nxt: List[Pattern] = []
        for pat in frontier:
            cands: List[Pattern] = []
            if len(pat) < max_pattern_itemsets:
                cands.extend(pat + ((i,),) for i in freq_items)
            last = pat[-1]
            if len(last) < max_itemset_size:
                cands.extend(
                    pat[:-1] + (tuple(sorted(last + (i,))),)
                    for i in freq_items if i > last[-1]
                )
            for c in cands:
                s = sup(c)
                if s >= minsup_abs:
                    results.append((c, s))
                    nxt.append(c)
        frontier = nxt
    return sort_patterns(results)


# ---------------------------------------------------------------------------
# CPU SPADE oracle (SPAM bitmap DFS)
# ---------------------------------------------------------------------------

def mine_spade_vertical(
    vdb: VerticalDB,
    minsup_abs: int,
    max_pattern_itemsets: Optional[int] = None,
) -> List[PatternResult]:
    """SPAM-style DFS over a prebuilt vertical DB.

    Equivalence-class candidate pruning per Ayres et al. 2002 (SURVEY.md
    sec 2.3 step 3): at each node with candidate lists (S, I), the frequent
    s-extension items S' become every child's S list; an s-child by item i
    gets I = {j in S' : j > i}; an i-child by item i gets I = {j in I' : j >
    i} where I' are the frequent i-extension items.
    """
    bm = vdb.bitmaps  # [n_items, n_seq, n_words]
    n_items = vdb.n_items
    ids = vdb.item_ids
    results: List[PatternResult] = []

    root_items = [i for i in range(n_items) if int(vdb.item_supports[i]) >= minsup_abs]

    # Stack-based DFS; node = (pattern, bitmap, s_list, i_list).
    stack: List[Tuple[Pattern, np.ndarray, List[int], List[int]]] = []
    for i in reversed(root_items):
        pat: Pattern = ((int(ids[i]),),)
        results.append((pat, int(vdb.item_supports[i])))
        stack.append((pat, bm[i], root_items, [j for j in root_items if j > i]))

    while stack:
        pat, bmp, s_list, i_list = stack.pop()
        if max_pattern_itemsets is not None and len(pat) >= max_pattern_itemsets and not i_list:
            continue
        s_ok: List[Tuple[int, np.ndarray, int]] = []
        allow_s = max_pattern_itemsets is None or len(pat) < max_pattern_itemsets
        if allow_s and s_list:
            trans = B.sext_transform(bmp)
            for i in s_list:
                nb = trans & bm[i]
                sup = int(B.support(nb))
                if sup >= minsup_abs:
                    s_ok.append((i, nb, sup))
        s_items = [i for i, _, _ in s_ok]
        i_ok: List[Tuple[int, np.ndarray, int]] = []
        for i in i_list:
            nb = bmp & bm[i]
            sup = int(B.support(nb))
            if sup >= minsup_abs:
                i_ok.append((i, nb, sup))
        i_items = [i for i, _, _ in i_ok]

        # Push in reverse so DFS visits ascending item order, s before i.
        for i, nb, sup in reversed(i_ok):
            child = pat[:-1] + (pat[-1] + (int(ids[i]),),)
            results.append((child, sup))
            stack.append((child, nb, s_items, [j for j in i_items if j > i]))
        for i, nb, sup in reversed(s_ok):
            child = pat + ((int(ids[i]),),)
            results.append((child, sup))
            stack.append((child, nb, s_items, [j for j in s_items if j > i]))
    return sort_patterns(results)


def mine_spade(
    db: SequenceDB,
    minsup_abs: int,
    max_pattern_itemsets: Optional[int] = None,
) -> List[PatternResult]:
    vdb = build_vertical(db, min_item_support=minsup_abs)
    if vdb.n_items == 0:
        return []
    return mine_spade_vertical(vdb, minsup_abs, max_pattern_itemsets)
