"""Shared host-side machinery for the batched-DFS device engines.

Both SPADE engines (bitmap and constrained max-start) drive the same
pattern: a device-resident state pool addressed by slot, a host DFS stack,
recompute-on-miss, and reclaim-from-stack-bottom when the pool runs dry.
"""

from __future__ import annotations

from typing import Callable, List, Optional


def next_pow2(n: int) -> int:
    k = 1
    while k < n:
        k *= 2
    return k


class SlotPool:
    """Free-list allocator over pool slot ids with stack reclaim.

    ``reclaim`` walks nodes bottom-of-stack-first (processed last, cheapest
    to recompute later), dropping their slots until ``need`` are free; the
    caller supplies which nodes are reclaimable (e.g. non-root).
    """

    def __init__(self, slots: range):
        self._free: List[int] = list(reversed(slots))
        self.reclaimed = 0

    def __len__(self) -> int:
        return len(self._free)

    def alloc(self) -> Optional[int]:
        return self._free.pop() if self._free else None

    def free(self, slot: int) -> None:
        self._free.append(slot)

    def reclaim(self, stack, need: int, reclaimable: Callable) -> None:
        for node in stack:
            if len(self._free) >= need:
                return
            if node.slot is not None and reclaimable(node):
                self._free.append(node.slot)
                node.slot = None
                self.reclaimed += 1
