"""Shared host-side machinery for the batched-DFS device engines.

Both SPADE engines (bitmap and constrained max-start) drive the same
pattern: a device-resident state pool addressed by slot, a host DFS stack,
recompute-on-miss, and reclaim-from-stack-bottom when the pool runs dry.
"""

from __future__ import annotations

from typing import Callable, List, Optional


def scatter_build_store(vdb, n_rows: int, n_seq: int, n_words: int,
                        mesh=None, put=None):
    """Scatter-build a ``[n_rows, n_seq, n_words]`` uint32 bitmap store IN
    HBM from the vertical DB's token table (SURVEY.md sec 2.3 step 1 as a
    device kernel) — the dense store never exists on host or crosses the
    link.  Item rows land in slots ``tok_item``; rows past the tokens'
    reach (pattern pool, scratch) start zeroed.

    With ``mesh``, each device scatters only the tokens whose sequence id
    lands in its seq-axis shard (out-of-shard tokens add a 0 mask — a
    no-op); ``n_seq`` must already be padded to a device multiple.
    ``put`` maps host token arrays to device inputs (the multi-host engine
    passes its global-replicate put; default jnp.asarray).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from spark_fsm_tpu.parallel.mesh import SEQ_AXIS

    if mesh is None:
        def init_store(ti, ts, tw, tm):
            z = jnp.zeros((n_rows, n_seq, n_words), jnp.uint32)
            return z.at[ti, ts, tw].add(tm)  # distinct bits: add == OR

        build = jax.jit(init_store)
    else:
        shard = n_seq // mesh.devices.size

        def init_store_shard(ti, ts, tw, tm):
            ls = ts - jax.lax.axis_index(SEQ_AXIS) * shard
            ok = (ls >= 0) & (ls < shard)
            z = jnp.zeros((n_rows, shard, n_words), jnp.uint32)
            return z.at[ti, jnp.clip(ls, 0, shard - 1), tw].add(
                jnp.where(ok, tm, jnp.uint32(0)))

        rep = P()
        build = jax.jit(jax.shard_map(
            init_store_shard, mesh=mesh,
            in_specs=(rep, rep, rep, rep),
            out_specs=P(None, SEQ_AXIS, None)))
    if put is None:
        put = jnp.asarray
    return build(put(vdb.tok_item), put(vdb.tok_seq),
                 put(vdb.tok_word), put(vdb.tok_mask))


def next_pow2(n: int) -> int:
    k = 1
    while k < n:
        k *= 2
    return k


class SlotPool:
    """Free-list allocator over pool slot ids with stack reclaim.

    ``reclaim`` walks nodes bottom-of-stack-first (processed last, cheapest
    to recompute later), dropping their slots until ``need`` are free; the
    caller supplies which nodes are reclaimable (e.g. non-root).
    """

    def __init__(self, slots: range):
        self._free: List[int] = list(reversed(slots))
        self.reclaimed = 0

    def __len__(self) -> int:
        return len(self._free)

    def alloc(self) -> Optional[int]:
        return self._free.pop() if self._free else None

    def free(self, slot: int) -> None:
        self._free.append(slot)

    def reclaim(self, stack, need: int, reclaimable: Callable) -> None:
        for node in stack:
            if len(self._free) >= need:
                return
            if node.slot is not None and reclaimable(node):
                self._free.append(node.slot)
                node.slot = None
                self.reclaimed += 1
