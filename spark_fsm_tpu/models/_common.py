"""Shared host-side machinery for the batched-DFS device engines.

Both SPADE engines (bitmap and constrained max-start) drive the same
pattern: a device-resident state pool addressed by slot, a host DFS stack,
recompute-on-miss, and reclaim-from-stack-bottom when the pool runs dry.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional


import dataclasses
from typing import Tuple


@dataclasses.dataclass
class FrontierNode:
    """DFS frontier node — the ONE shape `encode_frontier` serializes.
    Shared by every SPADE engine (classic, constrained, queue) so their
    snapshots interchange byte-for-byte: ``steps`` is the extension path
    in dense item indices, ``slot`` the device bitmap slot (None =
    rebuild on demand), ``s_list``/``i_list`` the surviving s-/i-
    extension candidate items."""

    steps: Tuple[Tuple[int, bool], ...]
    slot: object
    s_list: list
    i_list: list


def encode_frontier(fingerprint: dict, stack, results,
                    results_from: int = 0) -> dict:
    """JSON-able DFS snapshot shared by both SPADE engines (and persisted
    verbatim by the service's StoreCheckpoint): unexplored nodes by their
    extension paths — device state is rebuilt by each engine's
    recompute-on-miss machinery on resume — plus the results emitted since
    ``results_from`` (results are append-only during a mine, so periodic
    checkpoints serialize only the delta)."""
    return {
        "version": 1,
        "fingerprint": fingerprint,
        "stack": [{"steps": [[int(i), int(s)] for i, s in n.steps],
                   "s": [int(x) for x in n.s_list],
                   "i": [int(x) for x in n.i_list]} for n in stack],
        "results_done": int(results_from),
        "results": [[[list(map(int, s)) for s in pat], int(sup)]
                    for pat, sup in results[results_from:]],
    }


def decode_frontier(resume: dict, fingerprint: dict, node_cls):
    """Inverse of encode_frontier; refuses a snapshot whose fingerprint
    does not match this engine's (node steps hold dense item indices that
    are only meaningful for the exact same projection + parameters)."""
    fp = resume.get("fingerprint")
    if fp != fingerprint:
        raise ValueError(
            "frontier checkpoint does not match this engine's (vdb, "
            f"parameters); checkpointed {fp}, engine {fingerprint}")
    results = [
        (tuple(tuple(int(i) for i in s) for s in pat), int(sup))
        for pat, sup in resume["results"]]
    nodes = [
        node_cls(tuple((int(i), bool(s)) for i, s in n["steps"]),
                 None,  # state rebuilt on demand (recompute-on-miss)
                 [int(x) for x in n["s"]], [int(x) for x in n["i"]])
        for n in resume["stack"]]
    return results, nodes


def load_checkpoint(checkpoint, fingerprint: dict):
    """Wrapper-side plumbing: ``(resume, save_cb, every_s)`` from an
    optional checkpoint object; a stale/mismatched snapshot is ignored
    (the mine restarts fresh) rather than refused."""
    if checkpoint is None:
        return None, None, 30.0
    resume = checkpoint.load()
    if resume is not None and resume.get("fingerprint") != fingerprint:
        resume = None
    return resume, checkpoint.save, getattr(checkpoint, "every_s", 30.0)


@functools.lru_cache(maxsize=128)
def _store_builder(n_rows: int, n_seq: int, n_words: int, mesh,
                   flat: bool = False, remap: bool = False):
    """Cached jitted store-build kernel.  ``jax.jit`` caches traces per
    wrapped-function OBJECT, so handing it a fresh closure per engine
    construction recompiles the scatter build every time — and the service
    builds one engine per /train request.  Keyed on the store geometry and
    mesh, the compiled kernel is shared by every engine with that shape.

    ``flat=True`` emits the store as ``[n_rows, n_seq * n_words]`` (word
    minor).  A persistent ``[rows, S, 1]`` array makes XLA's layout
    assignment copy the ENTIRE store on every jit call that gathers from it
    (measured: a 6.7 GB temp per prep on the headline workload); the flat
    layout crosses jit boundaries copy-free and bodies reshape it back to
    [rows, S, W] internally for the word-wise bit ops.

    ``remap=True`` (streaming's drifting-projection variant) adds a fifth
    input mapping each token's dense item index -> store row; unneeded
    items point out of bounds and drop, so ONE compiled program serves
    every push's projection.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, shard_map

    kw = {"mode": "drop"} if remap else {}

    if mesh is None:
        def init_store(ti, ts, tw, tm, *rm):
            row = rm[0][ti] if remap else ti
            if flat:
                z = jnp.zeros((n_rows, n_seq * n_words), jnp.uint32)
                return z.at[row, ts * n_words + tw].add(tm, **kw)
            z = jnp.zeros((n_rows, n_seq, n_words), jnp.uint32)
            return z.at[row, ts, tw].add(tm, **kw)  # distinct bits: add == OR

        return jax.jit(init_store)

    shard = n_seq // mesh.devices.size

    def init_store_shard(ti, ts, tw, tm, *rm):
        row = rm[0][ti] if remap else ti
        ls = ts - jax.lax.axis_index(SEQ_AXIS) * shard
        ok = (ls >= 0) & (ls < shard)
        lc = jnp.clip(ls, 0, shard - 1)
        tm_ok = jnp.where(ok, tm, jnp.uint32(0))
        if flat:
            z = jnp.zeros((n_rows, shard * n_words), jnp.uint32)
            return z.at[row, lc * n_words + tw].add(tm_ok, **kw)
        z = jnp.zeros((n_rows, shard, n_words), jnp.uint32)
        return z.at[row, lc, tw].add(tm_ok, **kw)

    rep = P()
    out = P(None, SEQ_AXIS) if flat else P(None, SEQ_AXIS, None)
    n_in = 5 if remap else 4
    return jax.jit(shard_map(
        init_store_shard, mesh=mesh,
        in_specs=(rep,) * n_in, out_specs=out))


def scatter_build_store(vdb, n_rows: int, n_seq: int, n_words: int,
                        mesh=None, put=None, bucket_tokens: bool = False,
                        flat: bool = False):
    """Scatter-build a ``[n_rows, n_seq, n_words]`` uint32 bitmap store IN
    HBM from the vertical DB's token table (SURVEY.md sec 2.3 step 1 as a
    device kernel) — the dense store never exists on host or crosses the
    link.  Item rows land in slots ``tok_item``; rows past the tokens'
    reach (pattern pool, scratch) start zeroed.  ``flat=True`` emits
    ``[n_rows, n_seq * n_words]`` (word minor) instead — see
    :func:`_store_builder` for why persistent stores should be flat.

    With ``mesh``, each device scatters only the tokens whose sequence id
    lands in its seq-axis shard (out-of-shard tokens add a 0 mask — a
    no-op); ``n_seq`` must already be padded to a device multiple.
    ``put`` maps host token arrays to device inputs (the multi-host engine
    passes its global-replicate put; default jnp.asarray).

    Token arrays are ALWAYS pow2-padded (mask-0 pads scatter nothing):
    token-array length is a traced shape, so unpadded tokens would
    recompile the scatter for every distinct token count — which made
    the store-build compile unenumerable (a prewarmed deployment would
    still pay it on the first live ``/train``).  ``bucket_tokens`` is
    kept for call-site compatibility; padding no longer depends on it.
    """
    import jax.numpy as jnp
    import numpy as np

    build = _store_builder(n_rows, n_seq, n_words, mesh, flat)
    if put is None:
        put = jnp.asarray
    ti, ts, tw, tm = pad_tokens_pow2(
        vdb.tok_item, vdb.tok_seq, vdb.tok_word, vdb.tok_mask)
    return build(put(ti), put(ts), put(tw), put(tm))


@functools.lru_cache(maxsize=64)
def zeros_fn(shape, dt, mesh=None):
    """Cached jitted pool allocator (same per-object jit-cache reasoning
    as _store_builder; a zeros fill is trivial but a per-instance jit still
    costs a trace + compile per engine construction)."""
    import jax
    import jax.numpy as jnp

    from spark_fsm_tpu.parallel.mesh import store_sharding

    zeros = lambda: jnp.zeros(shape, dt)
    if mesh is None:
        return jax.jit(zeros)
    return jax.jit(zeros, out_shardings=store_sharding(mesh))


def next_pow2(n: int) -> int:
    k = 1
    while k < n:
        k *= 2
    return k


def device_axes(n_sequences: int, n_items: int, n_words: int, *,
                mesh=None, use_pallas: bool = False,
                shape_buckets: bool = False):
    """The seq-axis/item-row sizing shared by the classic, queue, and
    fused geometries: optional pow2 seq bucket, per-shard Pallas seq
    block, padding to a (shards x block) multiple, and the pair
    kernel's I_TILE-rounded item-row count.  ONE definition — these
    numbers feed the shape keys (utils/shapes.py), and a sizing drift
    between per-engine copies is exactly the unenumerable-compile bug
    the registry exists to prevent.  Returns (n_seq, s_block, ni_pad)."""
    from spark_fsm_tpu.ops import pallas_support as PS
    from spark_fsm_tpu.parallel.mesh import pad_to_multiple

    n_seq = int(n_sequences)
    if shape_buckets:
        n_seq = bucket_seq(n_seq)
    n_shards = 1 if mesh is None else mesh.devices.size
    s_block = min(PS.seq_block(n_words),
                  pad_to_multiple(-(-n_seq // n_shards), 128))
    mult = n_shards * s_block if use_pallas else n_shards
    n_seq = pad_to_multiple(n_seq, mult)
    ni_pad = pad_to_multiple(max(n_items, 1), PS.I_TILE)
    return n_seq, s_block, ni_pad


def concat_pow2(outs):
    """Concatenate per-chunk support outputs with the ARITY padded to a
    power of two (all-zero chunks; callers slice to the live candidate
    count anyway).  jnp.concatenate compiles one program per input
    count, and the raw arity ceil(n_cand/chunk) is unbounded — pow2
    bucketing makes the program set log-sized, hence enumerable by the
    prewarm driver (service/prewarm.py warms the ladder).  The padding
    cost is <2x on a ~KB-per-chunk int32 array — noise next to the
    support kernels that produced it."""
    import jax.numpy as jnp

    cap = next_pow2(len(outs))
    if cap != len(outs):
        z = jnp.zeros_like(outs[0])
        outs = list(outs) + [z] * (cap - len(outs))
    return jnp.concatenate(outs)


def bucket_seq(n_seq: int) -> int:
    """The shape_buckets sequence-axis bucket shared by every engine:
    pow2 with a 128-lane floor.  One definition so a retune (floor for a
    new TPU generation, bucket growth factor) cannot drift between the
    engines — streaming windows mix them and must land on consistent
    geometry."""
    return max(128, next_pow2(n_seq))


def pad_tokens_pow2(ti, ts, tw, tm):
    """Pow2-pad the four parallel token arrays (token-array LENGTH is a
    traced shape, so drifting windows would otherwise retrace the scatter
    per token count).  Pad tokens carry mask 0 — scattering them is an
    add of 0 to row 0, a no-op.  Shared by scatter_build_store's
    bucket_tokens path and TsrTPU's per-round prep (same one-definition
    rationale as bucket_seq)."""
    import numpy as np

    cap = next_pow2(max(1, len(ti)))
    pad = cap - len(ti)
    if pad:
        z = ((0, pad),)
        ti, ts, tw, tm = (np.pad(a, z) for a in (ti, ts, tw, tm))
    return ti, ts, tw, tm


def launch_width_cap(pool_bytes: int, slot_bytes: int, floor: int) -> int:
    """Memory-safety ceiling on per-launch candidate widths.

    A join/materialize launch materializes a ``[width, slot]`` tensor, so
    the width caps at the slots-worth that fits ~1/8 of the (per-device)
    pool budget, floored to a power of two; ``floor`` only guards against
    degenerate zero widths.  ``slot_bytes`` must be the PER-DEVICE
    footprint of one store row — under a mesh the launch is shard_map'd
    over the sequence axis, so divide the global row bytes by the device
    count before calling (a full-row figure would over-throttle the mesh
    path by the device count).  A fixed default width that was invisible
    at 77k sequences was a 7.5G temp at 990k (observed full-scale OOM:
    22.7G requested on a 15.75G chip)."""
    return max(int(floor), next_pow2(
        (int(pool_bytes) // 8) // max(int(slot_bytes), 1) + 1) // 2)


def auto_pool_bytes(mesh) -> int:
    """Default engine pool budget: 35% of the device's HBM.  Two engine
    working sets must be able to coexist (back-to-back mines overlap while
    the old engine is still referenced; the service can run multi-worker
    miners), plus kernel temps take their share."""
    import jax

    dev = mesh.devices.flat[0] if mesh is not None else jax.devices()[0]
    return int(device_hbm_budget(dev) * 0.35)


def device_hbm_budget(dev) -> int:
    """Usable per-device memory for engine working sets: 95% of the
    backend-reported limit, or a conservative per-generation table when the
    backend reports none (the tunneled-PJRT case), or 4 GiB on unknown
    hardware/CPU."""
    stats = None
    try:
        stats = dev.memory_stats()
    except Exception:
        pass
    limit = (stats or {}).get("bytes_limit")
    if limit:
        return int(limit * 0.95)
    kind = getattr(dev, "device_kind", "").lower()
    for key, gib in (("v5 lite", 15), ("v5e", 15), ("v5p", 90),
                     ("v6", 30), ("v4", 30), ("v3", 15), ("v2", 7)):
        if key in kind:
            return gib << 30
    return 4 << 30


class SlotPool:
    """Free-list allocator over pool slot ids with stack reclaim.

    ``reclaim`` walks nodes bottom-of-stack-first (processed last, cheapest
    to recompute later), dropping their slots until ``need`` are free; the
    caller supplies which nodes are reclaimable (e.g. non-root).
    """

    def __init__(self, slots: range):
        self._free: List[int] = list(reversed(slots))
        self.reclaimed = 0

    def __len__(self) -> int:
        return len(self._free)

    def alloc(self) -> Optional[int]:
        return self._free.pop() if self._free else None

    def free(self, slot: int) -> None:
        self._free.append(slot)

    def reclaim(self, stack, need: int, reclaimable: Callable) -> None:
        for node in stack:
            if len(self._free) >= need:
                return
            if node.slot is not None and reclaimable(node):
                self._free.append(node.slot)
                node.slot = None
                self.reclaimed += 1
