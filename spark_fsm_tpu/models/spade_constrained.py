"""Constrained SPADE (maxgap / maxwindow) on TPU — max-start state engine.

Same batched-DFS architecture as models/spade_tpu.py (slot pool in HBM,
chunked fused kernels, recompute-on-miss, sequence-axis shard_map + psum),
but the per-pattern device state is the max-start array of
ops/maxstart_jax.py instead of an end-position bitmap, because gap/window
checks need occurrence-start information (SURVEY.md sec 2.3 step 6).

Enumeration differences vs the unconstrained engine (see models/oracle.py
mine_cspade, the parity oracle):
- under maxgap, s-extension candidates are ALL frequent root items —
  sibling S-list pruning is unsound there (a valid occurrence of P.y.z
  does not contain a gap-valid occurrence of P.z), the cSPADE F2-join
  observation; with no gap bound the usual sibling prune applies;
- i-extension sibling pruning stays valid (same positions);
- pruning on the windowed support is exact: it is anti-monotone under
  prefix growth (a valid child occurrence contains a valid same-start
  prefix occurrence).

State dtype is int8 when positions fit (<=127), else int16 — constrained
state is positions-wide (not bit-packed), so this halves HBM traffic on
typical clickstream data.
"""

from __future__ import annotations

import functools
import time
from collections import deque
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import VerticalDB, build_vertical
from spark_fsm_tpu.models._common import (
    FrontierNode, SlotPool, auto_pool_bytes, bucket_seq, concat_pow2,
    decode_frontier, encode_frontier, launch_width_cap, load_checkpoint,
    next_pow2, scatter_build_store, zeros_fn)
from spark_fsm_tpu.ops import maxstart_jax as MS
from spark_fsm_tpu.parallel import multihost as MH
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, pad_to_multiple, shard_map
from spark_fsm_tpu.utils import shapes
from spark_fsm_tpu.utils.canonical import Pattern, PatternResult, sort_patterns

Step = Tuple[int, bool]


# the ONE frontier-node shape every engine snapshots (see _common);
# here s_list holds siblings when maxgap is None, else all roots
_Node = FrontierNode


def cspade_geometry(n_sequences: int, n_items: int, n_words: int, *,
                    maxgap: Optional[int] = None,
                    maxwindow: Optional[int] = None,
                    mesh: Optional[Mesh] = None, chunk: int = 256,
                    node_batch: int = 32, pipeline_depth: int = 4,
                    recompute_chunk: int = 32,
                    pool_bytes: Optional[int] = None,
                    shape_buckets: bool = False) -> dict:
    """Derived device geometry of a :class:`ConstrainedSpadeTPU` —
    shared by the constructor and the shape-key enumerator
    (utils/shapes.py).  maxgap/maxwindow ride in the shape key because
    ``_cspade_fns`` compiles a DIFFERENT kernel set per constraint pair
    (and per state dtype), even at identical array shapes."""
    n_seq = int(n_sequences)
    item_rows = n_items
    if shape_buckets:
        n_seq = bucket_seq(n_seq)
        item_rows = max(16, next_pow2(n_items))
    if mesh is not None:
        n_seq = pad_to_multiple(n_seq, mesh.devices.size)
    n_pos = n_words * 32
    state_bits = 8 if n_pos <= 127 else 16
    dtype = jnp.int8 if state_bits == 8 else jnp.int16

    # Same budget/invariant accounting as the unconstrained engine: the
    # pool shares HBM with pipeline_depth in-flight (m, pm) preps (2
    # slot-equivalents per node each), and node_batch is bounded so
    # in-flight batches can never starve a recompute.
    if pool_bytes is None:
        pool_bytes = auto_pool_bytes(mesh)
    slot_bytes = n_seq * n_pos * np.dtype(dtype.dtype).itemsize
    # memory-safety ceiling on per-launch candidate tensors (see
    # _common.launch_width_cap: [chunk, S, n_pos] temps scale with
    # the sequence axis, and a fixed width OOMs at ~1M sequences)
    n_shards = 1 if mesh is None else mesh.devices.size
    max_chunk = launch_width_cap(
        pool_bytes, -(-slot_bytes // n_shards), 4)
    chunk = min(int(chunk), max_chunk)
    recompute_chunk = min(int(recompute_chunk), max(2, max_chunk // 2))
    budget_slots = max(32, min(int(pool_bytes) // max(slot_bytes, 1), 8192))
    pipeline_depth = min(max(1, int(pipeline_depth)),
                         max(1, budget_slots // 8))
    d = pipeline_depth
    nb = max(1, min(int(node_batch), budget_slots // (3 * (d + 2))))
    pool_slots = max(8, budget_slots - 2 * d * nb)
    return {
        "n_seq": n_seq, "item_rows": item_rows, "n_pos": n_pos,
        "dtype": dtype, "state_bits": state_bits, "chunk": chunk,
        "recompute_chunk": recompute_chunk,
        "pipeline_depth": pipeline_depth, "node_batch": nb,
        "pool_slots": pool_slots,
        "shape_key": shapes.key_cspade(n_seq, n_words, item_rows,
                                       pool_slots, nb, chunk, maxgap,
                                       maxwindow, state_bits),
    }


@functools.lru_cache(maxsize=64)
def _cspade_fns(mesh: Optional[Mesh], maxgap: Optional[int],
                maxwindow: Optional[int], dt):
    """Jitted kernel set shared by every ConstrainedSpadeTPU with the same
    (mesh, constraints, state dtype) — jax.jit caches per wrapped-function
    object, so per-instance closures would recompile every kernel for each
    engine construction (see models/spade_tpu._spade_fns)."""
    NONE = jnp.asarray(-1, dt)

    def root_states(items, item_idx):
        occ = MS.expand_bits(items[item_idx])
        pos = jnp.arange(occ.shape[-1], dtype=dt)
        return jnp.where(occ, pos, NONE)

    def prep_body(pool, items, node_slot, node_root, is_root):
        # root nodes read their state straight from the item bitmaps
        m = jnp.where(is_root[:, None, None],
                      root_states(items, node_root),
                      pool[node_slot].astype(dt))
        return m, MS.prev_max(m, maxgap)

    def _child(m, pm, items, ref, item_idx, iss):
        occ = MS.expand_bits(items[item_idx])
        base = jnp.where(iss[:, None, None], pm[ref], m[ref])
        return jnp.where(occ & (base >= 0), base, NONE)

    def supports_body(m, pm, items, ref, item_idx, iss):
        part = MS.support(_child(m, pm, items, ref, item_idx, iss), maxwindow)
        if mesh is not None:
            part = jax.lax.psum(part, SEQ_AXIS)
        return part

    def materialize_body(m, pm, items, pool, ref, item_idx, iss, out_slot):
        c = _child(m, pm, items, ref, item_idx, iss)
        return pool.at[out_slot].set(c)

    def recompute_body(pool, items, step_items, step_iss, step_valid, out_slot):
        m = root_states(items, step_items[0])
        def body(state, xs):
            it, iss, valid = xs
            pm = MS.prev_max(state, maxgap)
            occ = MS.expand_bits(items[it])
            base = jnp.where(iss[:, None, None], pm, state)
            nm = jnp.where(occ & (base >= 0), base, NONE)
            return jnp.where(valid[:, None, None], nm, state), None
        m, _ = jax.lax.scan(body, m, (step_items[1:], step_iss[1:], step_valid[1:]))
        return pool.at[out_slot].set(m)

    if mesh is None:
        return {
            "prep": jax.jit(prep_body),
            "supports": jax.jit(supports_body),
            "materialize": jax.jit(materialize_body, donate_argnums=3),
            "recompute": jax.jit(recompute_body, donate_argnums=0),
        }
    st = P(None, SEQ_AXIS, None)
    rep = P()
    return {
        "prep": jax.jit(shard_map(
            prep_body, mesh=mesh, in_specs=(st, st, rep, rep, rep),
            out_specs=(st, st))),
        "supports": jax.jit(shard_map(
            supports_body, mesh=mesh,
            in_specs=(st, st, st, rep, rep, rep), out_specs=rep)),
        "materialize": jax.jit(shard_map(
            materialize_body, mesh=mesh,
            in_specs=(st, st, st, st, rep, rep, rep, rep), out_specs=st),
            donate_argnums=3),
        "recompute": jax.jit(shard_map(
            recompute_body, mesh=mesh,
            in_specs=(st, st, rep, rep, rep, rep), out_specs=st),
            donate_argnums=0),
    }


class ConstrainedSpadeTPU:
    def __init__(
        self,
        vdb: VerticalDB,
        minsup_abs: int,
        *,
        maxgap: Optional[int] = None,
        maxwindow: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        chunk: int = 256,
        node_batch: int = 32,
        pipeline_depth: int = 4,
        recompute_chunk: int = 32,
        pool_bytes: Optional[int] = None,
        max_pattern_itemsets: Optional[int] = None,
        shape_buckets: bool = False,
        partition=None,
    ):
        self.vdb = vdb
        self.minsup = int(minsup_abs)
        # equivalence-class partition slice (parallel/partition.py):
        # seed only the owned classes' roots; candidate lists stay
        # full-width (under maxgap the s-side is ALL frequent roots,
        # which must not shrink with the slice)
        self._partition = partition
        self.maxgap = maxgap
        self.maxwindow = maxwindow
        self.mesh = mesh
        # Multi-host mesh: host-side inputs must become global replicated
        # arrays (see parallel/multihost.py)
        self._put = functools.partial(MH.host_to_device, mesh)
        self.max_pattern_itemsets = max_pattern_itemsets

        n_items, n_seq, n_words = vdb.n_items, vdb.n_sequences, vdb.n_words
        # shape_buckets: pow2-bucket the sequence axis AND the item-row
        # count so streaming windows with drifting geometry (size + the
        # frequent-item projection) land on a handful of compiled shapes —
        # same trade as the unconstrained engine (spade_tpu.py).  Extra
        # item rows hold all-zero bitmaps; candidate indices stay < n_items.
        self._shape_buckets = bool(shape_buckets)
        # Derived sizing lives in cspade_geometry — shared with the
        # shape-key enumerator (utils/shapes.py).
        g = cspade_geometry(
            n_seq, n_items, n_words, maxgap=maxgap, maxwindow=maxwindow,
            mesh=mesh, chunk=chunk, node_batch=node_batch,
            pipeline_depth=pipeline_depth, recompute_chunk=recompute_chunk,
            pool_bytes=pool_bytes, shape_buckets=self._shape_buckets)
        n_seq = g["n_seq"]
        item_rows = g["item_rows"]
        self.n_items, self.n_seq, self.n_words = n_items, n_seq, n_words
        self.n_pos = g["n_pos"]
        self.dtype = g["dtype"]
        self.chunk = g["chunk"]
        self.recompute_chunk = g["recompute_chunk"]
        self.pipeline_depth = g["pipeline_depth"]
        pool_slots = g["pool_slots"]
        self.pool_slots = pool_slots
        self.item_rows = item_rows
        self.node_batch = g["node_batch"]
        self.scratch = pool_slots
        # Scatter-build the item bitmaps IN HBM from the token table and
        # allocate the state pool on device — neither the dense bitmaps nor
        # the (large, all-zero) pool ever exists in host memory or crosses
        # the link (same plan as the unconstrained engine's store build).
        self.items = scatter_build_store(vdb, item_rows, n_seq, n_words,
                                         mesh=mesh, put=self._put,
                                         bucket_tokens=self._shape_buckets)
        pool_shape = (pool_slots + 1, n_seq, self.n_pos)
        self.pool = zeros_fn(pool_shape, self.dtype, mesh)()
        self._pool_alloc = SlotPool(range(pool_slots))
        self._build_fns()
        # s_candidates vs i_candidates: under maxgap the s-side is ALL root
        # items per node (the unsound-sibling-prune rule), so its share of
        # the candidate volume is the cost of that constraint — measured
        # here, surfaced through job stats.  shape_key: compiled-geometry
        # identity (same contract as SpadeTPU.stats), registry-recorded.
        self.stats = {"candidates": 0, "s_candidates": 0, "i_candidates": 0,
                      "kernel_launches": 0, "recomputed_nodes": 0,
                      "reclaimed_slots": 0, "patterns": 0,
                      "shape_key": g["shape_key"]}
        shapes.record(g["shape_key"])

    def nbytes(self) -> int:
        """Device working set held BETWEEN mines (items store + state
        pool) — what a devcache entry pins in HBM."""
        item_bytes = self.item_rows * self.n_seq * self.n_words * 4
        pool_bytes = ((self.pool_slots + 1) * self.n_seq * self.n_pos
                      * np.dtype(self.dtype.dtype).itemsize)
        return item_bytes + pool_bytes

    # ------------------------------------------------------------------ fns

    def _build_fns(self) -> None:
        # Jitted callables are shared across engine instances (one engine
        # per /train request): see _cspade_fns.
        fns = _cspade_fns(self.mesh, self.maxgap, self.maxwindow, self.dtype)
        self._prep_fn = fns["prep"]
        self._supports_fn = fns["supports"]
        self._materialize_fn = fns["materialize"]
        self._recompute_fn = fns["recompute"]

    # ------------------------------------------------------------ slot mgmt

    def _alloc(self) -> Optional[int]:
        return self._pool_alloc.alloc()

    def _free_slot(self, slot: Optional[int]) -> None:
        if slot is not None:
            self._pool_alloc.free(slot)

    def _ensure_slots(self, batch: List[_Node], stack: List[_Node]) -> None:
        missing = [n for n in batch if n.slot is None and len(n.steps) > 1]
        if not missing:
            return
        self.stats["recomputed_nodes"] += len(missing)
        if len(self._pool_alloc) < len(missing):
            self._pool_alloc.reclaim(stack, len(missing),
                                     lambda n: len(n.steps) > 1)
            self.stats["reclaimed_slots"] = self._pool_alloc.reclaimed
        for lo in range(0, len(missing), self.recompute_chunk):
            group = missing[lo: lo + self.recompute_chunk]
            mcap = self.recompute_chunk
            k = next_pow2(max(len(n.steps) for n in group))
            items = np.zeros((k, mcap), np.int32)
            iss = np.zeros((k, mcap), bool)
            valid = np.zeros((k, mcap), bool)
            slots = np.full(mcap, self.scratch, np.int32)
            for col, node in enumerate(group):
                slot = self._alloc()
                assert slot is not None, "constrained pool exhausted beyond reclaim"
                node.slot = slot
                slots[col] = slot
                for row, (it, s) in enumerate(node.steps):
                    items[row, col], iss[row, col], valid[row, col] = it, s, True
            self.pool = self._recompute_fn(
                self.pool, self.items, self._put(items), self._put(iss),
                self._put(valid), self._put(slots))
            self.stats["kernel_launches"] += 1

    # ------------------------------------------------------------- kernels

    def _prep(self, batch: List[_Node]):
        slots = np.zeros(self.node_batch, np.int32)
        roots = np.zeros(self.node_batch, np.int32)
        is_root = np.zeros(self.node_batch, bool)
        for i, n in enumerate(batch):
            if len(n.steps) == 1:
                is_root[i] = True
                roots[i] = n.steps[0][0]
            else:
                slots[i] = n.slot
        m, pm = self._prep_fn(self.pool, self.items, self._put(slots),
                              self._put(roots), self._put(is_root))
        self.stats["kernel_launches"] += 1
        return m, pm

    def _run_chunks(self, fn_extra, ref, item, iss, out_slot=None):
        """Chunk-dispatch kernels.  Support mode (out_slot None) returns ONE
        device array for the whole list with its host copy in flight."""
        n = len(ref)
        c = self.chunk
        outs = [] if out_slot is None else None
        for lo in range(0, n, c):
            hi = min(lo + c, n)
            pad = c - (hi - lo)
            r = self._put(np.pad(ref[lo:hi], (0, pad)).astype(np.int32))
            it = self._put(np.pad(item[lo:hi], (0, pad)).astype(np.int32))
            ss = self._put(np.pad(iss[lo:hi], (0, pad)).astype(bool))
            if out_slot is None:
                outs.append(fn_extra(r, it, ss))
            else:
                os = self._put(np.pad(out_slot[lo:hi], (0, pad),
                                      constant_values=self.scratch).astype(np.int32))
                fn_extra(r, it, ss, os)
            self.stats["kernel_launches"] += 1
        if out_slot is not None:
            return None
        sup = outs[0] if len(outs) == 1 else concat_pow2(outs)
        try:
            sup.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass  # method unavailable on this backend
        return sup

    # ---------------------------------------------------------------- mine

    def _pattern_of(self, steps) -> Pattern:
        ids = self.vdb.item_ids
        pat: List[List[int]] = []
        for it, is_s in steps:
            if is_s:
                pat.append([int(ids[it])])
            else:
                pat[-1].append(int(ids[it]))
        return tuple(tuple(s) for s in pat)

    def frontier_fingerprint(self) -> dict:
        """Identity a frontier checkpoint binds to — (vdb, minsup) plus the
        constraint set, since maxgap/maxwindow/length change enumeration."""
        ids = self.vdb.item_ids
        return {
            "minsup": self.minsup,
            "maxgap": self.maxgap,
            "maxwindow": self.maxwindow,
            "n_items": self.n_items,
            "n_sequences": self.vdb.n_sequences,
            "max_itemsets": self.max_pattern_itemsets,
            "item_ids_head": [int(i) for i in ids[:8]],
            "item_ids_sum": int(ids.astype(np.int64).sum()),
        }

    def frontier_state(self, stack: List[_Node],
                       results: List[PatternResult],
                       results_from: int = 0) -> dict:
        """Same snapshot contract as SpadeTPU (see _common.encode_frontier)."""
        return encode_frontier(self.frontier_fingerprint(), stack, results,
                               results_from)

    def mine(self, *, resume: Optional[dict] = None,
             checkpoint_cb=None,
             checkpoint_every_s: float = 30.0) -> List[PatternResult]:
        minsup = self.minsup
        results: List[PatternResult] = []
        root_items = [i for i in range(self.n_items)
                      if int(self.vdb.item_supports[i]) >= minsup]
        stack: List[_Node] = []
        if resume is not None:
            results, stack = decode_frontier(
                resume, self.frontier_fingerprint(), _Node)
            self.stats["resumed_nodes"] = len(stack)
        else:
            seed = set(root_items)
            if self._partition is not None:
                plan, pidx = self._partition
                seed = set(plan.owned_slice(root_items,
                                            self.vdb.item_ids, pidx))
            for i in reversed(root_items):
                if i not in seed:
                    continue  # another partition's class slice
                results.append((self._pattern_of(((i, True),)),
                                int(self.vdb.item_supports[i])))
                stack.append(_Node(((i, True),), None, root_items,
                                   [j for j in root_items if j > i]))

        # Same software-pipelined dispatch/resolve loop as the unconstrained
        # engine (see models/spade_tpu.py): one async support readback per
        # node batch, pipeline_depth batches in flight.
        inflight: deque = deque()

        def dispatch():
            batch = [stack.pop() for _ in range(min(self.node_batch, len(stack)))]
            self._ensure_slots(batch, stack)
            m, pm = self._prep(batch)

            cand_ref: List[int] = []
            cand_item: List[int] = []
            cand_iss: List[bool] = []
            spans: List[Tuple[int, int, int]] = []
            for b_idx, node in enumerate(batch):
                n_itemsets = sum(1 for _, s in node.steps if s)
                allow_s = (self.max_pattern_itemsets is None
                           or n_itemsets < self.max_pattern_itemsets)
                s_lo = len(cand_ref)
                if allow_s:
                    # sibling s-prune is unsound under maxgap, so s_list is
                    # root_items then; with no gap bound it is the (valid)
                    # frequent-sibling list as in the unconstrained engine
                    for i in node.s_list:
                        cand_ref.append(b_idx); cand_item.append(i); cand_iss.append(True)
                s_hi = len(cand_ref)
                for i in node.i_list:
                    cand_ref.append(b_idx); cand_item.append(i); cand_iss.append(False)
                spans.append((s_lo, s_hi, len(cand_ref)))

            self.stats["candidates"] += len(cand_ref)
            n_s = sum(1 for x in cand_iss if x)
            self.stats["s_candidates"] += n_s
            self.stats["i_candidates"] += len(cand_iss) - n_s
            sup_dev = (self._run_chunks(
                           lambda r, it, ss: self._supports_fn(m, pm, self.items, r, it, ss),
                           np.array(cand_ref, np.int32), np.array(cand_item, np.int32),
                           np.array(cand_iss, bool))
                       if cand_ref else None)
            return batch, (m, pm), cand_item, cand_iss, spans, sup_dev

        def resolve(entry):
            batch, (m, pm), cand_item, cand_iss, spans, sup_dev = entry
            n_cand = spans[-1][2] if spans else 0
            sups = (np.asarray(sup_dev)[:n_cand] if sup_dev is not None
                    else np.empty(0, np.int32))

            children: List[_Node] = []
            mat_ref: List[int] = []; mat_item: List[int] = []
            mat_iss: List[bool] = []; mat_child: List[int] = []
            for b_idx, (node, (s_lo, s_hi, i_hi)) in enumerate(zip(batch, spans)):
                n_itemsets = sum(1 for _, s in node.steps if s)
                s_items = [cand_item[k] for k in range(s_lo, s_hi) if sups[k] >= minsup]
                i_items = [cand_item[k] for k in range(s_hi, i_hi) if sups[k] >= minsup]
                for k in range(s_lo, i_hi):
                    if sups[k] < minsup:
                        continue
                    it, is_s = cand_item[k], cand_iss[k]
                    steps = node.steps + ((it, is_s),)
                    results.append((self._pattern_of(steps), int(sups[k])))
                    src = s_items if is_s else i_items
                    child_i = [j for j in src if j > it]
                    child_s = s_items if self.maxgap is None else root_items
                    child_itemsets = n_itemsets + (1 if is_s else 0)
                    child_allow_s = (self.max_pattern_itemsets is None
                                     or child_itemsets < self.max_pattern_itemsets)
                    if not ((child_s and child_allow_s) or child_i):
                        continue
                    child = _Node(steps, None, child_s, child_i)
                    slot = self._alloc()
                    if slot is not None:
                        child.slot = slot
                        mat_ref.append(b_idx); mat_item.append(it)
                        mat_iss.append(is_s); mat_child.append(slot)
                    children.append(child)
            if mat_child:
                def mat(r, it, ss, os):
                    self.pool = self._materialize_fn(m, pm, self.items, self.pool,
                                                     r, it, ss, os)
                self._run_chunks(mat, np.array(mat_ref, np.int32),
                                 np.array(mat_item, np.int32),
                                 np.array(mat_iss, bool),
                                 np.array(mat_child, np.int32))
            stack.extend(reversed(children))
            for node in batch:
                if len(node.steps) > 1:
                    self._free_slot(node.slot)

        ckpt_done = len(results) if resume is not None else 0
        last_ckpt = time.monotonic()
        while stack or inflight:
            while stack and len(inflight) < self.pipeline_depth:
                inflight.append(dispatch())
            resolve(inflight.popleft())
            if (checkpoint_cb is not None
                    and time.monotonic() - last_ckpt >= checkpoint_every_s):
                while inflight:  # drain for a consistent frontier
                    resolve(inflight.popleft())
                checkpoint_cb(self.frontier_state(stack, results,
                                                  results_from=ckpt_done))
                ckpt_done = len(results)
                self.stats["checkpoints"] = self.stats.get("checkpoints", 0) + 1
                last_ckpt = time.monotonic()

        self.stats["patterns"] = len(results)
        return sort_patterns(results)


def mine_cspade_tpu(
    db: SequenceDB,
    minsup_abs: int,
    *,
    maxgap: Optional[int] = None,
    maxwindow: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    max_pattern_itemsets: Optional[int] = None,
    stats_out: Optional[dict] = None,
    checkpoint=None,
    partition_parts: int = 0,
    partition_classes: int = 64,
    **kwargs,
) -> List[PatternResult]:
    """DB -> vertical build -> constrained mine; ``checkpoint`` follows the
    same load/save/every_s contract as mine_spade_tpu (stale snapshots are
    ignored, the mine restarts fresh).  ``partition_parts >= 2`` routes
    through the equivalence-class partitioned slices
    (parallel/partition.py), byte-identical union."""
    vdb = build_vertical(db, min_item_support=minsup_abs)
    if vdb.n_items == 0:
        return []
    if partition_parts and int(partition_parts) > 1:
        return _mine_cspade_partitioned(
            vdb, minsup_abs, maxgap=maxgap, maxwindow=maxwindow,
            mesh=mesh, parts=int(partition_parts),
            classes=int(partition_classes),
            max_pattern_itemsets=max_pattern_itemsets,
            stats_out=stats_out, checkpoint=checkpoint, **kwargs)
    eng = ConstrainedSpadeTPU(vdb, minsup_abs, maxgap=maxgap, maxwindow=maxwindow,
                              mesh=mesh, max_pattern_itemsets=max_pattern_itemsets,
                              **kwargs)
    resume, save_cb, every_s = load_checkpoint(
        checkpoint, eng.frontier_fingerprint())
    results = eng.mine(resume=resume, checkpoint_cb=save_cb,
                       checkpoint_every_s=every_s)
    if stats_out is not None:
        stats_out.update(eng.stats)
    return results


def _mine_cspade_partitioned(
    vdb: VerticalDB,
    minsup_abs: int,
    *,
    maxgap: Optional[int],
    maxwindow: Optional[int],
    mesh: Optional[Mesh],
    parts: int,
    classes: int,
    max_pattern_itemsets: Optional[int],
    stats_out: Optional[dict],
    checkpoint,
    **kwargs,
) -> List[PatternResult]:
    """Equivalence-class partitioned cSPADE: same independent-slice
    regime as plain SPADE (fixed minsup; a pattern's class is its first
    item, so slices are disjoint and union exactly) — the gap/window
    constraints change support counting, not the class structure."""
    from spark_fsm_tpu.parallel import partition as PN

    plan = PN.plan_partitions(vdb.item_ids, vdb.item_supports, parts,
                              classes)
    meshes = PN.submeshes(mesh, parts)
    ids = vdb.item_ids
    # fingerprint built WITHOUT a probe engine: the constrained
    # constructor eagerly builds its device stores, and in a
    # multi-controller run meshes[0] is another process's row — same
    # dict ConstrainedSpadeTPU.frontier_fingerprint returns
    fingerprint = {
        "minsup": int(minsup_abs),
        "maxgap": maxgap,
        "maxwindow": maxwindow,
        "n_items": int(vdb.n_items),
        "n_sequences": int(vdb.n_sequences),
        "max_itemsets": max_pattern_itemsets,
        "item_ids_head": [int(i) for i in ids[:8]],
        "item_ids_sum": int(ids.astype(np.int64).sum()),
        "partition": plan.fingerprint(),
    }
    resume, save_cb, every_s = load_checkpoint(checkpoint, fingerprint)
    stats: dict = {
        "partition_parts": int(parts),
        "partition_classes": int(classes),
        "partition_imbalance": round(plan.imbalance_ratio, 4),
    }
    PN.count_mine("cspade")

    def mine_part(p, inner_mesh, resume_state, part_cb):
        eng = ConstrainedSpadeTPU(
            vdb, minsup_abs, maxgap=maxgap, maxwindow=maxwindow,
            mesh=inner_mesh,
            max_pattern_itemsets=max_pattern_itemsets,
            partition=(plan, p), **kwargs)
        res = eng.mine(resume=resume_state, checkpoint_cb=part_cb,
                       checkpoint_every_s=every_s)
        PN.fold_numeric_stats(stats, eng.stats)
        return PN.encode_patterns(res)

    rows = PN.mine_partitioned_slices(
        plan=plan, meshes=meshes, fingerprint=fingerprint,
        mine_part=mine_part, resume=resume, checkpoint_cb=save_cb,
        stats=stats)
    results = sort_patterns(PN.decode_patterns(rows))
    stats["patterns"] = len(results)
    if stats_out is not None:
        stats_out.update(stats)
    return results
