"""TSR — top-k sequential rules (TopSeqRules), CPU oracle + TPU engine.

Semantics (SURVEY.md sec 2.4): a rule X ==> Y (X, Y disjoint unordered
itemsets) occurs in a sequence iff every item of X occurs strictly before
every item of Y, i.e. max_x first(x) < min_y last(y).  sup(X=>Y) counts such
sequences; conf = sup(X=>Y) / sup(X).  The miner returns the top-k rules by
support among those with conf >= minconf — tie-inclusive (see
utils/canonical.py), with a dynamically rising internal minsup.

Bitmap formulation (the north star's "TSR reuses the same join/support
kernels"): with A = AND over x in X of prefix_or_incl(id-list(x)) ("all of X
occurred by p") and C = AND over y in Y of suffix_or_incl(id-list(y)) ("all
of Y occur at >= p"), the rule holds in a sequence iff
(shift_up_one(A) & C) != 0, and sup(X) = #sequences with A != 0.  Both
reduce to the engine's AND + per-sequence-any + popcount primitives, so the
TPU path is the same fused VPU chain as SPADE's temporal join, batched over
candidate rules and psum-reduced over the sharded sequence axis.

Search: best-first branch-and-bound over expansions (left = grow X, right =
grow Y, both adding item ids greater than the side's max, right-expanded
rules may still left-expand but not vice versa — the standard duplicate-free
expansion scheme), batch-evaluating candidates on device.  Large alphabets
are handled by iterative deepening over the top-M items by support: a run
restricted to M items is provably complete once sup(item_{M+1}) < s_k.

Two traffic levers on top of the search (this file + ops/ragged_batch.py):
DYNAMIC-THRESHOLD PRUNING — right-expansion candidates carry their exact
antecedent support (X is fixed along a right chain), so a support bound
below the confidence floor proves the rule can never enter the top-k;
when the antecedent can also never grow again, the whole right-growing
subtree is provably dead and is never materialized on device (sibling
chains end wholesale) — and RAGGED SUPER-BATCHING — per-km launch pools
split into full pow2 launches at their own km, with the per-km tails
merged into shared mixed-km launches, collapsing the one-launch-per-
bucket dispatch pattern of unlimited-side mines (BENCH_SCALE 3 vs 3d).
"""

from __future__ import annotations

import bisect
import functools
import heapq
import itertools
import time
from fractions import Fraction
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import VerticalDB, build_vertical
from spark_fsm_tpu.models._common import (
    bucket_seq, device_hbm_budget, load_checkpoint, next_pow2,
    pad_tokens_pow2)
from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.ops import bitops_np as Bnp
from spark_fsm_tpu.ops import pallas_tsr as PT
from spark_fsm_tpu.ops import ragged_batch as RB
from spark_fsm_tpu.ops import resident_frontier as RF
from spark_fsm_tpu.parallel import multihost as MH
from spark_fsm_tpu.parallel import partition as PN
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, pad_to_multiple, shard_map, store_sharding
from spark_fsm_tpu.service import fusion as FZ
from spark_fsm_tpu.service import meshguard as MGD
from spark_fsm_tpu.service import usage
from spark_fsm_tpu.utils import faults, jobctl, obs, shapes, watchdog
from spark_fsm_tpu.utils.canonical import RuleResult, sort_rules

# OOM degradation ladder floor (lanes): a failed launch re-plans at half
# width recursively down to here (the Pallas out-tile C_LANES, which is
# also the narrowest compiled geometry prewarm enumerates) before
# falling back to the jnp path.
_OOM_FLOOR_LANES = 128


def _is_oom(exc: BaseException) -> bool:
    """Device allocation failure — XLA spells it RESOURCE_EXHAUSTED
    across backends (and faults.InjectedOom matches on purpose)."""
    s = repr(exc)
    return "RESOURCE_EXHAUSTED" in s or "Resource exhausted" in s


# initial top-m item restriction for the iterative-deepening outer loop
# (the TsrTPU constructor default; the shape-key enumerator's fused-
# ladder m buckets derive from it, so one spelling for both)
ITEM_CAP_DEFAULT = 256

# transfer-pricing floor (bytes/s) for the resident final-records
# readback watchdog deadline: tunneled PJRT transports measure
# ~10-16 MB/s, so 8 MB/s is the conservative healthy-link floor
_RESIDENT_READBACK_FLOOR_BPS = 8e6

# the resident-frontier counters the bench harnesses export — ONE
# spelling (bench_scale.py and scripts/bench_smoke.py both serialize
# through resident_counters, so their row shapes can't drift apart)
RESIDENT_EXPORT_KEYS = (
    "resident_rounds", "resident_segments", "resident_waves",
    "resident_deferred", "resident_spills", "resident_handoffs",
    "resident_fallbacks", "resident_readback_bytes")


def resident_counters(stats: dict) -> dict:
    """Bench/smoke export of the resident-frontier counters: empty
    unless the planner routed (part of) the mine on-device, zero-filled
    otherwise so the same mine serializes the same row shape from every
    harness."""
    if not stats.get("resident"):
        return {}
    return {k: stats.get(k, 0) for k in RESIDENT_EXPORT_KEYS}


def tsr_geometry(n_sequences: int, n_words: int, *,
                 mesh: Optional[Mesh] = None, use_pallas: bool = False,
                 shape_buckets: bool = False) -> dict:
    """Static device geometry of a :class:`TsrTPU` (the per-round top-m
    and km-bucket shapes vary by design) — shared by the constructor and
    the shape-key enumerator (utils/shapes.py)."""
    n_seq = int(n_sequences)
    if shape_buckets:
        n_seq = bucket_seq(n_seq)
    n_shards = 1 if mesh is None else mesh.devices.size
    if mesh is not None:
        n_seq = pad_to_multiple(n_seq, n_shards)
    sb = None
    if use_pallas:
        # per-shard seq axis must tile the kernel's seq block, which
        # itself must tile the folded (8, 128) layout
        sb = PT.seq_block(n_words, -(-n_seq // n_shards))
        n_seq = pad_to_multiple(n_seq, n_shards * sb)
    return {"n_seq": n_seq, "sb": sb,
            "shape_key": shapes.key_tsr(n_seq, n_words)}


def conf_ok(sup: int, supx: int, minconf: float) -> bool:
    """Exact confidence test: sup/supx >= minconf (no float division)."""
    num, den = _conf_frac(minconf)
    return supx > 0 and sup * den >= supx * num


_auto_eval_budget = device_hbm_budget  # shared with the SPADE engines

# per-dispatch stat keys (fill/borrow/traffic decomposition, BENCH_SCALE
# 3 vs 3d); dispatch handles carry their deltas so fault recounts are
# exact.  launches_km/width_km/borrowed_km are keyed by launch GEOMETRY
# km; evaluated_km by each candidate's OWN km bucket; traffic_units is
# the kernel-streamed sum of width x geometry-km; superbatches counts
# mixed-km launches (ops/ragged_batch.py).
_KM_STAT_PREFIXES = ("evaluated_km", "launches_km", "width_km",
                     "borrowed_km", "traffic_units", "superbatches")


@functools.lru_cache(maxsize=64)
def _conf_frac(minconf: float) -> Tuple[int, int]:
    """minconf as an exact (numerator, denominator) for the hot-loop
    integer cross-multiply form of ``conf_ok``."""
    f = Fraction(str(minconf))
    return f.numerator, f.denominator


# ---------------------------------------------------------------------------
# Brute-force oracle (independent ground truth for tiny DBs)
# ---------------------------------------------------------------------------

def rule_counts_direct(db: SequenceDB, x_items: Tuple[int, ...],
                       y_items: Tuple[int, ...]) -> Tuple[int, int]:
    """(sup(X=>Y), sup(X)) by direct first/last-occurrence scanning."""
    sup = supx = 0
    for seq in db:
        first: dict[int, int] = {}
        last: dict[int, int] = {}
        for p, itemset in enumerate(seq):
            for it in itemset:
                first.setdefault(it, p)
                last[it] = p
        if all(x in first for x in x_items):
            supx += 1
            if all(y in last for y in y_items):
                if max(first[x] for x in x_items) < min(last[y] for y in y_items):
                    sup += 1
    return sup, supx


def brute_force_rules(db: SequenceDB, k: int, minconf: float,
                      max_side: int = 2) -> List[RuleResult]:
    """Enumerate every X, Y (sizes <= max_side, disjoint) directly."""
    items = sorted({i for seq in db for itemset in seq for i in itemset})
    qualifying: List[RuleResult] = []
    for nx in range(1, max_side + 1):
        for x in itertools.combinations(items, nx):
            rest = [i for i in items if i not in x]
            for ny in range(1, max_side + 1):
                for y in itertools.combinations(rest, ny):
                    sup, supx = rule_counts_direct(db, x, y)
                    if sup >= 1 and conf_ok(sup, supx, minconf):
                        qualifying.append((x, y, sup, supx))
    if not qualifying:
        return []
    sups = sorted((r[2] for r in qualifying), reverse=True)
    s_k = sups[k - 1] if len(sups) >= k else sups[-1]
    return sort_rules([r for r in qualifying if r[2] >= s_k])


# ---------------------------------------------------------------------------
# TPU engine
# ---------------------------------------------------------------------------

# Jitted kernels are module-level / lru_cached so every TsrTPU instance with
# the same (mesh, shape bucket) shares compiles — jax.jit caches per
# wrapped-function object, and the service builds one engine per /train
# request (see models/spade_tpu._spade_fns for the full reasoning).

@functools.partial(jax.jit, static_argnames=("m", "n_seq", "n_words"))
def _build_prep_single(ti, ts, tw, tm, *, m, n_seq, n_words):
    """Scatter-build the top-m item rows in HBM + prefix/suffix-OR them."""
    z = jnp.zeros((m, n_seq, n_words), jnp.uint32)
    b = z.at[ti, ts, tw].add(tm)  # distinct bits: add == OR
    return B.prefix_or_incl(b), B.suffix_or_incl(b)


@functools.lru_cache(maxsize=16)
def _prep_fn_mesh(mesh: Mesh):
    def body(b):
        return B.prefix_or_incl(b), B.suffix_or_incl(b)

    st = P(None, SEQ_AXIS, None)
    return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(st,), out_specs=(st, st)))


@functools.lru_cache(maxsize=16)
def _kernel_layout_fn(mesh: Optional[Mesh], single: bool):
    """[m, S, W] engine-layout prep rows -> FOLDED kernel layout
    [m+1, S/128, 128] (single-word) / [m+1, W, S/128, 128], with an
    appended ALL-ONES pad row — the AND identity rule_supports points
    unused candidate slots at (see ops/pallas_tsr.py for why the seq
    axis folds to (sublane, lane) tiles)."""
    def body(p):
        pk = jnp.transpose(p, (0, 2, 1))            # [m, W, S]
        m, w, s = pk.shape
        if single:
            pk = pk.reshape(m, s // PT.LANE, PT.LANE)
        else:
            pk = pk.reshape(m, w, s // PT.LANE, PT.LANE)
        ones = jnp.full((1,) + pk.shape[1:], 0xFFFFFFFF, jnp.uint32)
        return jnp.concatenate([pk, ones], axis=0)

    if mesh is None:
        return jax.jit(body)
    st_in = P(None, SEQ_AXIS, None)
    st_out = (P(None, SEQ_AXIS, None) if single
              else P(None, None, SEQ_AXIS, None))
    return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(st_in,), out_specs=st_out))


@functools.lru_cache(maxsize=128)
def _kernel_eval_fn(mesh: Optional[Mesh], km: int, sb: int,
                    interpret: bool, single: bool):
    """Jitted rule_supports launcher (+ psum under a mesh), cached per
    bucket geometry like _eval_kernel."""
    def body(p1k, s1k, xy):
        out = PT.rule_supports(p1k, s1k, xy, km=km, s_block=sb,
                               interpret=interpret)
        if mesh is not None:
            out = jax.lax.psum(out, SEQ_AXIS)
        return out

    if mesh is None:
        return jax.jit(body)
    st = (P(None, SEQ_AXIS, None) if single
          else P(None, None, SEQ_AXIS, None))
    return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(st, st, P()), out_specs=P()))


@functools.lru_cache(maxsize=256)
def _eval_kernel(mesh: Optional[Mesh], kmax: int):
    """Jitted rule evaluator for side sizes <= kmax (bucketed compile).

    Candidates arrive PACKED as one [chunk, 2, kmax] int32 array (row 0 = X
    item indices, row 1 = Y, -1 = unused slot) and results leave as one
    [2, chunk] stack — a single host->device transfer and a single
    device->host readback per launch.  On a tunneled TPU each transfer
    costs tens of ms of pure latency, so the 4-upload/2-readback layout
    this replaces paid ~6x the fixed cost per launch.
    """
    FULL = jnp.uint32(0xFFFFFFFF)

    def fold(t, idx):
        acc = None
        for j in range(kmax):
            i = idx[:, j]
            g = jnp.where((i >= 0)[:, None, None], t[jnp.maximum(i, 0)], FULL)
            acc = g if acc is None else acc & g
        return acc

    def body(p1, s1, xy):
        a = fold(p1, xy[:, 0])
        c = fold(s1, xy[:, 1])
        sup = B.support(B.shift_up_one(a) & c)
        supx = B.support(a)
        if mesh is not None:
            sup = jax.lax.psum(sup, SEQ_AXIS)
            supx = jax.lax.psum(supx, SEQ_AXIS)
        return jnp.stack([sup, supx])

    if mesh is None:
        return jax.jit(body)
    st = P(None, SEQ_AXIS, None)
    rep = P()
    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(st, st, rep), out_specs=rep))


class TsrTPU:
    """Batched best-first TopSeqRules over the vertical bitmap DB.

    Args:
      vdb: vertical DB (min_item_support=1 — TSR's internal minsup starts
        at 1 and rises as the top-k heap fills).
      k / minconf: the reference's request params (SURVEY.md sec 2.4).
      item_cap: initial restriction to the top-M items by support for the
        iterative-deepening outer loop.
      max_side: optional cap on |X| and |Y|.
    """

    # batches kept in flight by the mine loop; the device dispatch is
    # async so deeper pipelines hide the readback latency behind later
    # launches (measured on a Kosarak-shaped mine over the TPU tunnel:
    # depth 2 = 14.2s, depth 3 = 9.8s, depth 4 = 9.5s — 3 takes most of
    # the win with the least stale-minsup overspeculation)
    PIPELINE_DEPTH = 3

    # compiled-geometry registry participation (utils/shapes.py); the
    # NumPy TsrCPU subclass opts out — it compiles nothing
    _RECORD_SHAPES = True

    # resident-frontier route capability (ops/resident_frontier.py);
    # the NumPy TsrCPU subclass opts out — its dispatch is host numpy
    # and must never initialize the JAX backend
    _RESIDENT_CAPABLE = True

    def __init__(
        self,
        vdb: VerticalDB,
        k: int,
        minconf: float,
        *,
        mesh: Optional[Mesh] = None,
        chunk: Optional[int] = None,
        item_cap: int = ITEM_CAP_DEFAULT,
        max_side: Optional[int] = None,
        eval_budget_bytes: Optional[int] = None,
        use_pallas="auto",
        shape_buckets: bool = False,
        resident="auto",
        partition=None,
    ):
        self.vdb = vdb
        self.k = int(k)
        self.minconf = float(minconf)
        self.mesh = mesh
        # equivalence-class partition slice (parallel/partition.py):
        # (PartitionPlan, part_idx) restricts candidate GENERATION to
        # the roots whose class this partition owns — a candidate's
        # class is min(X), invariant under both expansion directions,
        # so the owned subtrees are exactly the owned classes.  None
        # (the default) is the classic whole-frontier engine.
        if partition is not None:
            plan, pidx = partition
            if not (0 <= int(pidx) < plan.n_parts):
                raise ValueError(f"partition index {pidx} out of range "
                                 f"for {plan.n_parts} partitions")
            partition = (plan, int(pidx))
        self._partition = partition
        # Multi-host mesh: host-side inputs must become global replicated
        # arrays (see parallel/multihost.py)
        self._multiproc = MH.is_multihost(mesh)
        self._put = functools.partial(MH.host_to_device, mesh)
        self.item_cap = int(item_cap)
        self.max_side = max_side
        # resident-frontier routing (ops/resident_frontier.py):
        # "auto" = the planner heuristic picks it for launch-bound deep
        # mines; "always"/"never" pin it (structural eligibility —
        # single device, fitting caps — still applies to "always");
        # bools accepted for request-param convenience
        if isinstance(resident, bool):
            resident = "always" if resident else "never"
        if resident not in ("auto", "always", "never"):
            raise ValueError(f"resident must be auto/always/never, "
                             f"got {resident!r}")
        self.resident = resident
        self._resident_caps: Optional[RF.ResidentCaps] = None
        self.stats = {"evaluated": 0, "kernel_launches": 0,
                      "deepening_rounds": 0, "pruned_conf": 0,
                      "traffic_units": 0}
        # per-geometry xy staging with donated-buffer lifetime
        # (ops/ragged_batch.py): candidate packing reuses free-listed
        # buffers and overlaps the in-flight device work of earlier
        # launches; each dispatch's buffers recycle at its readback
        self._stager = RB.XYStager()
        self._xy_bufs: List[np.ndarray] = []
        # budget-derived jnp launch width BEFORE the dispatch-efficiency
        # clamp (set by _round_chunk_jnp; the per-km memory caps divide
        # THIS, so a small-S mine is not narrowed by a rule that only
        # binds at full scale)
        self._jnp_raw = 8192

        # NEVER materialize vdb.bitmaps here: with a Kosarak-shaped alphabet
        # (~41k items x ~990k sequences) the full dense store is ~160 GB.
        # Each deepening round instead builds ONLY the top-m item rows from
        # the token table (host memory/HBM proportional to m, not n_items).
        # shape_buckets: pow2-bucket the sequence axis so streaming rule
        # windows with drifting geometry reuse compiled programs; padded
        # sequences hold all-zero bitmaps and support nothing.  Same knob
        # as the SPADE engines (models/_common.bucket_seq).  Single-device
        # prep additionally pow2-pads the token arrays (they are traced
        # shapes there — _prep_engine); the mesh branch scatter-builds the
        # [m, S, W] rows on HOST (numpy), so token length never enters
        # tracing and the seq-axis bucket above is the only shape knob.
        self._shape_buckets = bool(shape_buckets)
        self.n_words = vdb.n_words
        # Pallas rule-support kernel (ops/pallas_tsr.py): streams seq
        # blocks through VMEM instead of materializing [chunk, S, W]
        # gather temps, so launches can be dispatch-width-bound instead of
        # HBM-temp-bound.  "auto" = on for a real TPU backend; explicit
        # True runs interpret mode off-TPU (tests); explicit False never
        # probes the backend (the NumPy TsrCPU subclass must not
        # initialize JAX).
        if use_pallas == "auto":
            backend = jax.default_backend()
            self.use_pallas = backend == "tpu"
            self._interpret = backend != "tpu"
        elif use_pallas:
            self.use_pallas = True
            self._interpret = jax.default_backend() != "tpu"
        else:
            self.use_pallas = False
            self._interpret = False
        self._jnp_prep = None   # engine-layout prep for downgraded buckets
        self._jnp_chunk = None  # budget-derived width for those buckets
        self._pallas_bad: set = set()  # km buckets whose kernel failed
        self._round_m = 0
        # Derived static geometry lives in tsr_geometry — shared with the
        # shape-key enumerator (utils/shapes.py); same contract as the
        # SPADE engines' shape_key (per-round top-m and km-bucket shapes
        # vary by design).
        g = tsr_geometry(vdb.n_sequences, self.n_words, mesh=mesh,
                         use_pallas=self.use_pallas,
                         shape_buckets=self._shape_buckets)
        self.n_seq = g["n_seq"]
        if self.use_pallas:
            self._sb = g["sb"]
        self.stats["shape_key"] = g["shape_key"]
        if self._RECORD_SHAPES:  # CPU oracle engines stay out of the
            shapes.record(g["shape_key"])  # compiled-geometry registry

        # Per-launch dispatch latency dominates on remote/tunneled TPUs
        # (~100ms+ each; measured 6x wall-clock win going 256 -> 8192 on a
        # Kosarak-shaped mine), so launches are as WIDE as the per-device
        # eval budget allows.  The budget-derived chunk is computed per
        # deepening round (the prep store grows with m); a caller-supplied
        # chunk pins it.  Empirically the evaluator keeps ~4 live
        # [chunk, S_local, W] uint32 gather temps (verified against the
        # XLA OOM report on v5e: 16384-cand launch = 24G of temps).
        # chunk <= 0 (e.g. tsr_chunk = 0 in a config file) = adaptive sizing
        self._chunk_user = None if not chunk or chunk <= 0 else int(chunk)
        # None = resolve lazily in _round_chunk: probing the device budget
        # initializes the JAX backend, which must not happen for engines
        # that never need it (pinned chunk, or the NumPy TsrCPU subclass)
        self._eval_budget = (None if eval_budget_bytes is None
                             else int(eval_budget_bytes))
        self.chunk = self._chunk_user or 8192
        # tok_item is nondecreasing (build_vertical emits tokens sorted by
        # item), so per-item token ranges are a searchsorted away
        self._tok_starts = np.searchsorted(
            vdb.tok_item, np.arange(vdb.n_items + 1))
        # items sorted by support desc, stable by item id
        order = np.lexsort((vdb.item_ids, -vdb.item_supports))
        self._order = order
        self._sup_sorted = vdb.item_supports[order]
        if self._partition is not None:
            self.stats["partition"] = self._partition[1]
        # topology epoch at construction (service/meshguard.py; None
        # when the plane is off): every dispatch re-checks it, so a
        # partition-row death between planning and launch refuses the
        # launch instead of executing on dead silicon — the partitioned
        # orchestrator then rebuilds this engine against the survivors
        self._topo_epoch = MGD.current_epoch()

    def _part_idx(self) -> Optional[int]:
        return None if self._partition is None else self._partition[1]

    def _fault_ctx(self) -> dict:
        """Extra chaos-site context naming this engine's partition row
        (``part{p}``) so a drill can kill ONE row's dispatches with
        ``match="part0"`` (scripts/meshguard_smoke.py); empty when
        unpartitioned — the committed chaos-seed ctx must not shift."""
        p = self._part_idx()
        return {} if p is None else {"part": f"part{p}"}

    def _owned_mask(self, m: int) -> Optional[np.ndarray]:
        """Boolean mask over the round's local root indices 0..m-1: True
        where this partition owns the root's equivalence class (hash of
        the GLOBAL item id, parallel/partition.py — stable across
        deepening rounds and identical on every process).  None when the
        engine is unpartitioned (the classic whole-frontier search)."""
        if self._partition is None:
            return None
        plan, pidx = self._partition
        ids = self.vdb.item_ids[self._order[:m]]
        return plan.owner_of(ids) == pidx

    # ------------------------------------------------------------- kernels

    def _sel_tokens(self, sel: np.ndarray):
        """Token table restricted to the selected items, rows renumbered to
        0..len(sel)-1 (selection order)."""
        starts, vdb = self._tok_starts, self.vdb
        lens = starts[sel + 1] - starts[sel]
        if len(sel):
            # vectorized ragged arange: each selected item's token range
            # is its start repeated len times plus 0..len-1 within the
            # block (the per-item Python arange loop this replaces was
            # the hottest host line in the service-flood profile — prep
            # host time is the Amdahl floor every concurrent mine pays)
            ends = np.cumsum(lens)
            idx = (np.repeat(starts[sel], lens)
                   + np.arange(int(ends[-1])) - np.repeat(ends - lens, lens))
        else:
            idx = np.zeros(0, np.int64)
        ti = np.repeat(np.arange(len(sel), dtype=np.int32), lens)
        return ti, vdb.tok_seq[idx], vdb.tok_word[idx], vdb.tok_mask[idx]

    def _host_bitmaps(self, m: int, lo: int = 0,
                      hi: Optional[int] = None) -> np.ndarray:
        """[m, hi-lo, n_words] dense rows for the top-m items over the
        sequence range [lo, hi), host-built from the token slice (memory
        proportional to m and the range, never n_items x n_seq_global)."""
        hi = self.n_seq if hi is None else hi
        ti, ts, tw, tm = self._sel_tokens(self._order[:m])
        bm = np.zeros((m, hi - lo, self.n_words), np.uint32)
        keep = (ts >= lo) & (ts < hi)
        # distinct bits: add == OR
        np.add.at(bm, (ti[keep], ts[keep] - lo, tw[keep]), tm[keep])
        return bm

    def _sharded_bitmaps(self, m: int) -> jax.Array:
        """Multi-host sharded store build: each process materializes ONLY
        its seq-axis slice (replicating the full [m, n_seq, W] store on
        every device would cost D x the sharded footprint and defeat the
        per-device eval-budget sizing)."""
        sharding = store_sharding(self.mesh)
        shape = (m, self.n_seq, self.n_words)
        pidx = jax.process_index()
        slices = sorted(
            (idx[1].start or 0, idx[1].stop or self.n_seq)
            for dev, idx in sharding.devices_indices_map(shape).items()
            if dev.process_index == pidx)
        lo, hi = slices[0][0], slices[-1][1]
        if (hi - lo) != sum(b - a for a, b in slices):
            # non-contiguous addressable shards (exotic device order):
            # fall back to the replicate-and-reshard path
            return self._put(self._host_bitmaps(m))
        return jax.make_array_from_process_local_data(
            sharding, self._host_bitmaps(m, lo, hi))

    def _prep(self, m: int):
        """prefix/suffix-OR id-lists for the top-m items (one jit call).

        Single chip: the [m, n_seq, n_words] store is scatter-built in HBM
        straight from the ~KB-scale token slice and transformed in the same
        jit — the dense rows never exist on host.  Mesh: only the m selected
        rows are host-built, then sharded over the sequence axis.
        """
        p1, s1 = self._prep_engine(m)
        if self.use_pallas:
            # folded kernel layout (all-ones pad row); the engine-layout
            # intermediates are dropped — a downgraded bucket rebuilds
            # them once per round (_dispatch_eval)
            to_k = _kernel_layout_fn(self.mesh, self.n_words == 1)
            return to_k(p1), to_k(s1)
        return p1, s1

    def _prep_engine(self, m: int):
        """Engine-layout ([m, S, W]) prefix/suffix-OR rows."""
        with self._prep_span(m):
            return self._prep_engine_inner(m)

    def _prep_span(self, m: int):
        """One ``tsr.prep`` span per prep launch: every
        ``kernel_launches`` increment has a matching span, the invariant
        the bench_smoke cross-check guard pins (span-derived launch
        count == engine dispatch-shape counter)."""
        return obs.span("tsr.prep", m=m)

    def _prep_engine_inner(self, m: int):
        if self.mesh is None:
            ti, ts, tw, tm = self._sel_tokens(self._order[:m])
            if self._shape_buckets:
                # token-array length is a traced shape; see
                # _common.pad_tokens_pow2
                ti, ts, tw, tm = pad_tokens_pow2(ti, ts, tw, tm)
            p1, s1 = _build_prep_single(
                jnp.asarray(ti), jnp.asarray(ts), jnp.asarray(tw),
                jnp.asarray(tm), m=m, n_seq=self.n_seq,
                n_words=self.n_words)
        else:
            if self._multiproc:
                raw = self._sharded_bitmaps(m)
            else:
                raw = jax.device_put(self._host_bitmaps(m),
                                     store_sharding(self.mesh))
            p1, s1 = _prep_fn_mesh(self.mesh)(raw)
        self.stats["kernel_launches"] += 1
        return p1, s1

    def _eval_fn(self, kmax: int):
        return _eval_kernel(self.mesh, kmax)

    def _round_chunk(self, m: int) -> int:
        """Launch width for a deepening round over m items: what the eval
        budget allows after the round's [m, S, W] prefix/suffix stores,
        assuming ~4 live [chunk, S_local, W] uint32 gather temps (the
        XLA-verified factor), floored to a power of two for shape
        bucketing.  The Pallas kernel path holds NO [chunk, S, W] temps
        (seq blocks stream through VMEM), so its width is bounded by
        dispatch cost alone."""
        if self._chunk_user is not None:
            return self._chunk_user
        if self.use_pallas:
            # dispatch-efficiency quantum: 8192 lanes at the full
            # Kosarak axis (measured best), more lanes as the axis
            # shrinks — same device time per launch either way
            return RB.dispatch_quantum_lanes(self.n_seq, self.n_words)
        return self._round_chunk_jnp(m)

    def _round_chunk_jnp(self, m: int, resident_preps: int = 1) -> int:
        """Budget-derived width for the jnp gather path.

        ``resident_preps``: prep pairs alive in HBM when the launches
        run — 1 normally; 2 for a kernel-mode mine's downgraded buckets,
        where the kernel-layout pair stays resident next to the rebuilt
        engine-layout one."""
        if self._chunk_user is not None:
            return self._chunk_user
        self._ensure_budget()
        n_dev = 1 if self.mesh is None else self.mesh.devices.size
        s_local = max(1, self.n_seq // n_dev)
        per_cand = max(1, s_local * self.n_words * 4 * 4)
        prep = resident_preps * 2 * m * s_local * self.n_words * 4
        budget = max(per_cand, self._eval_budget - prep)
        # the raw budget width is what the per-km memory caps divide
        # (1/km live-temp growth, measured OOM boundary); the clamp
        # below is dispatch efficiency, not memory — applying the km
        # narrowing AFTER it would over-throttle small-S mines whose
        # budget allows far more than 8192 lanes at any km.  The
        # efficiency ceiling itself is the lane-time quantum (8192 at
        # the full Kosarak axis, wider as S shrinks).
        self._jnp_raw = max(128, next_pow2(budget // per_cand + 1) // 2)
        return min(RB.dispatch_quantum_lanes(self.n_seq, self.n_words),
                   self._jnp_raw)

    def _ensure_budget(self) -> int:
        """Resolve the per-device eval budget lazily (probing the
        device initializes the JAX backend, which must not happen for
        engines that never need it)."""
        if self._eval_budget is None:
            dev = (self.mesh.devices.flat[0] if self.mesh is not None
                   else jax.devices()[0])
            self._eval_budget = _auto_eval_budget(dev)
        return self._eval_budget

    def _dispatch_eval(self, p1, s1,
                       cands: List[Tuple[Tuple[int, ...], Tuple[int, ...]]]):
        """Traced wrapper around :meth:`_dispatch_eval_inner`: opens the
        per-dispatch flight-recorder span (the launch spans the planner
        emits nest under it) and appends the dispatch-start monotonic
        clock to the handle so :meth:`_resolve_eval` can put the
        measured wall next to the planner's prediction.  One global
        read when tracing is off (utils/obs.span)."""
        # meshguard fence: refuse a dispatch planned against a topology
        # a row death has invalidated (one global read when the plane
        # is off; StaleTopology sends the orchestrator to re-plan)
        MGD.check_epoch(self._topo_epoch)
        t0 = time.monotonic()
        with obs.span("tsr.dispatch", candidates=len(cands)) as sp:
            handle = self._dispatch_eval_inner(p1, s1, cands)
            if isinstance(handle, FZ.EvalWave):
                # the wave is in the fusion broker's window: launch
                # planning, spans and the cost-model observation happen
                # there — this dispatch's story continues under
                # fusion.launch/fusion.readback (or fusion.joined)
                sp.set(fusion=True)
                return handle
            sp.set(launches=handle[3], predicted_s=round(handle[6], 6))
        return handle + (t0,)

    def _dispatch_eval_inner(self, p1, s1,
                             cands: List[Tuple[Tuple[int, ...],
                                               Tuple[int, ...]]]):
        """Launch (sup, supx) evaluation for candidate rules (local item
        idx); returns a device handle with the host copy already in
        flight.  ``_resolve_eval`` blocks on it — the split lets the mine
        loop pipeline the next dispatch behind the current readback.

        Launch planning is the ragged super-batch packer
        (ops/ragged_batch.py): per-km pools split greedily into FULL
        pow2 launches at their own km (a candidate never pays a wider
        geometry's traffic when its pool fills launches alone), then the
        per-km TAILS merge into shared mixed-km launches at the largest
        participating km — what used to be one underfilled launch per
        (km bucket x dispatch) collapses into one shared launch when the
        packer's cost model says the pad traffic is cheaper than the
        extra dispatches (BENCH_SCALE 3d: 371 launches -> the ~41-launch
        profile of config 3).  The per-geometry width caps keep the old
        memory reasoning: the jnp evaluator's live-temp footprint grows
        with km, so its cap NARROWS 1/km (measured OOM boundary — km=4
        at the km=1 width allocated 27.2G on a 15G chip); the kernel
        path streams seq blocks through VMEM and stays flat at the
        engine chunk.  A caller-pinned chunk is honored as the cap.
        """
        n = len(cands)
        launches0 = self.stats["kernel_launches"]  # handle carries its own
        # launch count so a readback-fault recount can discard them (below)
        km_stats0 = {sk: v for sk, v in self.stats.items()
                     if sk.startswith(_KM_STAT_PREFIXES)}
        kms = np.empty(n, np.int32)
        for r, (x, y) in enumerate(cands):
            side = max(len(x), len(y))
            km = 1
            while km < side:
                km *= 2
            kms[r] = km
        # per-bucket accounting (evaluated by OWN km; launch widths land
        # in stats per GEOMETRY km below): these counters are what lets
        # BENCH_SCALE's 3-vs-3d gap be decomposed into candidate mix
        # (irreducible) vs launch packing (the packer's job)
        for km_v, cnt in zip(*np.unique(kms, return_counts=True)):
            key = f"evaluated_km{int(km_v)}"
            self.stats[key] = self.stats.get(key, 0) + int(cnt)
        pools: Dict[int, List[int]] = {}
        for r in range(n):
            pools.setdefault(int(kms[r]), []).append(r)
        if FZ.eval_enabled() and not self.use_pallas and self.mesh is None:
            # cross-job launch fusion (service/fusion.py): hand the
            # whole candidate wave to the broker — concurrent jobs that
            # share this engine's (n_seq, n_words) geometry co-schedule
            # into shared super-batched launches, and the readback
            # demuxes per job by the plan's per-lane job tags.  The
            # broker runs the SAME packer over the SAME per-km caps, so
            # a wave that finds no fusion peer dispatches exactly like
            # the direct path below.  Gated to the single-device jnp
            # path: fused prep stores concatenate along the item axis,
            # which the folded kernel layout and sharded meshes don't
            # support (their waves keep the direct path).
            ticket = self._submit_fusion_wave(p1, s1, cands, pools)
            if ticket is not None:
                self.stats["evaluated"] += n
                return ticket
        parts = []
        cols = np.empty(n, np.int64)  # candidate r -> column in `out`
        used_kernel = False  # any launch through the Pallas path: a
        base = 0             # readback fault is then recountable
        xy_bufs: List[np.ndarray] = []  # staging buffers donated to this
        # dispatch; recycled at readback (ops/ragged_batch.XYStager)
        self._xy_bufs = xy_bufs
        leftover: Dict[int, List[int]] = {}
        if self.use_pallas:
            leftover = {km: rows for km, rows in pools.items()
                        if km in self._pallas_bad}
            kern = {km: rows for km, rows in pools.items()
                    if km not in self._pallas_bad}
            plan = RB.plan_launches(
                kern, cap=lambda km: self.chunk, lane=PT.C_LANES,
                overhead=RB.overhead_units(self.n_seq, self.n_words),
                part=self._part_idx())
            for L in plan:
                if L.km in self._pallas_bad:
                    # a geometry that failed earlier in THIS plan: its
                    # remaining launches re-pool by each lane's own km
                    for r, k in zip(L.rows, L.kms):
                        leftover.setdefault(k, []).append(r)
                    continue
                try:
                    base = self._dispatch_kernel_launch(
                        p1, s1, cands, L, parts, cols, base)
                    used_kernel = True
                except Exception as exc:  # pragma: no cover - device-specific
                    # compile/lowering failures surface at the geometry's
                    # first launch; mark only THIS km geometry bad (other
                    # geometries keep the kernel).  Stats are recorded
                    # only after a successful dispatch, so a failed
                    # launch leaves nothing to roll back — its lanes
                    # (own-km and merged alike) re-pool for the jnp path.
                    self._pallas_bad.add(L.km)
                    self.stats[f"pallas_fallback_km{L.km}"] = repr(exc)
                    for r, k in zip(L.rows, L.kms):
                        leftover.setdefault(k, []).append(r)
        else:
            leftover = pools
        has_leftover = any(leftover.values())
        if has_leftover and self.use_pallas:
            # jnp launches while the kernel path is live: both prep pairs
            # stay resident (see _ensure_jnp_downgrade).  The
            # prep-rebuild launch is REAL retained work — exclude it
            # from this handle's discardable launch delta so a later
            # readback-fault recount cannot subtract it.
            before = self.stats["kernel_launches"]
            self._ensure_jnp_downgrade()
            launches0 += self.stats["kernel_launches"] - before
        if has_leftover:
            pj, sj = self._jnp_prep if self._jnp_prep is not None else (p1, s1)
            cw = self.chunk if not self.use_pallas else self._jnp_chunk
            # per-km memory cap: the jnp evaluator's live temps grow
            # with km, so the BUDGET-derived width narrows 1/km; the
            # dispatch-efficiency ceiling cw applies after (a pinned
            # chunk overrides both — honored as-is)
            cap = ((lambda km: cw) if self._chunk_user
                   else (lambda km: max(32, min(cw, self._jnp_raw // km))))
            for L in RB.plan_launches(
                    leftover, cap=cap, lane=32,
                    overhead=RB.overhead_units(self.n_seq, self.n_words),
                    part=self._part_idx()):
                with obs.span("tsr.launch", point="jnp", km=L.km,
                              width=L.width, predicted_s=round(
                                  RB.estimate_seconds(
                                      L.traffic_units, 1, self.n_seq,
                                      self.n_words), 6)):
                    faults.fault_site("device.dispatch", point="jnp",
                                      km=str(L.km), width=str(L.width),
                                      **self._fault_ctx())
                    fn = self._eval_fn(L.km)
                    xy = self._stager.take(L, cands)
                    xy_bufs.append(xy)
                    cols[L.rows] = base + np.arange(len(L.rows))
                    base += L.width
                    parts.append(fn(pj, sj, self._put(xy)))
                    self._count_launch(L)
        self.stats["evaluated"] += n
        out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        try:
            out.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass  # method unavailable on this backend
        # the handle also carries this dispatch's per-km counter DELTAS,
        # so a readback-fault recount can subtract them exactly — the
        # fill/borrow decomposition must not keep discarded launches
        # (km keys are never REMOVED during a dispatch — the bucket-
        # failure handler only pops keys absent at bucket start — so the
        # current key set covers every delta)
        km_delta = {sk: self.stats[sk] - km_stats0.get(sk, 0)
                    for sk in self.stats
                    if sk.startswith(_KM_STAT_PREFIXES)
                    and self.stats[sk] != km_stats0.get(sk, 0)}
        # the handle carries the planner's own wall estimate for this
        # dispatch — the watchdog deadline at readback derives from it
        est_s = RB.estimate_seconds(
            self.stats.get("traffic_units", 0)
            - km_stats0.get("traffic_units", 0),
            self.stats["kernel_launches"] - launches0,
            self.n_seq, self.n_words)
        return (out, cols, used_kernel,
                self.stats["kernel_launches"] - launches0, km_delta,
                xy_bufs, est_s)

    def _submit_fusion_wave(self, p1, s1, cands, pools):
        """Hand one dispatch's whole candidate wave to the cross-job
        fusion broker (service/fusion.py) and return the ticket, or
        None when the broker declined (shut off between the gate probe
        and here — the caller then dispatches directly).

        The broker re-runs the SAME planner inputs this engine's direct
        jnp path would use — per-km width caps (budget-derived 1/km
        narrowing, or the user-pinned chunk as-is), the jnp lane floor,
        and the engine's own eval/put functions — so a wave that finds
        no fusion peer launches exactly what the direct path would
        have.  ``_resolve_eval`` blocks on the ticket like any other
        handle, so the mine loop's pipelining is unchanged."""
        # after a mid-mine kernel->jnp downgrade the caller's p1/s1 are
        # the FOLDED kernel layout; the broker runs the engine-layout
        # jnp evaluator, so substitute the downgrade preps exactly like
        # the direct jnp branch below does
        if self._jnp_prep is not None:
            p1, s1 = self._jnp_prep
        cw = self.chunk
        cap = ((lambda km: cw) if self._chunk_user is not None
               else (lambda km: max(32, min(cw, self._jnp_raw // km))))
        return FZ.submit_eval(
            cands=cands, pools=pools, p1=p1, s1=s1,
            eval_fn=self._eval_fn, put=self._put, cap=cap, lane=32,
            n_seq=self.n_seq, n_words=self.n_words)

    def _ensure_jnp_downgrade(self) -> None:
        """Build the engine-layout prep + budget width the jnp evaluator
        needs after a kernel-path downgrade (the kernel path keeps
        folded-layout preps and kernel-sized chunks).  Shared by the
        per-bucket dispatch fallback and the readback recount so the two
        downgrade paths cannot drift in sizing or layout."""
        if self._jnp_prep is None:
            self._jnp_prep = self._prep_engine(self._round_m)
            self._jnp_chunk = self._round_chunk_jnp(self._round_m,
                                                    resident_preps=2)

    def _bucket_seq_block(self, km: int) -> int:
        """Per-bucket kernel seq block: halve the engine block until the
        bucket's 2*km double-buffered row blocks fit the scoped-VMEM
        budget (large-km buckets of unlimited-side mines would otherwise
        fail to compile); halving preserves the (8,128)-tile and
        S-divisibility invariants."""
        sb = self._sb
        need = lambda b: 2 * km * 2 * self.n_words * b * 4
        while (need(sb) > PT._VMEM_BUDGET and sb % 2 == 0
               and (sb // 2) % (8 * PT.LANE) == 0):
            sb //= 2
        return sb

    def _dispatch_kernel_launch(self, p1k, s1k, cands, L, parts, cols,
                                base):
        """Pallas-path dispatch of one planned super-batch launch (the
        kernel streams seq blocks through VMEM — no [chunk, S, W] gather
        temps to narrow for, so widths run at the engine chunk).  A lane
        whose own km is below the launch geometry rides with -1 unused
        slots pointed at the all-ones pad row — the packer's tail merge
        generalizes the old per-bucket pad borrowing.  Appends to
        parts/cols and returns the advanced base; stats land only after
        the dispatch succeeds (a compile failure leaves nothing to roll
        back).

        RESOURCE_EXHAUSTED gets its own recovery: a device OOM at a new
        ragged geometry used to kill the whole mine, but the failure is
        a function of launch WIDTH (the live-temp footprint), so the
        launch re-plans at HALF width — recursively, floored at
        ``_OOM_FLOOR_LANES`` — before the generic handler falls it back
        to the jnp path.  Each halving counts ``degraded_launches``;
        the sub-launches re-enter this method, so a half-width OOM
        halves again and stats/cols bookkeeping stays per-sub-launch.
        """
        with obs.span("tsr.launch", point="kernel", km=L.km, width=L.width,
                      predicted_s=round(RB.estimate_seconds(
                          L.traffic_units, 1, self.n_seq, self.n_words),
                          6)) as sp:
            try:
                faults.fault_site("device.dispatch", point="kernel",
                                  km=str(L.km), width=str(L.width),
                                  **self._fault_ctx())
                faults.fault_site("device.oom", point="kernel",
                                  km=str(L.km), width=str(L.width))
                fn = _kernel_eval_fn(self.mesh, L.km,
                                     self._bucket_seq_block(L.km),
                                     self._interpret, self.n_words == 1)
                xy = self._stager.take(L, cands)
                part = fn(p1k, s1k, self._put(xy))
            except Exception as exc:
                if not _is_oom(exc) or L.width <= _OOM_FLOOR_LANES:
                    raise
                self.stats["degraded_launches"] = (
                    self.stats.get("degraded_launches", 0) + 1)
                half = L.width // 2
                obs.log_event("oom_degraded_launch", km=L.km, width=L.width,
                              half=half)
                # the RESOURCE_EXHAUSTED lands on THIS launch's span and
                # the half-width re-plans below nest under it as child
                # spans — the degradation ladder reads straight off the
                # trace dump
                sp.event("resource_exhausted", km=L.km, width=L.width,
                         half=half, error=f"{type(exc).__name__}: {exc}")
                for lo, hi in ((0, half), (half, len(L.rows))):
                    rows = L.rows[lo:hi]
                    if rows:
                        # the half re-plans keep the parent's part tag:
                        # per-partition accounting must hold
                        # sum(launches_part*) == kernel_launches even
                        # under the degradation ladder
                        base = self._dispatch_kernel_launch(
                            p1k, s1k, cands,
                            RB.Launch(L.km, half, rows, L.kms[lo:hi],
                                      None, L.part),
                            parts, cols, base)
                return base
            self._xy_bufs.append(xy)
            self._count_launch(L)
            cols[L.rows] = base + np.arange(len(L.rows))
            parts.append(part)
            return base + L.width

    def _count_launch(self, L) -> None:
        """Per-launch accounting shared by the kernel and jnp dispatch
        paths: geometry-keyed fill counters (the 3-vs-3d decomposition),
        kernel-streamed traffic units, super-batch/borrow counts, and
        the compiled-geometry registry record (utils/shapes.py) that
        keeps the launch ladder enumerable by prewarm."""
        self.stats["kernel_launches"] += 1
        lk, wk = f"launches_km{L.km}", f"width_km{L.km}"
        self.stats[lk] = self.stats.get(lk, 0) + 1
        self.stats[wk] = self.stats.get(wk, 0) + L.width
        self.stats["traffic_units"] = (
            self.stats.get("traffic_units", 0) + L.traffic_units)
        borrowed = L.borrowed
        if borrowed:
            bk = f"borrowed_km{L.km}"
            self.stats[bk] = self.stats.get(bk, 0) + borrowed
        if L.mixed:
            self.stats["superbatches"] = (
                self.stats.get("superbatches", 0) + 1)
        if L.part is not None:
            # per-partition dispatch accounting (parallel/partition.py):
            # the scaling bench reads the partition split off these
            pk = f"launches_part{L.part}"
            self.stats[pk] = self.stats.get(pk, 0) + 1
        if self._RECORD_SHAPES:
            shapes.record(shapes.key_tsr_eval(
                self.n_seq, self.n_words, L.km, L.width))

    @staticmethod
    def _bill_readback(nbytes: int) -> None:
        """Attribute a device->host readback's bytes to the current
        job (service/usage.py); one module-global read when the plane
        is off."""
        if usage.get() is not None:
            ctl = jobctl.current()
            if ctl is not None:
                usage.deposit(ctl.uid, readback_bytes=int(nbytes))

    def _resolve_eval(self, handle, n: int):
        if isinstance(handle, FZ.EvalWave):
            # fusion-broker ticket: the broker planned, launched, traced
            # and demuxed (or failed) this wave — block on its result.
            # Broker launches land in fusion_* stats, NOT in this
            # engine's kernel_launches: a fused launch is SHARED device
            # work, so charging it to every rider would double-count
            # the dispatch the fusion existed to save (the broker's own
            # stats/metrics carry the launch truth).
            sups, supxs, report = handle.result()
            self.stats["fusion_waves"] = (
                self.stats.get("fusion_waves", 0) + 1)
            if report.get("fused_jobs", 1) > 1:
                self.stats["fusion_fused_waves"] = (
                    self.stats.get("fusion_fused_waves", 0) + 1)
            self.stats["fusion_launches"] = (
                self.stats.get("fusion_launches", 0)
                + report.get("launches", 0))
            return sups, supxs
        out, cols = handle[0], handle[1]

        def read():
            faults.fault_site("device.dispatch", point="readback",
                              **self._fault_ctx())
            return np.asarray(out)

        # the blocking readback runs under the dispatch watchdog: the
        # deadline derives from the packer's own cost-model estimate
        # carried on the handle (x configured slack; disabled = direct
        # call).  A hung device fails THIS launch (consume()'s fault
        # handling downgrades or the job supervisor retries) instead of
        # wedging the Miner worker forever.
        est_s = handle[6] if len(handle) > 6 else 0.0
        with obs.span("tsr.readback", predicted_s=round(est_s, 6)) as sp:
            arr = watchdog.run_with_deadline(
                read, watchdog.deadline_s(est_s), site="tsr.readback")
            # measured wall since the DISPATCH opened (the async device
            # work + queue wait this readback resolved), recorded next
            # to the planner's prediction — per-dispatch residuals are
            # the cost-model calibration input.  The EWMA gauge
            # (fsm_costmodel_drift_ratio) feeds the watchdog-slack
            # runbook; with a deep pipeline the wait includes earlier
            # in-flight dispatches, so the ratio is conservative (an
            # overestimate), which is the safe direction for a deadline.
            measured_s = 0.0
            if len(handle) > 7:
                measured_s = time.monotonic() - handle[7]
                sp.set(measured_s=round(measured_s, 6))
                obs.observe_costmodel(est_s, measured_s,
                                      family="tsr-eval")
        if usage.get() is not None:
            ctl = jobctl.current()
            if ctl is not None:
                usage.deposit(
                    ctl.uid,
                    launches=int(handle[3] if len(handle) > 3 else 0),
                    traffic_units=int((handle[4] or {}).get(
                        "traffic_units", 0) if len(handle) > 4 else 0),
                    seconds_est=est_s, seconds_measured=measured_s,
                    readback_bytes=int(arr.nbytes))
        # the blocking readback proves the compute consumed its staged
        # inputs: recycle the dispatch's xy buffers (a FAULTED handle
        # never reaches this line, so its buffers are never reused while
        # the device might still reference them)
        if len(handle) > 5:
            self._stager.release(handle[5])
        return arr[0, cols].astype(np.int64), arr[1, cols].astype(np.int64)

    # --------------------------------------------------------- checkpoints

    def frontier_fingerprint(self) -> dict:
        """Identity a frontier checkpoint binds to (SURVEY.md sec 5
        checkpoint row, same contract as SpadeTPU.frontier_fingerprint):
        queue entries hold support-order LOCAL item indices, which are
        only meaningful for the exact same (vdb, k, minconf, max_side) —
        a changed search must restart fresh, not resume garbage."""
        ids = self.vdb.item_ids
        return {
            "algo": "tsr",
            "stack_format": 3,  # 3 = sibling-chain entries + psupx
            # (antecedent support for right chains — the conf-bound
            # pruning input); format-2 snapshots restart fresh
            "k": self.k,
            "minconf": float(self.minconf),
            "max_side": self.max_side,
            "n_items": int(self.vdb.n_items),
            "n_sequences": int(self.vdb.n_sequences),
            "item_ids_head": [int(i) for i in ids[:8]],
            "item_ids_sum": int(ids.astype(np.int64).sum()),
        }

    def frontier_state(self, queue, results, m: int, minsup: int) -> dict:
        """JSON-able snapshot of a paused best-first round.

        Unlike the SPADE engines' append-only result deltas, a TSR round's
        accepted-rule set SHRINKS when the internal minsup rises, so every
        snapshot carries the FULL current set (``results_done=0`` makes
        StoreCheckpoint rewrite its list rather than append).  Bound-pruned
        queue entries (< minsup) are dropped — pop_batch would discard
        them anyway — keeping snapshots proportional to the live frontier.
        """
        return {
            "version": 1,
            "fingerprint": self.frontier_fingerprint(),
            "m": int(m),
            "minsup": int(minsup),
            "stack": [[int(-nb), [int(i) for i in x], [int(j) for j in y],
                       bool(cr), int(side), int(psup), int(psupx)]
                      for nb, x, y, cr, side, psup, psupx in queue
                      if -nb >= minsup],
            "results_done": 0,
            "results": [[[int(i) for i in x], [int(j) for j in y],
                         int(sup), int(supx)]
                        for sup, supx, x, y in results],
        }

    # ---------------------------------------------------------------- mine

    def _mine_restricted(self, m: int, resume: Optional[dict] = None,
                         checkpoint_cb=None,
                         every_s: float = 30.0,
                         floor: int = 1) -> Tuple[List[RuleResult], int]:
        """Full search over the top-m items; returns (results, s_k).

        Routes the round: the RESIDENT-FRONTIER path (whole km-ladders
        expanded on device inside one ``lax.while_loop``,
        ops/resident_frontier.py) when the planner heuristic predicts
        launch-bound behavior, else the classic host loop below.  The
        resident path spills back here on any capacity overflow, so the
        choice is a performance routing decision, never a correctness
        one.

        ``floor``: initial minsup — the partitioned route's conservative
        global top-k floor (parallel/partition.py ThresholdBoard).  It
        is a LOWER bound on the global s_k by construction, so starting
        the dynamic threshold there prunes only candidates that can
        never enter the global top-k; 1 (the default) is the classic
        whole-frontier behavior."""
        self.chunk = self._round_chunk(m)
        self._round_m = m
        self._jnp_prep = None  # cleared per round (downgrade state is stale)
        if self._resident_route(m):
            return self._mine_resident(m, resume=resume,
                                       checkpoint_cb=checkpoint_cb,
                                       every_s=every_s, floor=floor)
        return self._mine_host_restricted(m, resume=resume,
                                          checkpoint_cb=checkpoint_cb,
                                          every_s=every_s, floor=floor)

    def _resident_route(self, m: int) -> bool:
        """Should this round run on the resident-frontier path?

        Structural eligibility (applies even to ``resident="always"``):
        single device (the carry is unsharded; fused prep concat and
        psum demux don't exist here), k within the on-device top-k
        buffer, exact-conf products within int32, and a frontier/record
        capacity model that fits the eval budget.  The "auto" heuristic
        on top: only DEEP mines (unlimited or >2-item sides — the
        config-3d shape whose host loop is launch-bound) and only when
        one saved dispatch is worth at least a wave of km-ladder fold
        padding (``overhead_units >= nb`` — true at dryrun scale and on
        tunneled/drift-calibrated backends, false on a local full-axis
        chip where the host loop's dispatches are cheap)."""
        if not self._RESIDENT_CAPABLE or self.resident == "never":
            return False
        if self.mesh is not None or self._multiproc:
            return False
        if self.k > RF.K_PAD:
            return False
        num, den = _conf_frac(self.minconf)
        if max(num, den) * (self.n_seq + 1) >= 2 ** 31:
            return False  # the device conf test multiplies in int32
        if self.resident != "always" and not (
                self.max_side is None or self.max_side > 2):
            return False
        caps = RF.caps_for(self.n_seq, self.n_words, m,
                           self._ensure_budget())
        if caps is None or m > caps.ring:
            return False
        if (self.resident != "always"
                and RB.overhead_units(self.n_seq, self.n_words) < caps.nb):
            return False
        self._resident_caps = caps
        return True

    # ------------------------------------------------- resident route

    def _mine_resident(self, m: int, resume: Optional[dict],
                       checkpoint_cb, every_s: float, floor: int = 1,
                       ) -> Tuple[List[RuleResult], int]:
        """One deepening round on the resident-frontier path: the
        frontier, per-candidate antecedent supports and the top-k prune
        threshold stay in HBM, and whole km-ladders expand inside the
        compiled while_loop — the host reads back a 9-int counter
        vector per segment and the packed survivors at the end.

        Failure posture: a capacity overflow (ring/records/km-ladder)
        commits nothing on device — the intact frontier SPILLS into the
        host loop's own resume format and the round continues on the
        classic ragged-batch path.  A dispatch fault falls back the
        same way (or restarts the round host-side when the device state
        is unreadable); a watchdog timeout or job abort propagates to
        supervision like every other engine path.  Resident dispatches
        route through fusion.dispatch_wave for the one accounting/fault
        surface but NEVER enter a fusion window — a single long-lived
        while_loop dispatch must not wait on (or hold up) a fusion
        group (docs/DESIGN.md)."""
        caps = self._resident_caps
        num, den = _conf_frac(self.minconf)
        max_side_t = self.max_side if self.max_side is not None else 1 << 30
        sup_l = self._sup_sorted[:m].astype(np.int64).tolist()
        if resume is not None:
            minsup = max(int(resume["minsup"]), int(floor))
            results0 = [(int(sup), int(supx), tuple(x), tuple(y))
                        for x, y, sup, supx in resume["results"]
                        if int(sup) >= minsup]
            entries = [(int(b), tuple(x), tuple(y), bool(cr), int(side),
                        int(psup), int(psupx))
                       for b, x, y, cr, side, psup, psupx
                       in resume["stack"]]
            self.stats["resumed_nodes"] = len(entries)
        else:
            minsup = max(1, int(floor))
            results0 = []
            entries = RF.root_entries(sup_l, minsup, num, den,
                                      self.max_side)
            own = self._owned_mask(m)
            if own is not None:
                # partition-aware candidate generation: seed only the
                # owned classes' root chains — every descendant keeps
                # min(X) = the root, so the whole slice stays owned
                entries = [e for e in entries if own[e[1][0]]]
        state = RF.pack_state(entries, results0, caps)
        if state is None:
            # the resumed frontier outgrows the caps (e.g. a host
            # snapshot with sides past the km ladder): route host
            return self._mine_host_restricted(
                m, resume=resume, checkpoint_cb=checkpoint_cb,
                every_s=every_s, floor=floor)
        self.stats["resident"] = True
        self.stats["resident_rounds"] = (
            self.stats.get("resident_rounds", 0) + 1)
        if self._RECORD_SHAPES:
            shapes.record(shapes.key_tsr_resident(
                self.n_seq, self.n_words, m, caps.km, caps.nb, caps.ring))
        p1, s1 = self._prep_engine(m)
        put = self._put
        sup_items = put(np.asarray(sup_l, np.int32))
        carry = (
            put(state["exy"]), put(state["bound"]), put(state["psup"]),
            put(state["psupx"]), put(state["cr"]), put(state["side"]),
            put(np.int32(0)), put(np.int32(state["n_entries"])),
            put(state["rec_xy"]), put(state["rec_sup"]),
            put(state["rec_supx"]), put(np.int32(state["n_results"])),
            put(state["topk"]), put(np.int32(state["n_results"])),
            put(np.int32(minsup)), put(np.bool_(False)),
            put(np.int32(0)), put(np.int32(0)), put(np.int32(0)),
            put(state["dxy"]), put(state["dbound"]),
            put(state["dpsup"]), put(state["dpsupx"]),
            put(state["dcr"]), put(state["dside"]),
            put(np.int32(state["n_defer"])))
        num_d, den_d = put(np.int32(num)), put(np.int32(den))
        k_d = put(np.int32(self.k))
        ms_d = put(np.int32(max_side_t))

        narrow = caps.nb_late < caps.nb and state["n_entries"] <= caps.nb_late
        if narrow and self._RECORD_SHAPES:
            shapes.record(shapes.key_tsr_resident(
                self.n_seq, self.n_words, m, caps.km, caps.nb_late,
                caps.ring))
        narrow_recorded = narrow
        # segment budget: fine-grained when checkpointing (first
        # snapshot lands after wave 1, queue-engine style), coarse
        # otherwise; geometric growth bounds counter readbacks to
        # ~log + wall/interval
        budget = 1 if checkpoint_cb is not None else 256
        last_ckpt = time.monotonic()
        waves_done = ev_done = pr_done = 0
        tr_done = seg_launches = 0
        while True:
            # deadline/cancel safe point between segment dispatches
            jobctl.check()
            nbw = caps.nb_late if narrow else caps.nb
            fn = RF.segment_fn(caps, narrow)
            # watchdog ceiling from the cost model's ladder estimate:
            # the segment streams at most budget x nbw x km lane-units
            bound_s = RB.estimate_seconds(
                budget * nbw * caps.km, 1, self.n_seq, self.n_words)
            deadline = watchdog.deadline_s(bound_s)
            t_seg = time.monotonic()
            try:
                with obs.span("tsr.resident", point="segment", nb=nbw,
                              budget=budget, narrow=narrow,
                              bound_s=round(bound_s, 6)):
                    faults.fault_site("device.resident", point="segment",
                                      nb=str(nbw))
                    wave_end = put(np.int32(waves_done + budget))
                    # unfusable by construction (per-round device
                    # carry): dispatch_wave is the broker's accounting/
                    # fault surface only — the wave never sits in a
                    # fusion window
                    carry, counters_dev = FZ.dispatch_wave(
                        "tsr_resident",
                        lambda f=fn, c=carry, we=wave_end: f(
                            p1, s1, sup_items, num_d, den_d, k_d, ms_d,
                            we, *c),
                        point="resident_segment")
                    self.stats["kernel_launches"] += 1
                    seg_launches += 1

                    def read():
                        faults.fault_site("device.resident",
                                          point="readback")
                        return np.asarray(counters_dev)

                    counters = watchdog.run_with_deadline(
                        read, deadline, site="tsr.resident")
            except (watchdog.WatchdogTimeout, jobctl.JobAborted):
                # a hung device or an aborted job is not a resident
                # fault: supervision owns the re-run (the same posture
                # as _resolve_eval's direct path)
                raise
            except Exception as exc:
                # mid-ladder dispatch fault: abandon the round to the
                # host path (the carry may have been donated into the
                # failed dispatch, so no device state is assumed
                # readable here)
                return self._resident_abandon(
                    exc, m, resume, checkpoint_cb, every_s,
                    ev_done, pr_done, tr_done, seg_launches, floor)
            (n_rec, oflow, waves, head, tail, minsup, evaluated,
             pruned, _n_acc, n_def) = (int(v) for v in counters)
            RF.count_segment(waves - waves_done, nbw, caps.km)
            self.stats["resident_segments"] = (
                self.stats.get("resident_segments", 0) + 1)
            self.stats["resident_waves"] = (
                self.stats.get("resident_waves", 0) + waves - waves_done)
            seg_traffic = (waves - waves_done) * nbw * caps.km
            tr_done += seg_traffic
            self.stats["traffic_units"] = (
                self.stats.get("traffic_units", 0) + seg_traffic)
            # whole-segment attribution: a resident segment has exactly
            # one owning job (the device-carry loop never fuses), and
            # its residual feeds the tsr-resident family gauge ONLY —
            # the global recalibration EWMA must stay fed by the two
            # pre-existing surfaces (bench_smoke pins it byte-identical)
            seg_wall = time.monotonic() - t_seg
            seg_est = RB.estimate_seconds(seg_traffic, 1, self.n_seq,
                                          self.n_words)
            obs.observe_costmodel_family("tsr-resident", seg_est,
                                         seg_wall)
            if usage.get() is not None:
                ctl = jobctl.current()
                if ctl is not None:
                    usage.deposit(ctl.uid, launches=1,
                                  traffic_units=seg_traffic,
                                  seconds_est=seg_est,
                                  seconds_measured=seg_wall,
                                  readback_bytes=int(counters.nbytes))
            self.stats["evaluated"] += evaluated - ev_done
            self.stats["pruned_conf"] += pruned - pr_done
            waves_done, ev_done, pr_done = waves, evaluated, pruned
            budget = min(4096, budget * 4)
            pending = tail > head
            if oflow or (pending and waves >= caps.i_max):
                # overflow-to-host spill: the aborted wave committed
                # nothing, so the ring + records read back as a
                # consistent frontier the host loop resumes exactly
                return self._resident_spill(
                    m, carry, head, tail, n_rec, n_def, minsup,
                    checkpoint_cb=checkpoint_cb, every_s=every_s,
                    prep=(p1, s1))
            if not pending:
                break
            if not narrow and caps.nb_late < caps.nb and (
                    tail - head) <= caps.nb_late:
                narrow = True  # late-wave switch (never switched back)
                if not narrow_recorded and self._RECORD_SHAPES:
                    shapes.record(shapes.key_tsr_resident(
                        self.n_seq, self.n_words, m, caps.km,
                        caps.nb_late, caps.ring))
                    narrow_recorded = True
            if (checkpoint_cb is not None
                    and time.monotonic() - last_ckpt >= every_s):
                checkpoint_cb(self._resident_snapshot(
                    m, carry, head, tail, n_rec, n_def, minsup))
                self.stats["checkpoints"] = (
                    self.stats.get("checkpoints", 0) + 1)
                last_ckpt = time.monotonic()
        # final readback: the packed survivors (full arrays — a dynamic
        # slice would compile per result count).  The watchdog deadline
        # is sized from the actual buffer volume: RB.estimate_seconds
        # models compute lane-units, not transfer, and the record caps
        # reach MBs at full scale — on a tunneled PJRT backend
        # (~10-16 MB/s) a guessed constant would time out a healthy
        # pull, so price at a conservative 8 MB/s floor + 1 s latency
        rec_idx = (8, 9, 10) + ((19, 20, 21, 22, 23, 24) if n_def else ())
        rb_est_s = 1.0 + (sum(carry[i].nbytes for i in rec_idx)
                          / _RESIDENT_READBACK_FLOOR_BPS)
        try:
            with obs.span("tsr.resident", point="readback", records=n_rec,
                          deferred=n_def, bound_s=round(rb_est_s, 6)):
                def read_recs():
                    faults.fault_site("device.resident", point="records")
                    return [np.asarray(carry[i]) for i in rec_idx]

                arrs = watchdog.run_with_deadline(
                    read_recs, watchdog.deadline_s(rb_est_s),
                    site="tsr.resident")
        except (watchdog.WatchdogTimeout, jobctl.JobAborted):
            raise
        except Exception as exc:
            # a faulted FINAL readback abandons the round exactly like
            # a mid-ladder segment fault
            return self._resident_abandon(
                exc, m, resume, checkpoint_cb, every_s,
                ev_done, pr_done, tr_done, seg_launches, floor)
        nbytes = sum(a.nbytes for a in arrs)
        RF.count_readback(nbytes)
        self.stats["resident_readback_bytes"] = (
            self.stats.get("resident_readback_bytes", 0) + nbytes)
        self._bill_readback(nbytes)
        results = RF.unpack_results(*arrs[:3], n_rec, minsup)
        if n_def:
            # over-ladder children the device deferred: filter against
            # the FINAL exact top-k threshold — a deferred entry whose
            # bound still clears it is real deep-side work the host
            # path finishes (a handoff, not a spill: the in-ladder
            # search completed on device).  On every eval config the
            # filter kills them all and the round ends here.
            RF.count_deferred(n_def)
            self.stats["resident_deferred"] = (
                self.stats.get("resident_deferred", 0) + n_def)
            deep = RF.unpack_entries(*arrs[3:], 0, n_def, minsup)
            if deep:
                RF.count_handoff()
                self.stats["resident_handoffs"] = (
                    self.stats.get("resident_handoffs", 0) + 1)
                obs.trace_event("resident_handoff", entries=len(deep),
                                minsup=minsup)
                resume = {
                    "minsup": int(minsup),
                    "stack": [[b, list(x), list(y), cr, side, psup,
                               psupx]
                              for b, x, y, cr, side, psup, psupx
                              in deep],
                    "results": [[list(x), list(y), sup, supx]
                                for sup, supx, x, y in results],
                }
                return self._mine_host_restricted(
                    m, resume=resume, checkpoint_cb=checkpoint_cb,
                    every_s=every_s,
                    count_resume=False, prep=(p1, s1))
        return self._finish_round(m, results)

    def _resident_abandon(self, exc, m: int, resume, checkpoint_cb,
                          every_s: float, ev_done: int, pr_done: int,
                          tr_done: int, seg_launches: int, floor: int = 1,
                          ) -> Tuple[List[RuleResult], int]:
        """Abandon a faulted resident round to the host path from its
        ORIGINAL state: the frontier is never lost (roots/resume
        regenerate it exactly) and the re-run recomputes with full
        parity.  Recount, not new work — the abandoned segments'
        evaluations, prunes, traffic AND launches leave the exported
        dispatch-shape stats (the same contract as the kernel
        readback-fault recount in consume()); the resident_* route
        counters stay, with ``resident_fallbacks`` marking why."""
        RF.count_fallback()
        self.stats["resident_fallbacks"] = (
            self.stats.get("resident_fallbacks", 0) + 1)
        self.stats["resident_fallback"] = repr(exc)
        self.stats["evaluated"] -= ev_done
        self.stats["pruned_conf"] -= pr_done
        self.stats["kernel_launches"] -= seg_launches
        self.stats["traffic_units"] = (
            self.stats.get("traffic_units", 0) - tr_done)
        obs.trace_event("resident_fallback",
                        error=f"{type(exc).__name__}: {exc}")
        return self._mine_host_restricted(
            m, resume=resume, checkpoint_cb=checkpoint_cb,
            every_s=every_s, floor=floor)

    def _resident_entries(self, carry, head: int, tail: int, n_rec: int,
                          n_def: int, minsup: int):
        """Read the device frontier + records + deferred children back
        into host tuples (spill and snapshot share this one readback
        path; deferred entries ride along — they are the same tuple
        spelling, one item wider)."""
        arrs = [np.asarray(carry[i]) for i in (0, 1, 2, 3, 4, 5, 8, 9, 10)]
        darrs = ([np.asarray(carry[i]) for i in (19, 20, 21, 22, 23, 24)]
                 if n_def else [])
        nbytes = sum(a.nbytes for a in arrs + darrs)
        RF.count_readback(nbytes)
        self.stats["resident_readback_bytes"] = (
            self.stats.get("resident_readback_bytes", 0) + nbytes)
        self._bill_readback(nbytes)
        entries = RF.unpack_entries(*arrs[:6], head, tail, minsup)
        if n_def:
            entries += RF.unpack_entries(*darrs, 0, n_def, minsup)
        results = RF.unpack_results(*arrs[6:], n_rec, minsup)
        return entries, results

    def _resident_spill(self, m: int, carry, head: int, tail: int,
                        n_rec: int, n_def: int, minsup: int, *,
                        checkpoint_cb, every_s: float,
                        prep=None) -> Tuple[List[RuleResult], int]:
        """Overflow-to-host spill protocol: the intact device frontier
        becomes the host loop's own resume state — entries are the same
        sibling-chain tuples, so no candidate is lost or duplicated and
        the round finishes with exact parity on the ragged-batch path."""
        entries, results = self._resident_entries(carry, head, tail,
                                                  n_rec, n_def, minsup)
        RF.count_spill("capacity")
        self.stats["resident_spills"] = (
            self.stats.get("resident_spills", 0) + 1)
        obs.trace_event("resident_spill", entries=len(entries),
                        results=len(results), minsup=minsup)
        resume = {
            "minsup": int(minsup),
            "stack": [[b, list(x), list(y), cr, side, psup, psupx]
                      for b, x, y, cr, side, psup, psupx in entries],
            "results": [[list(x), list(y), sup, supx]
                        for sup, supx, x, y in results],
        }
        return self._mine_host_restricted(
            m, resume=resume, checkpoint_cb=checkpoint_cb,
            every_s=every_s, count_resume=False, prep=prep)

    def _resident_snapshot(self, m: int, carry, head: int, tail: int,
                           n_rec: int, n_def: int, minsup: int) -> dict:
        """Segment-boundary frontier snapshot in the ONE checkpoint
        format (``frontier_state``): a resident snapshot resumes on the
        host path and vice versa — the kill-restart drill's contract."""
        entries, results = self._resident_entries(carry, head, tail,
                                                  n_rec, n_def, minsup)
        queue = [(-b, x, y, cr, side, psup, psupx)
                 for b, x, y, cr, side, psup, psupx in entries]
        res = [(sup, supx, x, y) for sup, supx, x, y in results]
        return self.frontier_state(queue, res, m, minsup)

    def _finish_round(self, m: int, results: List[tuple],
                      ) -> Tuple[List[RuleResult], int]:
        """Exact end-of-round filter shared with the host loop: s_k =
        k-th largest accepted support, results filtered to >= s_k,
        local indices mapped to canonical global ids."""
        sups = sorted((r[0] for r in results), reverse=True)
        s_k = sups[self.k - 1] if len(sups) >= self.k else 1
        ids = self.vdb.item_ids[self._order[:m]]
        out = [
            (tuple(sorted(int(ids[i]) for i in x)),
             tuple(sorted(int(ids[i]) for i in y)), sup, supx)
            for sup, supx, x, y in results if sup >= s_k
        ]
        return sort_rules(out), s_k

    # ----------------------------------------------------- host route

    def _mine_host_restricted(self, m: int, resume: Optional[dict] = None,
                              checkpoint_cb=None, every_s: float = 30.0,
                              count_resume: bool = True, prep=None,
                              floor: int = 1,
                              ) -> Tuple[List[RuleResult], int]:
        """The classic host-driven round: best-first heap on host,
        ragged super-batched eval dispatches on device.

        ``count_resume=False``: the resume dict is an INTERNAL
        continuation (a resident spill or deep handoff), not a
        persisted checkpoint — ``resumed_nodes`` keeps whatever the
        real resume (if any) recorded.

        ``prep``: the resident round's live engine-layout preps.
        Segment dispatches never donate them (resident_frontier only
        donates the carry), so a spill/handoff continuation reuses
        them instead of paying the round's scatter-build dispatch
        again — jnp path only; the pallas route needs the folded
        kernel layout ``_prep`` builds."""
        sup_it = self._sup_sorted[:m].astype(np.int64)
        if prep is not None and not self.use_pallas:
            p1, s1 = prep
        else:
            p1, s1 = self._prep(m)
        ids = self.vdb.item_ids[self._order[:m]]

        results: List[Tuple[int, int, Tuple[int, ...], Tuple[int, ...]]] = []
        # the partitioned route's conservative global floor is a sound
        # initial threshold (parallel/partition.py: floor <= global s_k
        # always, so nothing prunable here can enter the global top-k);
        # 1 in the classic whole-frontier search
        floor = max(1, int(floor))
        minsup = floor
        sup_sorted: List[int] = []  # ascending supports of accepted rules
        # conf test as exact integer cross-multiply (no per-rule Fraction
        # construction): sup/supx >= num/den — shared by acceptance AND
        # the conf-bound pruning below
        num, den = _conf_frac(self.minconf)

        def s_k_threshold() -> int:
            if len(sup_sorted) < self.k:
                return floor
            return sup_sorted[-self.k]

        # queue: (-bound, X, Y, can_right, side, psup, psupx); X/Y are
        # local index tuples.  No tie-break counter: entries are totally
        # ordered by the tuples themselves, and the FINAL rule set is
        # pop-order independent (the end-of-round s_k filter is exact),
        # so tie order is free to vary.
        #
        # Expansion is LAZY ("sibling chains"): a popped entry re-pushes
        # only its next sibling — the same-parent candidate whose variable
        # item (the LAST of the `side` tuple, 0 = X, 1 = Y) is the next
        # admissible index — instead of a parent eagerly pushing its whole
        # child range.  Items are support-sorted, so sibling bounds
        # min(psup, sup[c]) are NONINCREASING in c: pushing the sibling at
        # pop time can never miss a higher-bound entry, best-first order
        # is preserved exactly, and a sibling whose bound drops below
        # minsup kills the whole remaining chain.  Eager expansion pushed
        # (and later bound-pruned) the full O(jcut) range per accepted
        # candidate — the dominant host cost of large mines.
        #
        # ``psupx`` is the EXACT antecedent support sup(X) for side-1
        # (grow-Y) entries — X is fixed along a right chain, so the
        # parent's evaluated supx stays valid for every sibling — and 0
        # (unknown) for side-0 entries, whose X varies.  It feeds the
        # DYNAMIC-THRESHOLD pruning (pop_batch/chain_push): a
        # right-expansion candidate with bound*den < supx*num can never
        # pass the confidence floor (sup <= bound), and when its X can
        # never grow again the whole right-growing subtree shares that
        # fate — those candidates are never materialized on device.
        # Conf-dead candidates whose X CAN still grow are evaluated
        # normally: their exact sup keeps child bounds tight, so the
        # pruned search explores a subset of the unpruned one, never a
        # superset.
        sup_l = sup_it.tolist()  # python ints: no np-scalar overhead below

        # sup_it is sorted descending, so "items with sup >= minsup" is the
        # prefix [0, jcut) — chains stop there instead of scanning all m
        # items against the sup check.
        def item_cut() -> int:
            return int(np.searchsorted(-sup_it, -minsup, side="right"))

        jcut = item_cut()
        queue: list = []
        push = heapq.heappush

        def chain_push(xf, yf, cr, side, psup, psupx, start):
            """Push the chain entry whose variable item is the first
            admissible index >= start (xf/yf are the FIXED side contents,
            the variable item excluded).  Admissible = not already used in
            the rule and bound >= minsup; bounds are nonincreasing along
            the chain, so a failing bound ends it for good.  When the
            antecedent can never grow again (max_side reached), a side-1
            chain whose bound drops below the confidence floor is dead
            IN FULL — supx is frozen, sup only shrinks along both the
            chain and every right descendant — so it ends here too."""
            fixed = set(xf) | set(yf)
            c = start
            while True:
                if c >= jcut:
                    return
                if c not in fixed:
                    s_c = sup_l[c]
                    b = s_c if s_c < psup else psup
                    if b < minsup:
                        return
                    if (side == 1 and psupx > 0 and b * den < psupx * num
                            and self.max_side is not None
                            and len(xf) >= self.max_side):
                        self.stats["pruned_conf_chains"] = (
                            self.stats.get("pruned_conf_chains", 0) + 1)
                        return
                    break
                c += 1
            if side == 0:
                push(queue, (-b, xf + (c,), yf, cr, 0, psup, 0))
            else:
                push(queue, (-b, xf, yf + (c,), cr, 1, psup, psupx))

        if resume is not None:
            minsup = max(int(resume["minsup"]), floor)
            results = [(int(sup), int(supx), tuple(x), tuple(y))
                       for x, y, sup, supx in resume["results"]
                       if int(sup) >= minsup]
            sup_sorted = sorted(r[0] for r in results)
            jcut = item_cut()
            queue = [(-int(b), tuple(x), tuple(y), bool(cr), int(side),
                      int(psup), int(psupx))
                     for b, x, y, cr, side, psup, psupx in resume["stack"]]
            heapq.heapify(queue)
            if count_resume:
                self.stats["resumed_nodes"] = len(queue)
        else:
            # roots: one right-side chain per item i over partners j != i
            # (bound min(sup_i, sup_j) is nonincreasing in j) — m entries
            # instead of the m^2 of eager enumeration.  X = {i} is fixed,
            # so psupx = sup(i) exactly.  A partitioned engine seeds only
            # its OWNED classes' roots (partition-aware candidate
            # generation: min(X) never changes, so the slice is closed
            # under both expansion directions).
            own = self._owned_mask(m)
            for i in range(m):
                if own is not None and not own[i]:
                    continue
                chain_push((i,), (), True, 1, sup_l[i], sup_l[i], 0)

        def left_viable(x, y):
            """Can the antecedent still grow into an above-threshold
            candidate?  Left expansion adds an admissible index >
            max(X): below jcut every item clears minsup, and the child
            bound min(b, sup_c') then clears it too (both terms do), so
            viability is just 'an unused index remains'.  When this is
            False it is False for EVERY right descendant as well — the
            fixed set only grows and jcut only shrinks — which is what
            makes whole-subtree conf pruning sound."""
            if self.max_side is not None and len(x) >= self.max_side:
                return False
            fixed = set(x) | set(y)
            c = max(x) + 1
            while c < jcut:
                if c not in fixed:
                    return True
                c += 1
            return False

        def pop_batch():
            batch = []
            while queue and len(batch) < self.chunk:
                nb, x, y, cr, side, psup, psupx = queue[0]
                if -nb < minsup:
                    # every remaining entry is bound-pruned, and chain
                    # siblings bound even lower (minsup only rises;
                    # in-flight batches may still push fresh
                    # above-threshold children afterwards, which is fine)
                    queue.clear()
                    break
                heapq.heappop(queue)
                # advance this entry's sibling chain before evaluating it
                if side == 0:
                    chain_push(x[:-1], y, cr, 0, psup, 0, x[-1] + 1)
                else:
                    chain_push(x, y[:-1], cr, 1, psup, psupx, y[-1] + 1)
                # dynamic-threshold pruning: side-1 entries carry the
                # EXACT antecedent support, so sup <= bound < minconf *
                # supx proves this rule can never be accepted.  If the
                # antecedent can also never grow again, every right
                # descendant shares both properties (supx frozen, sup
                # only shrinks, left growth stays impossible) — the
                # WHOLE subtree is dead and the candidate is never
                # materialized on device.  A conf-dead candidate whose X
                # can still grow is evaluated normally instead: its
                # exact sup keeps child bounds tight (expanding from
                # the bound measured 3x the evaluations — looser bounds
                # compound along right chains).
                if (side == 1 and psupx > 0
                        and (-nb) * den < psupx * num
                        and not left_viable(x, y)):
                    self.stats["pruned_conf"] += 1
                    continue
                batch.append((x, y, cr))
            return batch

        def consume(batch, handle):
            nonlocal minsup, results, jcut
            try:
                sups, supxs = self._resolve_eval(handle, len(batch))
            except Exception as exc:
                # A WATCHDOG timeout is not a kernel fault: the device
                # itself is suspect, so re-dispatching here would run
                # unguarded dispatch-side work on a possibly wedged
                # backend AND permanently downgrade the mine on what may
                # be a transient stall.  Fail the launch upward instead —
                # job supervision (the Miner retry) owns the re-run.
                if isinstance(exc, watchdog.WatchdogTimeout):
                    raise
                if isinstance(handle, FZ.EvalWave):
                    # a broker ticket failing means the wave already
                    # exhausted the broker's own degrade ladder (fused
                    # -> per-job solo) on the jnp path — there is no
                    # kernel state to recount; fail the job upward to
                    # Miner supervision like any jnp-only handle
                    raise
                # TPU kernel RUNTIME faults surface at readback (compile/
                # lowering faults were already caught per km bucket at
                # dispatch).  Gate on whether THIS handle involved the
                # kernel path — with PIPELINE_DEPTH>1 several kernel
                # batches are in flight when the first fault lands, and
                # each must be recounted (same contract as
                # spade_tpu._resolve's was_pallas gating); a jnp-only
                # handle failing is a real error.
                if not (len(handle) > 2 and handle[2]):
                    raise
                self.use_pallas = False
                self.stats["pallas_fallback"] = repr(exc)
                obs.trace_event("pallas_fallback", point="readback",
                                error=f"{type(exc).__name__}: {exc}")
                self._ensure_jnp_downgrade()
                if self._chunk_user is None:
                    self.chunk = self._jnp_chunk
                # recount, not new work: the faulted handle's evaluations,
                # its launches AND its per-km fill/borrow counters leave
                # the exported stats (same contract as the dispatch-time
                # fallback's marks) — the jnp re-dispatch recounts all of
                # them
                self.stats["evaluated"] -= len(batch)
                self.stats["kernel_launches"] -= handle[3]
                for sk, dv in (handle[4] if len(handle) > 4 else {}).items():
                    left = self.stats.get(sk, 0) - dv
                    if left:
                        self.stats[sk] = left
                    else:
                        self.stats.pop(sk, None)
                handle = self._dispatch_eval(
                    p1, s1, [(x, y) for x, y, _ in batch])
                sups, supxs = self._resolve_eval(handle, len(batch))
            for (x, y, can_right), sup, supx in zip(
                    batch, sups.tolist(), supxs.tolist()):
                if sup < minsup:
                    continue
                if supx > 0 and sup * den >= supx * num:
                    results.append((sup, supx, x, y))
                    bisect.insort(sup_sorted, sup)
                    new_t = s_k_threshold()
                    if new_t > minsup:
                        minsup = new_t
                        results = [r for r in results if r[0] >= minsup]
                        del sup_sorted[: bisect.bisect_left(sup_sorted, minsup)]
                        jcut = item_cut()
                # expansions: start one left chain (grow X; kills further
                # right expansion) and one right chain (grow Y) — their
                # siblings materialize lazily as the chains are popped.
                # The right chain inherits this rule's exact supx (X is
                # unchanged along it) — the conf-bound pruning input.
                if self.max_side is None or len(x) < self.max_side:
                    chain_push(x, y, False, 0, sup, 0, max(x) + 1)
                if can_right and (self.max_side is None or len(y) < self.max_side):
                    chain_push(x, y, True, 1, sup, supx, max(y) + 1)

        # Pipeline: keep PIPELINE_DEPTH batches in flight so the blocking
        # readback of batch i overlaps the device work of batch i+1 and the
        # host-side heap work below.  Candidates dispatched with a stale
        # (lower) minsup are wasted work at worst, never wrong — sup/conf
        # acceptance and the final s_k filter use exact values.
        inflight: List[Tuple[list, object]] = []
        last_ckpt = time.monotonic()
        while True:
            # deadline/cancel safe point, next to where the watchdog and
            # OOM ladder already live: between launches, one module-
            # global read when no deadline or cancel exists anywhere
            jobctl.check()
            while queue and len(inflight) < self.PIPELINE_DEPTH:
                batch = pop_batch()
                if not batch:
                    break
                handle = self._dispatch_eval(
                    p1, s1, [(x, y) for x, y, _ in batch])
                inflight.append((batch, handle))
            if not inflight:
                break
            consume(*inflight.pop(0))
            if (checkpoint_cb is not None
                    and time.monotonic() - last_ckpt >= every_s):
                while inflight:  # drain for a consistent frontier
                    consume(*inflight.pop(0))
                checkpoint_cb(self.frontier_state(queue, results, m, minsup))
                self.stats["checkpoints"] = self.stats.get("checkpoints", 0) + 1
                last_ckpt = time.monotonic()

        s_k = s_k_threshold()
        # local indices are support-ordered; canonical form sorts by item id
        out = [
            (tuple(sorted(int(ids[i]) for i in x)),
             tuple(sorted(int(ids[i]) for i in y)), sup, supx)
            for sup, supx, x, y in results
        ]
        return sort_rules(out), s_k

    def mine(self, *, resume: Optional[dict] = None, checkpoint_cb=None,
             checkpoint_every_s: float = 30.0) -> List[RuleResult]:
        """Run the top-k search; optionally resumable (SURVEY.md sec 5
        checkpoint row) — TSR mines are the framework's longest jobs, so
        they benefit most from surviving a crash.

        Args mirror SpadeTPU.mine: ``resume`` is a ``frontier_state``
        snapshot (fingerprint must match, ValueError otherwise);
        ``checkpoint_cb`` is called with a snapshot at most every
        ``checkpoint_every_s`` seconds, after draining the in-flight
        pipeline.  A resumed mine restarts at the snapshot's deepening
        round m — earlier (completed) rounds are never replayed.
        """
        if resume is not None:
            fp = resume.get("fingerprint")
            if fp != self.frontier_fingerprint():
                raise ValueError(
                    "frontier checkpoint does not match this engine's "
                    f"(vdb, k, minconf, max_side); checkpointed {fp}, "
                    f"engine {self.frontier_fingerprint()}")
        n_total = self.vdb.n_items
        if resume is not None:
            m = max(1, min(int(resume["m"]), n_total))
        else:
            m = max(1, min(self.item_cap, n_total))
        while True:
            self.stats["deepening_rounds"] += 1
            results, s_k = self._mine_restricted(
                m, resume=resume, checkpoint_cb=checkpoint_cb,
                every_s=checkpoint_every_s)
            resume = None  # only the first (snapshot's) round resumes
            if m >= n_total:
                return results
            next_item_sup = int(self._sup_sorted[m])
            if len(results) >= self.k and next_item_sup < s_k:
                return results
            m = min(m * 2, n_total)


class TsrCPU(TsrTPU):
    """CPU TopSeqRules: the same best-first search and iterative deepening,
    with the bitmap evaluation in NumPy on host (the reference's JVM-local
    miner analog; ``algorithm=TSR`` in the plugin registry, mirroring
    SPADE vs SPADE_TPU).  Shares byte semantics with the device engine via
    ops/bitops_np, so oracle comparisons are exact."""

    PIPELINE_DEPTH = 1  # dispatch is synchronous — nothing to overlap
    _RECORD_SHAPES = False  # host-only mines compile no device geometry
    _RESIDENT_CAPABLE = False  # numpy evaluation: no device frontier

    def __init__(self, *args, **kwargs):
        # never the device kernel — and never probe the JAX backend
        kwargs["use_pallas"] = False
        super().__init__(*args, **kwargs)

    def _round_chunk(self, m: int) -> int:
        # pure-NumPy evaluation: chunk is only the batch granularity of the
        # host loop — never probe the JAX device budget for it
        return self._chunk_user or 8192

    def _prep(self, m: int):
        assert self.mesh is None, "TsrCPU does not shard; use TsrTPU"
        bm = self._host_bitmaps(m)
        return Bnp.prefix_or_incl(bm), Bnp.suffix_or_incl(bm)

    def _dispatch_eval(self, p1, s1, cands):
        n = len(cands)
        sup = np.empty(n, np.int64)
        supx = np.empty(n, np.int64)
        for r, (x, y) in enumerate(cands):
            a = p1[x[0]]
            for i in x[1:]:
                a = a & p1[i]
            c = s1[y[0]]
            for j in y[1:]:
                c = c & s1[j]
            sup[r] = int(Bnp.support(Bnp.shift_up_one(a) & c))
            supx[r] = int(Bnp.support(a))
        self.stats["evaluated"] += n
        return sup, supx

    def _resolve_eval(self, handle, n: int):
        return handle


class TsrPartitioned:
    """Equivalence-class partitioned TSR over a 2-D ``hosts x seq`` mesh.

    The scaling regime the single engine cannot reach: the candidate
    frontier splits by km-prefix equivalence class (a rule's class is
    ``min(X)``, invariant under both expansion directions) across the
    OUTER partition axis, while each partition keeps the classic
    seq-axis shard + ICI ``psum`` on its INNER submesh row.  Each
    partition enumerates ONLY its owned classes — the host-side DFS that
    was duplicated SPMD on every process finally scales with hosts — and
    the only cross-partition traffic is ONE small exchange per deepening
    round (threshold floor + result slices), not a per-wave full-mesh
    ``psum``.

    Exactness (docs/DESIGN.md "Partitioned mining"): each partition's
    dynamic threshold starts at the board's conservative global floor —
    a lower bound on the global s_k, since the global k-th-largest is
    taken over a superset of any partition's results — so per-partition
    pruning removes only candidates that can never enter the global
    top-k; the final merge recomputes the exact global s_k over the
    union and filters, restoring BYTE-IDENTICAL output to the
    single-route mine.  The floor only ever tightens (within a round via
    the sequential in-process schedule, across rounds via the exchanged
    global s_k).  The honest trade: partition-local thresholds rise more
    slowly than the global one, so the partitioned route EVALUATES MORE
    candidates than the classic route at equal output (~2x on the
    kosarak miniature at 2 parts; docs/DESIGN.md) — the floor exchange
    bounds the overspend, and the win is each partition running on its
    own silicon, not fewer evaluations.

    Checkpoints: one composite snapshot per save, carrying the merged
    results (rewrite mode, like the engine's own) plus each partition's
    frontier in the engines' EXISTING ``frontier_state`` format — a
    resumed composite feeds every part exactly the snapshot its engine
    would have written solo.  The fingerprint binds the partition layout
    (plan fingerprint), so a changed parts/classes config restarts
    fresh instead of resuming another layout's slices.
    """

    def __init__(self, vdb: VerticalDB, k: int, minconf: float, *,
                 mesh: Optional[Mesh] = None, parts: int,
                 classes: int = 64, record_metrics: bool = True,
                 **engine_kwargs):
        self.vdb = vdb
        self.k = int(k)
        self.minconf = float(minconf)
        # record_metrics=False (prewarm's synthetic warm mine): the
        # fsm_partition_* business families must not report mines that
        # never happened, nor the warm plan's imbalance
        self._record_metrics = bool(record_metrics)
        self.plan = PN.plan_partitions(vdb.item_ids, vdb.item_supports,
                                       parts, classes,
                                       record=self._record_metrics)
        self.meshes = PN.submeshes(mesh, parts)
        self.owned = PN.owned_parts(self.plan)
        self.item_cap = int(engine_kwargs.get("item_cap",
                                              ITEM_CAP_DEFAULT))
        # kept for degraded-topology rebuilds (service/meshguard.py): an
        # adopted part re-instantiates its engine on the survivor's mesh
        # row with the SAME construction arguments
        self._engine_kwargs = dict(engine_kwargs)
        self.engines: Dict[int, TsrTPU] = {
            p: TsrTPU(vdb, k, minconf, mesh=self.meshes[p],
                      partition=(self.plan, p), **engine_kwargs)
            for p in self.owned}
        # register each partition row's devices with the meshguard so
        # its active probe exercises the same silicon the rows dispatch
        # on (no-op when the plane is off)
        g = MGD.get()
        if g is not None:
            g.register_rows({
                p: (tuple(self.meshes[p].devices.flat)
                    if self.meshes[p] is not None else ())
                for p in self.owned})
        first = self.engines[self.owned[0]]
        self.stats: dict = {
            "partition_parts": int(parts),
            "partition_classes": int(classes),
            "partition_owned": list(self.owned),
            "partition_imbalance": round(self.plan.imbalance_ratio, 4),
            "partition_exchanges": 0,
            "partition_cross_bytes": 0,
            "deepening_rounds": 0,
            "shape_key": shapes.key_tsr_part(
                int(parts), first.n_seq, vdb.n_words),
        }
        if first._RECORD_SHAPES:
            shapes.record(self.stats["shape_key"])
        if self._record_metrics:
            PN.count_mine("tsr")

    def frontier_fingerprint(self) -> dict:
        fp = self.engines[self.owned[0]].frontier_fingerprint()
        fp["partition"] = self.plan.fingerprint()
        return fp

    def _composite(self, m: int, floor: int, done: dict,
                   active_part, active_state) -> dict:
        """One checkpoint for the whole partitioned mine: the shared
        composite schema (parallel/partition.py ``composite_state`` —
        ONE owner for the crash-recovery format) extended with the TSR
        round's (m, floor) so a resume re-enters the right deepening
        round at the right threshold."""
        return PN.composite_state(
            self.frontier_fingerprint(), done, active_part,
            active_state, m=int(m), minsup=int(floor))

    def _mine_round(self, m: int, floor: int, resume: Optional[dict],
                    checkpoint_cb, every_s: float):
        """One deepening round: every owned partition mines its class
        slice (sequentially in-process — the schedule that makes the
        in-round floor tightening free), then ONE cross-partition
        exchange merges result slices and thresholds globally."""
        board = PN.ThresholdBoard(self.k, floor)
        done, active_resume = PN.decode_composite(
            resume, self.frontier_fingerprint())
        for rows_p in done.values():
            board.merge(int(r[2]) for r in rows_p)
        guard = MGD.get()
        for p in self.owned:
            if p in done:
                continue  # completed before the resumed snapshot
            eng = self.engines[p]
            cb = None
            # the part's latest frontier snapshot, kept host-side even
            # with no durable checkpoint sink: a mid-slice row death
            # resumes the ADOPTER from here with the conservative floor
            # carried over, instead of re-mining the slice from scratch
            last = {"fs": active_resume.get(p)}
            if checkpoint_cb is not None or guard is not None:
                def cb(fs, p=p, last=last):
                    last["fs"] = fs
                    if checkpoint_cb is not None:
                        checkpoint_cb(self._composite(
                            m, board.floor(), done, p, fs))
            row, attempts = p, 0
            while True:
                try:
                    res_p, _s_k_p = eng._mine_restricted(
                        m, resume=last["fs"], checkpoint_cb=cb,
                        every_s=every_s, floor=board.floor())
                    if guard is not None:
                        guard.note_row_ok(row)
                    break
                except Exception as exc:
                    if guard is None:
                        raise
                    attempts += 1
                    if attempts >= guard.max_retries:
                        raise  # the mesh is melting, not degrading
                    if isinstance(exc, MGD.StaleTopology):
                        # refused launch, not a device failure: the row
                        # keeps its health — rebuild at the new epoch
                        # (adopting below if OUR row is the dead one)
                        state = guard.state_of(row)
                    else:
                        state = guard.note_row_fault(row, exc)
                        if state is None:
                            raise  # not device-shaped: supervision owns it
                    if state == MGD.DEAD:
                        adopter = PN.adopters_for(
                            self.plan, guard.dead_rows()).get(row)
                        if adopter is None or adopter == row:
                            raise
                        MGD.note_replan(guard.dead_rows())
                        row = adopter
                    # rebuild: fresh topology epoch, and (after an
                    # adoption) the survivor's mesh row — the class
                    # restriction (plan, p) is unchanged, so the
                    # resumed frontier and the final merge are too
                    eng = TsrTPU(self.vdb, self.k, self.minconf,
                                 mesh=self.meshes[row],
                                 partition=(self.plan, p),
                                 **self._engine_kwargs)
                    self.engines[p] = eng
            done[p] = [[list(x), list(y), int(sup), int(supx)]
                       for x, y, sup, supx in res_p]
            board.merge(r[2] for r in done[p])
            if checkpoint_cb is not None:
                # part boundary: the next crash resumes past this slice
                checkpoint_cb(self._composite(m, board.floor(), done,
                                              None, None))
        # contribute ONLY owned parts (see partition.py
        # mine_partitioned_slices: a resumed shared composite carries
        # other processes' slices — re-contributing them would
        # duplicate supports and inflate the merged s_k)
        own = set(self.owned)
        payload = {"floor": board.floor(),
                   "rows": [r for p in sorted(done) if p in own
                            for r in done[p]]}
        gathered = PN.exchange_objects(payload, stats=self.stats,
                                       record=self._record_metrics)
        rows_all = [r for g in gathered for r in g["rows"]]
        # post-exchange floor from a FRESH board over the merged rows:
        # re-merging our own slice into the in-round board would insert
        # every support twice and inflate the "k-th largest" past the
        # true global s_k — an unsound floor that silently prunes real
        # top-k rules in later rounds.  Peer floors are valid lower
        # bounds too (each is a k-th largest over a subset), so fold
        # them in via max.
        out = PN.ThresholdBoard(
            self.k, max([board.floor()]
                        + [int(g.get("floor", 1)) for g in gathered]))
        out.merge(int(r[2]) for r in rows_all)
        return rows_all, out.floor()

    def _merge(self, rows: list) -> Tuple[List[RuleResult], int]:
        """Exact global top-k filter over the union of class slices —
        the step that restores byte-identical output: global s_k is the
        k-th largest support over ALL qualifying rules (each partition's
        floor never exceeded it, so none of them pruned a survivor)."""
        qual = [(tuple(int(i) for i in x), tuple(int(j) for j in y),
                 int(sup), int(supx)) for x, y, sup, supx in rows]
        sups = sorted((r[2] for r in qual), reverse=True)
        s_k = sups[self.k - 1] if len(sups) >= self.k else 1
        return sort_rules([r for r in qual if r[2] >= s_k]), s_k

    def mine(self, *, resume: Optional[dict] = None, checkpoint_cb=None,
             checkpoint_every_s: float = 30.0) -> List[RuleResult]:
        if resume is not None:
            fp = resume.get("fingerprint")
            if fp != self.frontier_fingerprint():
                raise ValueError(
                    "partitioned frontier checkpoint does not match this "
                    f"layout; checkpointed {fp}, engine "
                    f"{self.frontier_fingerprint()}")
        n_total = self.vdb.n_items
        if resume is not None:
            m = max(1, min(int(resume["m"]), n_total))
            floor = max(1, int(resume.get("minsup", 1)))
        else:
            m = max(1, min(self.item_cap, n_total))
            floor = 1
        first = self.engines[self.owned[0]]
        while True:
            self.stats["deepening_rounds"] += 1
            rows, floor = self._mine_round(m, floor, resume,
                                           checkpoint_cb,
                                           checkpoint_every_s)
            resume = None  # only the first (snapshot's) round resumes
            results, s_k = self._merge(rows)
            if m >= n_total:
                break
            # the deepening decision runs on MERGED global state, so
            # every process walks the identical m ladder (the exchange
            # made rows identical everywhere)
            next_item_sup = int(first._sup_sorted[m])
            if len(results) >= self.k and next_item_sup < s_k:
                break
            if len(results) >= self.k:
                # the exact global s_k of round m lower-bounds round
                # 2m's (more items only ADD qualifying rules) — carry it
                # as the next round's floor (monotone tightening)
                floor = max(floor, s_k)
            m = min(m * 2, n_total)
        self._fold_stats()
        return results

    def _fold_stats(self) -> None:
        """Aggregate the per-part engines' numeric counters (launches,
        evaluated, traffic, per-km and per-part families) into the
        orchestrator's stats for the bench/smoke exports."""
        for eng in self.engines.values():
            PN.fold_numeric_stats(
                self.stats, {k: v for k, v in eng.stats.items()
                             if k not in ("shape_key", "partition")})


def mine_tsr_tpu(db: SequenceDB, k: int, minconf: float, *,
                 mesh: Optional[Mesh] = None,
                 stats_out: Optional[dict] = None,
                 checkpoint=None, partition_parts: int = 0,
                 partition_classes: int = 64,
                 **kwargs) -> List[RuleResult]:
    """``checkpoint`` (optional): an object with ``load() -> Optional[dict]``,
    ``save(state)``, and ``every_s`` — a stale/mismatched snapshot is
    ignored (the mine restarts fresh), same contract as mine_spade_tpu.

    ``partition_parts >= 2`` routes the mine through the
    equivalence-class partitioned orchestrator (:class:`TsrPartitioned`;
    ``partition_classes`` sets the class-hash granularity): the mesh
    splits into a 2-D ``parts x seq`` arrangement and candidate work
    scales over the outer axis with byte-identical output.  0/1 (the
    default) is the classic whole-frontier engine, untouched."""
    vdb = build_vertical(db, min_item_support=1)
    if vdb.n_items == 0:
        return []
    if partition_parts and int(partition_parts) > 1:
        eng = TsrPartitioned(vdb, k, minconf, mesh=mesh,
                             parts=int(partition_parts),
                             classes=int(partition_classes), **kwargs)
    else:
        eng = TsrTPU(vdb, k, minconf, mesh=mesh, **kwargs)
    resume, save_cb, every_s = load_checkpoint(
        checkpoint, eng.frontier_fingerprint())
    results = eng.mine(resume=resume, checkpoint_cb=save_cb,
                       checkpoint_every_s=every_s)
    if stats_out is not None:
        stats_out.update(eng.stats)
    return results


def mine_tsr_cpu(db: SequenceDB, k: int, minconf: float, *,
                 stats_out: Optional[dict] = None,
                 checkpoint=None, **kwargs) -> List[RuleResult]:
    vdb = build_vertical(db, min_item_support=1)
    if vdb.n_items == 0:
        return []
    eng = TsrCPU(vdb, k, minconf, **kwargs)
    resume, save_cb, every_s = load_checkpoint(
        checkpoint, eng.frontier_fingerprint())
    results = eng.mine(resume=resume, checkpoint_cb=save_cb,
                       checkpoint_every_s=every_s)
    if stats_out is not None:
        stats_out.update(eng.stats)
    return results
