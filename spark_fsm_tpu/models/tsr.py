"""TSR — top-k sequential rules (TopSeqRules), CPU oracle + TPU engine.

Semantics (SURVEY.md sec 2.4): a rule X ==> Y (X, Y disjoint unordered
itemsets) occurs in a sequence iff every item of X occurs strictly before
every item of Y, i.e. max_x first(x) < min_y last(y).  sup(X=>Y) counts such
sequences; conf = sup(X=>Y) / sup(X).  The miner returns the top-k rules by
support among those with conf >= minconf — tie-inclusive (see
utils/canonical.py), with a dynamically rising internal minsup.

Bitmap formulation (the north star's "TSR reuses the same join/support
kernels"): with A = AND over x in X of prefix_or_incl(id-list(x)) ("all of X
occurred by p") and C = AND over y in Y of suffix_or_incl(id-list(y)) ("all
of Y occur at >= p"), the rule holds in a sequence iff
(shift_up_one(A) & C) != 0, and sup(X) = #sequences with A != 0.  Both
reduce to the engine's AND + per-sequence-any + popcount primitives, so the
TPU path is the same fused VPU chain as SPADE's temporal join, batched over
candidate rules and psum-reduced over the sharded sequence axis.

Search: best-first branch-and-bound over expansions (left = grow X, right =
grow Y, both adding item ids greater than the side's max, right-expanded
rules may still left-expand but not vice versa — the standard duplicate-free
expansion scheme), batch-evaluating candidates on device.  Large alphabets
are handled by iterative deepening over the top-M items by support: a run
restricted to M items is provably complete once sup(item_{M+1}) < s_k.
"""

from __future__ import annotations

import bisect
import functools
import heapq
import itertools
import time
from fractions import Fraction
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import VerticalDB, build_vertical
from spark_fsm_tpu.models._common import (
    bucket_seq, device_hbm_budget, load_checkpoint, next_pow2,
    pad_tokens_pow2)
from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.ops import bitops_np as Bnp
from spark_fsm_tpu.ops import pallas_tsr as PT
from spark_fsm_tpu.parallel import multihost as MH
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, pad_to_multiple, shard_map, store_sharding
from spark_fsm_tpu.utils import shapes
from spark_fsm_tpu.utils.canonical import RuleResult, sort_rules


def tsr_geometry(n_sequences: int, n_words: int, *,
                 mesh: Optional[Mesh] = None, use_pallas: bool = False,
                 shape_buckets: bool = False) -> dict:
    """Static device geometry of a :class:`TsrTPU` (the per-round top-m
    and km-bucket shapes vary by design) — shared by the constructor and
    the shape-key enumerator (utils/shapes.py)."""
    n_seq = int(n_sequences)
    if shape_buckets:
        n_seq = bucket_seq(n_seq)
    n_shards = 1 if mesh is None else mesh.devices.size
    if mesh is not None:
        n_seq = pad_to_multiple(n_seq, n_shards)
    sb = None
    if use_pallas:
        # per-shard seq axis must tile the kernel's seq block, which
        # itself must tile the folded (8, 128) layout
        sb = PT.seq_block(n_words, -(-n_seq // n_shards))
        n_seq = pad_to_multiple(n_seq, n_shards * sb)
    return {"n_seq": n_seq, "sb": sb,
            "shape_key": shapes.key_tsr(n_seq, n_words)}


def conf_ok(sup: int, supx: int, minconf: float) -> bool:
    """Exact confidence test: sup/supx >= minconf (no float division)."""
    num, den = _conf_frac(minconf)
    return supx > 0 and sup * den >= supx * num


_auto_eval_budget = device_hbm_budget  # shared with the SPADE engines

# per-km-bucket stat keys (fill/borrow decomposition, BENCH_SCALE 3 vs
# 3d); dispatch handles carry their deltas so fault recounts are exact
_KM_STAT_PREFIXES = ("evaluated_km", "launches_km", "width_km",
                     "borrowed_km")


@functools.lru_cache(maxsize=64)
def _conf_frac(minconf: float) -> Tuple[int, int]:
    """minconf as an exact (numerator, denominator) for the hot-loop
    integer cross-multiply form of ``conf_ok``."""
    f = Fraction(str(minconf))
    return f.numerator, f.denominator


# ---------------------------------------------------------------------------
# Brute-force oracle (independent ground truth for tiny DBs)
# ---------------------------------------------------------------------------

def rule_counts_direct(db: SequenceDB, x_items: Tuple[int, ...],
                       y_items: Tuple[int, ...]) -> Tuple[int, int]:
    """(sup(X=>Y), sup(X)) by direct first/last-occurrence scanning."""
    sup = supx = 0
    for seq in db:
        first: dict[int, int] = {}
        last: dict[int, int] = {}
        for p, itemset in enumerate(seq):
            for it in itemset:
                first.setdefault(it, p)
                last[it] = p
        if all(x in first for x in x_items):
            supx += 1
            if all(y in last for y in y_items):
                if max(first[x] for x in x_items) < min(last[y] for y in y_items):
                    sup += 1
    return sup, supx


def brute_force_rules(db: SequenceDB, k: int, minconf: float,
                      max_side: int = 2) -> List[RuleResult]:
    """Enumerate every X, Y (sizes <= max_side, disjoint) directly."""
    items = sorted({i for seq in db for itemset in seq for i in itemset})
    qualifying: List[RuleResult] = []
    for nx in range(1, max_side + 1):
        for x in itertools.combinations(items, nx):
            rest = [i for i in items if i not in x]
            for ny in range(1, max_side + 1):
                for y in itertools.combinations(rest, ny):
                    sup, supx = rule_counts_direct(db, x, y)
                    if sup >= 1 and conf_ok(sup, supx, minconf):
                        qualifying.append((x, y, sup, supx))
    if not qualifying:
        return []
    sups = sorted((r[2] for r in qualifying), reverse=True)
    s_k = sups[k - 1] if len(sups) >= k else sups[-1]
    return sort_rules([r for r in qualifying if r[2] >= s_k])


# ---------------------------------------------------------------------------
# TPU engine
# ---------------------------------------------------------------------------

# Jitted kernels are module-level / lru_cached so every TsrTPU instance with
# the same (mesh, shape bucket) shares compiles — jax.jit caches per
# wrapped-function object, and the service builds one engine per /train
# request (see models/spade_tpu._spade_fns for the full reasoning).

@functools.partial(jax.jit, static_argnames=("m", "n_seq", "n_words"))
def _build_prep_single(ti, ts, tw, tm, *, m, n_seq, n_words):
    """Scatter-build the top-m item rows in HBM + prefix/suffix-OR them."""
    z = jnp.zeros((m, n_seq, n_words), jnp.uint32)
    b = z.at[ti, ts, tw].add(tm)  # distinct bits: add == OR
    return B.prefix_or_incl(b), B.suffix_or_incl(b)


@functools.lru_cache(maxsize=16)
def _prep_fn_mesh(mesh: Mesh):
    def body(b):
        return B.prefix_or_incl(b), B.suffix_or_incl(b)

    st = P(None, SEQ_AXIS, None)
    return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(st,), out_specs=(st, st)))


@functools.lru_cache(maxsize=16)
def _kernel_layout_fn(mesh: Optional[Mesh], single: bool):
    """[m, S, W] engine-layout prep rows -> FOLDED kernel layout
    [m+1, S/128, 128] (single-word) / [m+1, W, S/128, 128], with an
    appended ALL-ONES pad row — the AND identity rule_supports points
    unused candidate slots at (see ops/pallas_tsr.py for why the seq
    axis folds to (sublane, lane) tiles)."""
    def body(p):
        pk = jnp.transpose(p, (0, 2, 1))            # [m, W, S]
        m, w, s = pk.shape
        if single:
            pk = pk.reshape(m, s // PT.LANE, PT.LANE)
        else:
            pk = pk.reshape(m, w, s // PT.LANE, PT.LANE)
        ones = jnp.full((1,) + pk.shape[1:], 0xFFFFFFFF, jnp.uint32)
        return jnp.concatenate([pk, ones], axis=0)

    if mesh is None:
        return jax.jit(body)
    st_in = P(None, SEQ_AXIS, None)
    st_out = (P(None, SEQ_AXIS, None) if single
              else P(None, None, SEQ_AXIS, None))
    return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(st_in,), out_specs=st_out))


@functools.lru_cache(maxsize=128)
def _kernel_eval_fn(mesh: Optional[Mesh], km: int, sb: int,
                    interpret: bool, single: bool):
    """Jitted rule_supports launcher (+ psum under a mesh), cached per
    bucket geometry like _eval_kernel."""
    def body(p1k, s1k, xy):
        out = PT.rule_supports(p1k, s1k, xy, km=km, s_block=sb,
                               interpret=interpret)
        if mesh is not None:
            out = jax.lax.psum(out, SEQ_AXIS)
        return out

    if mesh is None:
        return jax.jit(body)
    st = (P(None, SEQ_AXIS, None) if single
          else P(None, None, SEQ_AXIS, None))
    return jax.jit(shard_map(body, mesh=mesh,
                                 in_specs=(st, st, P()), out_specs=P()))


@functools.lru_cache(maxsize=256)
def _eval_kernel(mesh: Optional[Mesh], kmax: int):
    """Jitted rule evaluator for side sizes <= kmax (bucketed compile).

    Candidates arrive PACKED as one [chunk, 2, kmax] int32 array (row 0 = X
    item indices, row 1 = Y, -1 = unused slot) and results leave as one
    [2, chunk] stack — a single host->device transfer and a single
    device->host readback per launch.  On a tunneled TPU each transfer
    costs tens of ms of pure latency, so the 4-upload/2-readback layout
    this replaces paid ~6x the fixed cost per launch.
    """
    FULL = jnp.uint32(0xFFFFFFFF)

    def fold(t, idx):
        acc = None
        for j in range(kmax):
            i = idx[:, j]
            g = jnp.where((i >= 0)[:, None, None], t[jnp.maximum(i, 0)], FULL)
            acc = g if acc is None else acc & g
        return acc

    def body(p1, s1, xy):
        a = fold(p1, xy[:, 0])
        c = fold(s1, xy[:, 1])
        sup = B.support(B.shift_up_one(a) & c)
        supx = B.support(a)
        if mesh is not None:
            sup = jax.lax.psum(sup, SEQ_AXIS)
            supx = jax.lax.psum(supx, SEQ_AXIS)
        return jnp.stack([sup, supx])

    if mesh is None:
        return jax.jit(body)
    st = P(None, SEQ_AXIS, None)
    rep = P()
    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(st, st, rep), out_specs=rep))


class TsrTPU:
    """Batched best-first TopSeqRules over the vertical bitmap DB.

    Args:
      vdb: vertical DB (min_item_support=1 — TSR's internal minsup starts
        at 1 and rises as the top-k heap fills).
      k / minconf: the reference's request params (SURVEY.md sec 2.4).
      item_cap: initial restriction to the top-M items by support for the
        iterative-deepening outer loop.
      max_side: optional cap on |X| and |Y|.
    """

    # batches kept in flight by the mine loop; the device dispatch is
    # async so deeper pipelines hide the readback latency behind later
    # launches (measured on a Kosarak-shaped mine over the TPU tunnel:
    # depth 2 = 14.2s, depth 3 = 9.8s, depth 4 = 9.5s — 3 takes most of
    # the win with the least stale-minsup overspeculation)
    PIPELINE_DEPTH = 3

    # compiled-geometry registry participation (utils/shapes.py); the
    # NumPy TsrCPU subclass opts out — it compiles nothing
    _RECORD_SHAPES = True

    def __init__(
        self,
        vdb: VerticalDB,
        k: int,
        minconf: float,
        *,
        mesh: Optional[Mesh] = None,
        chunk: Optional[int] = None,
        item_cap: int = 256,
        max_side: Optional[int] = None,
        eval_budget_bytes: Optional[int] = None,
        use_pallas="auto",
        shape_buckets: bool = False,
    ):
        self.vdb = vdb
        self.k = int(k)
        self.minconf = float(minconf)
        self.mesh = mesh
        # Multi-host mesh: host-side inputs must become global replicated
        # arrays (see parallel/multihost.py)
        self._multiproc = MH.is_multihost(mesh)
        self._put = functools.partial(MH.host_to_device, mesh)
        self.item_cap = int(item_cap)
        self.max_side = max_side
        self.stats = {"evaluated": 0, "kernel_launches": 0, "deepening_rounds": 0}

        # NEVER materialize vdb.bitmaps here: with a Kosarak-shaped alphabet
        # (~41k items x ~990k sequences) the full dense store is ~160 GB.
        # Each deepening round instead builds ONLY the top-m item rows from
        # the token table (host memory/HBM proportional to m, not n_items).
        # shape_buckets: pow2-bucket the sequence axis so streaming rule
        # windows with drifting geometry reuse compiled programs; padded
        # sequences hold all-zero bitmaps and support nothing.  Same knob
        # as the SPADE engines (models/_common.bucket_seq).  Single-device
        # prep additionally pow2-pads the token arrays (they are traced
        # shapes there — _prep_engine); the mesh branch scatter-builds the
        # [m, S, W] rows on HOST (numpy), so token length never enters
        # tracing and the seq-axis bucket above is the only shape knob.
        self._shape_buckets = bool(shape_buckets)
        self.n_words = vdb.n_words
        # Pallas rule-support kernel (ops/pallas_tsr.py): streams seq
        # blocks through VMEM instead of materializing [chunk, S, W]
        # gather temps, so launches can be dispatch-width-bound instead of
        # HBM-temp-bound.  "auto" = on for a real TPU backend; explicit
        # True runs interpret mode off-TPU (tests); explicit False never
        # probes the backend (the NumPy TsrCPU subclass must not
        # initialize JAX).
        if use_pallas == "auto":
            backend = jax.default_backend()
            self.use_pallas = backend == "tpu"
            self._interpret = backend != "tpu"
        elif use_pallas:
            self.use_pallas = True
            self._interpret = jax.default_backend() != "tpu"
        else:
            self.use_pallas = False
            self._interpret = False
        self._jnp_prep = None   # engine-layout prep for downgraded buckets
        self._jnp_chunk = None  # budget-derived width for those buckets
        self._pallas_bad: set = set()  # km buckets whose kernel failed
        self._round_m = 0
        # Derived static geometry lives in tsr_geometry — shared with the
        # shape-key enumerator (utils/shapes.py); same contract as the
        # SPADE engines' shape_key (per-round top-m and km-bucket shapes
        # vary by design).
        g = tsr_geometry(vdb.n_sequences, self.n_words, mesh=mesh,
                         use_pallas=self.use_pallas,
                         shape_buckets=self._shape_buckets)
        self.n_seq = g["n_seq"]
        if self.use_pallas:
            self._sb = g["sb"]
        self.stats["shape_key"] = g["shape_key"]
        if self._RECORD_SHAPES:  # CPU oracle engines stay out of the
            shapes.record(g["shape_key"])  # compiled-geometry registry

        # Per-launch dispatch latency dominates on remote/tunneled TPUs
        # (~100ms+ each; measured 6x wall-clock win going 256 -> 8192 on a
        # Kosarak-shaped mine), so launches are as WIDE as the per-device
        # eval budget allows.  The budget-derived chunk is computed per
        # deepening round (the prep store grows with m); a caller-supplied
        # chunk pins it.  Empirically the evaluator keeps ~4 live
        # [chunk, S_local, W] uint32 gather temps (verified against the
        # XLA OOM report on v5e: 16384-cand launch = 24G of temps).
        # chunk <= 0 (e.g. tsr_chunk = 0 in a config file) = adaptive sizing
        self._chunk_user = None if not chunk or chunk <= 0 else int(chunk)
        # None = resolve lazily in _round_chunk: probing the device budget
        # initializes the JAX backend, which must not happen for engines
        # that never need it (pinned chunk, or the NumPy TsrCPU subclass)
        self._eval_budget = (None if eval_budget_bytes is None
                             else int(eval_budget_bytes))
        self.chunk = self._chunk_user or 8192
        # tok_item is nondecreasing (build_vertical emits tokens sorted by
        # item), so per-item token ranges are a searchsorted away
        self._tok_starts = np.searchsorted(
            vdb.tok_item, np.arange(vdb.n_items + 1))
        # items sorted by support desc, stable by item id
        order = np.lexsort((vdb.item_ids, -vdb.item_supports))
        self._order = order
        self._sup_sorted = vdb.item_supports[order]

    # ------------------------------------------------------------- kernels

    def _sel_tokens(self, sel: np.ndarray):
        """Token table restricted to the selected items, rows renumbered to
        0..len(sel)-1 (selection order)."""
        starts, vdb = self._tok_starts, self.vdb
        lens = starts[sel + 1] - starts[sel]
        idx = np.concatenate(
            [np.arange(starts[i], starts[i + 1]) for i in sel]
        ) if len(sel) else np.zeros(0, np.int64)
        ti = np.repeat(np.arange(len(sel), dtype=np.int32), lens)
        return ti, vdb.tok_seq[idx], vdb.tok_word[idx], vdb.tok_mask[idx]

    def _host_bitmaps(self, m: int, lo: int = 0,
                      hi: Optional[int] = None) -> np.ndarray:
        """[m, hi-lo, n_words] dense rows for the top-m items over the
        sequence range [lo, hi), host-built from the token slice (memory
        proportional to m and the range, never n_items x n_seq_global)."""
        hi = self.n_seq if hi is None else hi
        ti, ts, tw, tm = self._sel_tokens(self._order[:m])
        bm = np.zeros((m, hi - lo, self.n_words), np.uint32)
        keep = (ts >= lo) & (ts < hi)
        # distinct bits: add == OR
        np.add.at(bm, (ti[keep], ts[keep] - lo, tw[keep]), tm[keep])
        return bm

    def _sharded_bitmaps(self, m: int) -> jax.Array:
        """Multi-host sharded store build: each process materializes ONLY
        its seq-axis slice (replicating the full [m, n_seq, W] store on
        every device would cost D x the sharded footprint and defeat the
        per-device eval-budget sizing)."""
        sharding = store_sharding(self.mesh)
        shape = (m, self.n_seq, self.n_words)
        pidx = jax.process_index()
        slices = sorted(
            (idx[1].start or 0, idx[1].stop or self.n_seq)
            for dev, idx in sharding.devices_indices_map(shape).items()
            if dev.process_index == pidx)
        lo, hi = slices[0][0], slices[-1][1]
        if (hi - lo) != sum(b - a for a, b in slices):
            # non-contiguous addressable shards (exotic device order):
            # fall back to the replicate-and-reshard path
            return self._put(self._host_bitmaps(m))
        return jax.make_array_from_process_local_data(
            sharding, self._host_bitmaps(m, lo, hi))

    def _prep(self, m: int):
        """prefix/suffix-OR id-lists for the top-m items (one jit call).

        Single chip: the [m, n_seq, n_words] store is scatter-built in HBM
        straight from the ~KB-scale token slice and transformed in the same
        jit — the dense rows never exist on host.  Mesh: only the m selected
        rows are host-built, then sharded over the sequence axis.
        """
        p1, s1 = self._prep_engine(m)
        if self.use_pallas:
            # folded kernel layout (all-ones pad row); the engine-layout
            # intermediates are dropped — a downgraded bucket rebuilds
            # them once per round (_dispatch_eval)
            to_k = _kernel_layout_fn(self.mesh, self.n_words == 1)
            return to_k(p1), to_k(s1)
        return p1, s1

    def _prep_engine(self, m: int):
        """Engine-layout ([m, S, W]) prefix/suffix-OR rows."""
        if self.mesh is None:
            ti, ts, tw, tm = self._sel_tokens(self._order[:m])
            if self._shape_buckets:
                # token-array length is a traced shape; see
                # _common.pad_tokens_pow2
                ti, ts, tw, tm = pad_tokens_pow2(ti, ts, tw, tm)
            p1, s1 = _build_prep_single(
                jnp.asarray(ti), jnp.asarray(ts), jnp.asarray(tw),
                jnp.asarray(tm), m=m, n_seq=self.n_seq,
                n_words=self.n_words)
        else:
            if self._multiproc:
                raw = self._sharded_bitmaps(m)
            else:
                raw = jax.device_put(self._host_bitmaps(m),
                                     store_sharding(self.mesh))
            p1, s1 = _prep_fn_mesh(self.mesh)(raw)
        self.stats["kernel_launches"] += 1
        return p1, s1

    def _eval_fn(self, kmax: int):
        return _eval_kernel(self.mesh, kmax)

    def _round_chunk(self, m: int) -> int:
        """Launch width for a deepening round over m items: what the eval
        budget allows after the round's [m, S, W] prefix/suffix stores,
        assuming ~4 live [chunk, S_local, W] uint32 gather temps (the
        XLA-verified factor), floored to a power of two for shape
        bucketing.  The Pallas kernel path holds NO [chunk, S, W] temps
        (seq blocks stream through VMEM), so its width is bounded by
        dispatch cost alone."""
        if self._chunk_user is not None:
            return self._chunk_user
        if self.use_pallas:
            return 8192
        return self._round_chunk_jnp(m)

    def _round_chunk_jnp(self, m: int, resident_preps: int = 1) -> int:
        """Budget-derived width for the jnp gather path.

        ``resident_preps``: prep pairs alive in HBM when the launches
        run — 1 normally; 2 for a kernel-mode mine's downgraded buckets,
        where the kernel-layout pair stays resident next to the rebuilt
        engine-layout one."""
        if self._chunk_user is not None:
            return self._chunk_user
        if self._eval_budget is None:
            dev = (self.mesh.devices.flat[0] if self.mesh is not None
                   else jax.devices()[0])
            self._eval_budget = _auto_eval_budget(dev)
        n_dev = 1 if self.mesh is None else self.mesh.devices.size
        s_local = max(1, self.n_seq // n_dev)
        per_cand = max(1, s_local * self.n_words * 4 * 4)
        prep = resident_preps * 2 * m * s_local * self.n_words * 4
        budget = max(per_cand, self._eval_budget - prep)
        return max(128, min(8192, next_pow2(budget // per_cand + 1) // 2))

    def _dispatch_eval(self, p1, s1,
                       cands: List[Tuple[Tuple[int, ...], Tuple[int, ...]]]):
        """Launch (sup, supx) evaluation for candidate rules (local item
        idx); returns a device handle with the host copy already in
        flight.  ``_resolve_eval`` blocks on it — the split lets the mine
        loop pipeline the next dispatch behind the current readback."""
        n = len(cands)
        launches0 = self.stats["kernel_launches"]  # handle carries its own
        # launch count so a readback-fault recount can discard them (below)
        km_stats0 = {sk: v for sk, v in self.stats.items()
                     if sk.startswith(_KM_STAT_PREFIXES)}
        # Candidates dispatch per side-size bucket (pow2 km), NOT at one
        # batch-wide kmax: the km kernel's live-temp footprint grows with
        # km, so the adaptive width must NARROW as km grows — and
        # narrowing the WHOLE mixed batch for one large-side candidate
        # would multiply the dispatch latency of the small-side majority.
        # Bucketing keeps each candidate at its own bucket's widest safe
        # launch.  The 1/km scale factor is empirical (v5e, 15G budget,
        # Kosarak-shaped S): km=4 at the km=1 width allocated 27.2G and
        # OOMed; km=2 at that width fits (~12.4G, right at the ceiling,
        # with XLA remat fusions in the dump) but measured no faster than
        # half width, so the headroom is kept.  A caller-pinned chunk is
        # honored as-is.
        kms = np.empty(n, np.int32)
        for r, (x, y) in enumerate(cands):
            side = max(len(x), len(y))
            km = 1
            while km < side:
                km *= 2
            kms[r] = km
        # per-bucket accounting (evaluated + padded launch widths land in
        # stats below): the service-default unlimited-side path spreads
        # every dispatch over several km buckets, and these counters are
        # what lets BENCH_SCALE's 3-vs-3d gap be decomposed into candidate
        # mix (irreducible) vs launch underfill (fixable)
        for km_v, cnt in zip(*np.unique(kms, return_counts=True)):
            key = f"evaluated_km{int(km_v)}"
            self.stats[key] = self.stats.get(key, 0) + int(cnt)
        # candidate pools per km bucket; the kernel pass drains them
        # LARGEST km first so each bucket's tail-launch pad lanes can be
        # filled ("borrowed") from the still-unprocessed smaller pools
        remaining: Dict[int, List[int]] = {}
        for r in range(n):
            remaining.setdefault(int(kms[r]), []).append(r)
        parts = []
        cols = np.empty(n, np.int64)  # candidate r -> column in `out`
        used_kernel = False  # any bucket through the Pallas path: a
        base = 0             # readback fault is then recountable
        if self.use_pallas:
            for km in sorted(remaining, reverse=True):
                if km in self._pallas_bad or not remaining[km]:
                    continue
                mark = len(parts)
                launches_mark = self.stats["kernel_launches"]
                km_keys = (f"launches_km{km}", f"width_km{km}",
                           f"borrowed_km{km}")
                km_marks = {kk: self.stats.get(kk) for kk in km_keys}
                undo: List[Tuple[int, int]] = []
                try:
                    base = self._dispatch_kernel_bucket(
                        p1, s1, cands, remaining, km, parts, cols, base,
                        undo)
                    used_kernel = True
                    remaining[km] = []
                except Exception as exc:  # pragma: no cover - device-specific
                    # compile/lowering failures surface at the bucket's
                    # first launch; mark only THIS km bucket bad (other
                    # buckets keep the kernel) and evaluate it via the
                    # jnp path, whose prep/width differ from the kernel's.
                    # The bucket's own candidates are still in its pool;
                    # borrowed ones return to theirs.
                    del parts[mark:]
                    base = sum(p.shape[1] for p in parts)
                    # discarded launches must not stay in the exported
                    # per-job stats — neither the global launch count nor
                    # the per-km fill counters the 3-vs-3d decomposition
                    # reads (the jnp re-evaluation recounts)
                    self.stats["kernel_launches"] = launches_mark
                    for kk, v in km_marks.items():
                        if v is None:
                            self.stats.pop(kk, None)
                        else:
                            self.stats[kk] = v
                    for skm, r in undo:
                        remaining[skm].append(r)
                    self._pallas_bad.add(km)
                    self.stats[f"pallas_fallback_km{km}"] = repr(exc)
        leftover = sorted(km for km, idxs in remaining.items() if idxs)
        if leftover and self.use_pallas:
            # jnp buckets while the kernel path is live: both prep pairs
            # stay resident (see _ensure_jnp_downgrade).  The
            # prep-rebuild launch is REAL retained work — exclude it
            # from this handle's discardable launch delta so a later
            # readback-fault recount cannot subtract it.
            before = self.stats["kernel_launches"]
            self._ensure_jnp_downgrade()
            launches0 += self.stats["kernel_launches"] - before
        for km in leftover:
            pj, sj = self._jnp_prep if self._jnp_prep is not None else (p1, s1)
            fn = self._eval_fn(km)
            cw = self.chunk if not self.use_pallas else self._jnp_chunk
            c = cw if self._chunk_user else max(32, cw // km)
            idxs = remaining[km]
            for lo in range(0, len(idxs), c):
                hi = min(lo + c, len(idxs))
                xy = np.full((c, 2, km), -1, np.int32)
                for j, r in enumerate(idxs[lo:hi]):
                    x, y = cands[r]
                    xy[j, 0, :len(x)] = x
                    xy[j, 1, :len(y)] = y
                cols[idxs[lo:hi]] = base + np.arange(hi - lo)
                base += c
                parts.append(fn(pj, sj, self._put(xy)))
                self.stats["kernel_launches"] += 1
            remaining[km] = []
        self.stats["evaluated"] += n
        out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        try:
            out.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass  # method unavailable on this backend
        # the handle also carries this dispatch's per-km counter DELTAS,
        # so a readback-fault recount can subtract them exactly — the
        # fill/borrow decomposition must not keep discarded launches
        # (km keys are never REMOVED during a dispatch — the bucket-
        # failure handler only pops keys absent at bucket start — so the
        # current key set covers every delta)
        km_delta = {sk: self.stats[sk] - km_stats0.get(sk, 0)
                    for sk in self.stats
                    if sk.startswith(_KM_STAT_PREFIXES)
                    and self.stats[sk] != km_stats0.get(sk, 0)}
        return (out, cols, used_kernel,
                self.stats["kernel_launches"] - launches0, km_delta)

    def _ensure_jnp_downgrade(self) -> None:
        """Build the engine-layout prep + budget width the jnp evaluator
        needs after a kernel-path downgrade (the kernel path keeps
        folded-layout preps and kernel-sized chunks).  Shared by the
        per-bucket dispatch fallback and the readback recount so the two
        downgrade paths cannot drift in sizing or layout."""
        if self._jnp_prep is None:
            self._jnp_prep = self._prep_engine(self._round_m)
            self._jnp_chunk = self._round_chunk_jnp(self._round_m,
                                                    resident_preps=2)

    def _bucket_seq_block(self, km: int) -> int:
        """Per-bucket kernel seq block: halve the engine block until the
        bucket's 2*km double-buffered row blocks fit the scoped-VMEM
        budget (large-km buckets of unlimited-side mines would otherwise
        fail to compile); halving preserves the (8,128)-tile and
        S-divisibility invariants."""
        sb = self._sb
        need = lambda b: 2 * km * 2 * self.n_words * b * 4
        while (need(sb) > PT._VMEM_BUDGET and sb % 2 == 0
               and (sb // 2) % (8 * PT.LANE) == 0):
            sb //= 2
        return sb

    def _dispatch_kernel_bucket(self, p1k, s1k, cands, remaining, km,
                                parts, cols, base, undo):
        """Pallas-path dispatch for one km bucket: full launch width (the
        kernel streams seq blocks through VMEM — no [chunk, S, W] gather
        temps to narrow for), candidate count padded to the out-block lane
        width.  Appends to parts/cols and returns the advanced base.

        Pad BORROWING closes the launch-underfill gap (BENCH_SCALE 3d
        per_km: 61-78% fill at km>=2): a pad lane streams exactly the
        same seq blocks as a real lane, so tail-launch pads are filled
        with candidates from the smaller-km pools (largest km first —
        each filled lane saves that candidate's lane at its own km for
        free; a side of length <= skm < km trivially fits the km-wide
        layout).  ``undo`` records (km, candidate) borrows so a
        bucket-level compile failure restores the pools."""
        fn = _kernel_eval_fn(self.mesh, km, self._bucket_seq_block(km),
                             self._interpret, self.n_words == 1)
        c = self.chunk
        mine = remaining[km]
        lo = 0
        while lo < len(mine):
            rem = len(mine) - lo
            # Greedy pow2 split instead of one over-padded launch: the
            # kernel's wall is ~linear in the PADDED width (every lane
            # streams its km seq blocks).  Take the largest pow2 <=
            # remaining (capped at chunk) while >= 1024 — 100% fill —
            # then one padded tail launch.  Widths stay the same pow2
            # set, so no new kernel compiles.
            if rem >= 1024:
                take = min(c, 1 << (rem.bit_length() - 1))
            else:
                take = rem
            rows = list(mine[lo:lo + take])
            width = max(PT.C_LANES, next_pow2(take))
            pad = width - len(rows)
            if pad:
                for skm in sorted((k for k in remaining if k < km),
                                  reverse=True):
                    pool = remaining[skm]
                    while pad > 0 and pool:
                        r = pool.pop()
                        undo.append((skm, r))
                        rows.append(r)
                        pad -= 1
                    if pad == 0:
                        break
            xy = np.full((width, 2, km), -1, np.int32)
            for j, r in enumerate(rows):
                x, y = cands[r]
                xy[j, 0, :len(x)] = x
                xy[j, 1, :len(y)] = y
            part = fn(p1k, s1k, self._put(xy))
            self.stats["kernel_launches"] += 1
            lk = f"launches_km{km}"
            wk = f"width_km{km}"
            self.stats[lk] = self.stats.get(lk, 0) + 1
            self.stats[wk] = self.stats.get(wk, 0) + width
            if len(rows) > take:
                bk = f"borrowed_km{km}"
                self.stats[bk] = self.stats.get(bk, 0) + len(rows) - take
            cols[rows] = base + np.arange(len(rows))
            base += width
            parts.append(part)
            lo += take
        return base

    def _resolve_eval(self, handle, n: int):
        out, cols = handle[0], handle[1]
        arr = np.asarray(out)
        return arr[0, cols].astype(np.int64), arr[1, cols].astype(np.int64)

    # --------------------------------------------------------- checkpoints

    def frontier_fingerprint(self) -> dict:
        """Identity a frontier checkpoint binds to (SURVEY.md sec 5
        checkpoint row, same contract as SpadeTPU.frontier_fingerprint):
        queue entries hold support-order LOCAL item indices, which are
        only meaningful for the exact same (vdb, k, minconf, max_side) —
        a changed search must restart fresh, not resume garbage."""
        ids = self.vdb.item_ids
        return {
            "algo": "tsr",
            "stack_format": 2,  # 2 = lazy sibling-chain entries
            "k": self.k,
            "minconf": float(self.minconf),
            "max_side": self.max_side,
            "n_items": int(self.vdb.n_items),
            "n_sequences": int(self.vdb.n_sequences),
            "item_ids_head": [int(i) for i in ids[:8]],
            "item_ids_sum": int(ids.astype(np.int64).sum()),
        }

    def frontier_state(self, queue, results, m: int, minsup: int) -> dict:
        """JSON-able snapshot of a paused best-first round.

        Unlike the SPADE engines' append-only result deltas, a TSR round's
        accepted-rule set SHRINKS when the internal minsup rises, so every
        snapshot carries the FULL current set (``results_done=0`` makes
        StoreCheckpoint rewrite its list rather than append).  Bound-pruned
        queue entries (< minsup) are dropped — pop_batch would discard
        them anyway — keeping snapshots proportional to the live frontier.
        """
        return {
            "version": 1,
            "fingerprint": self.frontier_fingerprint(),
            "m": int(m),
            "minsup": int(minsup),
            "stack": [[int(-nb), [int(i) for i in x], [int(j) for j in y],
                       bool(cr), int(side), int(psup)]
                      for nb, x, y, cr, side, psup in queue
                      if -nb >= minsup],
            "results_done": 0,
            "results": [[[int(i) for i in x], [int(j) for j in y],
                         int(sup), int(supx)]
                        for sup, supx, x, y in results],
        }

    # ---------------------------------------------------------------- mine

    def _mine_restricted(self, m: int, resume: Optional[dict] = None,
                         checkpoint_cb=None,
                         every_s: float = 30.0) -> Tuple[List[RuleResult], int]:
        """Full search over the top-m items; returns (results, s_k)."""
        self.chunk = self._round_chunk(m)
        self._round_m = m
        self._jnp_prep = None  # cleared per round (downgrade state is stale)
        sup_it = self._sup_sorted[:m].astype(np.int64)
        p1, s1 = self._prep(m)
        ids = self.vdb.item_ids[self._order[:m]]

        results: List[Tuple[int, int, Tuple[int, ...], Tuple[int, ...]]] = []
        minsup = 1
        sup_sorted: List[int] = []  # ascending supports of accepted rules

        def s_k_threshold() -> int:
            if len(sup_sorted) < self.k:
                return 1
            return sup_sorted[-self.k]

        # queue: (-bound, X, Y, can_right, side, psup); X/Y are local index
        # tuples.  No tie-break counter: entries are totally ordered by the
        # tuples themselves, and the FINAL rule set is pop-order
        # independent (the end-of-round s_k filter is exact), so tie order
        # is free to vary.
        #
        # Expansion is LAZY ("sibling chains"): a popped entry re-pushes
        # only its next sibling — the same-parent candidate whose variable
        # item (the LAST of the `side` tuple, 0 = X, 1 = Y) is the next
        # admissible index — instead of a parent eagerly pushing its whole
        # child range.  Items are support-sorted, so sibling bounds
        # min(psup, sup[c]) are NONINCREASING in c: pushing the sibling at
        # pop time can never miss a higher-bound entry, best-first order
        # is preserved exactly, and a sibling whose bound drops below
        # minsup kills the whole remaining chain.  Eager expansion pushed
        # (and later bound-pruned) the full O(jcut) range per accepted
        # candidate — the dominant host cost of large mines.
        sup_l = sup_it.tolist()  # python ints: no np-scalar overhead below

        # sup_it is sorted descending, so "items with sup >= minsup" is the
        # prefix [0, jcut) — chains stop there instead of scanning all m
        # items against the sup check.
        def item_cut() -> int:
            return int(np.searchsorted(-sup_it, -minsup, side="right"))

        jcut = item_cut()
        queue: list = []
        push = heapq.heappush

        def chain_push(xf, yf, cr, side, psup, start):
            """Push the chain entry whose variable item is the first
            admissible index >= start (xf/yf are the FIXED side contents,
            the variable item excluded).  Admissible = not already used in
            the rule and bound >= minsup; bounds are nonincreasing along
            the chain, so a failing bound ends it for good."""
            fixed = set(xf) | set(yf)
            c = start
            while True:
                if c >= jcut:
                    return
                if c not in fixed:
                    s_c = sup_l[c]
                    b = s_c if s_c < psup else psup
                    if b < minsup:
                        return
                    break
                c += 1
            if side == 0:
                push(queue, (-b, xf + (c,), yf, cr, 0, psup))
            else:
                push(queue, (-b, xf, yf + (c,), cr, 1, psup))

        if resume is not None:
            minsup = int(resume["minsup"])
            results = [(int(sup), int(supx), tuple(x), tuple(y))
                       for x, y, sup, supx in resume["results"]]
            sup_sorted = sorted(r[0] for r in results)
            jcut = item_cut()
            queue = [(-int(b), tuple(x), tuple(y), bool(cr), int(side),
                      int(psup))
                     for b, x, y, cr, side, psup in resume["stack"]]
            heapq.heapify(queue)
            self.stats["resumed_nodes"] = len(queue)
        else:
            # roots: one right-side chain per item i over partners j != i
            # (bound min(sup_i, sup_j) is nonincreasing in j) — m entries
            # instead of the m^2 of eager enumeration
            for i in range(m):
                chain_push((i,), (), True, 1, sup_l[i], 0)

        def pop_batch():
            batch = []
            while queue and len(batch) < self.chunk:
                nb, x, y, cr, side, psup = queue[0]
                if -nb < minsup:
                    # every remaining entry is bound-pruned, and chain
                    # siblings bound even lower (minsup only rises;
                    # in-flight batches may still push fresh
                    # above-threshold children afterwards, which is fine)
                    queue.clear()
                    break
                heapq.heappop(queue)
                # advance this entry's sibling chain before evaluating it
                if side == 0:
                    chain_push(x[:-1], y, cr, 0, psup, x[-1] + 1)
                else:
                    chain_push(x, y[:-1], cr, 1, psup, y[-1] + 1)
                batch.append((x, y, cr))
            return batch

        def consume(batch, handle):
            nonlocal minsup, results, jcut
            try:
                sups, supxs = self._resolve_eval(handle, len(batch))
            except Exception as exc:
                # TPU kernel RUNTIME faults surface at readback (compile/
                # lowering faults were already caught per km bucket at
                # dispatch).  Gate on whether THIS handle involved the
                # kernel path — with PIPELINE_DEPTH>1 several kernel
                # batches are in flight when the first fault lands, and
                # each must be recounted (same contract as
                # spade_tpu._resolve's was_pallas gating); a jnp-only
                # handle failing is a real error.
                if not (len(handle) > 2 and handle[2]):
                    raise
                self.use_pallas = False
                self.stats["pallas_fallback"] = repr(exc)
                self._ensure_jnp_downgrade()
                if self._chunk_user is None:
                    self.chunk = self._jnp_chunk
                # recount, not new work: the faulted handle's evaluations,
                # its launches AND its per-km fill/borrow counters leave
                # the exported stats (same contract as the dispatch-time
                # fallback's marks) — the jnp re-dispatch recounts all of
                # them
                self.stats["evaluated"] -= len(batch)
                self.stats["kernel_launches"] -= handle[3]
                for sk, dv in (handle[4] if len(handle) > 4 else {}).items():
                    left = self.stats.get(sk, 0) - dv
                    if left:
                        self.stats[sk] = left
                    else:
                        self.stats.pop(sk, None)
                handle = self._dispatch_eval(
                    p1, s1, [(x, y) for x, y, _ in batch])
                sups, supxs = self._resolve_eval(handle, len(batch))
            # conf test as exact integer cross-multiply (no per-rule
            # Fraction construction): sup/supx >= num/den
            num, den = _conf_frac(self.minconf)
            for (x, y, can_right), sup, supx in zip(
                    batch, sups.tolist(), supxs.tolist()):
                if sup < minsup:
                    continue
                if supx > 0 and sup * den >= supx * num:
                    results.append((sup, supx, x, y))
                    bisect.insort(sup_sorted, sup)
                    new_t = s_k_threshold()
                    if new_t > minsup:
                        minsup = new_t
                        results = [r for r in results if r[0] >= minsup]
                        del sup_sorted[: bisect.bisect_left(sup_sorted, minsup)]
                        jcut = item_cut()
                # expansions: start one left chain (grow X; kills further
                # right expansion) and one right chain (grow Y) — their
                # siblings materialize lazily as the chains are popped
                if self.max_side is None or len(x) < self.max_side:
                    chain_push(x, y, False, 0, sup, max(x) + 1)
                if can_right and (self.max_side is None or len(y) < self.max_side):
                    chain_push(x, y, True, 1, sup, max(y) + 1)

        # Pipeline: keep PIPELINE_DEPTH batches in flight so the blocking
        # readback of batch i overlaps the device work of batch i+1 and the
        # host-side heap work below.  Candidates dispatched with a stale
        # (lower) minsup are wasted work at worst, never wrong — sup/conf
        # acceptance and the final s_k filter use exact values.
        inflight: List[Tuple[list, object]] = []
        last_ckpt = time.monotonic()
        while True:
            while queue and len(inflight) < self.PIPELINE_DEPTH:
                batch = pop_batch()
                if not batch:
                    break
                handle = self._dispatch_eval(
                    p1, s1, [(x, y) for x, y, _ in batch])
                inflight.append((batch, handle))
            if not inflight:
                break
            consume(*inflight.pop(0))
            if (checkpoint_cb is not None
                    and time.monotonic() - last_ckpt >= every_s):
                while inflight:  # drain for a consistent frontier
                    consume(*inflight.pop(0))
                checkpoint_cb(self.frontier_state(queue, results, m, minsup))
                self.stats["checkpoints"] = self.stats.get("checkpoints", 0) + 1
                last_ckpt = time.monotonic()

        s_k = s_k_threshold()
        # local indices are support-ordered; canonical form sorts by item id
        out = [
            (tuple(sorted(int(ids[i]) for i in x)),
             tuple(sorted(int(ids[i]) for i in y)), sup, supx)
            for sup, supx, x, y in results
        ]
        return sort_rules(out), s_k

    def mine(self, *, resume: Optional[dict] = None, checkpoint_cb=None,
             checkpoint_every_s: float = 30.0) -> List[RuleResult]:
        """Run the top-k search; optionally resumable (SURVEY.md sec 5
        checkpoint row) — TSR mines are the framework's longest jobs, so
        they benefit most from surviving a crash.

        Args mirror SpadeTPU.mine: ``resume`` is a ``frontier_state``
        snapshot (fingerprint must match, ValueError otherwise);
        ``checkpoint_cb`` is called with a snapshot at most every
        ``checkpoint_every_s`` seconds, after draining the in-flight
        pipeline.  A resumed mine restarts at the snapshot's deepening
        round m — earlier (completed) rounds are never replayed.
        """
        if resume is not None:
            fp = resume.get("fingerprint")
            if fp != self.frontier_fingerprint():
                raise ValueError(
                    "frontier checkpoint does not match this engine's "
                    f"(vdb, k, minconf, max_side); checkpointed {fp}, "
                    f"engine {self.frontier_fingerprint()}")
        n_total = self.vdb.n_items
        if resume is not None:
            m = max(1, min(int(resume["m"]), n_total))
        else:
            m = max(1, min(self.item_cap, n_total))
        while True:
            self.stats["deepening_rounds"] += 1
            results, s_k = self._mine_restricted(
                m, resume=resume, checkpoint_cb=checkpoint_cb,
                every_s=checkpoint_every_s)
            resume = None  # only the first (snapshot's) round resumes
            if m >= n_total:
                return results
            next_item_sup = int(self._sup_sorted[m])
            if len(results) >= self.k and next_item_sup < s_k:
                return results
            m = min(m * 2, n_total)


class TsrCPU(TsrTPU):
    """CPU TopSeqRules: the same best-first search and iterative deepening,
    with the bitmap evaluation in NumPy on host (the reference's JVM-local
    miner analog; ``algorithm=TSR`` in the plugin registry, mirroring
    SPADE vs SPADE_TPU).  Shares byte semantics with the device engine via
    ops/bitops_np, so oracle comparisons are exact."""

    PIPELINE_DEPTH = 1  # dispatch is synchronous — nothing to overlap
    _RECORD_SHAPES = False  # host-only mines compile no device geometry

    def __init__(self, *args, **kwargs):
        # never the device kernel — and never probe the JAX backend
        kwargs["use_pallas"] = False
        super().__init__(*args, **kwargs)

    def _round_chunk(self, m: int) -> int:
        # pure-NumPy evaluation: chunk is only the batch granularity of the
        # host loop — never probe the JAX device budget for it
        return self._chunk_user or 8192

    def _prep(self, m: int):
        assert self.mesh is None, "TsrCPU does not shard; use TsrTPU"
        bm = self._host_bitmaps(m)
        return Bnp.prefix_or_incl(bm), Bnp.suffix_or_incl(bm)

    def _dispatch_eval(self, p1, s1, cands):
        n = len(cands)
        sup = np.empty(n, np.int64)
        supx = np.empty(n, np.int64)
        for r, (x, y) in enumerate(cands):
            a = p1[x[0]]
            for i in x[1:]:
                a = a & p1[i]
            c = s1[y[0]]
            for j in y[1:]:
                c = c & s1[j]
            sup[r] = int(Bnp.support(Bnp.shift_up_one(a) & c))
            supx[r] = int(Bnp.support(a))
        self.stats["evaluated"] += n
        return sup, supx

    def _resolve_eval(self, handle, n: int):
        return handle


def mine_tsr_tpu(db: SequenceDB, k: int, minconf: float, *,
                 mesh: Optional[Mesh] = None,
                 stats_out: Optional[dict] = None,
                 checkpoint=None, **kwargs) -> List[RuleResult]:
    """``checkpoint`` (optional): an object with ``load() -> Optional[dict]``,
    ``save(state)``, and ``every_s`` — a stale/mismatched snapshot is
    ignored (the mine restarts fresh), same contract as mine_spade_tpu."""
    vdb = build_vertical(db, min_item_support=1)
    if vdb.n_items == 0:
        return []
    eng = TsrTPU(vdb, k, minconf, mesh=mesh, **kwargs)
    resume, save_cb, every_s = load_checkpoint(
        checkpoint, eng.frontier_fingerprint())
    results = eng.mine(resume=resume, checkpoint_cb=save_cb,
                       checkpoint_every_s=every_s)
    if stats_out is not None:
        stats_out.update(eng.stats)
    return results


def mine_tsr_cpu(db: SequenceDB, k: int, minconf: float, *,
                 stats_out: Optional[dict] = None,
                 checkpoint=None, **kwargs) -> List[RuleResult]:
    vdb = build_vertical(db, min_item_support=1)
    if vdb.n_items == 0:
        return []
    eng = TsrCPU(vdb, k, minconf, **kwargs)
    resume, save_cb, every_s = load_checkpoint(
        checkpoint, eng.frontier_fingerprint())
    results = eng.mine(resume=resume, checkpoint_cb=save_cb,
                       checkpoint_every_s=every_s)
    if stats_out is not None:
        stats_out.update(eng.stats)
    return results
