"""TSR — top-k sequential rules (TopSeqRules), CPU oracle + TPU engine.

Semantics (SURVEY.md sec 2.4): a rule X ==> Y (X, Y disjoint unordered
itemsets) occurs in a sequence iff every item of X occurs strictly before
every item of Y, i.e. max_x first(x) < min_y last(y).  sup(X=>Y) counts such
sequences; conf = sup(X=>Y) / sup(X).  The miner returns the top-k rules by
support among those with conf >= minconf — tie-inclusive (see
utils/canonical.py), with a dynamically rising internal minsup.

Bitmap formulation (the north star's "TSR reuses the same join/support
kernels"): with A = AND over x in X of prefix_or_incl(id-list(x)) ("all of X
occurred by p") and C = AND over y in Y of suffix_or_incl(id-list(y)) ("all
of Y occur at >= p"), the rule holds in a sequence iff
(shift_up_one(A) & C) != 0, and sup(X) = #sequences with A != 0.  Both
reduce to the engine's AND + per-sequence-any + popcount primitives, so the
TPU path is the same fused VPU chain as SPADE's temporal join, batched over
candidate rules and psum-reduced over the sharded sequence axis.

Search: best-first branch-and-bound over expansions (left = grow X, right =
grow Y, both adding item ids greater than the side's max, right-expanded
rules may still left-expand but not vice versa — the standard duplicate-free
expansion scheme), batch-evaluating candidates on device.  Large alphabets
are handled by iterative deepening over the top-M items by support: a run
restricted to M items is provably complete once sup(item_{M+1}) < s_k.
"""

from __future__ import annotations

import bisect
import functools
import heapq
import itertools
from fractions import Fraction
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import VerticalDB, build_vertical
from spark_fsm_tpu.models._common import next_pow2
from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.ops import bitops_np as Bnp
from spark_fsm_tpu.parallel import multihost as MH
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, pad_to_multiple, store_sharding
from spark_fsm_tpu.utils.canonical import RuleResult, sort_rules


def conf_ok(sup: int, supx: int, minconf: float) -> bool:
    """Exact confidence test: sup/supx >= minconf (no float division)."""
    return supx > 0 and Fraction(sup, supx) >= Fraction(str(minconf))


# ---------------------------------------------------------------------------
# Brute-force oracle (independent ground truth for tiny DBs)
# ---------------------------------------------------------------------------

def rule_counts_direct(db: SequenceDB, x_items: Tuple[int, ...],
                       y_items: Tuple[int, ...]) -> Tuple[int, int]:
    """(sup(X=>Y), sup(X)) by direct first/last-occurrence scanning."""
    sup = supx = 0
    for seq in db:
        first: dict[int, int] = {}
        last: dict[int, int] = {}
        for p, itemset in enumerate(seq):
            for it in itemset:
                first.setdefault(it, p)
                last[it] = p
        if all(x in first for x in x_items):
            supx += 1
            if all(y in last for y in y_items):
                if max(first[x] for x in x_items) < min(last[y] for y in y_items):
                    sup += 1
    return sup, supx


def brute_force_rules(db: SequenceDB, k: int, minconf: float,
                      max_side: int = 2) -> List[RuleResult]:
    """Enumerate every X, Y (sizes <= max_side, disjoint) directly."""
    items = sorted({i for seq in db for itemset in seq for i in itemset})
    qualifying: List[RuleResult] = []
    for nx in range(1, max_side + 1):
        for x in itertools.combinations(items, nx):
            rest = [i for i in items if i not in x]
            for ny in range(1, max_side + 1):
                for y in itertools.combinations(rest, ny):
                    sup, supx = rule_counts_direct(db, x, y)
                    if sup >= 1 and conf_ok(sup, supx, minconf):
                        qualifying.append((x, y, sup, supx))
    if not qualifying:
        return []
    sups = sorted((r[2] for r in qualifying), reverse=True)
    s_k = sups[k - 1] if len(sups) >= k else sups[-1]
    return sort_rules([r for r in qualifying if r[2] >= s_k])


# ---------------------------------------------------------------------------
# TPU engine
# ---------------------------------------------------------------------------

# Jitted kernels are module-level / lru_cached so every TsrTPU instance with
# the same (mesh, shape bucket) shares compiles — jax.jit caches per
# wrapped-function object, and the service builds one engine per /train
# request (see models/spade_tpu._spade_fns for the full reasoning).

@functools.partial(jax.jit, static_argnames=("m", "n_seq", "n_words"))
def _build_prep_single(ti, ts, tw, tm, *, m, n_seq, n_words):
    """Scatter-build the top-m item rows in HBM + prefix/suffix-OR them."""
    z = jnp.zeros((m, n_seq, n_words), jnp.uint32)
    b = z.at[ti, ts, tw].add(tm)  # distinct bits: add == OR
    return B.prefix_or_incl(b), B.suffix_or_incl(b)


@functools.lru_cache(maxsize=16)
def _prep_fn_mesh(mesh: Mesh):
    def body(b):
        return B.prefix_or_incl(b), B.suffix_or_incl(b)

    st = P(None, SEQ_AXIS, None)
    return jax.jit(jax.shard_map(body, mesh=mesh,
                                 in_specs=(st,), out_specs=(st, st)))


@functools.lru_cache(maxsize=256)
def _eval_kernel(mesh: Optional[Mesh], kmax: int):
    """Jitted rule evaluator for side sizes <= kmax (bucketed compile)."""
    FULL = jnp.uint32(0xFFFFFFFF)

    def fold(t, idx, valid):
        acc = None
        for j in range(kmax):
            g = jnp.where(valid[:, j, None, None], t[idx[:, j]], FULL)
            acc = g if acc is None else acc & g
        return acc

    def body(p1, s1, xs, xv, ys, yv):
        a = fold(p1, xs, xv)
        c = fold(s1, ys, yv)
        sup = B.support(B.shift_up_one(a) & c)
        supx = B.support(a)
        if mesh is not None:
            sup = jax.lax.psum(sup, SEQ_AXIS)
            supx = jax.lax.psum(supx, SEQ_AXIS)
        return sup, supx

    if mesh is None:
        return jax.jit(body)
    st = P(None, SEQ_AXIS, None)
    rep = P()
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(st, st, rep, rep, rep, rep), out_specs=(rep, rep)))


class TsrTPU:
    """Batched best-first TopSeqRules over the vertical bitmap DB.

    Args:
      vdb: vertical DB (min_item_support=1 — TSR's internal minsup starts
        at 1 and rises as the top-k heap fills).
      k / minconf: the reference's request params (SURVEY.md sec 2.4).
      item_cap: initial restriction to the top-M items by support for the
        iterative-deepening outer loop.
      max_side: optional cap on |X| and |Y|.
    """

    def __init__(
        self,
        vdb: VerticalDB,
        k: int,
        minconf: float,
        *,
        mesh: Optional[Mesh] = None,
        chunk: Optional[int] = None,
        item_cap: int = 256,
        max_side: Optional[int] = None,
        eval_budget_bytes: int = 4 << 30,
    ):
        self.vdb = vdb
        self.k = int(k)
        self.minconf = float(minconf)
        self.mesh = mesh
        # Multi-host mesh: host-side inputs must become global replicated
        # arrays (see parallel/multihost.py)
        self._multiproc = MH.is_multihost(mesh)
        self._put = functools.partial(MH.host_to_device, mesh)
        self.item_cap = int(item_cap)
        self.max_side = max_side
        self.stats = {"evaluated": 0, "kernel_launches": 0, "deepening_rounds": 0}

        # NEVER materialize vdb.bitmaps here: with a Kosarak-shaped alphabet
        # (~41k items x ~990k sequences) the full dense store is ~160 GB.
        # Each deepening round instead builds ONLY the top-m item rows from
        # the token table (host memory/HBM proportional to m, not n_items).
        self.n_seq = vdb.n_sequences
        if mesh is not None:
            self.n_seq = pad_to_multiple(self.n_seq, mesh.devices.size)
        self.n_words = vdb.n_words

        if chunk is None:
            # Per-launch dispatch latency dominates on remote/tunneled TPUs
            # (~100ms+ each; measured 6x wall-clock win going 256 -> 8192
            # on a Kosarak-shaped mine), so make launches as WIDE as the
            # per-device eval budget allows: the evaluator keeps ~4 live
            # [chunk, S_local, W] uint32 intermediates.  Pow2 so the eval
            # fn's compiled shapes stay bucketed.
            s_local = self.n_seq // (1 if mesh is None else mesh.devices.size)
            per_cand = max(1, s_local * self.n_words * 4 * 4)
            chunk = max(128, min(8192,
                                 next_pow2(eval_budget_bytes // per_cand + 1) // 2))
        self.chunk = int(chunk)
        # tok_item is nondecreasing (build_vertical emits tokens sorted by
        # item), so per-item token ranges are a searchsorted away
        self._tok_starts = np.searchsorted(
            vdb.tok_item, np.arange(vdb.n_items + 1))
        # items sorted by support desc, stable by item id
        order = np.lexsort((vdb.item_ids, -vdb.item_supports))
        self._order = order
        self._sup_sorted = vdb.item_supports[order]

    # ------------------------------------------------------------- kernels

    def _sel_tokens(self, sel: np.ndarray):
        """Token table restricted to the selected items, rows renumbered to
        0..len(sel)-1 (selection order)."""
        starts, vdb = self._tok_starts, self.vdb
        lens = starts[sel + 1] - starts[sel]
        idx = np.concatenate(
            [np.arange(starts[i], starts[i + 1]) for i in sel]
        ) if len(sel) else np.zeros(0, np.int64)
        ti = np.repeat(np.arange(len(sel), dtype=np.int32), lens)
        return ti, vdb.tok_seq[idx], vdb.tok_word[idx], vdb.tok_mask[idx]

    def _host_bitmaps(self, m: int, lo: int = 0,
                      hi: Optional[int] = None) -> np.ndarray:
        """[m, hi-lo, n_words] dense rows for the top-m items over the
        sequence range [lo, hi), host-built from the token slice (memory
        proportional to m and the range, never n_items x n_seq_global)."""
        hi = self.n_seq if hi is None else hi
        ti, ts, tw, tm = self._sel_tokens(self._order[:m])
        bm = np.zeros((m, hi - lo, self.n_words), np.uint32)
        keep = (ts >= lo) & (ts < hi)
        # distinct bits: add == OR
        np.add.at(bm, (ti[keep], ts[keep] - lo, tw[keep]), tm[keep])
        return bm

    def _sharded_bitmaps(self, m: int) -> jax.Array:
        """Multi-host sharded store build: each process materializes ONLY
        its seq-axis slice (replicating the full [m, n_seq, W] store on
        every device would cost D x the sharded footprint and defeat the
        per-device eval-budget sizing)."""
        sharding = store_sharding(self.mesh)
        shape = (m, self.n_seq, self.n_words)
        pidx = jax.process_index()
        slices = sorted(
            (idx[1].start or 0, idx[1].stop or self.n_seq)
            for dev, idx in sharding.devices_indices_map(shape).items()
            if dev.process_index == pidx)
        lo, hi = slices[0][0], slices[-1][1]
        if (hi - lo) != sum(b - a for a, b in slices):
            # non-contiguous addressable shards (exotic device order):
            # fall back to the replicate-and-reshard path
            return self._put(self._host_bitmaps(m))
        return jax.make_array_from_process_local_data(
            sharding, self._host_bitmaps(m, lo, hi))

    def _prep(self, m: int):
        """prefix/suffix-OR id-lists for the top-m items (one jit call).

        Single chip: the [m, n_seq, n_words] store is scatter-built in HBM
        straight from the ~KB-scale token slice and transformed in the same
        jit — the dense rows never exist on host.  Mesh: only the m selected
        rows are host-built, then sharded over the sequence axis.
        """
        if self.mesh is None:
            ti, ts, tw, tm = self._sel_tokens(self._order[:m])
            p1, s1 = _build_prep_single(
                jnp.asarray(ti), jnp.asarray(ts), jnp.asarray(tw),
                jnp.asarray(tm), m=m, n_seq=self.n_seq,
                n_words=self.n_words)
        else:
            if self._multiproc:
                raw = self._sharded_bitmaps(m)
            else:
                raw = jax.device_put(self._host_bitmaps(m),
                                     store_sharding(self.mesh))
            p1, s1 = _prep_fn_mesh(self.mesh)(raw)
        self.stats["kernel_launches"] += 1
        return p1, s1

    def _eval_fn(self, kmax: int):
        return _eval_kernel(self.mesh, kmax)

    def _evaluate(self, p1, s1, cands: List[Tuple[Tuple[int, ...], Tuple[int, ...]]]):
        """Batch-evaluate (sup, supx) for candidate rules (local item idx)."""
        n = len(cands)
        kmax = 1
        for x, y in cands:
            kmax = max(kmax, len(x), len(y))
        km = 1
        while km < kmax:
            km *= 2
        fn = self._eval_fn(km)
        c = self.chunk
        sup_parts = []; supx_parts = []
        for lo in range(0, n, c):
            hi = min(lo + c, n)
            xs = np.zeros((c, km), np.int32); xv = np.zeros((c, km), bool)
            ys = np.zeros((c, km), np.int32); yv = np.zeros((c, km), bool)
            for r, (x, y) in enumerate(cands[lo:hi]):
                xs[r, :len(x)] = x; xv[r, :len(x)] = True
                ys[r, :len(y)] = y; yv[r, :len(y)] = True
            sup, supx = fn(p1, s1, self._put(xs), self._put(xv),
                           self._put(ys), self._put(yv))
            sup_parts.append(sup); supx_parts.append(supx)
            self.stats["kernel_launches"] += 1
        self.stats["evaluated"] += n
        # One device->host readback for the whole candidate list (latency
        # on remote TPUs dwarfs the transfer itself).
        sup_all = sup_parts[0] if len(sup_parts) == 1 else jnp.concatenate(sup_parts)
        supx_all = supx_parts[0] if len(supx_parts) == 1 else jnp.concatenate(supx_parts)
        try:
            sup_all.copy_to_host_async(); supx_all.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass  # method unavailable on this backend
        return (np.asarray(sup_all)[:n].astype(np.int64),
                np.asarray(supx_all)[:n].astype(np.int64))

    # ---------------------------------------------------------------- mine

    def _mine_restricted(self, m: int) -> Tuple[List[RuleResult], int]:
        """Full search over the top-m items; returns (results, s_k)."""
        sup_it = self._sup_sorted[:m].astype(np.int64)
        p1, s1 = self._prep(m)
        ids = self.vdb.item_ids[self._order[:m]]

        results: List[Tuple[int, int, Tuple[int, ...], Tuple[int, ...]]] = []
        minsup = 1
        sup_sorted: List[int] = []  # ascending supports of accepted rules

        def s_k_threshold() -> int:
            if len(sup_sorted) < self.k:
                return 1
            return sup_sorted[-self.k]

        # queue: (-bound, seq#, X, Y, can_right); X/Y are local index tuples
        counter = itertools.count()
        queue: List[Tuple[int, int, Tuple[int, ...], Tuple[int, ...], bool]] = []
        for i in range(m):
            for j in range(m):
                if i != j:
                    bound = int(min(sup_it[i], sup_it[j]))
                    heapq.heappush(queue, (-bound, next(counter), (i,), (j,), True))

        while queue:
            batch = []
            while queue and len(batch) < self.chunk:
                nb, _, x, y, cr = queue[0]
                if -nb < minsup:
                    queue.clear()
                    break
                heapq.heappop(queue)
                batch.append((x, y, cr))
            if not batch:
                break
            sups, supxs = self._evaluate(p1, s1, [(x, y) for x, y, _ in batch])
            for (x, y, can_right), sup, supx in zip(batch, sups, supxs):
                sup, supx = int(sup), int(supx)
                if sup < minsup:
                    continue
                if conf_ok(sup, supx, self.minconf):
                    results.append((sup, supx, x, y))
                    bisect.insort(sup_sorted, sup)
                    new_t = s_k_threshold()
                    if new_t > minsup:
                        minsup = new_t
                        results = [r for r in results if r[0] >= minsup]
                        del sup_sorted[: bisect.bisect_left(sup_sorted, minsup)]
                # expansions (bound = min(sup, sup of added item))
                used = set(x) | set(y)
                if self.max_side is None or len(x) < self.max_side:
                    for c in range(max(x) + 1, m):
                        if c in used or sup_it[c] < minsup:
                            continue
                        bound = int(min(sup, sup_it[c]))
                        if bound >= minsup:
                            heapq.heappush(queue, (-bound, next(counter),
                                                   x + (c,), y, False))
                if can_right and (self.max_side is None or len(y) < self.max_side):
                    for c in range(max(y) + 1, m):
                        if c in used or sup_it[c] < minsup:
                            continue
                        bound = int(min(sup, sup_it[c]))
                        if bound >= minsup:
                            heapq.heappush(queue, (-bound, next(counter),
                                                   x, y + (c,), True))

        s_k = s_k_threshold()
        # local indices are support-ordered; canonical form sorts by item id
        out = [
            (tuple(sorted(int(ids[i]) for i in x)),
             tuple(sorted(int(ids[i]) for i in y)), sup, supx)
            for sup, supx, x, y in results
        ]
        return sort_rules(out), s_k

    def mine(self) -> List[RuleResult]:
        n_total = self.vdb.n_items
        m = max(1, min(self.item_cap, n_total))
        while True:
            self.stats["deepening_rounds"] += 1
            results, s_k = self._mine_restricted(m)
            if m >= n_total:
                return results
            next_item_sup = int(self._sup_sorted[m])
            if len(results) >= self.k and next_item_sup < s_k:
                return results
            m = min(m * 2, n_total)


class TsrCPU(TsrTPU):
    """CPU TopSeqRules: the same best-first search and iterative deepening,
    with the bitmap evaluation in NumPy on host (the reference's JVM-local
    miner analog; ``algorithm=TSR`` in the plugin registry, mirroring
    SPADE vs SPADE_TPU).  Shares byte semantics with the device engine via
    ops/bitops_np, so oracle comparisons are exact."""

    def _prep(self, m: int):
        assert self.mesh is None, "TsrCPU does not shard; use TsrTPU"
        bm = self._host_bitmaps(m)
        return Bnp.prefix_or_incl(bm), Bnp.suffix_or_incl(bm)

    def _evaluate(self, p1, s1, cands):
        n = len(cands)
        sup = np.empty(n, np.int64)
        supx = np.empty(n, np.int64)
        for r, (x, y) in enumerate(cands):
            a = p1[x[0]]
            for i in x[1:]:
                a = a & p1[i]
            c = s1[y[0]]
            for j in y[1:]:
                c = c & s1[j]
            sup[r] = int(Bnp.support(Bnp.shift_up_one(a) & c))
            supx[r] = int(Bnp.support(a))
        self.stats["evaluated"] += n
        return sup, supx


def mine_tsr_tpu(db: SequenceDB, k: int, minconf: float, *,
                 mesh: Optional[Mesh] = None,
                 stats_out: Optional[dict] = None, **kwargs) -> List[RuleResult]:
    vdb = build_vertical(db, min_item_support=1)
    if vdb.n_items == 0:
        return []
    eng = TsrTPU(vdb, k, minconf, mesh=mesh, **kwargs)
    results = eng.mine()
    if stats_out is not None:
        stats_out.update(eng.stats)
    return results


def mine_tsr_cpu(db: SequenceDB, k: int, minconf: float, *,
                 stats_out: Optional[dict] = None, **kwargs) -> List[RuleResult]:
    vdb = build_vertical(db, min_item_support=1)
    if vdb.n_items == 0:
        return []
    eng = TsrCPU(vdb, k, minconf, **kwargs)
    results = eng.mine()
    if stats_out is not None:
        stats_out.update(eng.stats)
    return results
