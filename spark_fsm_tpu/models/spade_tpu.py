"""SPADE on TPU: batched SPAM DFS over a device-resident bitmap store.

Architecture (the TPU-native replacement for the reference's JVM miner,
SURVEY.md sec 3.1 hot loop):

- The vertical DB and all live pattern bitmaps sit in one HBM-resident
  ``store[slot, seq*word]`` uint32 tensor (word minor; kernels reshape
  gathered rows to [*, seq, word] internally — a persistent trailing
  word axis makes XLA's layout assignment copy the whole store on every
  gather-launch).  Slots ``0..n_items-1`` are the
  item id-lists (never freed); the rest is a pool for pattern bitmaps plus a
  final scratch slot that absorbs padded-lane writes.
- Host-side DFS pops nodes in batches; every candidate (parent x item x
  ext-type) in the batch goes through one fused device kernel chain:
  gather -> s-ext transform / AND join -> per-sequence any -> support sum.
  The host then applies the minsup prune (SURVEY.md sec 2.3 step 5) and
  materializes only surviving children back into pool slots.
- The dispatch/resolve split pipelines the host loop: several node batches
  are in flight at once, each with ONE asynchronously-copied support array,
  so device->host latency (large on remote/tunneled TPUs, where a round
  trip can cost tens of ms) overlaps with compute and with other batches'
  transfers instead of serializing the DFS.  Device ops stay correctly
  ordered because a single device executes dispatches in order.
- Memory safety is recompute-on-miss, not spill: a child that gets no free
  slot (or whose slot was reclaimed) carries its extension path
  ``steps = ((item, is_s), ...)``; when popped, its bitmap is rebuilt by a
  ``lax.scan`` fold of joins from the item id-lists — bit-exact, because a
  pattern's bitmap IS the fold of its extension joins.
- With a mesh, the sequence axis shards over devices (``shard_map``); joins
  are embarrassingly parallel and per-shard partial supports ``psum`` over
  ICI before the global prune — the reference's Spark-partition aggregation
  (SURVEY.md sec 2.2), natively.

Enumeration (S/I equivalence-class pruning) is identical to the CPU oracle
in models/oracle.py, so the output pattern set is byte-identical by
construction; supports are exact integers from popcounts.
"""

from __future__ import annotations

import functools
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import VerticalDB, build_vertical
from spark_fsm_tpu.models._common import (
    FrontierNode, SlotPool, auto_pool_bytes, concat_pow2, decode_frontier,
    device_axes, encode_frontier, launch_width_cap, load_checkpoint,
    next_pow2, scatter_build_store)
from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.ops import pallas_support as PS
from spark_fsm_tpu.parallel import multihost as MH
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, pad_to_multiple, shard_map
from spark_fsm_tpu.utils import shapes
from spark_fsm_tpu.utils.canonical import Pattern, PatternResult, sort_patterns

Step = Tuple[int, bool]  # (item index, is_s_extension)


# the ONE frontier-node shape every engine snapshots (see _common)
_Node = FrontierNode


def classic_geometry(n_sequences: int, n_items: int, n_words: int, *,
                     mesh: Optional[Mesh] = None, chunk: int = 2048,
                     node_batch: int = 1024, pipeline_depth: int = 4,
                     recompute_chunk: int = 256,
                     pool_bytes: Optional[int] = None,
                     use_pallas: bool = False,
                     shape_buckets: bool = False) -> dict:
    """Derived device geometry of a :class:`SpadeTPU` — the ONE sizing
    routine shared by the constructor and the shape-key enumerator
    (utils/shapes.py), so "what will compile" cannot drift from "what
    does compile".  Pure host arithmetic: no device allocation.

    ``use_pallas`` must be the RESOLVED boolean (the constructor probes
    the backend; the enumerator passes the service's resolution)."""
    n_shards = 1 if mesh is None else mesh.devices.size
    # ni_tile: the pair kernel's static item-row arg, pre-rounded to its
    # I_TILE — passing raw n_items would recompile the kernel for every
    # distinct alphabet size even though the lowered grid only changes
    # per tile of 128 (matters for streaming, where the frequent-item
    # projection drifts a little every window)
    n_seq, s_block, ni_tile = device_axes(
        n_sequences, n_items, n_words, mesh=mesh, use_pallas=use_pallas,
        shape_buckets=shape_buckets)

    # HBM budget covers the slot pool PLUS the in-flight prep tensors
    # (each pipelined batch holds a [2*node_batch, S, W] prep), and
    # node_batch is bounded so pipeline_depth in-flight batches can
    # never starve a recompute: slots held in flight <= depth*nb, so
    # free+stack-reclaimable >= pool - (depth+1)*nb >= nb holds whenever
    # nb <= pool // (depth+2).
    if pool_bytes is None:
        # each blocking readback on a tunneled TPU costs ~130ms of
        # latency, so bigger batches (= fewer DFS sync points) are
        # worth real memory
        pool_bytes = auto_pool_bytes(mesh)
    slot_bytes = n_seq * n_words * 4
    # Memory-safety ceiling on launch widths (see launch_width_cap) —
    # overrides even an explicit chunk knob; per-device row footprint,
    # since mesh launches shard the sequence axis.
    max_chunk = launch_width_cap(
        pool_bytes, -(-slot_bytes // n_shards), 8)
    chunk = min(int(chunk), max_chunk)
    recompute_chunk = min(int(recompute_chunk), max(4, max_chunk // 2))
    budget_slots = max(64, min(int(pool_bytes) // max(slot_bytes, 1), 32768))
    pipeline_depth = min(max(1, int(pipeline_depth)),
                         max(1, budget_slots // 8))
    d = pipeline_depth
    nb = max(1, min(int(node_batch), budget_slots // (3 * (d + 2))))
    pool_slots = max(8, budget_slots - 2 * d * nb)
    total = n_items + pool_slots + 1
    floor_rows = n_items + 8 + 1  # min rows: items + minimal pool + scratch
    if use_pallas:  # pair kernel reads item rows rounded to I_TILE
        floor_rows = max(floor_rows, ni_tile)
        total = max(total, ni_tile)
    if shape_buckets:
        # Round the store row count up too and hand the extra rows to
        # the pool (pool SIZE is host-only state; only the row COUNT is
        # a device shape).  Rounding UP can overshoot the pool_bytes
        # budget by up to 2x, so when it does — and a pow2 below still
        # fits the items + a minimal pool — round DOWN instead and
        # re-clamp node_batch to keep the recompute-starvation
        # invariant (nb <= pool // (3*(d+2))).
        total = next_pow2(total)
        budget_rows = n_items + 1 + budget_slots
        if total > budget_rows and total // 2 >= floor_rows:
            total //= 2
        pool_slots = total - n_items - 1
        nb = max(1, min(nb, pool_slots // (3 * (d + 2))))
    return {
        "n_seq": n_seq, "s_block": s_block, "ni_tile": ni_tile,
        "chunk": chunk, "recompute_chunk": recompute_chunk,
        "pipeline_depth": pipeline_depth, "node_batch": nb,
        "pool_slots": pool_slots, "total_rows": total,
        "scratch": n_items + pool_slots,
        "shape_key": shapes.key_classic(n_seq, n_words, total, nb, chunk),
    }


@functools.lru_cache(maxsize=64)
def _spade_fns(mesh: Optional[Mesh], n_words: int):
    """Jitted kernel set shared by every SpadeTPU with the same mesh.
    ``jax.jit`` caches traces per wrapped-function OBJECT, so per-instance
    closures would recompile the whole kernel chain on every engine
    construction — ~10s per /train request on a v5e even for tiny
    databases.  The Pallas launcher is cached separately
    (:func:`_pallas_supports_fn`) because its key varies per DB geometry
    and must not evict/miss these geometry-independent four.

    The store and the pt tensor cross every jit boundary FLAT
    ([rows, S*W], word minor): XLA's layout assignment gives a persistent
    [rows, S, 1] array a pathological tiled layout and inserts a copy of
    the ENTIRE store into every program that gathers from it (a 6.7 GB
    temp per call on the headline workload).  Bodies reshape gathered
    rows back to [*, S, W] for the word-wise bit ops — reshaping the
    small gathered subset, never the store.
    """
    W = n_words

    def _rows3(rows2):  # [n, S*W] -> [n, S, W] (free inside the program)
        return rows2.reshape(rows2.shape[0], -1, W)

    # The s-ext transform (~6 word-ops) dominates the AND (1 op), and a
    # node typically has tens of candidates, so gather + transform the
    # popped batch's bitmaps ONCE per batch.  Plain and transformed rows
    # interleave into ONE [2*Bn, S*W] tensor so each candidate costs a
    # single gathered row (a where(iss, trans[ref], parents[ref]) would
    # gather BOTH branches — 2x HBM traffic on the parent side).
    def prep_body(store, node_slot):
        parents = _rows3(store[node_slot])    # [Bn, S, W]
        pt = jnp.stack([parents, B.sext_transform(parents)], axis=1)
        return pt.reshape(-1, parents.shape[1] * W)   # [2*Bn, S*W]

    def _joined(pt, store, parent_ref, item_slot, iss):
        base = pt[2 * parent_ref + iss.astype(jnp.int32)]
        return base & store[item_slot]        # [c, S*W]

    def supports_body(pt, store, parent_ref, item_slot, iss):
        part = B.support(_rows3(_joined(pt, store, parent_ref, item_slot, iss)))
        if mesh is not None:
            part = jax.lax.psum(part, SEQ_AXIS)
        return part

    def materialize_body(pt, store, parent_ref, item_slot, iss, out_slot):
        j = _joined(pt, store, parent_ref, item_slot, iss)
        return store.at[out_slot].set(j)

    def recompute_body(store, step_items, step_iss, step_valid, out_slot):
        # step_* : [K, M]; fold the join chain along K.
        bmp = _rows3(store[step_items[0]])
        def body(b, xs):
            it, iss, valid = xs
            nb = B.join(b, _rows3(store[it]), iss)
            return jnp.where(valid[:, None, None], nb, b), None
        bmp, _ = jax.lax.scan(body, bmp, (step_items[1:], step_iss[1:], step_valid[1:]))
        return store.at[out_slot].set(bmp.reshape(bmp.shape[0], -1))

    if mesh is None:
        return {
            "prep": jax.jit(prep_body),
            "supports": jax.jit(supports_body),
            "materialize": jax.jit(materialize_body, donate_argnums=1),
            "recompute": jax.jit(recompute_body, donate_argnums=0),
        }

    st = P(None, SEQ_AXIS)
    rep = P()
    return {
        "prep": jax.jit(
            shard_map(prep_body, mesh=mesh,
                          in_specs=(st, rep), out_specs=st)),
        "supports": jax.jit(
            shard_map(supports_body, mesh=mesh,
                          in_specs=(st, st, rep, rep, rep), out_specs=rep)),
        "materialize": jax.jit(
            shard_map(materialize_body, mesh=mesh,
                          in_specs=(st, st, rep, rep, rep, rep), out_specs=st),
            donate_argnums=1),
        "recompute": jax.jit(
            shard_map(recompute_body, mesh=mesh,
                          in_specs=(st, rep, rep, rep, rep), out_specs=st),
            donate_argnums=0),
    }


@functools.lru_cache(maxsize=64)
def _items_transpose(mesh: Optional[Mesh], ni: int, n_words: int):
    """Cached jitted item-row transpose (flat store rows -> kernel layout
    [row, word, seq]) for the multiword Pallas path — once per mine, so a
    per-instance jit would recompile it per engine construction."""
    tr = lambda s: jnp.transpose(
        s[:ni].reshape(ni, -1, n_words), (0, 2, 1))
    if mesh is None:
        return jax.jit(tr)
    return jax.jit(tr, out_shardings=NamedSharding(
        mesh, P(None, None, SEQ_AXIS)))


@functools.lru_cache(maxsize=64)
def _pallas_supports_fn(mesh: Mesh, n_items: int, s_block: int,
                        n_words: int, interpret: bool):
    """Cached mesh launcher for the Pallas pair-support kernel.  Keyed
    separately from :func:`_spade_fns` because it varies with the DB
    geometry (item-row count, seq block, word count) while the other four
    kernels do not — bundling the keys would re-jit those four on every
    new dataset alphabet."""
    multiword = n_words > 1

    def pallas_supports_body(pt, items, pref, item):
        # Per-shard pair-support kernel launch; psum the extracted
        # candidate supports over ICI (same contract as supports_body).
        sup = PS.batch_supports(
            pt, items, n_items, pref, item,
            items_kernel_layout=multiword, s_block=s_block,
            interpret=interpret, n_words=n_words)
        return jax.lax.psum(sup, SEQ_AXIS)

    st = P(None, SEQ_AXIS)
    rep = P()
    items_spec = P(None, None, SEQ_AXIS) if multiword else st
    # check_vma=False: pallas_call's out_shape carries no varying-mesh-
    # axes annotation and the vma validator rejects it on EVERY real-TPU
    # lowering (interpret mode, which the CPU tests use, skips the check
    # — which is how a check_vma=True version once passed tests yet
    # silently knocked the whole mesh path onto the jnp fallback on
    # hardware).
    return jax.jit(
        shard_map(pallas_supports_body, mesh=mesh,
                      in_specs=(st, items_spec, rep, rep),
                      out_specs=rep,
                      check_vma=False))


class SpadeTPU:
    """Single- or multi-chip SPADE miner.

    Args:
      vdb: vertical DB (build with ``min_item_support=minsup_abs`` for the
        frequent-item projection; extra items are filtered here anyway).
      minsup_abs: absolute minimum sequence support.
      mesh: optional 1-D ``Mesh`` over SEQ_AXIS; sequence axis is padded to
        a device multiple and sharded.
      chunk: candidates per support-kernel launch.
      node_batch: DFS nodes popped per host iteration.
      pipeline_depth: node batches in flight (dispatched, support readback
        pending) at once.
      pool_bytes: HBM budget for the pattern-bitmap pool.
      max_pattern_itemsets: optional cap on pattern length in itemsets.
    """

    def __init__(
        self,
        vdb: VerticalDB,
        minsup_abs: int,
        *,
        mesh: Optional[Mesh] = None,
        chunk: int = 2048,
        node_batch: int = 1024,
        pipeline_depth: int = 4,
        recompute_chunk: int = 256,
        pool_bytes: Optional[int] = None,
        max_pattern_itemsets: Optional[int] = None,
        use_pallas="auto",
        shape_buckets: bool = False,
        partition=None,
    ):
        self.vdb = vdb
        self.minsup = int(minsup_abs)
        self.mesh = mesh
        # equivalence-class partition slice (parallel/partition.py):
        # seed only the owned classes' ROOTS — candidate lists stay
        # full-width (extensions draw from every frequent item), so the
        # owned subtrees are exactly the patterns whose first item this
        # partition owns, and the slices union to the full set
        self._partition = partition
        # Multi-host mesh (jax.distributed): host-side inputs must become
        # global replicated arrays; see parallel/multihost.py.
        self._multiproc = MH.is_multihost(mesh)
        self._put = functools.partial(MH.host_to_device, mesh)
        self.max_pattern_itemsets = max_pattern_itemsets

        n_items, n_seq, n_words = vdb.n_items, vdb.n_sequences, vdb.n_words
        # Pallas pair-support kernel (ops/pallas_support.py): covers single-
        # chip AND mesh (per-shard launch + psum), any word count.  "auto"
        # enables it on a real TPU backend; explicit True runs interpret
        # mode off-TPU (tests).
        eligible = n_items > 0
        if use_pallas == "auto":
            self.use_pallas = eligible and jax.default_backend() == "tpu"
        else:
            self.use_pallas = bool(use_pallas) and eligible
        self._pallas_interpret = jax.default_backend() != "tpu"
        # shape_buckets: round the device shapes up to powers of two so a
        # stream of engines over growing/sliding windows (streaming/window.py
        # re-mines per micro-batch) lands on a handful of compiled shapes
        # instead of recompiling the whole kernel chain per window size.
        # Trades bounded padding (<2x seq axis / store rows) for shape reuse;
        # padded sequences are all-zero bitmaps and count nothing.
        self._shape_buckets = bool(shape_buckets)
        # All derived sizing lives in classic_geometry — the one routine
        # the shape-key enumerator (utils/shapes.py) shares, so the keys
        # prewarm compiles are exactly the keys this constructor will fix.
        g = classic_geometry(
            n_seq, n_items, n_words, mesh=mesh, chunk=chunk,
            node_batch=node_batch, pipeline_depth=pipeline_depth,
            recompute_chunk=recompute_chunk, pool_bytes=pool_bytes,
            use_pallas=self.use_pallas,
            shape_buckets=self._shape_buckets)
        n_seq = g["n_seq"]
        self.n_items, self.n_seq, self.n_words = n_items, n_seq, n_words
        self._s_block = g["s_block"]
        self._ni_tile = g["ni_tile"]
        self.chunk = g["chunk"]
        self.recompute_chunk = g["recompute_chunk"]
        self.pipeline_depth = g["pipeline_depth"]
        self.pool_slots = g["pool_slots"]
        self.node_batch = g["node_batch"]
        self.scratch = g["scratch"]
        total = g["total_rows"]

        self.store = scatter_build_store(vdb, total, n_seq, n_words,
                                         mesh=mesh, put=self._put,
                                         bucket_tokens=self._shape_buckets,
                                         flat=True)

        # Multiword Pallas: the kernel wants [row, word, seq] layout, and
        # transposing the store per call would copy it — so transpose the
        # (immutable) item rows once.  W == 1 feeds the store directly (the
        # layouts are the same bytes there; see ops/pallas_support.py).
        self._items_t = None
        if self.use_pallas and n_words > 1:
            self._items_t = _items_transpose(mesh, self._ni_tile,
                                             n_words)(self.store)
        self._pool = SlotPool(range(n_items, n_items + self.pool_slots))
        self._build_fns()

        # mining statistics (observability, SURVEY.md sec 5).  shape_key
        # identifies the compiled device geometry: two mines with equal
        # keys reuse every compiled program, so the number of DISTINCT
        # keys across a stream of mines bounds its recompile count — the
        # quantity shape_buckets exists to hold down (streaming/window.py).
        # Recorded in the process-wide registry so /admin/shapes can diff
        # observed geometry against the prewarm enumeration.
        self.stats = {
            "candidates": 0, "kernel_launches": 0, "recomputed_nodes": 0,
            "reclaimed_slots": 0, "patterns": 0,
            "shape_key": g["shape_key"],
        }
        shapes.record(g["shape_key"])

    # ------------------------------------------------------------------ fns

    def _build_fns(self) -> None:
        # Jitted callables are shared across engine instances (the service
        # builds one engine per /train): see _spade_fns.
        fns = _spade_fns(self.mesh, self.n_words)
        self._prep_fn = fns["prep"]
        self._supports_fn = fns["supports"]
        self._materialize_fn = fns["materialize"]
        self._recompute_fn = fns["recompute"]
        self._pallas_supports_fn = None
        if self.mesh is not None and self.use_pallas:
            self._pallas_supports_fn = _pallas_supports_fn(
                self.mesh, self._ni_tile, self._s_block, self.n_words,
                self._pallas_interpret)

    # ------------------------------------------------------------ slot mgmt

    def _alloc(self) -> Optional[int]:
        return self._pool.alloc()

    def _free_slot(self, slot: Optional[int]) -> None:
        if slot is not None and slot >= self.n_items:  # item rows never free
            self._pool.free(slot)

    # ------------------------------------------------------------- kernels

    def _prep(self, batch: List[_Node]):
        """Gather + s-ext-transform the popped batch's bitmaps, once.

        Returns the interleaved [2*Bn, S*W] plain/transformed tensor; row
        ``2*b`` is node b's bitmap, row ``2*b+1`` its s-ext transform.
        """
        slots = np.zeros(self.node_batch, np.int32)
        for i, n in enumerate(batch):
            slots[i] = n.slot
        pt = self._prep_fn(self.store, self._put(slots))
        self.stats["kernel_launches"] += 1
        return pt

    def _chunks(self, *arrays: np.ndarray, pad_values=None):
        """Yield chunk-padded jnp views of parallel 1-D arrays."""
        n = len(arrays[0])
        c = self.chunk
        pad_values = pad_values or [0] * len(arrays)
        for lo in range(0, n, c):
            hi = min(lo + c, n)
            pad = c - (hi - lo)
            yield lo, hi, tuple(
                self._put(np.pad(a[lo:hi], (0, pad), constant_values=pv))
                for a, pv in zip(arrays, pad_values)
            )

    def _supports_dispatch(self, prep, ref: np.ndarray, item: np.ndarray,
                           iss: np.ndarray, *, count: bool = True):
        """Dispatch the batch's support kernels; return ``(sup, was_pallas)``
        — ONE device array for the whole batch with its host copy already in
        flight (the readback is the expensive half on tunneled TPUs, so
        batches make exactly one), plus which path produced it, so a
        pipelined resolve can recount exactly the Pallas-produced batches
        after a kernel fault downgrade.  ``count=False`` skips the candidate
        counter on fallback recounts of the same candidates."""
        if count:
            self.stats["candidates"] += len(ref)
        if self.use_pallas:
            # Pair matrix over (parent x ALL item rows) + on-device
            # extraction; candidate count padded to pow2 buckets to bound
            # recompilation.  A lowering/runtime failure downgrades to the
            # jnp path for the rest of the mine (results are identical).
            n = len(ref)
            cap = max(1024, next_pow2(n))
            pref = np.zeros(cap, np.int32)
            itm = np.zeros(cap, np.int32)
            pref[:n] = 2 * ref + iss
            itm[:n] = item
            items = self._items_t if self._items_t is not None else self.store
            try:
                if self.mesh is None:
                    sup = PS.batch_supports(
                        prep, items, self._ni_tile,
                        jnp.asarray(pref), jnp.asarray(itm),
                        items_kernel_layout=self._items_t is not None,
                        s_block=self._s_block,
                        interpret=self._pallas_interpret,
                        n_words=self.n_words)
                else:
                    sup = self._pallas_supports_fn(
                        prep, items, self._put(pref), self._put(itm))
                self.stats["kernel_launches"] += 1
                try:
                    sup.copy_to_host_async()
                except (AttributeError, NotImplementedError):
                    pass  # method unavailable on this backend
                return sup, True
            except Exception as exc:  # pragma: no cover - device-specific
                self.use_pallas = False
                self.stats["pallas_fallback"] = repr(exc)
        outs = []
        for _, _, (r, it, ss) in self._chunks(
                ref.astype(np.int32), item.astype(np.int32), iss.astype(bool)):
            outs.append(self._supports_fn(prep, self.store, r, it, ss))
            self.stats["kernel_launches"] += 1
        sup = outs[0] if len(outs) == 1 else concat_pow2(outs)
        try:
            sup.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass  # method unavailable on this backend
        return sup, False

    def _materialize(self, prep, ref, item, iss, out_slot) -> None:
        for _, _, (r, it, ss, os) in self._chunks(
                ref.astype(np.int32), item.astype(np.int32), iss.astype(bool),
                out_slot.astype(np.int32),
                pad_values=[0, 0, False, self.scratch]):
            self.store = self._materialize_fn(prep, self.store, r, it, ss, os)
            self.stats["kernel_launches"] += 1

    def _ensure_slots(self, batch: List[_Node], stack: List[_Node]) -> None:
        """Recompute bitmaps for popped nodes that lost (or never had) a slot."""
        missing = [n for n in batch if n.slot is None]
        if not missing:
            return
        self.stats["recomputed_nodes"] += len(missing)
        if len(self._pool) < len(missing):
            self._pool.reclaim(stack, len(missing),
                               lambda n: n.slot >= self.n_items)
            self.stats["reclaimed_slots"] = self._pool.reclaimed
        for lo in range(0, len(missing), self.recompute_chunk):
            group = missing[lo: lo + self.recompute_chunk]
            m = self.recompute_chunk
            k = next_pow2(max(len(n.steps) for n in group))
            items = np.zeros((k, m), np.int32)
            iss = np.zeros((k, m), bool)
            valid = np.zeros((k, m), bool)
            slots = np.full(m, self.scratch, np.int32)
            for col, node in enumerate(group):
                slot = self._alloc()
                assert slot is not None, "slot pool exhausted beyond reclaim"
                node.slot = slot
                slots[col] = slot
                for row, (it, s) in enumerate(node.steps):
                    items[row, col], iss[row, col], valid[row, col] = it, s, True
            self.store = self._recompute_fn(
                self.store, self._put(items), self._put(iss),
                self._put(valid), self._put(slots)
            )
            self.stats["kernel_launches"] += 1

    # ---------------------------------------------------------------- mine

    def _pattern_of(self, steps: Sequence[Step]) -> Pattern:
        ids = self.vdb.item_ids
        pat: List[List[int]] = []
        for it, is_s in steps:
            if is_s:
                pat.append([int(ids[it])])
            else:
                pat[-1].append(int(ids[it]))
        return tuple(tuple(s) for s in pat)

    def _dispatch(self, stack: List[_Node]):
        """Pop a node batch, dispatch its support kernels, start the async
        host copy.  Returns everything the resolve step needs."""
        batch = [stack.pop() for _ in range(min(self.node_batch, len(stack)))]
        self._ensure_slots(batch, stack)
        prep = self._prep(batch)

        # Flat candidate list for the whole batch (ref = index in batch).
        cand_item: List[int] = []
        cand_iss: List[bool] = []
        cand_ref: List[int] = []
        spans: List[Tuple[int, int, int]] = []  # (s_lo, s_hi == i_lo, i_hi)
        for b_idx, node in enumerate(batch):
            n_itemsets = sum(1 for _, s in node.steps if s)
            allow_s = (self.max_pattern_itemsets is None
                       or n_itemsets < self.max_pattern_itemsets)
            s_lo = len(cand_ref)
            if allow_s:
                for i in node.s_list:
                    cand_ref.append(b_idx); cand_item.append(i); cand_iss.append(True)
            s_hi = len(cand_ref)
            for i in node.i_list:
                cand_ref.append(b_idx); cand_item.append(i); cand_iss.append(False)
            spans.append((s_lo, s_hi, len(cand_ref)))

        sup_dev, was_pallas = (
            self._supports_dispatch(prep, np.array(cand_ref, np.int32),
                                    np.array(cand_item, np.int32),
                                    np.array(cand_iss, bool))
            if cand_ref else (None, False))
        return batch, prep, cand_item, cand_iss, spans, sup_dev, was_pallas

    def _resolve(self, inflight, stack: List[_Node],
                 results: List[PatternResult]) -> None:
        """Wait for a dispatched batch's supports; prune, materialize
        surviving children, push them on the DFS stack."""
        batch, prep, cand_item, cand_iss, spans, sup_dev, was_pallas = inflight
        minsup = self.minsup
        n_cand = spans[-1][2] if spans else 0
        if sup_dev is None:
            sups = np.empty(0, np.int32)
        else:
            try:
                sups = np.asarray(sup_dev)[:n_cand]
            except Exception as exc:  # pragma: no cover - device-specific
                # TPU kernel runtime faults surface at readback; downgrade
                # to the jnp path and recount this batch.  Gate on THIS
                # batch's dispatch path, not the mutable self.use_pallas:
                # with pipeline_depth>1 several Pallas batches are in flight
                # when the first fault lands, and each must be recounted.
                if not was_pallas:
                    raise
                self.use_pallas = False
                self.stats["pallas_fallback"] = repr(exc)
                ref = np.empty(n_cand, np.int32)
                for b_idx, (s_lo, _, i_hi) in enumerate(spans):
                    ref[s_lo:i_hi] = b_idx
                sup_dev, _ = self._supports_dispatch(
                    prep, ref, np.array(cand_item, np.int32),
                    np.array(cand_iss, bool), count=False)
                sups = np.asarray(sup_dev)[:n_cand]

        children: List[_Node] = []
        mat_ref: List[int] = []; mat_item: List[int] = []
        mat_iss: List[bool] = []; mat_child: List[int] = []
        for b_idx, (node, (s_lo, s_hi, i_hi)) in enumerate(zip(batch, spans)):
            n_itemsets = sum(1 for _, s in node.steps if s)
            s_items = [cand_item[k] for k in range(s_lo, s_hi) if sups[k] >= minsup]
            i_items = [cand_item[k] for k in range(s_hi, i_hi) if sups[k] >= minsup]
            for k in range(s_lo, i_hi):
                if sups[k] < minsup:
                    continue
                it, is_s = cand_item[k], cand_iss[k]
                steps = node.steps + ((it, is_s),)
                results.append((self._pattern_of(steps), int(sups[k])))
                src = s_items if is_s else i_items
                child_i = [j for j in src if j > it]
                child_itemsets = n_itemsets + (1 if is_s else 0)
                child_allow_s = (self.max_pattern_itemsets is None
                                 or child_itemsets < self.max_pattern_itemsets)
                if not ((s_items and child_allow_s) or child_i):
                    continue  # leaf: no possible extensions
                child = _Node(steps, None, s_items, child_i)
                slot = self._alloc()
                if slot is not None:
                    child.slot = slot
                    mat_ref.append(b_idx); mat_item.append(it)
                    mat_iss.append(is_s); mat_child.append(slot)
                children.append(child)
        if mat_child:
            self._materialize(prep, np.array(mat_ref, np.int32),
                              np.array(mat_item, np.int32),
                              np.array(mat_iss, bool), np.array(mat_child, np.int32))
        stack.extend(reversed(children))
        for node in batch:
            self._free_slot(node.slot)

    def frontier_fingerprint(self) -> dict:
        """Identity of the (vdb, minsup) a frontier checkpoint binds to.

        Node steps store DENSE item indices, which are only meaningful for
        the exact same frequent-item projection — resuming against a
        different dataset or minsup must be refused, not garbled.
        """
        ids = self.vdb.item_ids
        return {
            "minsup": self.minsup,
            "n_items": self.n_items,
            "n_sequences": self.vdb.n_sequences,
            "max_itemsets": self.max_pattern_itemsets,  # changes enumeration
            "item_ids_head": [int(i) for i in ids[:8]],
            "item_ids_sum": int(ids.astype(np.int64).sum()),
        }

    def frontier_state(self, stack: List[_Node],
                       results: List[PatternResult],
                       results_from: int = 0) -> dict:
        """Snapshot of a paused DFS (see _common.encode_frontier).  A
        ``resume`` dict passed back to :meth:`mine` must carry the MERGED
        results list (StoreCheckpoint.load reassembles the deltas)."""
        return encode_frontier(self.frontier_fingerprint(), stack, results,
                               results_from)

    def mine(self, *, resume: Optional[dict] = None,
             checkpoint_cb=None,
             checkpoint_every_s: float = 30.0) -> List[PatternResult]:
        """Run the DFS; optionally resumable (SURVEY.md sec 5 checkpoint
        row: per-level frontier checkpointing for long mines).

        Args:
          resume: a ``frontier_state`` snapshot to continue from; its
            fingerprint must match this engine's (vdb, minsup).
          checkpoint_cb: called with a ``frontier_state`` dict at most
            every ``checkpoint_every_s`` seconds (the in-flight pipeline is
            drained first so the snapshot is consistent).
        """
        minsup = self.minsup
        stack: List[_Node] = []
        results: List[PatternResult]
        if resume is not None:
            results, stack = decode_frontier(
                resume, self.frontier_fingerprint(), _Node)
            self.stats["resumed_nodes"] = len(stack)
        else:
            results = []
            root_items = [i for i in range(self.n_items)
                          if int(self.vdb.item_supports[i]) >= minsup]
            seed = set(root_items)
            if self._partition is not None:
                plan, pidx = self._partition
                seed = set(plan.owned_slice(root_items,
                                            self.vdb.item_ids, pidx))
            for i in reversed(root_items):
                if i not in seed:
                    continue  # another partition's class slice
                results.append((self._pattern_of(((i, True),)),
                                int(self.vdb.item_supports[i])))
                stack.append(_Node(((i, True),), i, root_items,
                                   [j for j in root_items if j > i]))

        # Software-pipelined DFS: keep up to pipeline_depth batches in
        # flight so support readbacks overlap with compute and each other.
        # Resolving out of strict DFS order only permutes enumeration order;
        # the pattern SET is unchanged (canonicalized in sort_patterns).
        # On resume the persisted results already cover everything in
        # ``results`` — checkpoints only ever append the delta.
        ckpt_done = len(results) if resume is not None else 0
        last_ckpt = time.monotonic()
        inflight: deque = deque()
        while stack or inflight:
            while stack and len(inflight) < self.pipeline_depth:
                inflight.append(self._dispatch(stack))
            self._resolve(inflight.popleft(), stack, results)
            if (checkpoint_cb is not None
                    and time.monotonic() - last_ckpt >= checkpoint_every_s):
                while inflight:  # drain for a consistent frontier
                    self._resolve(inflight.popleft(), stack, results)
                checkpoint_cb(self.frontier_state(stack, results,
                                                  results_from=ckpt_done))
                ckpt_done = len(results)
                self.stats["checkpoints"] = self.stats.get("checkpoints", 0) + 1
                last_ckpt = time.monotonic()

        self.stats["patterns"] = len(results)
        return sort_patterns(results)


def mine_spade_tpu(
    db: SequenceDB,
    minsup_abs: int,
    *,
    mesh: Optional[Mesh] = None,
    max_pattern_itemsets: Optional[int] = None,
    stats_out: Optional[dict] = None,
    checkpoint=None,
    fused: str = "auto",
    partition_parts: int = 0,
    partition_classes: int = 64,
    **kwargs,
) -> List[PatternResult]:
    """Convenience wrapper: DB -> vertical build -> TPU mine.

    ``checkpoint`` (optional): an object with ``load() -> Optional[dict]``,
    ``save(state)``, and ``every_s`` — a saved frontier is resumed when its
    fingerprint still matches (a stale/mismatched one is ignored, the mine
    restarts fresh).

    ``fused``: "auto" routes through the best whole-mine-on-device engine
    (ONE blocking readback instead of one per DFS wave, the dominant cost
    on remote/tunneled TPUs): first the sparse-frontier queue engine
    (models/spade_queue.py — classic-engine compute, works at headline
    scale), then the dense fused engine (models/spade_fused.py) where
    only it is eligible; a static-cap overflow falls back to this classic
    engine transparently.  "never" pins the classic engine, "queue" /
    "dense" pin one fused engine (still falling back on overflow),
    "always" tries queue then dense regardless of the size heuristics.
    A checkpointed job routes through the queue engine too (it runs in
    wave segments and snapshots the frontier in the classic engine's
    format, so the two engines resume each other's checkpoints); only
    the dense engine has no resumable frontier — a pinned "dense" with a
    checkpoint degrades to the classic engine with ``stats_out``
    ``fused_skipped="checkpoint"``.
    """
    vdb = build_vertical(db, min_item_support=minsup_abs)
    if vdb.n_items == 0:
        return []
    if fused not in ("auto", "always", "never", "queue", "dense"):
        raise ValueError(f"fused must be 'auto', 'always', 'never', "
                         f"'queue' or 'dense', got {fused!r}")
    if partition_parts and int(partition_parts) > 1:
        # equivalence-class partitioned route (parallel/partition.py):
        # independent class slices over the 2-D parts x seq mesh, one
        # exchange at the end, byte-identical union
        return _mine_spade_partitioned(
            vdb, minsup_abs, mesh=mesh, parts=int(partition_parts),
            classes=int(partition_classes),
            max_pattern_itemsets=max_pattern_itemsets,
            stats_out=stats_out, checkpoint=checkpoint, fused=fused,
            **kwargs)
    return _route_spade(
        vdb, minsup_abs, mesh=mesh,
        max_pattern_itemsets=max_pattern_itemsets, stats_out=stats_out,
        checkpoint=checkpoint, fused=fused, **kwargs)


def _route_spade(
    vdb: VerticalDB,
    minsup_abs: int,
    *,
    mesh: Optional[Mesh] = None,
    max_pattern_itemsets: Optional[int] = None,
    stats_out: Optional[dict] = None,
    checkpoint=None,
    fused: str = "auto",
    partition=None,
    **kwargs,
) -> List[PatternResult]:
    """The engine-routing body shared by the plain and partitioned
    entries: queue -> dense -> classic, with ``partition`` (a
    (PartitionPlan, part_idx) slice) threaded into the engines that
    support root slices — the dense whole-mine engine does not, so the
    partitioned caller remaps its routing away from it."""
    shape_buckets = kwargs.get("shape_buckets", False)
    ekw = dict(mesh=mesh, max_pattern_itemsets=max_pattern_itemsets,
               use_pallas=kwargs.get("use_pallas", "auto"),
               shape_buckets=shape_buckets)
    if fused in ("auto", "always", "queue"):
        from spark_fsm_tpu.models.spade_queue import (
            QueueSpadeTPU, queue_eligible)
        if fused in ("always", "queue") or queue_eligible(
                vdb, mesh=mesh, shape_buckets=shape_buckets):
            qeng = QueueSpadeTPU(vdb, minsup_abs, partition=partition,
                                 **ekw)
            q_resume, q_save, q_every = load_checkpoint(
                checkpoint, qeng.frontier_fingerprint())
            res = qeng.mine(resume=q_resume, checkpoint_cb=q_save,
                            checkpoint_every_s=q_every)
            if res is not None:
                if stats_out is not None:
                    stats_out.update(qeng.stats)
                return res
            # cap overflow: fall through (classic, or dense under
            # "always"), keeping the overflow marker visible so
            # steady-state callers (e.g. streaming windows that overflow
            # every push) can detect the doubled work and pin
            # fused="never".  A checkpointed mine's classic fallback
            # resumes from the queue engine's last snapshot — shared
            # frontier format, same fingerprint.
            if stats_out is not None:
                stats_out["fused_overflow"] = True
                stats_out["fused_waves"] = qeng.stats.get("waves", 0)
    if checkpoint is not None and fused in ("always", "dense", "auto"):
        # the dense engine alone has no resumable frontier; a checkpointed
        # job that would otherwise have used it (pinned, or auto with the
        # queue route unavailable but dense eligible) degrades to the
        # classic engine — flagged, not fatal (the service's
        # checkpoint-unsupported convention)
        if stats_out is not None:
            from spark_fsm_tpu.models.spade_fused import fused_eligible
            if fused in ("always", "dense") or fused_eligible(
                    vdb, mesh=mesh, shape_buckets=shape_buckets):
                stats_out["fused_skipped"] = "checkpoint"
    if checkpoint is None and partition is None \
            and fused in ("always", "dense", "auto"):
        # dense engine: pinned, or "auto"/"always"'s second try — reached
        # when the queue engine was ineligible OR overflowed its caps
        # (a queue success returned above), so an overflowing workload
        # still gets the one-readback path where the dense engine fits.
        # Gated off under a partition slice: the whole-mine dense
        # program has no root slice to restrict
        from spark_fsm_tpu.models.spade_fused import (
            FusedSpadeTPU, fused_eligible)
        if fused in ("always", "dense") or fused_eligible(
                vdb, mesh=mesh, shape_buckets=shape_buckets):
            feng = FusedSpadeTPU(vdb, minsup_abs, **ekw)
            res = feng.mine()
            if res is not None:
                if stats_out is not None:
                    stats_out.update(feng.stats)
                return res
            if stats_out is not None:
                stats_out["fused_overflow"] = True
                stats_out["fused_levels"] = feng.stats.get("levels", 0)
    eng = SpadeTPU(vdb, minsup_abs, mesh=mesh,
                   max_pattern_itemsets=max_pattern_itemsets,
                   partition=partition, **kwargs)
    resume, save_cb, every_s = load_checkpoint(
        checkpoint, eng.frontier_fingerprint())
    results = eng.mine(resume=resume, checkpoint_cb=save_cb,
                       checkpoint_every_s=every_s)
    if stats_out is not None:
        stats_out.update(eng.stats)
        # the routing decision is always recorded: callers (the suite's
        # `route` field, streaming diagnostics) distinguish "routed
        # classic" from "no routing exists" by this key's presence
        stats_out.setdefault("fused", False)
    return results


class _SliceCheckpoint:
    """Adapter handing a partition slice its resumed state and snapshot
    callback through the engines' standard checkpoint contract."""

    def __init__(self, state, save, every_s: float):
        self._state = state
        self.save = save
        self.every_s = every_s

    def load(self):
        return self._state


def _mine_spade_partitioned(
    vdb: VerticalDB,
    minsup_abs: int,
    *,
    mesh: Optional[Mesh],
    parts: int,
    classes: int,
    max_pattern_itemsets: Optional[int],
    stats_out: Optional[dict],
    checkpoint,
    fused: str,
    **kwargs,
) -> List[PatternResult]:
    """Equivalence-class partitioned SPADE: each partition mines the
    patterns rooted at its owned classes as an INDEPENDENT slice (fixed
    minsup — no dynamic threshold, so the slices share nothing beyond
    the replicated F1 seed already inside ``vdb``), and the union of
    slices IS the exact pattern set: a pattern's class is its first
    item, so every pattern belongs to exactly one slice.

    Routing per slice is the normal queue -> classic ladder with the
    DENSE engine remapped away (its whole-mine device program has no
    root slice).  Checkpoints are composite — merged patterns at top
    level plus the active slice's frontier in the engines' existing
    ``frontier_state`` format (parallel/partition.py
    ``mine_partitioned_slices``)."""
    from spark_fsm_tpu.parallel import partition as PN

    plan = PN.plan_partitions(vdb.item_ids, vdb.item_supports, parts,
                              classes)
    meshes = PN.submeshes(mesh, parts)
    # dense has no root slice: "always"/"dense" remap to "auto" — the
    # eligibility-gated queue-first ladder (forcing "queue" would
    # bypass queue_eligible's alphabet/memory bounds and OOM exactly
    # the large-alphabet mines partitioning targets); _route_spade
    # additionally gates its dense branch off under a partition slice
    fused_p = fused if fused in ("never", "queue", "auto") else "auto"
    ids = vdb.item_ids
    fingerprint = {
        "minsup": int(minsup_abs),
        "n_items": int(vdb.n_items),
        "n_sequences": int(vdb.n_sequences),
        "max_itemsets": max_pattern_itemsets,
        "item_ids_head": [int(i) for i in ids[:8]],
        "item_ids_sum": int(ids.astype(np.int64).sum()),
        "partition": plan.fingerprint(),
    }
    resume, save_cb, every_s = load_checkpoint(checkpoint, fingerprint)
    stats: dict = {
        "partition_parts": int(parts),
        "partition_classes": int(classes),
        "partition_imbalance": round(plan.imbalance_ratio, 4),
    }
    PN.count_mine("spade")

    def mine_part(p, inner_mesh, resume_state, part_cb):
        part_stats: dict = {}
        ckpt = None
        if resume_state is not None or part_cb is not None:
            ckpt = _SliceCheckpoint(resume_state, part_cb, every_s)
        res = _route_spade(
            vdb, minsup_abs, mesh=inner_mesh,
            max_pattern_itemsets=max_pattern_itemsets,
            stats_out=part_stats, checkpoint=ckpt, fused=fused_p,
            partition=(plan, p), **kwargs)
        PN.fold_numeric_stats(stats, part_stats)
        return PN.encode_patterns(res)

    rows = PN.mine_partitioned_slices(
        plan=plan, meshes=meshes, fingerprint=fingerprint,
        mine_part=mine_part, resume=resume, checkpoint_cb=save_cb,
        stats=stats)
    results = sort_patterns(PN.decode_patterns(rows))
    stats["patterns"] = len(results)
    stats["fused"] = "partitioned"
    if stats_out is not None:
        stats_out.update(stats)
    return results
