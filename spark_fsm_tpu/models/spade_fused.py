"""Fused SPADE: the ENTIRE mine as one device program (single readback).

The classic engine (models/spade_tpu.py) is a host-driven DFS: the host
pops node batches, dispatches support kernels, reads supports back, prunes,
and pushes children.  Each DFS "wave" costs one blocking device->host
readback — ~130ms of pure latency on a tunneled TPU — so a 4-level mine
pays ~0.5s of latency regardless of how little compute it needs.  That is
the whole wall-clock for small databases.

This engine instead runs the level-wise BFS INSIDE one ``lax.while_loop``:

- the frontier lives on device as fixed-capacity mask arrays
  (``s_mask``/``i_mask`` over the dense item axis — the SPAM equivalence-
  class candidate lists of models/oracle.py, vectorized);
- each level computes the dense parent x item pair-support matrix (the
  Pallas kernel on TPU, a blocked jnp reduction elsewhere), prunes by
  minsup ON DEVICE (minsup is a traced scalar, NOT a compile-time
  constant — streaming windows re-mine with drifting minsup on one
  compiled program), emits surviving
  (parent, item, ext-type, support) records into a device buffer, and
  compacts surviving children into the next frontier with
  ``jnp.nonzero(size=...)``;
- child bitmaps are materialized into a double-buffered slot region
  (parents of level k and children of level k alternate regions, so slot
  allocation is just ``base + rank`` — no free-list);
- the host makes exactly ONE blocking readback at the end: the record
  buffer, from which it reconstructs the pattern set by following parent
  links (records are appended level by level, so parents always precede
  children).

Static caps (frontier width, emissions per level, total records, levels)
keep every shape compile-time constant.  Any cap overflow sets a flag and
the caller falls back to the classic engine — capacity never costs
correctness.  Enumeration is byte-identical to the oracle by construction:
the masks implement exactly its S/I candidate-list rules
(SURVEY.md sec 2.3 step 3; oracle.py mine_spade_vertical).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_fsm_tpu.data.vertical import VerticalDB
from spark_fsm_tpu.models._common import (
    bucket_seq, device_axes, next_pow2, scatter_build_store)
from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.ops import pallas_support as PS
from spark_fsm_tpu.parallel import multihost as MH
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, pad_to_multiple, shard_map
from spark_fsm_tpu.utils import shapes
from spark_fsm_tpu.utils.canonical import PatternResult, sort_patterns


def fused_geometry(n_sequences: int, n_items: int, n_words: int, *,
                   mesh: Optional[Mesh] = None, use_pallas: bool = False,
                   shape_buckets: bool = False,
                   caps: Optional["FusedCaps"] = None) -> dict:
    """Derived device geometry of a :class:`FusedSpadeTPU` — shared by
    the constructor and the shape-key enumerator (utils/shapes.py)."""
    caps = caps or FusedCaps.for_mesh(mesh)
    n_seq, s_block, ni_pad = device_axes(
        n_sequences, n_items, n_words, mesh=mesh, use_pallas=use_pallas,
        shape_buckets=shape_buckets)
    return {"n_seq": n_seq, "s_block": s_block, "ni_pad": ni_pad,
            "caps": caps,
            "shape_key": shapes.key_fused(n_seq, n_words, ni_pad,
                                          caps.f_cap)}


def _dense_pair_jnp(pt3: jax.Array, items3: jax.Array, i_tile: int = 128,
                    block_elems: int = 1 << 28):
    """[P, S, W] x [NI, S, W] -> [P, NI] support matrix, blocked over item
    tiles AND sequence chunks so the [P, i_tile, s_chunk] hit tensor stays
    bounded (a full-S block at mesh-validation sizes would be tens of GB).
    The chunk is sized from P — mesh-scaled caps widen P (FusedCaps.
    for_mesh), so a fixed chunk would defeat the bound exactly there.
    Non-TPU stand-in for ops/pallas_support.pair_supports (bit-identical
    counts)."""
    p_rows, s, w = pt3.shape
    ni = items3.shape[0]
    n_tiles = ni // i_tile
    sc = min(max(128, block_elems // (p_rows * i_tile)), s)
    n_s = -(-s // sc)
    pad = n_s * sc - s
    if pad:  # zero-pad: padded sequences contribute no support
        pt3 = jnp.pad(pt3, ((0, 0), (0, pad), (0, 0)))
        items3 = jnp.pad(items3, ((0, 0), (0, pad), (0, 0)))

    def tile(idx):
        def s_step(j, acc):
            p_blk = jax.lax.dynamic_slice(pt3, (0, j * sc, 0),
                                          (p_rows, sc, w))
            i_blk = jax.lax.dynamic_slice(items3, (idx * i_tile, j * sc, 0),
                                          (i_tile, sc, w))
            hit = jnp.any(
                (p_blk[:, None, :, :] & i_blk[None, :, :, :]) != 0, axis=3)
            return acc + jnp.sum(hit, axis=2, dtype=jnp.int32)

        return jax.lax.fori_loop(
            0, n_s, s_step, jnp.zeros((p_rows, i_tile), jnp.int32))

    out = jax.lax.map(tile, jnp.arange(n_tiles))          # [T, P, i_tile]
    return jnp.moveaxis(out, 0, 1).reshape(p_rows, ni)


def fused_eligible(vdb: VerticalDB, mesh: Optional[Mesh] = None,
                   caps: Optional["FusedCaps"] = None,
                   shape_buckets: bool = False) -> bool:
    """Size heuristic for auto-routing, two independent ceilings:

    TRAFFIC: the fused program computes the DENSE [2*f_cap, ni_pad] pair
    matrix every level (inactive lanes included — shapes are static), so
    its PER-DEVICE per-level HBM traffic is ~S_local*W*4 * 2*f_cap*ni_pad
    * (1/I_TILE + 1/P_TILE) bytes (the sequence axis shards over the
    mesh).  Routing is worth it while that stays well under the
    ~130ms/wave readback latency the fusion removes (24 GB ~= 30ms on a
    v5e); beyond that the classic host-driven DFS's exact candidate
    lists win.

    ALLOCATION: the while_loop body holds the store (ni_pad + 2*f_cap
    rows), the [2*f_cap, S*W] prep stack, the joins temp, and the
    kernel-layout transposes LIVE AT ONCE — traffic can pass while peak
    allocation OOMs (a 99k-seq x 3-word streaming window did exactly
    that: ~22 GB traffic 'eligible', ~16 GB live on a 16 GB chip).  The
    model store + 4x prep must fit ~45% of the device budget, leaving
    the rest for XLA temps and a coexisting engine (the
    auto_pool_bytes reasoning).

    ``shape_buckets`` mirrors the engine knob: bucketed mines pad the
    sequence axis to a power of two, so eligibility must judge the
    PADDED size (streaming windows route through here).

    Multi-host meshes are eligible: every process runs the identical
    program on replicated frontier state, exactly the SPMD contract of
    parallel/multihost.py (validated by the 2-process parity test)."""
    import jax

    from spark_fsm_tpu.models._common import device_hbm_budget

    caps = caps or FusedCaps.for_mesh(mesh)
    ni_pad = pad_to_multiple(max(vdb.n_items, 1), PS.I_TILE)
    if ni_pad > 1024:
        return False
    n_dev = 1 if mesh is None else mesh.devices.size
    n_seq = vdb.n_sequences
    if shape_buckets:
        n_seq = bucket_seq(n_seq)
    s_local = -(-n_seq // n_dev)
    row_bytes = s_local * vdb.n_words * 4
    est = (row_bytes * 2 * caps.f_cap * ni_pad
           * (1 / PS.I_TILE + 1 / PS.P_TILE))
    if est > 24 << 30:
        return False
    store_bytes = (ni_pad + 2 * caps.f_cap + 1) * row_bytes
    prep_bytes = 2 * caps.f_cap * row_bytes
    dev = mesh.devices.flat[0] if mesh is not None else jax.devices()[0]
    return store_bytes + 4 * prep_bytes <= 0.45 * device_hbm_budget(dev)


class FusedCaps:
    """Static capacities of the fused program (compile-time shapes)."""

    def __init__(self, f_cap: int = 1024, c_cap: Optional[int] = None,
                 r_cap: int = 1 << 17, l_max: int = 128):
        # f_cap rounded up so 2*f_cap rows tile the Pallas P_TILE (the
        # kernel asserts P % P_TILE == 0 — a raw odd cap would crash on
        # TPU instead of overflowing gracefully)
        self.f_cap = pad_to_multiple(int(f_cap), PS.P_TILE // 2)
        self.c_cap = (8 * self.f_cap if c_cap is None
                      else int(c_cap))  # emissions/level
        self.r_cap = int(r_cap)    # total records (patterns)
        self.l_max = int(l_max)    # levels (pattern steps)

    @classmethod
    def for_mesh(cls, mesh: Optional[Mesh]) -> "FusedCaps":
        """Default caps scaled to the mesh: the dense pair matrix shards
        its sequence axis over the devices, so the frontier cap can grow
        with the device count at CONSTANT per-device traffic — on a
        v5e-8 the headline-scale frontier (~2.6k nodes) fits fused."""
        n_dev = 1 if mesh is None else mesh.devices.size
        return cls(f_cap=min(8192, 1024 * n_dev))


@functools.lru_cache(maxsize=32)
def _fused_init_fn(mesh: Optional[Mesh], f_cap: int, ni: int, r_cap: int):
    """Device-side frontier/record-buffer init.  Shipping the zero-filled
    host buffers instead (records alone is r_cap*16 B = ~2 MB at the
    default caps) costs ~200 ms of host->device transfer per mine on a
    tunneled TPU (~10 MB/s) — for buffers that are almost entirely zeros.
    This builds them from ~8 KB of root data: padded root ids/supports,
    the root item mask, and the live root count."""
    m = min(f_cap, r_cap)

    def init(root_ids, root_sups, root_mask, n_roots):
        lane = jnp.arange(f_cap, dtype=jnp.int32)
        active = lane < n_roots
        slots = jnp.where(active, root_ids, 0).astype(jnp.int32)
        s_mask = active[:, None] & root_mask[None, :]
        i_mask = s_mask & (jnp.arange(ni)[None, :] > slots[:, None])
        nits = jnp.ones(f_cap, jnp.int32)
        rec_idx = lane
        rec_head = jnp.stack(
            [jnp.where(active, -1, 0), slots, active.astype(jnp.int32)],
            axis=1)
        records = jnp.zeros((r_cap, 3), jnp.int32).at[:m].set(rec_head[:m])
        recsup = jnp.zeros(r_cap, jnp.int32).at[:m].set(
            jnp.where(active, root_sups, 0)[:m])
        return slots, s_mask, i_mask, nits, rec_idx, records, recsup

    if mesh is None:
        return jax.jit(init)
    from jax.sharding import NamedSharding
    rep = NamedSharding(mesh, P())
    return jax.jit(init, out_shardings=(rep,) * 7)


@functools.lru_cache(maxsize=32)
def _fused_mine_fn(mesh: Optional[Mesh], n_words: int, ni_pad: int,
                   max_its: Optional[int],
                   f_cap: int, c_cap: int, r_cap: int, l_max: int,
                   use_pallas: bool, s_block: int, interpret: bool):
    """Compiled whole-mine program, cached per geometry (see _spade_fns for
    the per-object jit-cache reasoning).  ``minsup`` is a traced argument,
    not part of the cache key — streaming windows re-mine with a drifting
    absolute minsup and must reuse the compile.

    Store rows: [0, ni_pad) item id-lists; two child regions of f_cap rows
    each (double buffer); last row = scratch, which must STAY all zeros —
    inactive lanes read it as their parent bitmap, so every masked scatter
    drops its garbage rows OUT OF BOUNDS (jnp mode='drop'), never into
    scratch.
    """
    W = n_words
    region_a = ni_pad
    region_b = ni_pad + f_cap
    scratch = ni_pad + 2 * f_cap

    def pair_matrix(pt_flat, store):
        # [2F, S*W] x item rows -> [2F, ni_pad] supports
        pt3 = pt_flat.reshape(pt_flat.shape[0], -1, W)
        items3 = store[:ni_pad].reshape(ni_pad, -1, W)
        if use_pallas:
            return PS.pair_supports(
                jnp.transpose(pt3, (0, 2, 1)),
                jnp.transpose(items3, (0, 2, 1)),
                ni_pad, s_block=s_block, interpret=interpret)
        return _dense_pair_jnp(pt3, items3)

    def body(carry):
        (store, slots, s_mask, i_mask, nits, rec_idx,
         n_nodes, rec_count, records, recsup, overflow, level,
         minsup, n_cand) = carry

        lane = jnp.arange(f_cap, dtype=jnp.int32)
        active = lane < n_nodes
        gslots = jnp.where(active, slots, scratch)

        # prep: gather + s-ext transform, interleaved [2F, S*W]
        parents = store[gslots].reshape(f_cap, -1, W)
        pt = jnp.stack([parents, B.sext_transform(parents)], axis=1)
        pt_flat = pt.reshape(2 * f_cap, -1)

        pair = pair_matrix(pt_flat, store)
        if mesh is not None:
            pair = jax.lax.psum(pair, SEQ_AXIS)
        pair = pair.reshape(f_cap, 2, ni_pad)
        sup_i = pair[:, 0, :]     # plain & item  = i-extension
        sup_s = pair[:, 1, :]     # transformed & item = s-extension

        allow_s = active if max_its is None else (active & (nits < max_its))
        cand_s = s_mask & allow_s[:, None]
        cand_i = i_mask & active[:, None]
        n_cand = n_cand + jnp.sum(cand_s, dtype=jnp.int32) + jnp.sum(
            cand_i, dtype=jnp.int32)
        surv_s = cand_s & (sup_s >= minsup)
        surv_i = cand_i & (sup_i >= minsup)

        # ---- emission: records for every surviving candidate ----
        # flat order: (node, ext-type: s then i, item) — any fixed order
        # works, the pattern SET is canonicalized on host.
        flat = jnp.stack([surv_s, surv_i], axis=1).reshape(-1)
        n_emit = jnp.sum(flat, dtype=jnp.int32)
        (pos,) = jnp.nonzero(flat, size=c_cap, fill_value=2 * f_cap * ni_pad)
        valid = jnp.arange(c_cap) < n_emit
        e_f = (pos // (2 * ni_pad)).astype(jnp.int32)
        e_iss = (1 - (pos // ni_pad) % 2).astype(jnp.int32)  # 1 = s-ext
        e_item = (pos % ni_pad).astype(jnp.int32)
        e_f_c = jnp.where(valid, e_f, 0)
        e_item_c = jnp.where(valid, e_item, 0)
        e_sup = jnp.where(
            e_iss == 1,
            sup_s[e_f_c, e_item_c], sup_i[e_f_c, e_item_c])
        e_rec = rec_count + jnp.cumsum(valid.astype(jnp.int32)) - 1
        widx = jnp.where(valid, e_rec, r_cap)
        rec_rows = jnp.stack(
            [rec_idx[e_f_c], e_item_c, e_iss], axis=1).astype(jnp.int32)
        records = records.at[widx].set(rec_rows, mode="drop")
        recsup = recsup.at[widx].set(e_sup.astype(jnp.int32), mode="drop")

        # ---- children: surviving candidates with possible extensions ----
        # child.s_mask = parent's surviving s-items; child.i_mask =
        # (s-child ? surviving s-items : surviving i-items) restricted to
        # items > extension item (oracle.py mine_spade_vertical).
        srow = surv_s[e_f_c]                            # [C, NI]
        irow = jnp.where((e_iss == 1)[:, None], srow, surv_i[e_f_c])
        gt = jnp.arange(ni_pad)[None, :] > e_item_c[:, None]
        child_i_mask = irow & gt
        child_nits = nits[e_f_c] + e_iss
        child_allow_s = (jnp.ones((c_cap,), bool) if max_its is None
                         else child_nits < max_its)
        has_ext = (jnp.any(srow, axis=1) & child_allow_s) | jnp.any(
            child_i_mask, axis=1)
        is_child = valid & has_ext
        n_children = jnp.sum(is_child, dtype=jnp.int32)
        (cpos,) = jnp.nonzero(is_child, size=f_cap, fill_value=c_cap - 1)
        cvalid = jnp.arange(f_cap) < n_children
        c_f = e_f_c[cpos]
        c_item = e_item_c[cpos]
        c_iss = e_iss[cpos]

        # materialize child bitmaps into the other region
        child_base = jnp.where(level % 2 == 0, region_a, region_b)
        new_slots = (child_base + lane).astype(jnp.int32)
        # pt interleave: row 2f is the PLAIN parent, 2f+1 its s-ext
        # TRANSFORM; an s-extension (iss=1) joins the transform.
        # invalid child lanes drop their (garbage) joins rows out of
        # bounds, like the records path — writing them into scratch would
        # break its all-zeros invariant (inactive lanes READ scratch)
        joins = pt_flat[2 * c_f + c_iss] & store[c_item]
        widx2 = jnp.where(cvalid, new_slots, store.shape[0])
        store = store.at[widx2].set(joins, mode="drop")

        new_s_mask = srow[cpos] & cvalid[:, None]
        new_i_mask = child_i_mask[cpos] & cvalid[:, None]
        new_nits = jnp.where(cvalid, child_nits[cpos], 0).astype(jnp.int32)
        new_rec = jnp.where(cvalid, e_rec[cpos], 0).astype(jnp.int32)

        overflow = (overflow | (n_emit > c_cap)
                    | (rec_count + n_emit > r_cap) | (n_children > f_cap))
        return (store, new_slots, new_s_mask, new_i_mask, new_nits, new_rec,
                n_children, rec_count + n_emit, records, recsup, overflow,
                level + 1, minsup, n_cand)

    def cond(carry):
        n_nodes, overflow, level = carry[6], carry[10], carry[11]
        return (n_nodes > 0) & (~overflow) & (level < l_max)

    def run(store, slots, s_mask, i_mask, nits, rec_idx, n_nodes, rec_count,
            records, recsup, minsup):
        carry = (store, slots, s_mask, i_mask, nits, rec_idx, n_nodes,
                 rec_count, records, recsup, jnp.bool_(False),
                 jnp.int32(0), minsup, jnp.int32(0))
        out = jax.lax.while_loop(cond, body, carry)
        # Pack EVERYTHING the host needs into two arrays: on a tunneled
        # TPU every separate device->host array read costs its own
        # ~100ms latency, so six scalar/array outputs would cost ~6
        # roundtrips.  recsup rides as a 4th column of records.
        packed = jnp.concatenate([out[8], out[9][:, None]], axis=1)
        counters = jnp.stack([
            out[7],                                  # rec_count
            (out[10] | (out[6] > 0)).astype(jnp.int32),  # overflow
            out[11],                                 # levels
            out[13],                                 # candidates
        ])
        return packed, counters

    # no donate: the store is not among run's outputs, so XLA cannot alias
    # it anyway (donating would only emit a "not usable" warning); the
    # while_loop carry reuses its buffer internally regardless
    if mesh is None:
        return jax.jit(run)
    st = P(None, SEQ_AXIS)
    rep = P()
    return jax.jit(
        shard_map(
            run, mesh=mesh,
            in_specs=(st, rep, rep, rep, rep, rep, rep, rep, rep, rep, rep),
            out_specs=(rep, rep),
            check_vma=False))


class FusedSpadeTPU:
    """Whole-mine-on-device SPADE for small/medium databases.

    Returns None from :meth:`mine` when a static cap overflowed — the
    caller (``mine_spade_tpu(fused="auto")``) falls back to the classic
    engine, which has no capacity limits.
    """

    def __init__(
        self,
        vdb: VerticalDB,
        minsup_abs: int,
        *,
        mesh: Optional[Mesh] = None,
        max_pattern_itemsets: Optional[int] = None,
        caps: Optional[FusedCaps] = None,
        use_pallas="auto",
        shape_buckets: bool = False,
    ):
        self.vdb = vdb
        self.minsup = int(minsup_abs)
        self.mesh = mesh
        self.max_its = max_pattern_itemsets
        self.caps = caps or FusedCaps.for_mesh(mesh)
        self._put = functools.partial(MH.host_to_device, mesh)

        n_items, n_seq, n_words = vdb.n_items, vdb.n_sequences, vdb.n_words
        if use_pallas == "auto":
            self.use_pallas = (n_items > 0
                               and jax.default_backend() == "tpu")
        else:
            self.use_pallas = bool(use_pallas) and n_items > 0
        self._interpret = jax.default_backend() != "tpu"

        # shape_buckets: pow2-bucket the sequence axis (and the item-row
        # count, via ni_pad below on the bucketed alphabet) so streaming
        # windows with drifting sizes reuse the compiled program — same
        # trade as the classic engine's shape_buckets.  Derived sizing
        # lives in fused_geometry, shared with the shape-key enumerator.
        g = fused_geometry(n_seq, n_items, n_words, mesh=mesh,
                           use_pallas=self.use_pallas,
                           shape_buckets=shape_buckets, caps=self.caps)
        n_seq = g["n_seq"]
        self._s_block = g["s_block"]
        self.n_seq, self.n_words = n_seq, n_words
        self.ni_pad = g["ni_pad"]
        self.n_items = n_items
        # shape_key: compiled-geometry identity (same contract as
        # SpadeTPU.stats) — distinct keys across a stream of mines bound
        # its recompile count; registry-recorded for /admin/shapes
        self.stats = {"patterns": 0, "levels": 0, "fused": True,
                      "shape_key": g["shape_key"]}
        shapes.record(g["shape_key"])

    def nbytes(self) -> int:
        rows = self.ni_pad + 2 * self.caps.f_cap + 1
        return rows * self.n_seq * self.n_words * 4

    def mine(self) -> Optional[List[PatternResult]]:
        vdb, cap = self.vdb, self.caps
        roots = [i for i in range(self.n_items)
                 if int(vdb.item_supports[i]) >= self.minsup]
        n_roots = len(roots)
        if n_roots == 0:
            return []
        if n_roots > min(cap.f_cap, cap.r_cap):
            self.stats["fused_overflow"] = True
            return None  # frontier can't hold the roots: classic engine

        rows = self.ni_pad + 2 * cap.f_cap + 1
        store = scatter_build_store(vdb, rows, self.n_seq, self.n_words,
                                    mesh=self.mesh, put=self._put, flat=True)

        ni = self.ni_pad
        root_mask = np.zeros(ni, bool)
        root_mask[roots] = True
        root_ids = np.zeros(cap.f_cap, np.int32)
        root_sups = np.zeros(cap.f_cap, np.int32)
        for k, i in enumerate(roots):
            root_ids[k] = i
            root_sups[k] = int(vdb.item_supports[i])
        # frontier + record buffers are built ON DEVICE from the ~8 KB of
        # root data (see _fused_init_fn) — the zero-dominated buffers
        # themselves never cross the host->device link
        n_roots_dev = self._put(np.int32(n_roots))
        slots, s_mask, i_mask, nits, rec_idx, records, recsup = (
            _fused_init_fn(self.mesh, cap.f_cap, ni, cap.r_cap)(
                self._put(root_ids), self._put(root_sups),
                self._put(root_mask), n_roots_dev))

        fn = _fused_mine_fn(
            self.mesh, self.n_words, ni, self.max_its,
            cap.f_cap, cap.c_cap, cap.r_cap, cap.l_max,
            self.use_pallas, self._s_block, self._interpret)
        # scalars go through _put too: a bare jnp.int32 is a committed
        # single-device array, which cannot feed a multi-controller
        # computation (parallel/multihost.py replicate)
        packed_dev, counters_dev = fn(
            store, slots, s_mask, i_mask, nits, rec_idx, n_roots_dev,
            n_roots_dev, records, recsup, self._put(np.int32(self.minsup)))
        try:
            counters_dev.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass  # method unavailable on this backend

        counters = np.asarray(counters_dev)
        n_rec = int(counters[0])
        self.stats["levels"] = int(counters[2])
        self.stats["candidates"] = int(counters[3])
        self.stats["kernel_launches"] = 1  # the whole mine is one dispatch
        if bool(counters[1]):
            self.stats["fused_overflow"] = True
            return None  # the record buffer is garbage: never transferred
        # Two-step readback: fetch only the VALID prefix of the record
        # buffer.  The full [r_cap, 4] buffer is ~2 MB, and on a tunneled
        # TPU (~10 MB/s, ~100 ms/roundtrip) its transfer dominates small
        # mines; reading the counters first and slicing costs one extra
        # roundtrip but transfers n_rec rows instead of r_cap.  The slice
        # length is pow2-bucketed so the lowered slice program is reused
        # across mines instead of recompiling per result count.
        n_fetch = min(cap.r_cap, next_pow2(max(n_rec, 1)))
        packed = np.asarray(packed_dev[:n_fetch])
        rec, sup = packed[:, :3], packed[:, 3]

        # reconstruct patterns by following parent links (parents always
        # precede children in the record order)
        ids = vdb.item_ids
        pats: List[Optional[tuple]] = [None] * n_rec
        results: List[PatternResult] = []
        for k in range(n_rec):
            parent, item, iss = int(rec[k, 0]), int(rec[k, 1]), int(rec[k, 2])
            it_id = int(ids[item])
            if parent < 0:
                pat = ((it_id,),)
            elif iss:
                pat = pats[parent] + ((it_id,),)
            else:
                pat = pats[parent][:-1] + (pats[parent][-1] + (it_id,),)
            pats[k] = pat
            results.append((pat, int(sup[k])))
        self.stats["patterns"] = len(results)
        return sort_patterns(results)
