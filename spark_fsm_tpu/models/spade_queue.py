"""Queue-fused SPADE: sparse-frontier whole-mine-on-device engine.

The dense fused engine (models/spade_fused.py) removes the classic
engine's per-wave readbacks, but pays for it with a DENSE
[2*f_cap, ni_pad] pair matrix every level — inactive frontier lanes
included, because the frontier cap is a static shape.  At headline scale
(~2.6k-node frontier over a 78k-sequence store) that is ~70 GB of HBM
traffic per level, which is why the router correctly refuses it there and
the classic engine eats ~1.1 s of readback latency instead
(docs/DESIGN.md "Measured wall anatomy").

This engine keeps the classic engine's cost model — each wave evaluates
only ~node_batch REAL nodes against the item rows — but runs the whole
DFS inside ONE ``lax.while_loop``:

- the frontier is a device-resident FIFO queue over a RING of bitmap
  slots.  FIFO order makes slot lifetime equal queue residency, so the
  ring needs to hold only the live frontier (~two BFS levels), not the
  whole mine;
- each iteration pops a fixed-width wave of ``nb`` nodes (inactive lanes
  read the all-zero scratch row), computes the [2*nb, ni_pad] pair matrix
  (Pallas on TPU — the classic engine's exact per-wave compute), prunes
  by a TRACED minsup on device, appends surviving records to the packed
  record buffer, and enqueues children (bitmap + candidate masks) at the
  ring tail;
- root nodes alias the item rows through a slot-indirection array, so
  enqueueing the root level copies nothing;
- the host makes ONE blocking readback at the end (packed records +
  counters), exactly like the dense engine.

So: classic-engine compute, dense-engine latency.  Per-wave HBM traffic
scales with the ACTUAL frontier (padded to one wave), and total waves
equal the classic engine's — the win is removing every intermediate
readback from the DFS critical path (~1.09 s of the 1.18 s headline wall
on a tunneled TPU).

Static caps (wave width, ring size, emissions/wave, total records, wave
count) keep all shapes compile-time constant; any overflow sets a flag
and the caller falls back to the classic engine — capacity is a routing
concern, never a correctness one (same contract as the dense engine).
Enumeration is byte-identical to the oracle by construction: the masks
implement its S/I candidate-list rules (SURVEY.md sec 2.3 step 3), and
FIFO wave order only permutes record order — the pattern SET is
canonicalized on host.
"""

from __future__ import annotations

import functools
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_fsm_tpu.data.vertical import VerticalDB
from spark_fsm_tpu.models._common import (
    FrontierNode, bucket_seq, decode_frontier, device_axes,
    device_hbm_budget, encode_frontier, next_pow2, scatter_build_store)
from spark_fsm_tpu.models.spade_fused import _dense_pair_jnp
from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.ops import pallas_support as PS
from spark_fsm_tpu.ops import ragged_batch as RB
from spark_fsm_tpu.parallel import multihost as MH
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, pad_to_multiple, shard_map
from spark_fsm_tpu.service import fusion as FZ
from spark_fsm_tpu.utils import faults, jobctl, obs, shapes, watchdog
from spark_fsm_tpu.utils.canonical import PatternResult, sort_patterns


def queue_geometry(n_sequences: int, n_items: int, n_words: int, *,
                   mesh: Optional[Mesh] = None, use_pallas: bool = False,
                   shape_buckets: bool = False,
                   caps: Optional["QueueCaps"] = None) -> dict:
    """Derived device geometry of a :class:`QueueSpadeTPU` — shared by
    the constructor and the shape-key enumerator (utils/shapes.py); pure
    host arithmetic, no device allocation (the budget probe reads device
    metadata only)."""
    import jax as _jax

    n_shards = 1 if mesh is None else mesh.devices.size
    n_seq, s_block, ni_pad = device_axes(
        n_sequences, n_items, n_words, mesh=mesh, use_pallas=use_pallas,
        shape_buckets=shape_buckets)
    if caps is None:
        dev = mesh.devices.flat[0] if mesh is not None else _jax.devices()[0]
        caps = QueueCaps.for_budget(
            n_seq * n_words * 4, ni_pad,
            int(0.45 * device_hbm_budget(dev)), n_shards)
    return {"n_seq": n_seq, "s_block": s_block, "ni_pad": ni_pad,
            "caps": caps,
            # late-wave geometry (ops/ragged_batch.py): the narrow wave
            # width the mine switches to once the live frontier drops
            # below it.  Derived from nb by a pure function, so it adds
            # no shape-key axis — prewarm compiles both wave programs
            # under the one key.
            "nb_late": RB.late_wave_nb(caps.nb, PS.P_TILE),
            "shape_key": shapes.key_queue(n_seq, n_words, ni_pad,
                                          caps.nb, caps.ring)}


class QueueCaps:
    """Static capacities of the queue-fused program (compile-time shapes).

    ``nb``: nodes popped per wave (the classic engine's node_batch).
    ``ring``: live-frontier capacity — bitmap slots + candidate masks.
      FIFO slot reuse means this bounds ``tail - head`` (roughly two BFS
      levels), NOT the total node count of the mine.
    ``c_cap``: records emitted per wave.
    ``m_cap``: child bitmaps MATERIALIZED per wave.  Kept narrower than
      c_cap because the [m_cap, S*W] join tensor is the wave's dominant
      gather cost and real child counts run well below emission counts
      (leaves emit records but materialize nothing).
    ``r_cap``: total records (= patterns) for the whole mine.
    ``i_max``: wave-count ceiling (overflow guard, not a tuning knob).

    Defaults are measured on the headline workload (tunneled v5e,
    BMS-WebView-2-shaped @ 0.1%): nb=512/m_cap=1024 ran 0.41 s steady vs
    0.59 s at nb=1024/m_cap=2048 and 0.86 s at nb=1024/m_cap=4096 — the
    [m_cap, S*W] child-join tensor and the per-wave gathers are the
    marginal costs, and total pair-kernel traffic is nb-invariant (the
    item-side re-read halves per wave as the wave count doubles).
    """

    def __init__(self, nb: int = 512, ring: int = 8192,
                 c_cap: Optional[int] = None, m_cap: Optional[int] = None,
                 r_cap: int = 1 << 17, i_max: int = 8192):
        # 2*nb rows feed the Pallas pair kernel, which asserts
        # P % P_TILE == 0 — round up instead of crashing on TPU.
        self.nb = pad_to_multiple(int(nb), PS.P_TILE)
        self.ring = int(ring)
        self.c_cap = 4 * self.nb if c_cap is None else int(c_cap)
        self.m_cap = min(self.c_cap,
                         max(2 * self.nb, self.c_cap // 2)
                         if m_cap is None else int(m_cap))
        self.r_cap = int(r_cap)
        self.i_max = int(i_max)

    @classmethod
    def for_budget(cls, row_bytes: int, ni_pad: int,
                   budget: int, n_dev: int = 1) -> "QueueCaps":
        """Size the ring to the memory budget: largest pow2 ring in
        [256, 65536] whose working set (the ONE estimator
        ``working_set_bytes`` — also what ``queue_eligible`` judges)
        fits ``budget`` per device.  When even the smallest ring
        overshoots, the smallest is returned anyway — ``queue_eligible``
        refuses such workloads, so only an explicit ``fused="queue"``
        pin reaches the engine then, at the least-memory geometry."""
        per_dev_row = max(1, -(-row_bytes // n_dev))
        best = None
        ring = 256
        while ring <= 65536:
            caps = cls(ring=ring)
            if working_set_bytes(caps, per_dev_row, ni_pad) > budget:
                break
            best = caps
            ring *= 2
        return best if best is not None else cls(ring=256)


def working_set_bytes(caps: QueueCaps, per_dev_row: int,
                      ni_pad: int) -> int:
    """Per-device working set of the queue program — the SINGLE estimator
    shared by ``QueueCaps.for_budget`` (sizing) and ``queue_eligible``
    (routing), so the two can never disagree about what fits.

    Counts: the store carry-doubled (the ``lax.while_loop`` carry cannot
    alias the engine's persistent input store), the per-wave parent/join
    temps, both boolean candidate masks carry-doubled, the int32 queue
    bookkeeping (``q_slot``/``q_nits``/``q_rec``) carry-doubled, and the
    record buffer + supports carry-doubled."""
    store_rows = ni_pad + caps.ring + 1
    return (2 * store_rows * per_dev_row                 # store (x2 carry)
            + (2 * caps.nb + caps.m_cap) * per_dev_row   # wave temps
            + 2 * (2 * caps.ring * ni_pad)               # bool masks (x2)
            + 2 * (3 * caps.ring * 4)                    # int32 queue state
            + 2 * (4 * caps.r_cap * 4))                  # records + recsup


def queue_eligible(vdb: VerticalDB, mesh: Optional[Mesh] = None,
                   caps: Optional[QueueCaps] = None,
                   shape_buckets: bool = False) -> bool:
    """Routing heuristic.  Unlike the dense engine there is no traffic
    ceiling: per-wave traffic tracks the ACTUAL frontier, so total
    traffic ~= the classic engine's — the queue engine is preferable
    whenever it fits.  Two bounds remain:

    - alphabet: the pair matrix spans ALL item rows, so huge alphabets
      (Kosarak-scale frequent projections) belong to the classic
      engine's candidate-exact dispatch;
    - memory: ~2x store (while_loop carry + persistent input) + prep +
      joins + masks must fit ~45% of the device budget (the
      auto_pool_bytes coexistence reasoning)."""
    ni_pad = pad_to_multiple(max(vdb.n_items, 1), PS.I_TILE)
    if ni_pad > 1024:
        return False
    n_dev = 1 if mesh is None else mesh.devices.size
    n_seq = vdb.n_sequences
    if shape_buckets:
        n_seq = bucket_seq(n_seq)
    row_bytes = -(-n_seq // n_dev) * vdb.n_words * 4
    dev = mesh.devices.flat[0] if mesh is not None else jax.devices()[0]
    budget = 0.45 * device_hbm_budget(dev)
    if caps is None:
        # judge the caps the engine would actually auto-size (for_budget
        # shrinks the ring to fit), not the roomy defaults — otherwise
        # eligibility refuses workloads the engine handles fine.  Feed it
        # the SAME per-device row bytes this check uses (row_bytes is
        # already ceil-per-device), so sizing and judging cannot diverge
        # on non-divisible seq counts.
        caps = QueueCaps.for_budget(row_bytes * n_dev, ni_pad,
                                    int(budget), n_dev)
    if caps.ring < vdb.n_items:
        # the ring must hold the whole root level or every mine would
        # build the store only to abort at n_roots > ring (the smaller
        # rings for_budget can now return make this reachable)
        return False
    return working_set_bytes(caps, row_bytes, ni_pad) <= budget


@functools.lru_cache(maxsize=32)
def _queue_init_fn(mesh: Optional[Mesh], ring: int, ni: int, r_cap: int,
                   scratch: int):
    """Device-side queue/record init from ~KBs of root data (the same
    host->device economy as spade_fused._fused_init_fn: the zero-dominated
    buffers never cross the tunnel).  Root nodes alias their item rows via
    ``q_slot`` — no bitmap copies."""
    m = min(ring, r_cap)

    def init(root_ids, root_sups, root_mask, n_roots):
        lane = jnp.arange(ring, dtype=jnp.int32)
        active = lane < n_roots
        rows = jnp.where(active, root_ids, 0).astype(jnp.int32)
        q_slot = jnp.where(active, rows, scratch).astype(jnp.int32)
        q_smask = active[:, None] & root_mask[None, :]
        q_imask = q_smask & (jnp.arange(ni)[None, :] > rows[:, None])
        q_nits = jnp.ones(ring, jnp.int32)
        q_rec = lane
        rec_head = jnp.stack(
            [jnp.where(active, -1, 0), rows, active.astype(jnp.int32)],
            axis=1)
        records = jnp.zeros((r_cap, 3), jnp.int32).at[:m].set(rec_head[:m])
        recsup = jnp.zeros(r_cap, jnp.int32).at[:m].set(
            jnp.where(active, root_sups, 0)[:m])
        return q_slot, q_smask, q_imask, q_nits, q_rec, records, recsup

    if mesh is None:
        return jax.jit(init)
    from jax.sharding import NamedSharding
    rep = NamedSharding(mesh, P())
    return jax.jit(init, out_shardings=(rep,) * 7)


@functools.lru_cache(maxsize=32)
def _queue_refill_fn(mesh: Optional[Mesh], n_words: int,
                     k_steps: int, m_nodes: int):
    """Resume-time ring rebuild: fold each node's join chain from the
    item rows (a pattern's bitmap IS the fold of its extension joins —
    the classic engine's recompute-on-miss contract) and write it into
    the node's ring slot.  ``items/iss/valid`` are [K, M] (M nodes, K
    pow2-bucketed steps; rows past a node's chain carry valid=False and
    leave the fold carry untouched); padded lanes' ``out_slot`` points
    past the store and drops."""
    W = n_words

    def fill(store, items, iss, valid, out_slot):
        b = store[items[0]].reshape(m_nodes, -1, W)

        def body(c, xs):
            it, s, v = xs
            nb = B.join(c, store[it].reshape(c.shape), s)
            return jnp.where(v[:, None, None], nb, c), None

        b, _ = jax.lax.scan(body, b, (items[1:], iss[1:], valid[1:]))
        return store.at[out_slot].set(
            b.reshape(m_nodes, -1), mode="drop")

    if mesh is None:
        return jax.jit(fill)
    st = P(None, SEQ_AXIS)
    rep = P()
    return jax.jit(shard_map(
        fill, mesh=mesh, in_specs=(st, rep, rep, rep, rep),
        out_specs=st, check_vma=False))


@functools.lru_cache(maxsize=32)
def _queue_mine_fn(mesh: Optional[Mesh], n_words: int, ni_pad: int,
                   max_its: Optional[int],
                   nb: int, ring: int, c_cap: int, m_cap: int, r_cap: int,
                   i_max: int,
                   use_pallas: bool, s_block: int, interpret: bool,
                   seg: bool = False, donate: bool = False,
                   nb_late: int = 0):
    """Compiled whole-mine program, cached per geometry.  ``minsup`` is a
    traced argument (streaming windows re-mine on one compile).

    ``seg``: False compiles the whole-mine program (one dispatch, packed
    records out).  True compiles the SEGMENTED variant for checkpointed
    mines: run at most ``wave_budget`` waves (a TRACED argument — one
    compile serves every segment size), return the full device carry plus
    a small counter vector — the host loops segments, reading only the
    counters between them, and snapshots the frontier at wave boundaries.
    ``donate`` donates the carry arrays (segments >= 2, whose inputs are
    the previous segment's outputs — the FIRST segment must not donate
    the engine's persistent store).

    ``nb_late`` (one-shot only; 0 or >= nb disables): the LATE-WAVE
    geometry (ops/ragged_batch.py).  The wave width is static, so a
    shrinking frontier pays a full [2*nb, ni_pad] pair matrix for a
    handful of live lanes every late wave; the carry, however, is
    nb-INDEPENDENT (ring/record shapes only), so the one dispatch runs
    TWO while_loops back to back — wide waves while the live frontier
    exceeds ``nb_late``, then narrow ``nb_late`` waves to drain it —
    merging what were many underfilled full-width waves into well-filled
    narrow ones at zero extra readbacks.  The segmented path gets the
    same ladder host-side: the caller constructs a second seg program at
    ``nb = nb_late`` and switches when the counters show a small
    frontier (carry shapes match, so programs interchange mid-mine).

    Store rows: [0, ni_pad) item id-lists (read-only — child writes index
    >= ni_pad by construction); [ni_pad, ni_pad + ring) the slot ring;
    last row = scratch, kept all-zero by dropping every masked write out
    of bounds (inactive lanes READ scratch as their parent bitmap).
    """
    W = n_words
    scratch = ni_pad + ring

    def pair_matrix(pt_flat, store):
        pt3 = pt_flat.reshape(pt_flat.shape[0], -1, W)
        items3 = store[:ni_pad].reshape(ni_pad, -1, W)
        if use_pallas:
            return PS.pair_supports(
                jnp.transpose(pt3, (0, 2, 1)),
                jnp.transpose(items3, (0, 2, 1)),
                ni_pad, s_block=s_block, interpret=interpret)
        return _dense_pair_jnp(pt3, items3)

    def make_body(nbw: int):
        return lambda carry: _body(carry, nbw)

    def _body(carry, nb):
        # ``nb`` here is the BODY's wave width (wide or late geometry);
        # every carry shape below is width-independent
        (store, q_slot, q_smask, q_imask, q_nits, q_rec, head, tail,
         rec_count, records, recsup, overflow, wave, minsup, n_cand) = carry

        lane = jnp.arange(nb, dtype=jnp.int32)
        qid = head + lane
        active = qid < tail
        ridx = jnp.where(active, qid % ring, ring - 1)
        gslot = jnp.where(active, q_slot[ridx], scratch)

        parents = store[gslot].reshape(nb, -1, W)
        pt = jnp.stack([parents, B.sext_transform(parents)], axis=1)
        pt_flat = pt.reshape(2 * nb, -1)

        pair = pair_matrix(pt_flat, store)
        if mesh is not None:
            pair = jax.lax.psum(pair, SEQ_AXIS)
        pair = pair.reshape(nb, 2, ni_pad)
        sup_i = pair[:, 0, :]     # plain & item       = i-extension
        sup_s = pair[:, 1, :]     # transformed & item = s-extension

        nits = q_nits[ridx]
        allow_s = active if max_its is None else (active & (nits < max_its))
        cand_s = q_smask[ridx] & allow_s[:, None]
        cand_i = q_imask[ridx] & active[:, None]
        n_cand = n_cand + jnp.sum(cand_s, dtype=jnp.int32) + jnp.sum(
            cand_i, dtype=jnp.int32)
        surv_s = cand_s & (sup_s >= minsup)
        surv_i = cand_i & (sup_i >= minsup)

        # ---- records for every surviving candidate (spade_fused order:
        # (lane, ext-type: s then i, item); the SET is canonicalized) ----
        flat = jnp.stack([surv_s, surv_i], axis=1).reshape(-1)
        n_emit = jnp.sum(flat, dtype=jnp.int32)
        (pos,) = jnp.nonzero(flat, size=c_cap, fill_value=2 * nb * ni_pad)
        valid = jnp.arange(c_cap) < n_emit
        e_f = (pos // (2 * ni_pad)).astype(jnp.int32)
        e_iss = (1 - (pos // ni_pad) % 2).astype(jnp.int32)  # 1 = s-ext
        e_item = (pos % ni_pad).astype(jnp.int32)
        e_f_c = jnp.where(valid, e_f, 0)
        e_item_c = jnp.where(valid, e_item, 0)
        e_sup = jnp.where(
            e_iss == 1, sup_s[e_f_c, e_item_c], sup_i[e_f_c, e_item_c])
        e_rec = rec_count + jnp.cumsum(valid.astype(jnp.int32)) - 1
        widx = jnp.where(valid, e_rec, r_cap)
        rec_rows = jnp.stack(
            [q_rec[ridx][e_f_c], e_item_c, e_iss], axis=1).astype(jnp.int32)
        records = records.at[widx].set(rec_rows, mode="drop")
        recsup = recsup.at[widx].set(e_sup.astype(jnp.int32), mode="drop")

        # ---- children: surviving candidates with possible extensions ----
        srow = surv_s[e_f_c]                            # [C, NI]
        irow = jnp.where((e_iss == 1)[:, None], srow, surv_i[e_f_c])
        gt = jnp.arange(ni_pad)[None, :] > e_item_c[:, None]
        child_i_mask = irow & gt
        child_nits = nits[e_f_c] + e_iss
        child_allow_s = (jnp.ones((c_cap,), bool) if max_its is None
                         else child_nits < max_its)
        has_ext = (jnp.any(srow, axis=1) & child_allow_s) | jnp.any(
            child_i_mask, axis=1)
        is_child = valid & has_ext
        n_children = jnp.sum(is_child, dtype=jnp.int32)
        (cpos,) = jnp.nonzero(is_child, size=m_cap, fill_value=c_cap - 1)
        cvalid = jnp.arange(m_cap) < n_children
        c_f = e_f_c[cpos]
        c_item = e_item_c[cpos]
        c_iss = e_iss[cpos]

        # enqueue at the ring tail.  Ring safety: children may reuse the
        # slots of nodes popped THIS wave (reads of those slots precede
        # these writes in dataflow order); overwriting a still-live slot
        # implies new_tail - new_head > ring, which raises overflow and
        # discards the whole mine.  Invalid lanes drop out of bounds so
        # scratch stays all-zero (spade_fused's invariant).
        child_qid = tail + jnp.cumsum(cvalid.astype(jnp.int32)) - 1
        child_ridx = child_qid % ring
        joins = pt_flat[2 * c_f + c_iss] & store[c_item]
        store = store.at[jnp.where(cvalid, ni_pad + child_ridx,
                                   store.shape[0])].set(joins, mode="drop")
        mwidx = jnp.where(cvalid, child_ridx, ring)
        q_slot = q_slot.at[mwidx].set(ni_pad + child_ridx, mode="drop")
        q_smask = q_smask.at[mwidx].set(srow[cpos], mode="drop")
        q_imask = q_imask.at[mwidx].set(child_i_mask[cpos], mode="drop")
        q_nits = q_nits.at[mwidx].set(child_nits[cpos], mode="drop")
        q_rec = q_rec.at[mwidx].set(e_rec[cpos], mode="drop")

        new_head = jnp.minimum(head + nb, tail)
        new_tail = tail + n_children
        overflow = (overflow | (n_emit > c_cap) | (n_children > m_cap)
                    | (rec_count + n_emit > r_cap)
                    | (new_tail - new_head > ring))
        return (store, q_slot, q_smask, q_imask, q_nits, q_rec, new_head,
                new_tail, rec_count + n_emit, records, recsup, overflow,
                wave + 1, minsup, n_cand)

    body = make_body(nb)

    def cond(carry):
        head, tail, overflow, wave = carry[6], carry[7], carry[11], carry[12]
        return (tail > head) & (~overflow) & (wave < i_max)

    # late-wave phase shapes (one-shot only): the narrow loop gets a
    # proportionally larger wave ceiling — it pops nb/nb_late fewer
    # nodes per wave, so the same mine legitimately needs that many
    # more waves before the overflow guard may fire
    ladder = bool(nb_late) and nb_late < nb and not seg
    if ladder:
        i_max_late = i_max * max(1, nb // nb_late)
        body_late = make_body(nb_late)

        def cond_wide(carry):
            head, tail = carry[6], carry[7]
            overflow, wave = carry[11], carry[12]
            return ((tail - head) > nb_late) & (~overflow) & (wave < i_max)

        def cond_late(carry):
            head, tail = carry[6], carry[7]
            overflow, wave = carry[11], carry[12]
            return (tail > head) & (~overflow) & (wave < i_max_late)

    def run(store, q_slot, q_smask, q_imask, q_nits, q_rec, n_roots,
            records, recsup, minsup):
        carry = (store, q_slot, q_smask, q_imask, q_nits, q_rec,
                 jnp.int32(0), n_roots, n_roots, records, recsup,
                 jnp.bool_(False), jnp.int32(0), minsup, jnp.int32(0))
        if ladder:
            # two sequential while_loops in the ONE compiled program:
            # wide waves while the live frontier exceeds nb_late (a
            # frontier of <= nb_late roots skips straight to narrow),
            # then narrow waves drain the tail.  The frontier may
            # briefly regrow past nb_late inside the narrow phase —
            # correct either way, just more (cheap) waves.
            out = jax.lax.while_loop(cond_wide, body, carry)
            wide_waves = out[12]
            out = jax.lax.while_loop(cond_late, body_late, out)
            late_waves = out[12] - wide_waves
        else:
            out = jax.lax.while_loop(cond, body, carry)
            late_waves = jnp.int32(0)
        # ONE packed array: rows 0-1 the counter block, rows 2.. the
        # records with supports as a 4th column.  Folding the counters in
        # lets the host prefetch a fixed-size prefix and finish typical
        # mines in a single device->host roundtrip (~100 ms each on a
        # tunneled TPU).
        counters = jnp.stack([
            out[8],                                      # rec_count
            (out[11] | (out[7] > out[6])).astype(jnp.int32),  # overflow
            out[12],                                     # waves
            out[14],                                     # candidates
        ])
        z = jnp.int32(0)
        counters2 = jnp.stack([late_waves, z, z, z])  # late-wave row
        return jnp.concatenate(
            [counters[None, :], counters2[None, :],
             jnp.concatenate([out[9], out[10][:, None]], axis=1)], axis=0)

    def run_seg(store, q_slot, q_smask, q_imask, q_nits, q_rec, head, tail,
                rec_count, records, recsup, overflow, wave, minsup, n_cand,
                wave_budget):
        wave_end = wave + wave_budget

        def body_seg(c):
            return body(c[:15]) + (c[15],)

        def cond_seg(c):
            return cond(c[:15]) & (c[12] < c[15])

        out = jax.lax.while_loop(
            cond_seg, body_seg,
            (store, q_slot, q_smask, q_imask, q_nits, q_rec, head, tail,
             rec_count, records, recsup, overflow, wave, minsup, n_cand,
             wave_end))
        counters = jnp.stack([
            out[8],                                   # rec_count
            out[11].astype(jnp.int32),                # overflow
            out[12],                                  # waves so far
            out[14],                                  # candidates
            (out[7] > out[6]).astype(jnp.int32),      # work pending
            out[6],                                   # head
            out[7],                                   # tail
        ])
        return out[:15], counters

    if not seg:
        if mesh is None:
            return jax.jit(run)
        st = P(None, SEQ_AXIS)
        rep = P()
        return jax.jit(
            shard_map(
                run, mesh=mesh,
                in_specs=(st, rep, rep, rep, rep, rep, rep, rep, rep, rep),
                out_specs=rep,
                check_vma=False))
    donate_nums = (0, 1, 2, 3, 4, 5, 9, 10) if donate else ()
    if mesh is None:
        return jax.jit(run_seg, donate_argnums=donate_nums)
    st = P(None, SEQ_AXIS)
    rep = P()
    carry_specs = (st,) + (rep,) * 14
    return jax.jit(
        shard_map(
            run_seg, mesh=mesh,
            in_specs=carry_specs + (rep,),
            out_specs=(carry_specs, rep),
            check_vma=False),
        donate_argnums=donate_nums)


class QueueSpadeTPU:
    """Sparse-frontier whole-mine-on-device SPADE.

    Returns None from :meth:`mine` when a static cap overflowed — the
    caller (``mine_spade_tpu(fused="auto")``) falls back to the classic
    engine.  The store is built once in ``__init__`` and reused across
    :meth:`mine` calls (the loop never writes item rows), so steady-state
    re-mines skip the token upload + scatter-build like the classic
    engine does.
    """

    def __init__(
        self,
        vdb: VerticalDB,
        minsup_abs: int,
        *,
        mesh: Optional[Mesh] = None,
        max_pattern_itemsets: Optional[int] = None,
        caps: Optional[QueueCaps] = None,
        use_pallas="auto",
        shape_buckets: bool = False,
        partition=None,
    ):
        self.vdb = vdb
        self.minsup = int(minsup_abs)
        self.mesh = mesh
        self.max_its = max_pattern_itemsets
        # equivalence-class partition slice (parallel/partition.py):
        # (PartitionPlan, part_idx) seeds ONLY the owned classes' roots
        # — a pattern's class is its first item (the DFS root; itemset
        # extensions add larger items only), so the owned slices are
        # disjoint and their union is the full pattern set.  Candidate
        # MASKS stay full-width: extensions draw from every frequent
        # item regardless of who owns the root.
        self._partition = partition
        self._put = functools.partial(MH.host_to_device, mesh)

        n_items, n_seq, n_words = vdb.n_items, vdb.n_sequences, vdb.n_words
        if use_pallas == "auto":
            self.use_pallas = (n_items > 0
                               and jax.default_backend() == "tpu")
        else:
            self.use_pallas = bool(use_pallas) and n_items > 0
        self._interpret = jax.default_backend() != "tpu"

        # Derived sizing lives in queue_geometry — shared with the
        # shape-key enumerator (utils/shapes.py) so prewarm's key set is
        # exactly what this constructor fixes.
        g = queue_geometry(n_seq, n_items, n_words, mesh=mesh,
                           use_pallas=self.use_pallas,
                           shape_buckets=shape_buckets, caps=caps)
        n_seq = g["n_seq"]
        self._s_block = g["s_block"]
        self.n_seq, self.n_words = n_seq, n_words
        self.ni_pad = g["ni_pad"]
        self.n_items = n_items
        caps = g["caps"]
        self.caps = caps
        self._nb_late = g["nb_late"]
        self.stats = {"patterns": 0, "waves": 0, "fused": "queue",
                      "shape_key": g["shape_key"]}
        shapes.record(g["shape_key"])

        rows = self.ni_pad + caps.ring + 1
        self.store = scatter_build_store(
            vdb, rows, n_seq, n_words, mesh=mesh, put=self._put,
            bucket_tokens=shape_buckets, flat=True)

    def nbytes(self) -> int:
        rows = self.ni_pad + self.caps.ring + 1
        return rows * self.n_seq * self.n_words * 4

    def mine(self, *, resume: Optional[dict] = None,
             checkpoint_cb=None, checkpoint_every_s: float = 30.0,
             seg_waves: int = 256) -> Optional[List[PatternResult]]:
        """Run the queue-fused mine.  Without checkpoint plumbing this is
        the ONE-dispatch/one-readback program (the headline path).  With
        ``resume``/``checkpoint_cb`` (SURVEY.md sec 5 checkpoint row) the
        mine runs in <= ``seg_waves``-wave segments: between segments the
        host reads a 7-int counter vector, and at most every
        ``checkpoint_every_s`` seconds snapshots the live frontier into
        the classic engine's ``encode_frontier`` format — so a snapshot
        taken here resumes in EITHER engine (e.g. the classic fallback
        after a mid-mine cap overflow)."""
        if resume is None and checkpoint_cb is None:
            return self._mine_oneshot()
        return self._mine_segmented(resume, checkpoint_cb,
                                    checkpoint_every_s, seg_waves)

    def frontier_fingerprint(self) -> dict:
        """Identical dict to ``SpadeTPU.frontier_fingerprint`` — the two
        engines enumerate identically, so their snapshots interchange
        (a queue snapshot resumes in the classic engine and vice versa)."""
        ids = self.vdb.item_ids
        return {
            "minsup": self.minsup,
            "n_items": self.n_items,
            "n_sequences": self.vdb.n_sequences,
            "max_itemsets": self.max_its,
            "item_ids_head": [int(i) for i in ids[:8]],
            "item_ids_sum": int(ids.astype(np.int64).sum()),
        }

    def _roots(self) -> List[int]:
        return [i for i in range(self.n_items)
                if int(self.vdb.item_supports[i]) >= self.minsup]

    def _seed_roots(self) -> List[int]:
        """The roots THIS engine seeds: every frequent item, or only
        the owned classes' items under a partition slice (the shared-F1
        split — ownership hashes the GLOBAL item id, so every process
        computes the same slice with no coordination)."""
        roots = self._roots()
        if self._partition is None:
            return roots
        plan, pidx = self._partition
        return plan.owned_slice(roots, self.vdb.item_ids, pidx)

    def _root_init(self, roots: List[int]):
        """Device-side queue init from the root level (shared by both
        mine paths; uploads only ~KBs of root data + one counter)."""
        cap, ni = self.caps, self.ni_pad
        root_mask = np.zeros(ni, bool)
        # the mask is the EXTENSION universe — always every frequent
        # item, even when a partition slice seeds only its own roots
        root_mask[self._roots()] = True
        root_ids = np.zeros(cap.ring, np.int32)
        root_sups = np.zeros(cap.ring, np.int32)
        for k, i in enumerate(roots):
            root_ids[k] = i
            root_sups[k] = int(self.vdb.item_supports[i])
        n_roots_dev = self._put(np.int32(len(roots)))
        q_state = _queue_init_fn(self.mesh, cap.ring, ni, cap.r_cap,
                                 ni + cap.ring)(
            self._put(root_ids), self._put(root_sups),
            self._put(root_mask), n_roots_dev)
        return q_state, n_roots_dev

    def _root_carry(self, roots: List[int]):
        """Fresh-mine init as the segmented carry tuple (the scalar
        extras here are segmented-only — the one-shot hot path must not
        pay their uploads)."""
        (q_slot, q_smask, q_imask, q_nits, q_rec, records, recsup), \
            n_roots_dev = self._root_init(roots)
        z = self._put(np.int32(0))
        return (self.store, q_slot, q_smask, q_imask, q_nits, q_rec,
                z, n_roots_dev, n_roots_dev, records, recsup,
                self._put(np.bool_(False)), self._put(np.int32(0)),
                self._put(np.int32(self.minsup)), self._put(np.int32(0)))

    def _decode_records(self, rec: np.ndarray, sup: np.ndarray, n_rec: int,
                        want_steps: bool = False):
        """Patterns (GLOBAL ids) from the packed parent-linked records;
        optionally also each record's step chain in LOCAL indices (the
        snapshot encoder needs both)."""
        ids = self.vdb.item_ids
        pats: List[Optional[tuple]] = [None] * n_rec
        steps_of: List[Optional[tuple]] = [None] * n_rec
        results: List[PatternResult] = []
        for k in range(n_rec):
            parent, item, iss = int(rec[k, 0]), int(rec[k, 1]), int(rec[k, 2])
            it_id = int(ids[item])
            if parent < 0:
                pat = ((it_id,),)
            elif iss:
                pat = pats[parent] + ((it_id,),)
            else:
                pat = pats[parent][:-1] + (pats[parent][-1] + (it_id,),)
            pats[k] = pat
            if want_steps:  # snapshot-only lineage; skip on the hot path
                steps_of[k] = (((item, True),) if parent < 0
                               else steps_of[parent] + ((item, bool(iss)),))
            results.append((pat, int(sup[k])))
        return results, steps_of if want_steps else None

    def _mine_oneshot(self) -> Optional[List[PatternResult]]:
        vdb, cap = self.vdb, self.caps
        roots = self._seed_roots()
        n_roots = len(roots)
        if n_roots == 0:
            return []
        if n_roots > min(cap.ring, cap.r_cap):
            self.stats["fused_overflow"] = True
            return None  # ring can't hold the root level: classic engine

        # deadline/cancel safe point before committing the whole-mine
        # dispatch (one global read when no deadline/cancel is live)
        jobctl.check()
        ni = self.ni_pad
        (q_slot, q_smask, q_imask, q_nits, q_rec, records, recsup), \
            n_roots_dev = self._root_init(roots)
        # watchdog deadline for the whole-mine dispatch: the wave ceiling
        # times the wave width is the program's own upper bound on lanes
        # streamed — the same cost-model units the ragged planner uses.
        # (A CEILING, not a prediction: the span carries it for the
        # trace, but only TSR dispatches — whose planner predicts actual
        # traffic — feed the cost-model drift gauge.)
        bound_s = RB.estimate_seconds(
            cap.nb * cap.i_max, 1, self.n_seq, self.n_words)
        wd_deadline = watchdog.deadline_s(bound_s)
        with obs.span("queue.dispatch", point="oneshot", nb=cap.nb,
                      bound_s=round(bound_s, 6)):
            faults.fault_site("device.dispatch", point="queue_launch")
            fn = _queue_mine_fn(
                self.mesh, self.n_words, ni, self.max_its,
                cap.nb, cap.ring, cap.c_cap, cap.m_cap, cap.r_cap, cap.i_max,
                self.use_pallas, self._s_block, self._interpret,
                nb_late=self._nb_late)
            # the whole-mine program carries per-job device carry state,
            # so it is unfusable by construction — but it IS a device
            # wave, and every wave routes through the fusion broker's
            # accounting/fault surface (one global read when the broker
            # is off; an armed fusion.dispatch fault degrades to this
            # same direct dispatch)
            packed_dev = FZ.dispatch_wave(
                "queue", lambda: fn(
                    self.store, q_slot, q_smask, q_imask, q_nits, q_rec,
                    n_roots_dev, records, recsup,
                    self._put(np.int32(self.minsup))),
                point="oneshot")
        # Single-roundtrip fast path: prefetch a fixed prefix (counter
        # block + the first PREFETCH records, 64 KB) — most mines fit it,
        # so the counter read and the record read share one device->host
        # roundtrip.  Bigger result sets pay one more pow2-bucketed fetch.
        PREFETCH = 4096
        prefix_dev = packed_dev[:2 + min(PREFETCH, cap.r_cap)]
        try:
            prefix_dev.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass  # method unavailable on this backend

        def read():
            faults.fault_site("device.dispatch", point="queue_readback")
            return np.asarray(prefix_dev)

        # a hung whole-mine dispatch fails the launch (the Miner's
        # supervision retries the job) instead of wedging the worker
        with obs.span("queue.readback", bound_s=round(bound_s, 6)):
            prefix = watchdog.run_with_deadline(read, wd_deadline,
                                                site="queue.readback")
        counters = prefix[0]
        n_rec = int(counters[0])
        self.stats["waves"] = int(counters[2])
        self.stats["candidates"] = int(counters[3])
        # narrow-phase waves (row 1 of the counter block): how much of
        # the drain ran at the late-wave geometry instead of full width
        self.stats["late_waves"] = int(prefix[1][0])
        self.stats["kernel_launches"] = 1  # the whole mine is one dispatch
        if bool(counters[1]):
            self.stats["fused_overflow"] = True
            return None  # the record buffer is garbage: never transferred
        if n_rec <= PREFETCH:
            packed = prefix[2:2 + n_rec]
        else:
            n_fetch = min(cap.r_cap, next_pow2(n_rec))
            # the big-result second fetch blocks too — same watchdog
            # deadline as the prefix read (a wedge after the prefix
            # resolved must still fail the launch, not the worker)
            with obs.span("queue.readback", point="big_fetch",
                          n_fetch=n_fetch):
                packed = watchdog.run_with_deadline(
                    lambda: np.asarray(packed_dev[2:2 + n_fetch]),
                    wd_deadline, site="queue.readback")
        rec, sup = packed[:, :3], packed[:, 3]
        results, _ = self._decode_records(rec, sup, n_rec)
        self.stats["patterns"] = len(results)
        return sort_patterns(results)

    # ------------------------------------------------ checkpointed path

    def _mine_segmented(self, resume, checkpoint_cb, every_s: float,
                        seg_waves: int) -> Optional[List[PatternResult]]:
        cap, ni = self.caps, self.ni_pad
        if resume is not None:
            results, nodes = decode_frontier(
                resume, self.frontier_fingerprint(), FrontierNode)
            self.stats["resumed_nodes"] = len(nodes)
            if not nodes:
                self.stats["patterns"] = len(results)
                return sort_patterns(results)
            carry = self._resume_carry(results, nodes)
            if carry is None:
                self.stats["fused_overflow"] = True
                return None
            ckpt_done = len(results)
            pending_n = len(nodes)
        else:
            roots = self._seed_roots()
            if not roots:
                return []
            if len(roots) > min(cap.ring, cap.r_cap):
                self.stats["fused_overflow"] = True
                return None
            carry = self._root_carry(roots)
            ckpt_done = 0
            pending_n = len(roots)
        nbl = self._nb_late
        ratio = max(1, cap.nb // max(1, nbl))

        def seg_fn(narrow: bool, first: bool):
            # the late-wave ladder, host-driven: the narrow program is
            # the SAME segmented program at nb = nb_late (carry shapes
            # are width-independent, so programs interchange mid-mine);
            # its wave ceiling scales by the width ratio, like the
            # one-shot narrow phase
            nbw = nbl if narrow else cap.nb
            return _queue_mine_fn(
                self.mesh, self.n_words, ni, self.max_its,
                nbw, cap.ring, cap.c_cap, cap.m_cap, cap.r_cap,
                cap.i_max * (ratio if narrow else 1),
                self.use_pallas, self._s_block, self._interpret, True,
                not first)

        narrow = nbl < cap.nb and pending_n <= nbl
        last_ckpt = time.monotonic()
        first = True
        last_waves = 0
        # geometric wave-budget growth: fine-grained early boundaries (a
        # checkpoint=1 job writes its first snapshot after wave 1, even
        # for mines that finish inside one interval), coarse later so a
        # long mine pays ~log + wall/interval counter readbacks, not one
        # per wave.  One compiled program serves every budget (traced).
        budget = 1 if checkpoint_cb is not None else seg_waves
        while True:
            # deadline/cancel safe point between segment dispatches —
            # the same boundary the watchdog deadline guards
            jobctl.check()
            nbw = nbl if narrow else cap.nb
            seg_bound_s = RB.estimate_seconds(
                nbw * budget, 1, self.n_seq, self.n_words)
            seg_deadline = watchdog.deadline_s(seg_bound_s)
            with obs.span("queue.segment", nb=nbw, budget=budget,
                          narrow=narrow, bound_s=round(seg_bound_s, 6)):
                faults.fault_site("device.dispatch", point="queue_segment")
                # unfusable (per-job carry) but broker-accounted, like
                # the one-shot dispatch above
                carry, counters_dev = FZ.dispatch_wave(
                    "queue",
                    lambda fnf=seg_fn(narrow, first), c=carry: fnf(
                        *c, self._put(np.int32(budget))),
                    point="segment")
                budget = min(seg_waves, budget * 4)
                first = False
                self.stats["kernel_launches"] = (
                    self.stats.get("kernel_launches", 0) + 1)
                # per-segment counter readback under the dispatch
                # watchdog: the deadline scales with this segment's own
                # wave budget
                counters = watchdog.run_with_deadline(
                    lambda: np.asarray(counters_dev), seg_deadline,
                    site="queue.segment_readback")
            n_rec, oflow, waves, n_cand, pending, head, tail = (
                int(x) for x in counters)
            if narrow:
                self.stats["late_waves"] = (
                    self.stats.get("late_waves", 0) + waves - last_waves)
            last_waves = waves
            wave_ceil = cap.i_max * (ratio if narrow else 1)
            if oflow or (pending and waves >= wave_ceil):
                self.stats["fused_overflow"] = True
                self.stats["waves"] = waves
                return None  # classic fallback resumes from the last save
            if not pending:
                break
            if not narrow and nbl < cap.nb and (tail - head) <= nbl:
                narrow = True  # frontier drained below the late-wave
                # geometry: switch programs for the remaining segments
                # (never switched back — a late regrow just costs waves)
            if (checkpoint_cb is not None
                    and time.monotonic() - last_ckpt >= every_s):
                checkpoint_cb(
                    self._snapshot(carry, head, tail, n_rec, ckpt_done))
                ckpt_done = n_rec
                self.stats["checkpoints"] = (
                    self.stats.get("checkpoints", 0) + 1)
                last_ckpt = time.monotonic()
        self.stats["waves"] = waves
        self.stats["candidates"] = n_cand
        rec = np.asarray(carry[9][:max(n_rec, 1)])[:n_rec]
        sup = np.asarray(carry[10][:max(n_rec, 1)])[:n_rec]
        results, _ = self._decode_records(rec, sup, n_rec)
        self.stats["patterns"] = len(results)
        return sort_patterns(results)

    def _snapshot(self, carry, head: int, tail: int, n_rec: int,
                  ckpt_done: int) -> dict:
        """Wave-boundary frontier snapshot in the classic engine's
        format: live ring entries become stack nodes (their candidate
        masks ARE the s/i candidate lists), records become results.
        Cost: one readback of the two candidate masks + the record
        buffer — never the ring bitmaps, which are rebuilt by join-chain
        fold on resume."""
        cap = self.caps
        q_smask = np.asarray(carry[2])
        q_imask = np.asarray(carry[3])
        q_rec = np.asarray(carry[5])
        rec = np.asarray(carry[9][:max(n_rec, 1)])[:n_rec]
        sup = np.asarray(carry[10][:max(n_rec, 1)])[:n_rec]
        results, steps_of = self._decode_records(rec, sup, n_rec,
                                                 want_steps=True)
        nodes = []
        nim = self.n_items
        for qid in range(head, tail):
            ridx = qid % cap.ring
            steps = steps_of[int(q_rec[ridx])]
            s_list = np.nonzero(q_smask[ridx][:nim])[0]
            i_list = np.nonzero(q_imask[ridx][:nim])[0]
            nodes.append(FrontierNode(steps, None,
                                [int(x) for x in s_list],
                                [int(x) for x in i_list]))
        return encode_frontier(self.frontier_fingerprint(), nodes, results,
                               ckpt_done)

    def _resume_carry(self, results, nodes):
        """Rebuild the device state a snapshot describes: re-upload the
        parent-linked records (reconstructed from the result patterns),
        the candidate masks, and the queue bookkeeping; recompute the
        live ring BITMAPS on device by folding each node's join chain
        from the item rows.  Returns None when the snapshot does not fit
        this engine's caps (the caller falls back to the classic engine,
        which resumes the same snapshot)."""
        vdb, cap, ni = self.vdb, self.caps, self.ni_pad
        ring = cap.ring
        scratch = ni + ring
        n_live = len(nodes)
        if n_live > min(ring, cap.r_cap) or len(results) > cap.r_cap:
            return None
        ids = vdb.item_ids
        g2l = {int(g): l for l, g in enumerate(ids)}
        rec_np = np.zeros((cap.r_cap, 3), np.int32)
        sup_np = np.zeros(cap.r_cap, np.int32)
        idx_of: dict = {}
        for k, (pat, s) in enumerate(results):
            # the last step is removable from the canonical pattern:
            # i-extensions only ever add items LARGER than the itemset's
            # current max, so the last itemset's last (max) item is the
            # most recent extension
            last = pat[-1]
            if len(last) == 1:
                ppat, g, iss = pat[:-1], last[0], 1
            else:
                ppat, g, iss = pat[:-1] + (last[:-1],), last[-1], 0
            loc = g2l.get(int(g))
            if loc is None:
                return None  # projection drift the fingerprint missed
            if ppat:
                parent = idx_of.get(ppat)
                if parent is None:
                    return None  # malformed snapshot: orphan pattern
            else:
                parent = -1
            rec_np[k] = (parent, loc, iss)
            sup_np[k] = int(s)
            idx_of[pat] = k

        def pattern_of_steps(steps):
            pat: List[List[int]] = []
            for it, s in steps:
                if s:
                    pat.append([int(ids[it])])
                else:
                    pat[-1].append(int(ids[it]))
            return tuple(tuple(p) for p in pat)

        q_slot_np = np.full(ring, scratch, np.int32)
        q_smask_np = np.zeros((ring, ni), bool)
        q_imask_np = np.zeros((ring, ni), bool)
        q_nits_np = np.ones(ring, np.int32)
        q_rec_np = np.zeros(ring, np.int32)
        K = next_pow2(max(2, max(len(n.steps) for n in nodes)))
        M = next_pow2(max(8, n_live))
        items = np.zeros((K, M), np.int32)
        iss_a = np.zeros((K, M), bool)
        valid = np.zeros((K, M), bool)
        out_slot = np.full(M, scratch + 1, np.int32)  # pad lanes drop
        for k, node in enumerate(nodes):
            r = idx_of.get(pattern_of_steps(node.steps))
            if r is None:
                return None  # node without its own record: malformed
            q_rec_np[k] = r
            q_slot_np[k] = ni + k
            for j in node.s_list:
                if 0 <= j < ni:
                    q_smask_np[k, j] = True
            for j in node.i_list:
                if 0 <= j < ni:
                    q_imask_np[k, j] = True
            q_nits_np[k] = sum(1 for _, s in node.steps if s)
            for d, (it, s) in enumerate(node.steps):
                if not 0 <= it < self.n_items:
                    return None
                items[d, k] = it
                iss_a[d, k] = s
                valid[d, k] = True
            out_slot[k] = ni + k
        store = _queue_refill_fn(self.mesh, self.n_words, K, M)(
            self.store, self._put(items), self._put(iss_a),
            self._put(valid), self._put(out_slot))
        return (store, self._put(q_slot_np), self._put(q_smask_np),
                self._put(q_imask_np), self._put(q_nits_np),
                self._put(q_rec_np), self._put(np.int32(0)),
                self._put(np.int32(n_live)),
                self._put(np.int32(len(results))),
                self._put(rec_np), self._put(sup_np),
                self._put(np.bool_(False)), self._put(np.int32(0)),
                self._put(np.int32(self.minsup)), self._put(np.int32(0)))
