"""Miners: CPU oracles and TPU engines for SPADE and TSR."""
