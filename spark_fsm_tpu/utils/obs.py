"""Observability: unified metrics registry + per-job flight recorder
(plus the structured-log / jax.profiler seams that predate them).

The reference gets logging from log4j/slf4j, metrics from the Spark web
UI and profiling from Spark's event timeline (SURVEY.md sec 5 tracing +
metrics rows).  The rebuild grew deep machinery those analogs cannot
see: the ragged planner picks launch geometries from a cost model, the
watchdog derives deadlines from the same model, and the recovery paths
(retry/backoff, OOM degradation ladder, devcache breaker) fire with no
record of WHEN or in what order — lifetime counters cannot show a
straggler launch or a retry storm.  This module is the one
zero-dependency substrate for all of it:

- **metrics registry** (:data:`REGISTRY`): process-global counters,
  gauges, and fixed-bucket latency histograms under ONE naming scheme
  (``fsm_<subsystem>_<name>``, counters suffixed ``_total``), rendered
  in Prometheus text exposition format by ``GET /metrics``
  (service/app.py).  Subsystems that already keep their own counters
  (utils/retry, utils/watchdog, utils/faults, service/devcache,
  streaming/consumer, the job counters in the result store) register
  scrape-time COLLECTORS that read those counters into canonical
  ``fsm_*`` names — the existing dicts stay the source of truth, the
  registry is the one window onto them, and ``/admin/stats`` /
  ``/admin/health`` keep their old JSON keys as aliases (the mapping is
  tabled in docs/OPERATIONS.md).
- **flight recorder**: a per-job bounded ring of structured SPANS
  (``trace_id`` = job uid, site, monotonic t_start/t_end, a wall-clock
  ``ts`` for cross-process merging, attrs, and point-in-time EVENTS for
  fault trips, retry waits, watchdog timeouts, OOM downgrades, breaker
  transitions).  A trace opens at mine submit (service/actors.Miner)
  and threads through engine dispatch, ragged-planner launches, device
  readback, and store/checkpoint/Kafka I/O via a contextvar — no
  constructor plumbing.  Each launch span carries the planner's
  PREDICTED seconds next to the measured wall, so cost-model residuals
  become a first-class gauge (``fsm_costmodel_drift_ratio``) that
  calibrates the watchdog slack.  ``GET /admin/trace/<job_id>`` dumps a
  trace; ``/admin/trace/last`` the most recent one.
- **trace spine hook** (ISSUE 9): when a SPINE SINK is installed
  (:func:`set_spine` — service/obsplane.py wires it to the result
  store through the lease-fenced write path), completed spans also
  buffer per trace and flush to the sink in batches: at the configured
  span count, at every :func:`flush_trace` call (checkpoint saves and
  terminal paths), and on trace eviction.  The recorder stays the
  in-memory truth; the spine is the durable, cross-replica copy that
  survives a kill -9.  No sink installed (the solo default) costs one
  module-global read per probe.
- **sliding-window quantiles** (:class:`SlidingQuantiles`): bounded
  (wall-ts, value) samples per label set with exact quantiles over a
  trailing window — the /admin/slo substrate (fixed-bucket histograms
  cannot answer "p99 over the last five minutes").

Tracing is config-gated (``[observability] trace``) and the DISABLED
path costs one module-global read per probe — the same pin as the fault
registry (scripts/bench_smoke.sh asserts the dispatch-shape counters
stay byte-identical).  Metrics are always on: registry writes are a
lock + dict update, and ``/metrics`` must serve even when tracing is
off.
"""

from __future__ import annotations

import bisect
import contextlib
import contextvars
import itertools
import json
import logging
import re
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("spark_fsm_tpu")


def engine_route(stats: dict) -> str:
    """Canonical route label from a SPADE engine stats dict: the
    ``fused`` key is False (classic DFS), True (dense fused engine) or
    an engine name string ("queue").  One definition so every artifact
    (BENCH_SUITE, BENCH_SCALE, service stats) records identical labels —
    a new engine name must not drift between them."""
    f = stats.get("fused")
    if isinstance(f, str):
        return f
    return "fused" if f else "classic"


def log_event(event: str, **fields) -> None:
    """Emit one JSON object per line: {"event": ..., "ts": ..., **fields}.

    Quiet unless the host app configures the ``spark_fsm_tpu`` logger (or
    logging.basicConfig); the service CLI enables INFO by default.
    """
    payload = {"event": event, "ts": round(time.time(), 3)}
    payload.update(fields)
    logger.info(json.dumps(payload, default=str, sort_keys=True))


_trace_lock = threading.Lock()


@contextlib.contextmanager
def profile_trace(trace_dir: str):
    """``jax.profiler.trace`` scope when ``trace_dir`` is set; no-op else.

    jax.profiler allows ONE active trace per process, so concurrently
    profiled jobs serialize on a lock rather than failing the second job.
    """
    if not trace_dir:
        yield
        return
    import jax

    with _trace_lock, jax.profiler.trace(trace_dir):
        yield


# ===========================================================================
# Metrics registry
# ===========================================================================

# One naming scheme for every exported series: fsm_<subsystem>_<name>,
# counters suffixed _total.  The registry REFUSES other spellings — a
# metric that drifts off the scheme would silently fork the namespace
# the Prometheus scrape (and the OPERATIONS.md table) is keyed on.
_NAME_RE = re.compile(r"^fsm_[a-z][a-z0-9_]*$")

# Default latency bucket edges (seconds): sub-ms store ops through
# minutes-long prewarm compiles share one ladder so cross-metric
# comparisons read off the same edges.
LATENCY_BUCKETS_S = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                     30.0, 60.0)


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """Base: thread-safe {label-key: value} map."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} violates the fsm_<subsystem>_<name> "
                "scheme (lowercase, fsm_ prefix)")
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def _set(self, value: float, labels: dict) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def _add(self, n: float, labels: dict) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def samples(self) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
        """[(suffix, label_key, value)] — suffix appended to the family
        name in exposition ("" for plain counters/gauges)."""
        with self._lock:
            return [("", k, v) for k, v in self._values.items()]

    def snapshot(self):
        """JSON-able value view: scalar for the unlabelled series, else
        {"k=v,...": value}."""
        with self._lock:
            if list(self._values) == [()]:
                return self._values[()]
            return {",".join(f"{k}={v}" for k, v in key): val
                    for key, val in self._values.items()}


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        # seed the unlabelled series at 0: a scrape must distinguish
        # "zero events" from "metric missing" (the orphan-counter
        # failure mode the collectors' KNOWN_SITES zero-seeding guards
        # against, applied to the registry's own counters) — rate()
        # alerts on never-touched counters read 0, not no-data
        self._values[()] = 0.0

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({n})")
        self._add(n, labels)

    def seed(self, **labels) -> "Counter":
        """Zero-seed one LABELLED series (idempotent; never clobbers a
        live count).  The labelled analog of the unlabelled seed above:
        a subsystem with a known outcome vocabulary (lease acquire
        ok/held/error, steal stolen/lost_race/error) seeds every outcome
        at registration so a scrape reads 0, not no-data, for outcomes
        that simply have not happened yet — the same orphan-series
        posture as the fault registry's KNOWN_SITES zero-seeding."""
        key = _label_key(labels)
        with self._lock:
            self._values.setdefault(key, 0.0)
        return self

    def total(self) -> float:
        """Sum over every series of this counter — what the lease
        heartbeat piggybacks into its compact metric snapshot."""
        with self._lock:
            return sum(self._values.values())


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self._set(float(value), labels)


class Histogram(_Metric):
    """Fixed-bucket cumulative histogram (Prometheus semantics: bucket
    edges are INCLUSIVE upper bounds, ``+Inf`` is implicit, ``_sum`` and
    ``_count`` ride along)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS_S):
        super().__init__(name, help)
        edges = tuple(float(b) for b in buckets)
        if not edges or list(edges) != sorted(set(edges)):
            raise ValueError(f"histogram {name}: bucket edges must be a "
                             f"nonempty strictly increasing tuple ({buckets})")
        self.buckets = edges
        # label_key -> [per-edge counts..., +Inf count, sum]
        self._h: Dict[Tuple[Tuple[str, str], ...], List[float]] = {}

    def observe(self, value: float, **labels) -> None:
        v = float(value)
        key = _label_key(labels)
        i = bisect.bisect_left(self.buckets, v)  # first edge >= v
        with self._lock:
            row = self._h.get(key)
            if row is None:
                row = self._h[key] = [0.0] * (len(self.buckets) + 1) + [0.0]
            row[min(i, len(self.buckets))] += 1
            row[-1] += v

    def seed(self, **labels) -> "Histogram":
        """Zero-seed one series (all-zero buckets, count 0) — the
        histogram analog of :meth:`Counter.seed`, so a fresh scrape
        shows ``_count 0`` for a label vocabulary (e.g. every priority
        class) instead of no data."""
        key = _label_key(labels)
        with self._lock:
            if key not in self._h:
                self._h[key] = [0.0] * (len(self.buckets) + 1) + [0.0]
        return self

    def samples(self):
        out = []
        with self._lock:
            rows = {k: list(v) for k, v in self._h.items()}
        for key, row in rows.items():
            cum = 0.0
            for edge, n in zip(self.buckets, row):
                cum += n
                out.append(("_bucket", key + (("le", _fmt(edge)),), cum))
            cum += row[len(self.buckets)]
            out.append(("_bucket", key + (("le", "+Inf"),), cum))
            out.append(("_count", key, cum))
            out.append(("_sum", key, row[-1]))
        return out

    def snapshot(self):
        with self._lock:
            return {
                (",".join(f"{k}={v}" for k, v in key) or "all"): {
                    "count": sum(row[:-1]), "sum": round(row[-1], 6)}
                for key, row in self._h.items()}


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(v)


class MetricsRegistry:
    """Process-global metric store + scrape-time collector list.

    ``counter``/``gauge``/``histogram`` are get-or-create (re-requesting
    a name returns the same object; a kind mismatch is a bug and
    raises).  ``register_collector(name, fn)`` installs a callable run
    at scrape time that returns a list of
    ``(name, kind, help, [(labels_dict, value), ...])`` families —
    the bridge for subsystems that already keep counters elsewhere
    (retry/watchdog/faults/devcache/consumer/job counters); registering
    the same collector name again REPLACES it (tests build many masters).
    A collector that raises is skipped — ``/metrics`` must stay
    readable during a chaos drill, same posture as /admin/health.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "OrderedDict[str, _Metric]" = OrderedDict()
        self._collectors: "OrderedDict[str, Callable]" = OrderedDict()

    def _get_or_make(self, cls, name, help, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif type(m) is not cls:
                raise ValueError(f"metric {name!r} already registered as "
                                 f"{m.kind}, not {cls.kind}")
            elif ("buckets" in kw
                  and tuple(float(b) for b in kw["buckets"]) != m.buckets):
                # a silent edge mismatch would bin the second caller's
                # observations against a ladder it never asked for
                raise ValueError(
                    f"histogram {name!r} already registered with buckets "
                    f"{m.buckets}, requested {tuple(kw['buckets'])}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_make(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = LATENCY_BUCKETS_S) -> Histogram:
        return self._get_or_make(Histogram, name, help, buckets=buckets)

    def register_collector(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._collectors[name] = fn

    def _collected(self):
        with self._lock:
            collectors = list(self._collectors.items())
        fams = []
        for cname, fn in collectors:
            try:
                fams.extend(fn())
            except Exception as exc:  # scrape survives a failing subsystem
                log_event("metrics_collector_failed", collector=cname,
                          error=f"{type(exc).__name__}: {exc}")
        return fams

    def render_prometheus(self) -> str:
        """The full registry + collectors in Prometheus text exposition
        format (version 0.0.4)."""
        lines: List[str] = []

        def emit(name, kind, help, samples):
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {kind}")
            for suffix, key, value in samples:
                lbl = ("{" + ",".join(
                    f'{k}="{_escape(v)}"' for k, v in key) + "}"
                    if key else "")
                lines.append(f"{name}{suffix}{lbl} {_fmt(float(value))}")

        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            emit(m.name, m.kind, m.help, m.samples())
        for name, kind, help, rows in self._collected():
            if not _NAME_RE.match(name):
                continue  # a collector cannot fork the namespace either
            emit(name, kind, help,
                 [("", _label_key(labels), value) for labels, value in rows])
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able {canonical name: value} view of the whole registry
        (collectors included) — what /admin/stats and /admin/health
        embed so their old JSON keys become documented aliases of these
        names."""
        out: dict = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            out[m.name] = m.snapshot()
        for name, kind, help, rows in self._collected():
            vals = {(",".join(f"{k}={v}" for k, v in _label_key(labels))):
                    value for labels, value in rows}
            out[name] = vals.pop("", None) if list(vals) == [""] else vals
        return out


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


REGISTRY = MetricsRegistry()

# -- registry-native metrics owned by this module ---------------------------

_SPANS_TOTAL = REGISTRY.counter(
    "fsm_trace_spans_total", "flight-recorder spans completed")
_SPANS_DROPPED = REGISTRY.counter(
    "fsm_trace_spans_dropped_total",
    "spans evicted from per-job rings (ring full)")
_COSTMODEL_SAMPLES = REGISTRY.counter(
    "fsm_costmodel_samples_total",
    "dispatch walls compared against the ragged planner's estimate")
_COSTMODEL_DRIFT = REGISTRY.gauge(
    "fsm_costmodel_drift_ratio",
    "EWMA of measured/predicted dispatch wall — the watchdog-slack "
    "calibration input (slack should exceed this with margin)")
_COSTMODEL_RESIDUAL = REGISTRY.histogram(
    "fsm_costmodel_residual_ratio",
    "distribution of measured/predicted dispatch wall",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 64.0, 256.0))

_DRIFT_ALPHA = 0.2  # EWMA weight for the newest residual
_drift_lock = threading.Lock()
_drift_ewma: Optional[float] = None

#: per-shape-family drift (ISSUE 19): the single global EWMA above
#: stays the ``drift_factor`` recalibration input, unchanged; these
#: labeled gauges break the same residuals out per dispatch family so
#: the hardware-recalibration session can see WHICH shape family the
#: planner misprices.  The vocabulary is closed (shapes.py families) —
#: unknown families are dropped, keeping the label space bounded.
COSTMODEL_FAMILIES = ("tsr-eval", "tsr-fused", "tsr-resident", "spam",
                      "predict")
_COSTMODEL_FAMILY_DRIFT = REGISTRY.gauge(
    "fsm_costmodel_family_drift_ratio",
    "EWMA of measured/predicted dispatch wall per shape family")
for _f in COSTMODEL_FAMILIES:
    _COSTMODEL_FAMILY_DRIFT.set(0.0, family=_f)
del _f
_family_ewma: Dict[str, float] = {}


def observe_costmodel_family(family: str, predicted_s: float,
                             measured_s: float) -> None:
    """Feed one (predicted, measured) pair into a FAMILY drift gauge
    only — for dispatch surfaces (resident segments, SPAM waves) whose
    residuals must NOT perturb the global recalibration EWMA that
    ``drift_factor`` consumes (pinned byte-identical by bench_smoke)."""
    if predicted_s <= 0 or family not in COSTMODEL_FAMILIES:
        return
    ratio = measured_s / predicted_s
    with _drift_lock:
        prev = _family_ewma.get(family)
        cur = (ratio if prev is None
               else _DRIFT_ALPHA * ratio + (1 - _DRIFT_ALPHA) * prev)
        _family_ewma[family] = cur
        _COSTMODEL_FAMILY_DRIFT.set(cur, family=family)


def observe_costmodel(predicted_s: float, measured_s: float,
                      family: Optional[str] = None) -> None:
    """Feed one (predicted, measured) dispatch-wall pair into the
    cost-model calibration gauge.  Ratios are measured/predicted, so a
    drifting gauge reads directly as "the planner underestimates by
    Nx" — the number ``[engine] watchdog_slack`` must stay above.
    Pairs with a degenerate prediction are dropped (a zero-traffic
    dispatch says nothing about the model).  ``family`` additionally
    routes the pair into that family's labeled drift gauge; the global
    EWMA path is byte-identical with or without it."""
    global _drift_ewma
    if predicted_s <= 0:
        return
    ratio = measured_s / predicted_s
    _COSTMODEL_SAMPLES.inc()
    _COSTMODEL_RESIDUAL.observe(ratio)
    with _drift_lock:
        _drift_ewma = (ratio if _drift_ewma is None
                       else _DRIFT_ALPHA * ratio
                       + (1 - _DRIFT_ALPHA) * _drift_ewma)
        _COSTMODEL_DRIFT.set(_drift_ewma)
    if family is not None:
        observe_costmodel_family(family, predicted_s, measured_s)


def costmodel_drift() -> Optional[float]:
    """Current measured/predicted EWMA (None until the first sample)."""
    with _drift_lock:
        return _drift_ewma


def costmodel_family_drift() -> Dict[str, float]:
    """Per-family measured/predicted EWMAs (families with samples)."""
    with _drift_lock:
        return dict(_family_ewma)


# ===========================================================================
# Flight recorder
# ===========================================================================

# Fast-path flag: every probe (span(), trace_event(), trace()) returns
# after ONE module-global read when tracing is off — the same contract
# as utils/faults._active, and pinned the same way (test_obs.py asserts
# zero span allocations + bench_smoke asserts byte-identical dispatch
# counters).
_trace_on = False

_cfg_lock = threading.Lock()
_max_spans = 512   # per-job completed-span ring bound
_max_jobs = 16     # job traces kept (oldest evicted)

_span_ids = itertools.count(1)

# the active trace/span of THIS logical context (worker thread / task):
# engine internals record into whatever job is mining on their thread
# without any constructor plumbing
_cur_trace: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "fsm_trace", default=None)
_cur_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "fsm_span", default=None)


class Span:
    """One timed unit of work inside a trace.  ``event`` records a
    point-in-time marker (fault trip, retry wait, OOM downgrade,
    breaker transition); ``set`` attaches/overrides attrs (e.g. the
    measured wall next to the predicted one).  Close via the context
    manager — the span enters its trace's ring only on exit."""

    __slots__ = ("trace_id", "span_id", "parent_id", "site", "t0", "t0w",
                 "t1", "attrs", "events", "error", "_token")

    def __init__(self, trace_id: str, parent_id: Optional[int], site: str,
                 attrs: dict):
        self.trace_id = trace_id
        self.span_id = next(_span_ids)
        self.parent_id = parent_id
        self.site = site
        self.t0 = time.monotonic()
        # wall-clock twin of t0: monotonic clocks are PER-PROCESS, so
        # the cross-replica merged timeline (service/obsplane.py) can
        # only order spans from different replicas by wall time
        self.t0w = time.time()
        self.t1: Optional[float] = None
        self.attrs = attrs
        self.events: List[dict] = []
        self.error: Optional[str] = None
        self._token = None

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def event(self, name: str, **attrs) -> None:
        e = {"name": name, "t": round(time.monotonic() - self.t0, 6)}
        if attrs:
            e.update(attrs)
        self.events.append(e)

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def __enter__(self) -> "Span":
        self._token = _cur_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _cur_span.reset(self._token)
            self._token = None
        self.t1 = time.monotonic()
        if exc is not None:
            self.error = f"{type(exc).__name__}: {exc}"
        _recorder.record(self)

    def to_dict(self) -> dict:
        d = {"span_id": self.span_id, "parent_id": self.parent_id,
             "site": self.site, "t_start": round(self.t0, 6),
             "ts": round(self.t0w, 6),
             "t_end": None if self.t1 is None else round(self.t1, 6),
             "duration_s": (None if self.t1 is None
                            else round(self.t1 - self.t0, 6))}
        if self.attrs:
            d["attrs"] = {k: v for k, v in self.attrs.items()}
        if self.events:
            d["events"] = list(self.events)
        if self.error:
            d["error"] = self.error
        return d


class _NoopSpan:
    """The shared disabled-path span: every method is a no-op and
    ``span()`` returns THIS SINGLETON when tracing is off — no
    allocation, no clock read (the disabled-cost pin in test_obs.py)."""

    __slots__ = ()

    def set(self, **attrs) -> None:
        pass

    def event(self, name: str, **attrs) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        pass


_NOOP = _NoopSpan()

# -- trace spine hook (ISSUE 9) ---------------------------------------------
# The sink is a callable ``fn(trace_id, [span_dict, ...])`` installed by
# service/obsplane.py when the cluster observability plane is active; it
# owns durability, fencing and failure handling (a sink error must never
# fail the recorded work).  None (the default) keeps every probe at one
# module-global read — the same disabled-cost pin as ``_trace_on``.
_spine: Optional[Callable[[str, List[dict]], None]] = None
_spine_flush_spans = 32


def set_spine(sink: Optional[Callable[[str, List[dict]], None]],
              flush_spans: Optional[int] = None) -> None:
    """Install (or remove, with None) the process-wide spine sink.
    ``flush_spans`` sets how many completed spans buffer per trace
    before an automatic flush."""
    global _spine, _spine_flush_spans
    with _cfg_lock:
        if flush_spans is not None:
            if flush_spans < 1:
                raise ValueError(
                    f"flush_spans must be >= 1 (got {flush_spans})")
            _spine_flush_spans = int(flush_spans)
        _spine = sink


def set_spine_flush(flush_spans: int) -> None:
    """Adjust the per-trace flush threshold without touching the sink
    (the boot config's ``[observability] spine_flush_spans`` knob)."""
    set_spine(_spine, flush_spans=flush_spans)


def _spine_send(trace_id: str, batch: List[dict]) -> None:
    sink = _spine
    if sink is None or not batch:
        return
    try:
        sink(trace_id, batch)
    except Exception as exc:  # the sink must never fail the work
        log_event("trace_spine_sink_failed", trace=trace_id,
                  error=f"{type(exc).__name__}: {exc}")


class _Trace:
    __slots__ = ("trace_id", "spans", "dropped", "started_wall", "attrs",
                 "pending")

    def __init__(self, trace_id: str, max_spans: int, attrs: dict):
        self.trace_id = trace_id
        self.spans: "deque[Span]" = deque(maxlen=max_spans)
        self.dropped = 0
        self.started_wall = time.time()
        self.attrs = attrs
        # spans completed since the last spine flush (only populated
        # while a spine sink is installed — see set_spine)
        self.pending: List[dict] = []


class FlightRecorder:
    """Bounded ring-of-rings: at most ``_max_jobs`` traces, each a
    deque of at most ``_max_spans`` COMPLETED spans (completion order;
    oldest evicted first — the straggler hunt cares about the tail of
    a job, not its warmup).  Spans record on close, under one lock —
    concurrent miner workers interleave safely."""

    def __init__(self):
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, _Trace]" = OrderedDict()
        self._last: Optional[str] = None
        self._sinks: List[Callable] = []

    def begin(self, trace_id: str, attrs: dict) -> None:
        evicted: List[_Trace] = []
        with self._lock:
            t = self._traces.get(trace_id)
            if t is None:
                # a re-run/retried uid keeps ONE ring: the old spans stay
                # until evicted, so a retry's trace shows the failed
                # attempt's tail next to the re-run — the order of
                # recovery events is the point of the recorder
                t = self._traces[trace_id] = _Trace(trace_id, _max_spans,
                                                    attrs)
                while len(self._traces) > _max_jobs:
                    evicted.append(self._traces.popitem(last=False)[1])
            else:
                t.attrs.update(attrs)
            self._traces.move_to_end(trace_id)
            self._last = trace_id
        for old in evicted:  # outside the lock: the sink does store I/O
            if old.pending:
                _spine_send(old.trace_id, old.pending)

    def record(self, span: Span) -> None:
        sinks = None
        flush: Optional[List[dict]] = None
        with self._lock:
            t = self._traces.get(span.trace_id)
            if t is not None:
                if len(t.spans) == t.spans.maxlen:
                    t.dropped += 1
                    _SPANS_DROPPED.inc()
                t.spans.append(span)
                self._last = span.trace_id
                if _spine is not None:
                    # buffer for the durable spine; flush in batches so
                    # the store pays one append per N spans, not per span
                    t.pending.append(span.to_dict())
                    if len(t.pending) >= _spine_flush_spans:
                        flush, t.pending = t.pending, []
            if self._sinks:
                sinks = list(self._sinks)
        _SPANS_TOTAL.inc()
        if flush is not None:
            _spine_send(span.trace_id, flush)
        if sinks:
            for fn in sinks:
                try:
                    fn(span)
                except Exception:
                    pass  # a reporting sink must never fail the work
        if logger.isEnabledFor(logging.INFO):  # skip the dumps when quiet
            log_event("span", trace=span.trace_id, site=span.site,
                      duration_s=round(span.duration_s or 0.0, 6),
                      **({"error": span.error} if span.error else {}))

    def take_pending(self, trace_id: str) -> List[dict]:
        """Pop the trace's un-flushed spine batch (empty when no spine
        is installed or nothing accumulated)."""
        with self._lock:
            t = self._traces.get(trace_id)
            if t is None or not t.pending:
                return []
            batch, t.pending = t.pending, []
            return batch

    def dump(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            t = self._traces.get(trace_id)
            if t is None:
                return None
            spans = [s.to_dict() for s in t.spans]
            return {"trace_id": t.trace_id, "started_ts": t.started_wall,
                    "attrs": dict(t.attrs), "spans": spans,
                    "dropped_spans": t.dropped, "n_spans": len(spans)}

    def last_trace_id(self) -> Optional[str]:
        with self._lock:
            return self._last

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def stats(self) -> dict:
        with self._lock:
            return {"traces": len(self._traces),
                    "spans": sum(len(t.spans) for t in
                                 self._traces.values()),
                    "dropped": sum(t.dropped for t in
                                   self._traces.values())}

    def add_sink(self, fn: Callable) -> None:
        with self._lock:
            self._sinks.append(fn)

    def remove_sink(self, fn: Callable) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._last = None


_recorder = FlightRecorder()


def configure_tracing(enabled: bool, max_spans: Optional[int] = None,
                      max_jobs: Optional[int] = None) -> None:
    """Set the process-wide tracing policy (the boot config's
    ``[observability]`` block owns it via config.set_config; tests may
    call directly).  Ring bounds apply to traces begun AFTER the call."""
    global _trace_on, _max_spans, _max_jobs
    with _cfg_lock:
        if max_spans is not None:
            if max_spans < 1:
                raise ValueError(f"max_spans must be >= 1 (got {max_spans})")
            _max_spans = int(max_spans)
        if max_jobs is not None:
            if max_jobs < 1:
                raise ValueError(f"max_jobs must be >= 1 (got {max_jobs})")
            _max_jobs = int(max_jobs)
        _trace_on = bool(enabled)


def tracing_enabled() -> bool:
    return _trace_on


@contextlib.contextmanager
def trace(trace_id: str, site: str = "job", **attrs):
    """Activate ``trace_id`` for this context and open its root span.
    No-op (one global read) when tracing is off."""
    if not _trace_on:
        yield _NOOP
        return
    _recorder.begin(trace_id, dict(attrs))
    token = _cur_trace.set(trace_id)
    try:
        with Span(trace_id, None, site, dict(attrs)) as sp:
            yield sp
    finally:
        _cur_trace.reset(token)


def trace_begin(trace_id: str, **attrs) -> None:
    """Create the trace ring (idempotent) and stamp a zero-length
    ``submit`` span — called from the HTTP handler thread at mine
    submit, before the worker thread opens the job's root span."""
    if not _trace_on:
        return
    _recorder.begin(trace_id, dict(attrs))
    with Span(trace_id, None, "job.submit", dict(attrs)):
        pass


def span(site: str, trace_id: Optional[str] = None, **attrs):
    """Open a span under the current trace (or an explicit one).
    Returns the no-op singleton when tracing is off OR no trace is
    active — engine code calls this unconditionally and pays one global
    read outside a traced job."""
    if not _trace_on:
        return _NOOP
    tid = trace_id if trace_id is not None else _cur_trace.get()
    if tid is None:
        return _NOOP
    parent = _cur_span.get()
    return Span(tid, parent.span_id if parent is not None else None,
                site, dict(attrs))


def trace_event(name: str, **attrs) -> None:
    """Record a point-in-time event on the current innermost span —
    the one-liner fault/retry/watchdog/breaker call sites use.  One
    global read when tracing is off or no span is open."""
    if not _trace_on:
        return
    sp = _cur_span.get()
    if sp is not None:
        sp.event(name, **attrs)


def lifecycle(trace_id: str, event: str, **attrs) -> None:
    """Record a first-class job lifecycle event (admitted / started /
    checkpointed / stolen / adopted / fenced / settled) as a zero-length
    ``lifecycle.{event}`` span on the job's trace — and therefore on the
    durable spine, where these markers are the observation points for
    the failover/steal latency histograms.  One global read when
    tracing is off."""
    if not _trace_on:
        return
    with span(f"lifecycle.{event}", trace_id=trace_id, **attrs):
        pass


def flush_trace(trace_id: str) -> None:
    """Flush the trace's buffered spans to the spine sink NOW — called
    at the durable milestones (admission, checkpoint saves, terminal
    paths) so a kill -9 loses at most the spans since the last
    milestone.  One module-global read when no spine is installed."""
    if _spine is None:
        return
    batch = _recorder.take_pending(trace_id)
    if batch:
        _spine_send(trace_id, batch)


def trace_dump(trace_id: str) -> Optional[dict]:
    return _recorder.dump(trace_id)


def last_trace_id() -> Optional[str]:
    return _recorder.last_trace_id()


def trace_ids() -> List[str]:
    return _recorder.trace_ids()


def recorder_stats() -> dict:
    return _recorder.stats()


def add_span_sink(fn: Callable) -> None:
    """Register a callable invoked with every COMPLETED span (tracing
    on only).  Used by the opt-in test-suite slow-span report
    (tests/conftest.py, SPARKFSM_TRACE_TESTS=1)."""
    _recorder.add_sink(fn)


def remove_span_sink(fn: Callable) -> None:
    _recorder.remove_sink(fn)


def clear_traces() -> None:
    """Drop every recorded trace (test isolation helper)."""
    _recorder.clear()


# ===========================================================================
# Sliding-window quantiles (the /admin/slo substrate)
# ===========================================================================

class SlidingQuantiles:
    """Exact quantiles over a trailing wall-clock window, per label set.

    A fixed-bucket histogram answers "how many ever fell under 1 s";
    an SLO report needs "what was p99 over the last five minutes".
    This keeps a bounded deque of ``(wall_ts, value)`` per label key —
    at most ``max_samples``, pruned to ``window_s`` on every observe and
    snapshot — and sorts on demand (snapshot-time cost, bounded by
    ``max_samples``; /admin/slo is an operator poll, not a hot path).
    ``clock`` is injectable (tests drive a virtual clock)."""

    def __init__(self, window_s: float = 300.0, max_samples: int = 2048,
                 clock=time.time):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0 (got {window_s})")
        if max_samples < 1:
            raise ValueError(f"max_samples must be >= 1 (got {max_samples})")
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: Dict[Tuple[Tuple[str, str], ...],
                            "deque[Tuple[float, float]]"] = {}

    def set_window(self, window_s: float) -> None:
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0 (got {window_s})")
        with self._lock:
            self.window_s = float(window_s)

    def _prune(self, dq, now: float) -> None:
        horizon = now - self.window_s
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        now = self._clock()
        with self._lock:
            dq = self._samples.get(key)
            if dq is None:
                dq = self._samples[key] = deque(maxlen=self.max_samples)
            dq.append((now, float(value)))
            self._prune(dq, now)

    def stats(self, quantiles: Tuple[float, ...] = (0.5, 0.95, 0.99),
              **labels) -> dict:
        """{"count": n, "p50": ..., "p95": ..., "p99": ..., "max": ...}
        over the live window ({"count": 0} when it is empty)."""
        key = _label_key(labels)
        now = self._clock()
        with self._lock:
            dq = self._samples.get(key)
            if dq is not None:
                self._prune(dq, now)
            values = sorted(v for _, v in dq) if dq else []
        if not values:
            return {"count": 0}
        out = {"count": len(values), "max": round(values[-1], 6)}
        for q in quantiles:
            idx = min(len(values) - 1, int(q * (len(values) - 1) + 0.5))
            out[f"p{int(q * 100)}"] = round(values[idx], 6)
        return out

    def label_keys(self) -> List[Tuple[Tuple[str, str], ...]]:
        with self._lock:
            return list(self._samples)

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()
