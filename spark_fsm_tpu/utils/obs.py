"""Observability: structured logging + profiler trace capture.

The reference gets logging from log4j/slf4j and profiling from the Spark
web UI (SURVEY.md sec 5 tracing + metrics rows).  The rebuild's analogs:
structured JSON-line logs through stdlib ``logging`` (one object per line
— grep/jq-able job lifecycle events), and ``jax.profiler`` trace capture
(XProf/Perfetto-readable) scoped around a mine when a job asks for it.
"""

from __future__ import annotations

import contextlib
import json
import logging
import threading
import time

logger = logging.getLogger("spark_fsm_tpu")


def engine_route(stats: dict) -> str:
    """Canonical route label from a SPADE engine stats dict: the
    ``fused`` key is False (classic DFS), True (dense fused engine) or
    an engine name string ("queue").  One definition so every artifact
    (BENCH_SUITE, BENCH_SCALE, service stats) records identical labels —
    a new engine name must not drift between them."""
    f = stats.get("fused")
    if isinstance(f, str):
        return f
    return "fused" if f else "classic"


def log_event(event: str, **fields) -> None:
    """Emit one JSON object per line: {"event": ..., "ts": ..., **fields}.

    Quiet unless the host app configures the ``spark_fsm_tpu`` logger (or
    logging.basicConfig); the service CLI enables INFO by default.
    """
    payload = {"event": event, "ts": round(time.time(), 3)}
    payload.update(fields)
    logger.info(json.dumps(payload, default=str, sort_keys=True))


_trace_lock = threading.Lock()


@contextlib.contextmanager
def profile_trace(trace_dir: str):
    """``jax.profiler.trace`` scope when ``trace_dir`` is set; no-op else.

    jax.profiler allows ONE active trace per process, so concurrently
    profiled jobs serialize on a lock rather than failing the second job.
    """
    if not trace_dir:
        yield
        return
    import jax

    with _trace_lock, jax.profiler.trace(trace_dir):
        yield
