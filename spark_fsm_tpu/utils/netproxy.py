"""Partition-chaos TCP proxy — the storm harness's network fault plane.

A fault-site guard (utils/faults) can make one CALL fail, but a store
outage is a property of the WIRE: half-open connections, black holes
that swallow bytes without closing, latency cliffs, mid-stream resets,
and per-replica asymmetry (replica A partitioned from the store while
B still talks to it).  :class:`NetProxy` sits between one client
(e.g. a service replica) and one upstream (e.g. MiniRedis/Redis) and
injects exactly those, per proxy — so the storm harness
(scripts/storm_smoke.py) gives each replica ITS OWN proxy and
partitions them asymmetrically by flipping modes per instance.

Modes (thread-safe, effective immediately, composable):

- ``blackhole(True)``: bytes in either direction are silently
  swallowed (held connections stay open — the client's recv just
  never returns data: the classic half-open partition).  New
  connections are accepted and equally black-holed.
- ``delay(seconds)``: every forwarded chunk waits first (latency
  injection; 0 restores).
- ``refuse(True)``: new connections are accepted and immediately
  closed (the connection-refused-ish fast failure), existing ones
  keep flowing.
- ``reset_all()``: hard-close every live connection NOW (mid-stream
  reset); the proxy keeps listening.
- ``heal()``: clear blackhole/delay/refuse.

Counters (``stats()``) record connections, forwarded bytes per
direction, swallowed bytes, and resets — the harness prints them next
to the invariant report.

Stdlib sockets + threads only; no external packages.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Tuple


class _Pipe(threading.Thread):
    """One direction of one proxied connection."""

    def __init__(self, proxy: "NetProxy", src: socket.socket,
                 dst: socket.socket, direction: str):
        super().__init__(daemon=True,
                         name=f"netproxy-{proxy.port}-{direction}")
        self.proxy = proxy
        self.src = src
        self.dst = dst
        self.direction = direction  # "up" (client->upstream) | "down"

    def run(self) -> None:
        try:
            while True:
                try:
                    chunk = self.src.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                p = self.proxy
                if p._blackhole:
                    # swallow silently; keep reading so the sender's
                    # buffers drain and the hole looks bottomless
                    with p._lock:
                        p._stats["swallowed_bytes"] += len(chunk)
                    continue
                if p._delay_s > 0:
                    time.sleep(p._delay_s)
                    if p._blackhole:  # flipped during the sleep
                        with p._lock:
                            p._stats["swallowed_bytes"] += len(chunk)
                        continue
                try:
                    self.dst.sendall(chunk)
                except OSError:
                    break
                with p._lock:
                    p._stats[f"bytes_{self.direction}"] += len(chunk)
        finally:
            # one side closing tears both down (half-closed TCP is not
            # part of the RESP conversation this proxy exists for)
            for s in (self.src, self.dst):
                try:
                    s.close()
                except OSError:
                    pass


class NetProxy:
    """TCP proxy to ``(upstream_host, upstream_port)`` listening on an
    ephemeral loopback port (``.port``)."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 host: str = "127.0.0.1") -> None:
        self.upstream = (upstream_host, int(upstream_port))
        self._blackhole = False
        self._delay_s = 0.0
        self._refuse = False
        self._closed = False
        self._lock = threading.Lock()
        self._conns: List[Tuple[socket.socket, socket.socket]] = []
        self._stats: Dict[str, int] = {
            "connections": 0, "refused": 0, "resets": 0,
            "bytes_up": 0, "bytes_down": 0, "swallowed_bytes": 0}
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True,
                         name=f"netproxy-{self.port}-accept").start()

    # ------------------------------------------------------------- server

    def _accept(self) -> None:
        while True:
            try:
                client, _ = self._srv.accept()
            except OSError:
                return  # closed
            if self._refuse or self._closed:
                with self._lock:
                    self._stats["refused"] += 1
                try:
                    client.close()
                except OSError:
                    pass
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=10)
            except OSError:
                with self._lock:
                    self._stats["refused"] += 1
                try:
                    client.close()
                except OSError:
                    pass
                continue
            with self._lock:
                self._stats["connections"] += 1
                self._conns.append((client, up))
                # prune dead pairs so a long storm doesn't hoard fds
                self._conns = [(c, u) for c, u in self._conns
                               if c.fileno() != -1]
            _Pipe(self, client, up, "up").start()
            _Pipe(self, up, client, "down").start()

    # -------------------------------------------------------------- modes

    def blackhole(self, on: bool = True) -> None:
        self._blackhole = bool(on)

    def delay(self, seconds: float) -> None:
        self._delay_s = max(0.0, float(seconds))

    def refuse(self, on: bool = True) -> None:
        self._refuse = bool(on)

    def reset_all(self) -> int:
        """Hard-close every live proxied connection; returns how many
        pairs were torn down.  The listener stays up."""
        with self._lock:
            conns, self._conns = self._conns, []
        n = 0
        for client, up in conns:
            alive = client.fileno() != -1 or up.fileno() != -1
            for s in (client, up):
                try:
                    s.close()
                except OSError:
                    pass
            n += 1 if alive else 0
        with self._lock:
            self._stats["resets"] += n
        return n

    def heal(self) -> None:
        """Clear every injected mode (live connections that died under
        blackhole/reset stay dead — clients reconnect through the now-
        clean proxy, exactly like a healed network)."""
        self._blackhole = False
        self._delay_s = 0.0
        self._refuse = False

    @property
    def modes(self) -> Dict[str, object]:
        return {"blackhole": self._blackhole, "delay_s": self._delay_s,
                "refuse": self._refuse}

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        self.reset_all()
