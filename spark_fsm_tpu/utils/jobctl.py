"""Per-job deadlines and cancellation — the admission layer's abort seam.

A train job used to be unstoppable once submitted: no deadline, no
cancel, and a worker burning device time on a job whose client gave up
long ago.  This module is the process-global registry of LIVE jobs
(one :class:`JobControl` per submitted uid, registered by
``Miner.submit`` and released on every terminal status) carrying the
two abort signals:

- **deadline**: stamped at submit as an absolute monotonic instant
  (``now + deadline_s``), so time spent WAITING in the admission queue
  spends the budget exactly like time spent mining;
- **cancelled**: flipped by ``POST /admin/cancel/{uid}`` (or
  :func:`cancel`) at any point of the job's life.

The signals are enforced at the engines' existing safe points — the
spots between device launches where the dispatch watchdog and the OOM
degradation ladder already live (models/tsr.py pipeline loop,
models/spade_queue.py segment loop) plus the Miner's own step
boundaries — via :func:`check`, which raises :class:`JobCancelled` /
:class:`JobDeadlineExceeded` (both :class:`JobAborted`).  Job
supervision treats a JobAborted as TERMINAL: no retry, a durable
``failure`` status whose error text leads with ``CANCELLED`` /
``DEADLINE_EXCEEDED``, and a trace event in the flight recorder.

Cost contract (the same pin as utils/faults and the flight recorder):
with no deadline set and no cancel pending anywhere in the process,
:func:`check` is ONE module-global read — scripts/bench_smoke.sh's
byte-identical dispatch counters hold.  The current job rides a
contextvar (set by ``Miner._loop`` around the run), so engine code
calls :func:`check` with zero plumbing, exactly like obs spans.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Dict, Optional

from spark_fsm_tpu.utils import obs

_CANCELLED_TOTAL = obs.REGISTRY.counter(
    "fsm_jobs_cancelled_total",
    "jobs aborted by /admin/cancel (queued or mid-mine)")
_DEADLINE_TOTAL = obs.REGISTRY.counter(
    "fsm_jobs_deadline_exceeded_total",
    "jobs aborted because their deadline_s budget ran out")
_LEASE_LOST_TOTAL = obs.REGISTRY.counter(
    "fsm_jobs_lease_lost_total",
    "jobs self-fenced because their replica lease expired or was "
    "superseded (service/lease.py)")


class JobAborted(RuntimeError):
    """Base of the two abort signals.  TERMINAL for supervision: the
    Miner records a durable failure instead of retrying (a retry would
    just re-spend a budget the client already exhausted)."""

    code = "ABORTED"

    def __init__(self, uid: str, detail: str):
        self.uid = uid
        super().__init__(f"{self.code}: job {uid!r} {detail}")


class JobCancelled(JobAborted):
    code = "CANCELLED"


class JobDeadlineExceeded(JobAborted):
    code = "DEADLINE_EXCEEDED"


class JobLeaseLost(JobAborted):
    """The multi-replica fence signal (service/lease.py): this replica's
    lease on the job expired or was superseded by a peer, so continuing
    to mine — and above all continuing to WRITE — risks double-commit
    against the adopting replica's run.  Terminal like every JobAborted;
    the failure-settling path additionally refuses the store writes when
    the lease is confirmed superseded."""

    code = "LEASE_LOST"


class JobControl:
    """The live-job record.  ``cancelled`` is a plain bool flipped under
    the module lock and read lock-free at check sites (a stale read
    costs one extra launch, never a missed abort — the next check sees
    it)."""

    __slots__ = ("uid", "deadline", "cancelled", "running", "priority",
                 "lease_lost", "submitted_t", "started_t", "dataset_fp",
                 "follower_of", "stalled", "tenant", "ephemeral", "usage")

    def __init__(self, uid: str, deadline: Optional[float],
                 priority: str = "normal"):
        self.uid = uid
        self.deadline = deadline  # absolute time.monotonic(), or None
        self.cancelled = False
        self.running = False  # False = still queued (set by activate())
        # result-reuse tier (service/resultcache.py): the content-
        # addressed fingerprint of the job's resolved dataset, stamped
        # once at dataset load (None until then / when the tier is off)
        self.dataset_fp: Optional[str] = None
        # follower linkage: set to the leader uid when this entry is a
        # coalesced follower awaiting fan-out instead of a queued job —
        # its deadline/cancel signals are honored at fan-out time
        self.follower_of: Optional[str] = None
        # admission class ("high"/"normal"/"low") — read by the fusion
        # broker's window rule (a high job's waves never wait for fill)
        self.priority = priority
        # flipped by the lease heartbeat (service/lease.py) when this
        # replica can no longer prove it owns the job — same read
        # discipline as ``cancelled``: lock-free at check sites, a stale
        # read costs one extra launch, never a missed fence
        self.lease_lost = False
        # store-outage stall (service/storeguard.py): while True, the
        # job PAUSES at its next safe point (frontier kept in memory)
        # instead of raising — cleared by the guard on store return, or
        # superseded by ``lease_lost`` when the outage ends badly
        self.stalled = False
        # multi-tenant identity (service/fairness.py): the admission
        # tenant, stamped at submit — the fsm_job_*_seconds tenant label
        self.tenant = "default"
        # storeguard ephemeral admission: True marks a loudly-flagged
        # NO-JOURNAL job admitted during a store outage — its durable
        # writes ride the spool ungated (no lease, no journal intent)
        self.ephemeral = False
        # usage metering (service/usage.py): the live per-job device-
        # cost accumulator, attached by the meter's first deposit —
        # None when the plane is off or nothing was dispatched yet
        self.usage = None
        # SLO accounting stamps (service/obsplane.py): submit instant
        # and FIRST worker pickup — e2e = terminal - submitted_t,
        # queue wait = started_t - submitted_t (retries re-activate but
        # keep the first pickup; the client waited once)
        self.submitted_t = time.monotonic()
        self.started_t: Optional[float] = None


_lock = threading.Lock()
_jobs: Dict[str, JobControl] = {}
# Fast-path flag: True only while some live job carries a deadline or a
# pending cancel — check() returns on this one global read otherwise.
_active = False

# the job whose worker thread this is (None on handler/stream threads)
_cur: contextvars.ContextVar[Optional[JobControl]] = contextvars.ContextVar(
    "fsm_jobctl", default=None)


def _recompute_active_locked() -> None:
    global _active
    _active = any(c.deadline is not None or c.cancelled or c.lease_lost
                  or c.stalled for c in _jobs.values())


def register(uid: str, deadline_s: Optional[float] = None,
             priority: str = "normal") -> JobControl:
    """Register a submitted job; the deadline budget starts NOW (queue
    wait spends it).  Re-registering a uid replaces the old entry — the
    admission layer's 409 conflict check guarantees the old incarnation
    is dead by then."""
    ctl = JobControl(uid, None if deadline_s is None
                     else time.monotonic() + float(deadline_s),
                     priority=priority)
    with _lock:
        _jobs[uid] = ctl
        _recompute_active_locked()
    return ctl


def release(uid: str) -> None:
    """Drop a job's entry on ANY terminal status (idempotent)."""
    with _lock:
        _jobs.pop(uid, None)
        _recompute_active_locked()


def release_entry(ctl: Optional[JobControl]) -> None:
    """Drop a job's entry ONLY if the registry still maps its uid to
    THIS control object.  The victim side of a work steal must use
    this: in a multi-replica-in-one-process topology the thief's
    re-register has replaced the uid's entry, and a release-by-uid from
    the victim would strip the thief's live job of its deadline/cancel/
    fence signals."""
    if ctl is None:
        return
    with _lock:
        if _jobs.get(ctl.uid) is ctl:
            _jobs.pop(ctl.uid, None)
            _recompute_active_locked()


def get(uid: str) -> Optional[JobControl]:
    with _lock:
        return _jobs.get(uid)


def cancel(uid: str) -> Optional[str]:
    """Request cancellation of a live job.  Returns ``"running"`` /
    ``"queued"`` (what the job was doing when flagged) or None when no
    live job owns the uid (unknown, or already terminal) — the 404
    case.  The abort lands at the job's next safe point."""
    global _active
    with _lock:
        ctl = _jobs.get(uid)
        if ctl is None:
            return None
        ctl.cancelled = True
        _active = True
        return "running" if ctl.running else "queued"


# stalled job threads wait here; the storeguard notifies on every
# unstall so a healed outage resumes jobs within one wait quantum
_stall_cond = threading.Condition()


def stall_entry(ctl: Optional[JobControl]) -> None:
    """Flip a job's outage-stall flag (service/storeguard.py calls this
    on the control OBJECT captured at lease-attach time): the job
    PAUSES at its next safe point — frontier kept in memory — until
    :func:`unstall_entry` or a fence/cancel/deadline supersedes."""
    global _active
    if ctl is None:
        return
    with _lock:
        ctl.stalled = True
        _active = True


def unstall_entry(ctl: Optional[JobControl]) -> None:
    """Release a stalled job (store returned, or the guard fenced it —
    in the fenced case ``lease_lost`` is already set and the woken
    check raises terminal LEASE_LOST instead of resuming)."""
    if ctl is None:
        return
    with _lock:
        ctl.stalled = False
        _recompute_active_locked()
    with _stall_cond:
        _stall_cond.notify_all()


def fence_lost(ctl: Optional[JobControl]) -> None:
    """Flip a job's lease-lost flag (lease heartbeat / fence checks call
    this on the CONTROL OBJECT they captured at attach time, never by
    uid lookup: in multi-replica-in-one-process tests two miners may
    register the same uid, and the flag must land on the incarnation
    that actually lost its lease)."""
    global _active
    if ctl is None:
        return
    with _lock:
        ctl.lease_lost = True
        _active = True


def live_count() -> int:
    with _lock:
        return len(_jobs)


@contextlib.contextmanager
def activate(ctl: Optional[JobControl]):
    """Bind ``ctl`` as the current job for this thread/context (the
    Miner wraps each run in this), so engine-level :func:`check` calls
    see it with no plumbing."""
    if ctl is None:
        yield
        return
    ctl.running = True
    if ctl.started_t is None:
        ctl.started_t = time.monotonic()
    token = _cur.set(ctl)
    try:
        yield
    finally:
        _cur.reset(token)


def check_entry(ctl: Optional[JobControl]) -> None:
    """Raise the abort owed by ``ctl``, if any — or BLOCK while the
    job is outage-stalled (service/storeguard.py): the safe point the
    abort signals land on doubles as the pause point a store outage
    parks the job at, frontier kept in memory.  Cancel, deadline and
    fence signals are re-checked every wait quantum, so a stall never
    shadows an abort the client is owed.  Used directly by the Miner on
    dequeue (the queued-job path, where no context is bound)."""
    if ctl is None:
        return
    while ctl.stalled:
        _check_signals(ctl)
        with _stall_cond:
            if ctl.stalled:  # re-check under the condition: an unstall
                _stall_cond.wait(0.05)  # between the reads must not
                # strand this thread for a full quantum more than once
    _check_signals(ctl)


def _check_signals(ctl: JobControl) -> None:
    if ctl.cancelled:
        _CANCELLED_TOTAL.inc()
        obs.trace_event("job_cancelled", uid=ctl.uid)
        raise JobCancelled(ctl.uid, "cancelled via /admin/cancel")
    if ctl.lease_lost:
        _LEASE_LOST_TOTAL.inc()
        obs.trace_event("job_lease_lost", uid=ctl.uid)
        raise JobLeaseLost(
            ctl.uid, "lost its replica lease (expired or superseded); "
                     "self-fencing instead of risking a double-commit")
    if ctl.deadline is not None and time.monotonic() > ctl.deadline:
        _DEADLINE_TOTAL.inc()
        obs.trace_event("job_deadline_exceeded", uid=ctl.uid)
        raise JobDeadlineExceeded(
            ctl.uid, "outran its deadline_s budget (includes queue wait)")


def check() -> None:
    """The engine-side safe-point probe: one module-global read when no
    deadline/cancel exists anywhere; otherwise consult the current
    job's entry and raise its abort."""
    if not _active:
        return
    check_entry(_cur.get())


def current() -> Optional[JobControl]:
    """The job bound to this thread/context (None outside a mine run) —
    how the fusion broker learns a wave's uid and admission class with
    zero engine plumbing."""
    return _cur.get()
