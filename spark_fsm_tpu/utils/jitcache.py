"""Persistent XLA compilation cache wiring.

Every process that mines pays the full XLA compile bill for the kernel
chain (~10-30s on a v5e; the Mosaic pair-support kernel dominates) even
though the compiled artifacts are byte-stable across runs.  JAX ships a
persistent on-disk compilation cache that turns those into millisecond
deserializations; this module enables it with sane defaults for every
entry point (service boot, bench harnesses, tests).

The reference has no analog — JVM warmup played the same role and was
equally re-paid per process — so this is purely a TPU-native cold-start
win (the driver's recorded ``cold_wall_s`` is mostly compile time).

Env knobs: ``SPARKFSM_COMPILE_CACHE=0`` disables; ``SPARKFSM_COMPILE_CACHE_DIR``
overrides the location (default ``~/.cache/spark_fsm_tpu/xla``).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

# ---------------------------------------------------------------------------
# Fresh-compile observability: count actual XLA backend compiles via
# jax.monitoring ('/jax/core/compile/backend_compile_duration' fires once
# per compiled program; in-process jit-cache hits and persistent-cache
# deserializations do not).  The prewarm driver uses this to report how
# much compile work it prepaid, and the drift test to assert a prewarmed
# first mine compiles NOTHING fresh.
# ---------------------------------------------------------------------------

_counter_lock = threading.Lock()
_compile_counter = {"count": 0, "seconds": 0.0}
_counter_registered = False


def enable_compile_counter() -> bool:
    """Install the (idempotent, process-wide) compile-event listener.
    Returns False when this jax version emits no such event — callers
    fall back to wall-clock heuristics then."""
    global _counter_registered
    with _counter_lock:
        if _counter_registered:
            return True
        try:
            from jax import monitoring

            def _on_event(event: str, duration: float, **kw) -> None:
                if event.endswith("backend_compile_duration"):
                    with _counter_lock:
                        _compile_counter["count"] += 1
                        _compile_counter["seconds"] += float(duration)

            monitoring.register_event_duration_secs_listener(_on_event)
            _counter_registered = True
            return True
        except Exception:
            return False


def compile_counts() -> dict:
    """Snapshot of fresh-compile count + total seconds since process
    start (zeros until :func:`enable_compile_counter` ran)."""
    with _counter_lock:
        return dict(_compile_counter)


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (or the default
    location).  Returns the directory in use, or None when disabled or
    unsupported.  Safe to call multiple times / before or after backend
    init; never raises (a broken cache must not take down a mine)."""
    if os.environ.get("SPARKFSM_COMPILE_CACHE") == "0":
        return None
    path = (path
            or os.environ.get("SPARKFSM_COMPILE_CACHE_DIR")
            or os.path.join(os.path.expanduser("~"), ".cache",
                            "spark_fsm_tpu", "xla"))
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # default min-compile-time gate (1s) would skip most of the small
        # per-shape kernels whose count is exactly what hurts cold starts;
        # each tuning knob is individually guarded — a renamed/absent knob
        # must not disable the cache dir that already took effect
        for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.2),
                          ("jax_persistent_cache_min_entry_size_bytes", 0)):
            try:
                jax.config.update(knob, val)
            except Exception:
                pass  # knob renamed/absent on some versions; cache works
        return path
    except Exception as exc:
        logging.getLogger(__name__).warning(
            "persistent compile cache disabled (%s: %s) — every process "
            "will re-pay full XLA compile time", type(exc).__name__, exc)
        return None
