"""TPU-tunnel reachability probe shared by the bench harnesses.

On this deployment the TPU backend is reached through a local relay; if
the relay is down, *importing the backend hangs forever*, so harnesses
must probe the socket BEFORE the first jax import and fall back to CPU
loudly when it is unreachable.
"""

from __future__ import annotations

import socket
import time

TUNNEL_PORT = 8082


def tpu_probe(wait_s: float, port: int = TUNNEL_PORT) -> str:
    """Empty string if the tunnel answers (retrying up to ``wait_s``), else
    the fallback reason.  Connection-refused means nothing listens at all
    (a CPU-only box, not a flaky tunnel), so it gets a short retry budget
    rather than stalling every run the full wait."""
    start = time.time()
    last = "unknown"
    budget = wait_s
    while True:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=2.0):
                return ""
        except ConnectionRefusedError as e:
            last = str(e)
            budget = min(budget, 6.0)  # relay definitively absent
        except OSError as e:
            last = str(e)
        if time.time() - start >= budget:
            return (f"TPU tunnel port {port} unreachable after "
                    f"{budget:.0f}s of retries: {last}")
        time.sleep(2.0)
