"""Bounded exponential backoff + jitter, and a device circuit breaker.

The reference gets I/O retry for free from its runtime (Spark task
re-execution, Akka supervision backoff); this rebuild's store and broker
seams had none — a single Redis hiccup mid-checkpoint failed the whole
job.  This module is the ONE retry policy those seams share
(:class:`RetryPolicy`: StoreCheckpoint's store I/O, the consumer loop's
error backoff), plus :class:`CircuitBreaker` for the devcache's
device-put seam — N consecutive failures stop paying the failing path's
cost and fall back to the host path, with an automatic half-open probe
after a cooldown.

Every retry/give-up is counted per site (module-global, surfaced by
``/admin/health``), and jitter is SEEDED so chaos runs are reproducible.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from spark_fsm_tpu.utils import obs
from spark_fsm_tpu.utils.obs import log_event

_lock = threading.Lock()
_counters: Dict[str, Dict[str, int]] = {}

# Retry-policy sites wired into the framework itself (callers may add
# ad-hoc sites; these are the ones scripts/obs_smoke.sh asserts have
# registry series even before their first use — a policy with no
# metric would be invisible exactly when it matters).
KNOWN_SITES = ("store.checkpoint",)


def _collect_metrics():
    """fsm_retry_* families for the unified registry; every KNOWN_SITES
    policy emits zero-valued series from boot (no orphan counters)."""
    with _lock:
        per_site = {s: dict(c) for s, c in _counters.items()}
    for s in KNOWN_SITES:
        per_site.setdefault(s, {"attempts": 0, "retries": 0, "gave_up": 0})
    fams = []
    for key in ("attempts", "retries", "gave_up"):
        fams.append((f"fsm_retry_{key}_total", "counter", "",
                     [({"site": s}, c.get(key, 0))
                      for s, c in sorted(per_site.items())]))
    return fams


obs.REGISTRY.register_collector("retry", _collect_metrics)


def _count(site: str, key: str, n: int = 1) -> None:
    with _lock:
        c = _counters.setdefault(
            site, {"attempts": 0, "retries": 0, "gave_up": 0})
        c[key] += n


def retry_counters() -> Dict[str, Dict[str, int]]:
    """Per-site attempt/retry/give-up counters (``/admin/health``)."""
    with _lock:
        return {s: dict(c) for s, c in _counters.items()}


def reset_retry_counters() -> None:
    with _lock:
        _counters.clear()


class RetryPolicy:
    """Bounded exponential backoff with seeded jitter.

    ``delay_s(attempt)`` for attempt n (1-based) is
    ``base_s * factor**(n-1)`` scaled UP by a jitter factor in
    ``[1, 1+jitter]`` (a retry never waits less than the un-jittered
    schedule — a backoff that can undercut the base interval would
    hammer the failing dependency harder than the happy path), then
    clamped to ``max_s`` (the documented hard bound, jitter included).
    Seeded, so a chaos run's schedule is reproducible.
    """

    def __init__(self, retries: int = 3, base_s: float = 0.05,
                 max_s: float = 2.0, factor: float = 2.0,
                 jitter: float = 0.5, seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep,
                 no_retry: Tuple[type, ...] = ()) -> None:
        if retries < 0:
            raise ValueError(f"retries must be >= 0 (got {retries})")
        self.retries = int(retries)
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.factor = float(factor)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        self._sleep = sleep
        self.no_retry = tuple(no_retry)

    def delay_s(self, attempt: int) -> float:
        d = self.base_s * self.factor ** max(0, attempt - 1)
        if self.jitter:
            d *= 1.0 + self.jitter * self._rng.random()
        return min(self.max_s, max(0.0, d))

    def run(self, fn: Callable, *args, site: str = "retry", **kwargs):
        """Call ``fn`` with up to ``retries`` re-runs on exception.

        ``no_retry`` exception types fail immediately (deterministic
        errors — re-running would just repeat them, the Miner's
        ValueError convention).  The final failure re-raises the last
        exception after counting a give-up.
        """
        attempt = 0
        while True:
            _count(site, "attempts")
            try:
                return fn(*args, **kwargs)
            except self.no_retry:
                _count(site, "gave_up")
                raise
            except Exception as exc:
                attempt += 1
                if attempt > self.retries:
                    _count(site, "gave_up")
                    raise
                _count(site, "retries")
                wait_s = self.delay_s(attempt)
                log_event("io_retry", site=site, attempt=attempt,
                          error=f"{type(exc).__name__}: {exc}")
                obs.trace_event("io_retry", site=site, attempt=attempt,
                                wait_s=round(wait_s, 4),
                                error=f"{type(exc).__name__}: {exc}")
                self._sleep(wait_s)


class CircuitBreaker:
    """closed -> open after N consecutive failures -> half-open probe.

    ``allow()`` gates the protected path: True while closed; False while
    open (callers take their fallback — counted as ``short_circuited``);
    after ``cooldown_s`` the next ``allow()`` lets exactly ONE probe
    through (half-open) while concurrent callers keep falling back.  The
    probe's ``success()`` closes the breaker; its ``failure()`` reopens
    it for another cooldown.  Callers must pair every True ``allow()``
    with exactly one ``success()``/``failure()`` — but a probe that dies
    without reporting (a hung device, a BaseException skipping the
    caller's handler) EXPIRES after another ``cooldown_s``, so a lost
    probe degrades to one more cooldown of fallbacks instead of wedging
    the breaker open for the life of the process.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, name: str, threshold: int = 3,
                 cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1 (got {threshold})")
        self.name = name
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False
        self._probe_started = 0.0
        self._counts = {"successes": 0, "failures": 0, "opens": 0,
                        "short_circuited": 0}

    def allow(self) -> bool:
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = self._clock()
            if (self._state == self.OPEN
                    and now - self._opened_at >= self.cooldown_s):
                self._state = self.HALF_OPEN
                self._probing = False
            if self._state == self.HALF_OPEN:
                if (self._probing
                        and now - self._probe_started >= self.cooldown_s):
                    self._probing = False  # lost probe: expire it
                if not self._probing:
                    self._probing = True  # this caller IS the probe
                    self._probe_started = now
                    return True
            self._counts["short_circuited"] += 1
            return False

    def success(self) -> None:
        with self._lock:
            self._counts["successes"] += 1
            self._consecutive = 0
            self._probing = False
            if self._state != self.CLOSED:
                log_event("breaker_closed", breaker=self.name)
                obs.trace_event("breaker_closed", breaker=self.name)
            self._state = self.CLOSED

    def failure(self) -> None:
        with self._lock:
            self._counts["failures"] += 1
            self._consecutive += 1
            was = self._state
            if (self._state == self.HALF_OPEN
                    or self._consecutive >= self.threshold):
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._probing = False
                if was != self.OPEN:
                    self._counts["opens"] += 1
                    log_event("breaker_opened", breaker=self.name,
                              consecutive=self._consecutive)
                    obs.trace_event("breaker_opened", breaker=self.name,
                                    consecutive=self._consecutive)

    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive,
                    **self._counts}
