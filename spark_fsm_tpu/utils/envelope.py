"""Checksummed self-describing envelope for every durable store write
(ISSUE 18 — the durable-state integrity plane).

Until this layer existed, every durable artifact — checkpoint metas and
delta chunks, journal intents, rescache entries, trace-spine chunks,
lease heartbeats, autoscale records — was trusted blindly on read: a
single flipped bit in a checkpoint delta silently resumed a wrong
frontier, and a corrupt rescache entry was amplified by dominance
serving to every future request for that fingerprint.  The envelope
makes corruption *detectable* at each read site, so each surface can
degrade by its own blast radius (service/integrity.py owns the
per-surface posture; this module owns only the bytes).

Wire format (text-safe — every store value in this system is a str)::

    FSME1:<sha256-hex 64>:<payload-len decimal>:<payload>

* ``FSME`` — magic; a value not starting with it is a *legacy*
  (pre-envelope) value, accepted as ``verify=legacy`` and upgraded the
  next time its writer rewrites it.  No flag-day migration.
* ``1`` — schema version.  An envelope with an UNKNOWN version is
  treated as corrupt, not legacy: we know it claims to be checked but
  cannot check it, and integrity must fail loud, not open.
* sha256 over the UTF-8 payload bytes, computed in streaming chunks so
  multi-MB rescache entries never need a second contiguous copy.
* explicit payload length — catches truncation even when the truncated
  tail happens to re-hash (it cannot, but the length check is free and
  fails faster than the digest on short reads).

The clean-path cost contract (pinned by bench_smoke's byte-identical
dispatch counters): ONE sha256 verify per durable read, zero extra
store round-trips.
"""
from __future__ import annotations

import hashlib
import re
from typing import Optional, Tuple

MAGIC = "FSME"
VERSION = 1
_PREFIX = f"{MAGIC}{VERSION}:"
# header: magic+version, 64 hex digest chars, decimal length, then payload
_HEADER = re.compile(r"^FSME(\d+):([0-9a-f]{64}):(\d+):")
# streaming digest chunk: 1 MiB of UTF-8 bytes per update
_CHUNK = 1 << 20

#: verdicts `unwrap` can return (service/integrity.py seeds counters
#: over the first three; "missing" is a None value, not a read outcome)
VERDICTS = ("ok", "legacy", "corrupt")


def _digest(payload: str) -> str:
    h = hashlib.sha256()
    data = payload.encode("utf-8")
    for i in range(0, len(data), _CHUNK):
        h.update(data[i:i + _CHUNK])
    return h.hexdigest()


def wrap(payload: str) -> str:
    """Envelope ``payload`` for a durable write."""
    return f"{_PREFIX}{_digest(payload)}:{len(payload)}:{payload}"


def is_enveloped(value: Optional[str]) -> bool:
    return isinstance(value, str) and value.startswith(MAGIC)


def unwrap(value: Optional[str]) -> Tuple[Optional[str], str]:
    """Verified open of a durable value: ``(payload, verdict)``.

    * ``(payload, "ok")``     — intact envelope, digest + length check out.
    * ``(value, "legacy")``   — pre-envelope value: returned untouched so
      existing parsers keep working; the writer upgrades it on next write.
    * ``(None, "corrupt")``   — claims to be enveloped but fails the
      header parse, version check, length, or digest.  The caller must
      degrade per its surface's posture, never parse the bytes.
    * ``(None, "missing")``   — value was None (key absent).
    """
    if value is None:
        return None, "missing"
    if not isinstance(value, str):
        # non-str values never come out of the store layer; treat as
        # legacy so an exotic caller degrades through its own parser
        return value, "legacy"
    if not value.startswith(MAGIC):
        return value, "legacy"
    m = _HEADER.match(value)
    if m is None:
        return None, "corrupt"  # truncated or garbled header
    if int(m.group(1)) != VERSION:
        return None, "corrupt"  # claims a schema we cannot verify
    payload = value[m.end():]
    if len(payload) != int(m.group(3)):
        return None, "corrupt"  # truncation (or tail growth)
    if _digest(payload) != m.group(2):
        return None, "corrupt"  # bit rot
    return payload, "ok"
