"""Deterministic fault injection — the chaos seam every recovery path
is proven against.

The reference inherits Spark's lineage re-execution and actor
supervision; this rebuild supplies that layer itself (Miner retry,
StoreCheckpoint, queue->classic downgrades), and none of it counts as
*proven* until an injected failure exercises it.  This module is a
process-global registry of NAMED fault sites — every place the
framework touches a device, a store, a broker, or a compile pipeline
declares one — with seeded, scriptable triggers, so a test (or an
operator via ``/admin/faults``) can make exactly one dispatch hang,
every third store write fail, or a device launch OOM, deterministically.

Contract:

- ``fault_site(name, **ctx)`` is woven into the REAL call sites
  (ops/ragged_batch consumers, models/tsr, models/spade_queue,
  service/{actors,store,devcache,prewarm}, streaming/{kafka,consumer}).
  With nothing armed it is a single module-global read — the hardening
  layer costs nothing on the happy path.
- Sites must come from :data:`KNOWN_SITES`: an unknown name is a typo
  that would silently never fire, so ``arm`` refuses it.
- Triggers are deterministic: nth-call, every-k, or seeded probability.
  ``delay_s`` simulates a HANG (the call sleeps before returning or
  raising — what the dispatch watchdog exists to bound); ``exc`` picks
  the raised type (``"oom"`` raises :class:`InjectedOom`, whose text
  matches the engines' RESOURCE_EXHAUSTED detection; ``"none"`` only
  delays).
- ``match`` restricts a spec to calls whose context carries the given
  substring (e.g. only ``store.set`` calls for ``fsm:frontier:`` keys),
  so one site guard can serve many callers without collateral damage.

tests/conftest.py asserts the registry is DISARMED at session start and
end, so injections can never leak between tests or into a live suite.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from spark_fsm_tpu.utils import obs


class FaultInjected(RuntimeError):
    """Raised by :func:`fault_site` when an armed trigger fires."""


class InjectedOom(FaultInjected):
    """Injected device OOM.  The message carries RESOURCE_EXHAUSTED so
    the engines' substring-based OOM detection (models/tsr._is_oom)
    treats it exactly like a real XLA allocation failure."""

    def __init__(self, site: str):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected device OOM at fault site "
            f"{site!r}")


# The registered fault sites.  Adding a call-site guard for a NEW name
# requires listing it here (arm refuses unknowns) — and tests/test_chaos.py
# asserts it sweeps this exact set, so a new site cannot ship untested.
KNOWN_SITES = (
    "device.dispatch",   # device launch/readback (TSR ragged + queue)
    "device.oom",        # allocation failure on a device launch
    "store.get",         # result-store reads
    "store.set",         # result-store writes
    "store.rpush",       # result-store list appends (checkpoint deltas)
    "kafka.poll",        # broker poll (streaming/kafka.KafkaFetch)
    "checkpoint.save",   # whole-snapshot save (service/actors)
    "prewarm.compile",   # per-shape-key AOT compile (service/prewarm)
    "devcache.put",      # engine-cache device build/insert (service/devcache)
    "service.admit",     # train-submit admission (service/actors.Miner.submit)
    "service.journal",   # write-ahead job-journal intent write (service/store)
    "fusion.dispatch",   # cross-job fusion broker launch (service/fusion) —
                         # injection must DEGRADE to unfused per-job
                         # dispatch, never lose a wave
    "device.resident",   # resident-frontier segment dispatch/readback
                         # (models/tsr._mine_resident) — injection must
                         # fall back to the host-driven path with full
                         # parity, never lose the frontier
    "lease.acquire",     # per-job lease acquisition at admission
                         # (service/lease.py) — injection must be a clean
                         # synchronous 503 with ZERO journal/store trace
    "lease.renew",       # heartbeat renewal + stale-fence verification —
                         # injection lets the job keep running until its
                         # TTL lapses, then it self-fences at the next
                         # safe point (terminal LEASE_LOST, no retry)
    "lease.steal",       # work-steal claim on a peer's queued job —
                         # injection must abort the steal cleanly: the
                         # job stays with (and finishes on) the victim
    "rescache.lookup",   # result-reuse lookup at admission
                         # (service/resultcache.py) — injection must
                         # degrade the request to a plain cold mine
                         # with oracle parity, never fail the submit
    "rescache.store",    # cache-entry store / fingerprint learn after a
                         # finished mine — injection must leave the job
                         # green (results already durable); only the
                         # reuse entry is lost
    "storeguard.probe",  # active store health probe (service/storeguard)
                         # — an injected raise IS a failed probe (the
                         # site's whole purpose: drive the health state
                         # machine to DOWN deterministically); recovery
                         # on disarm must replay the spool and heal
    "storeguard.replay", # per-write spool replay after an outage —
                         # injection must degrade to the current
                         # terminal-failure path (job fenced, spool
                         # dropped, store left heal-able), NEVER a
                         # corrupt/partial state accepted on resume
    "store.corrupt",     # bitrot simulation on durable READS
                         # (service/store get/lrange/spine_chunks, via
                         # :func:`corrupt_value`) — fires by RETURNING
                         # deterministically damaged bytes (odd
                         # injections byte-flip the middle character,
                         # even injections truncate to the first half)
                         # instead of raising; ``exc``/``delay_s`` are
                         # ignored.  The envelope layer
                         # (utils/envelope.py) must detect every hit
                         # and each surface must degrade per its
                         # integrity posture (service/integrity.py),
                         # never parse the damage
)

_EXC_BY_NAME = {"fault": FaultInjected, "oom": InjectedOom, "none": None}


class _Spec:
    __slots__ = ("site", "nth", "every", "p", "seed", "times", "delay_s",
                 "exc", "match", "rng", "calls", "injected")

    def __init__(self, site, nth, every, p, seed, times, delay_s, exc,
                 match):
        self.site = site
        self.nth = nth
        self.every = every
        self.p = p
        self.seed = seed
        self.times = times
        self.delay_s = delay_s
        self.exc = exc
        self.match = match
        self.rng = random.Random(seed)
        self.calls = 0
        self.injected = 0

    def describe(self) -> dict:
        out = {"calls": self.calls, "injected": self.injected,
               "exc": next((k for k, v in _EXC_BY_NAME.items()
                            if v is self.exc), getattr(self.exc, "__name__",
                                                       str(self.exc)))}
        for k in ("nth", "every", "p", "seed", "times", "delay_s", "match"):
            v = getattr(self, k)
            if v not in (None, 0, 0.0):
                out[k] = v
        return out


_lock = threading.Lock()
_armed: Dict[str, _Spec] = {}
# lifetime per-site counters (survive disarm — /admin/health reads them)
_counters: Dict[str, Dict[str, int]] = {}
_active = False  # fast-path flag: fault_site returns on one global read


def _collect_metrics():
    """fsm_fault_site_* families for the unified registry.  EVERY
    registered site emits series (zero-valued until touched): an armed
    site with no metric would be an orphan counter, which
    scripts/obs_smoke.sh exists to catch."""
    with _lock:
        per_site = {s: dict(c) for s, c in _counters.items()}
        n_armed = len(_armed)
    for s in KNOWN_SITES:
        per_site.setdefault(s, {"calls": 0, "injected": 0})
    return [
        ("fsm_fault_site_calls_total", "counter",
         "guarded calls observed while the site was armed",
         [({"site": s}, c["calls"]) for s, c in sorted(per_site.items())]),
        ("fsm_fault_site_injected_total", "counter",
         "injections actually fired",
         [({"site": s}, c["injected"]) for s, c in sorted(per_site.items())]),
        ("fsm_fault_sites_armed", "gauge",
         "armed fault sites (should be 0 outside a chaos drill)",
         [({}, n_armed)]),
    ]


obs.REGISTRY.register_collector("faults", _collect_metrics)


def arm(site: str, *, nth: Optional[int] = None, every: Optional[int] = None,
        p: Optional[float] = None, seed: int = 0,
        times: Optional[int] = None, delay_s: float = 0.0,
        exc="fault", match: Optional[str] = None) -> None:
    """Arm ``site`` with one trigger (re-arming replaces the spec).

    Exactly one of ``nth`` (fire on the nth matching call), ``every``
    (fire on every k-th matching call), ``p`` (fire with probability p,
    seeded — deterministic per arm) must be given.  ``times`` bounds the
    total injections (default unbounded).  ``delay_s`` sleeps before
    acting (a hang); ``exc`` is "fault"/"oom"/"none" or an Exception
    subclass.  ``match`` restricts to calls whose context contains it.
    """
    global _active
    if site not in KNOWN_SITES:
        raise ValueError(f"unknown fault site {site!r} "
                         f"(known: {sorted(KNOWN_SITES)})")
    if sum(x is not None for x in (nth, every, p)) != 1:
        raise ValueError("arm needs exactly one of nth/every/p")
    if nth is not None and nth < 1:
        raise ValueError(f"nth must be >= 1 (got {nth}; calls are 1-based)")
    if every is not None and every < 1:
        raise ValueError(f"every must be >= 1 (got {every})")
    if p is not None and not 0.0 < p <= 1.0:
        raise ValueError(f"p must be in (0, 1] (got {p})")
    if exc == "fault" and site == "device.oom":
        exc = "oom"  # the OOM site injects OOM semantics by default
    if isinstance(exc, str):
        if exc not in _EXC_BY_NAME:
            raise ValueError(f"exc must be one of {sorted(_EXC_BY_NAME)} "
                             f"or an Exception subclass, got {exc!r}")
        exc = _EXC_BY_NAME[exc]
    if exc is None and not delay_s:
        raise ValueError("exc='none' needs delay_s (an injection that "
                         "neither raises nor delays is a no-op)")
    with _lock:
        _armed[site] = _Spec(site, nth, every, p, int(seed), times,
                             float(delay_s), exc, match)
        _active = True


def disarm(site: Optional[str] = None) -> list:
    """Disarm one site (or all when None); returns the disarmed names."""
    global _active
    with _lock:
        names = [site] if site is not None else list(_armed)
        out = [n for n in names if _armed.pop(n, None) is not None]
        _active = bool(_armed)
        return out


def armed() -> Dict[str, dict]:
    """Snapshot of armed sites -> spec description (JSON-able)."""
    with _lock:
        return {s: spec.describe() for s, spec in _armed.items()}


def counters() -> Dict[str, Dict[str, int]]:
    """Lifetime per-site call/injection counters (survive disarm)."""
    with _lock:
        return {s: dict(c) for s, c in _counters.items()}


def reset_counters() -> None:
    with _lock:
        _counters.clear()


def _ctx_matches(match: str, ctx: dict) -> bool:
    """Spec-``match`` predicate: substring over the call's string ctx
    values, PLUS the ambient job identity — a ``match`` of the exact
    form ``uid=<job uid>`` matches any guarded call made on that job's
    worker thread (utils/jobctl contextvar), so a chaos drill can arm a
    poison DATASET (every holder of the job crashes at dispatch, on
    every replica that adopts it) without the engines threading uids
    into every site's ctx."""
    if any(match in v for v in ctx.values() if isinstance(v, str)):
        return True
    if match.startswith("uid="):
        from spark_fsm_tpu.utils import jobctl  # lazy: no import cycle
        ctl = jobctl.current()
        return ctl is not None and match == f"uid={ctl.uid}"
    return False


def fault_site(site: str, **ctx) -> None:
    """The guard woven into real call sites; raises/delays when armed.

    Context values are matched as substrings against the spec's
    ``match`` (all calls match when unset; a ``uid=...`` match also
    consults the ambient job identity — see :func:`_ctx_matches`).
    Counting happens only while the site is armed — the disarmed path
    is one global read.
    """
    if not _active:
        return
    with _lock:
        spec = _armed.get(site)
        if spec is None:
            return
        if spec.match is not None and not _ctx_matches(spec.match, ctx):
            return
        spec.calls += 1
        c = _counters.setdefault(site, {"calls": 0, "injected": 0})
        c["calls"] += 1
        fire = ((spec.nth is not None and spec.calls == spec.nth)
                or (spec.every is not None
                    and spec.calls % spec.every == 0)
                or (spec.p is not None and spec.rng.random() < spec.p))
        if not fire or (spec.times is not None
                        and spec.injected >= spec.times):
            return
        spec.injected += 1
        c["injected"] += 1
        delay_s, exc = spec.delay_s, spec.exc
    # sleep OUTSIDE the lock: a simulated hang must not block every
    # other site's bookkeeping (or the watchdog's own log path)
    obs.trace_event("fault_injected", site=site,
                    delay_s=delay_s, raises=exc is not None)
    if delay_s:
        time.sleep(delay_s)
    if exc is not None:
        raise exc(site) if exc is InjectedOom else exc(
            f"injected fault at site {site!r} (ctx {ctx!r})")


def corrupt_value(site: str, value, **ctx):
    """The value-TRANSFORMING sibling of :func:`fault_site`, woven into
    durable read verbs for the ``store.corrupt`` bitrot site: when the
    armed trigger fires, the read returns a deterministically damaged
    copy of ``value`` instead of raising.

    Damage alternates by injection parity so one arm exercises both
    envelope failure modes: odd injections BYTE-FLIP (xor 0x01 on the
    middle character — digest mismatch at intact length), even
    injections TRUNCATE to the first half (length mismatch).  ``None``
    and empty values pass through WITHOUT counting a call, so ``nth``
    deterministically addresses the nth damageable read of a matched
    key.  ``exc``/``delay_s`` on the spec are ignored.  Disarmed cost:
    one module-global read.
    """
    if not _active:
        return value
    if value is None or value == "":
        return value
    with _lock:
        spec = _armed.get(site)
        if spec is None:
            return value
        if spec.match is not None and not _ctx_matches(spec.match, ctx):
            return value
        spec.calls += 1
        c = _counters.setdefault(site, {"calls": 0, "injected": 0})
        c["calls"] += 1
        fire = ((spec.nth is not None and spec.calls == spec.nth)
                or (spec.every is not None
                    and spec.calls % spec.every == 0)
                or (spec.p is not None and spec.rng.random() < spec.p))
        if not fire or (spec.times is not None
                        and spec.injected >= spec.times):
            return value
        spec.injected += 1
        c["injected"] += 1
        flip = spec.injected % 2 == 1
    obs.trace_event("fault_injected", site=site,
                    mode="flip" if flip else "truncate")
    if flip:
        i = len(value) // 2
        return value[:i] + chr(ord(value[i]) ^ 0x01) + value[i + 1:]
    return value[:max(1, len(value) // 2)]


def corrupt_list(site: str, values, **ctx):
    """`corrupt_value` over a list read (lrange / spine_chunks): each
    element is one trigger call, so ``nth`` addresses a specific chunk
    of a matched key (e.g. the 2nd checkpoint delta).  Disarmed cost:
    one module-global read — the list is returned untouched."""
    if not _active:
        return values
    return [corrupt_value(site, v, **ctx) for v in values]


@contextmanager
def injected(site: str, **kwargs):
    """Scoped arm/disarm for tests: the site is disarmed on exit even
    when the body raises — the no-leak contract conftest enforces."""
    arm(site, **kwargs)
    try:
        yield
    finally:
        disarm(site)
