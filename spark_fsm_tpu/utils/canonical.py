"""Canonical ordering + serialization of mined patterns and rules.

The north star requires a *byte-identical* frequent-sequence set between the
CPU oracle and the TPU engine (BASELINE.md).  Byte-identical is defined over
this canonical text form, used by both paths and by the parity checker:

    <item> <item> ... -1 <item> ... -1 #SUP: <support>

one pattern per line, items ascending within an itemset, patterns sorted by
(#itemsets, total #items, the pattern tuple itself).  This mirrors SPMF's
output format, which the reference's miners inherit (SURVEY.md sec 2.3).
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Tuple

Pattern = Tuple[Tuple[int, ...], ...]
PatternResult = Tuple[Pattern, int]


def sort_patterns(results: Iterable[PatternResult]) -> List[PatternResult]:
    return sorted(results, key=lambda r: (len(r[0]), sum(len(s) for s in r[0]), r[0]))


def pattern_line(pattern: Pattern, sup: int) -> str:
    parts: List[str] = []
    for itemset in pattern:
        parts.extend(str(i) for i in itemset)
        parts.append("-1")
    parts.append(f"#SUP: {sup}")
    return " ".join(parts)


def patterns_text(results: Iterable[PatternResult]) -> str:
    return "\n".join(pattern_line(p, s) for p, s in sort_patterns(results)) + "\n"


def patterns_digest(results: Iterable[PatternResult]) -> str:
    return hashlib.sha256(patterns_text(results).encode()).hexdigest()


# ---------------------------------------------------------------------------
# Rules (TSR).  A rule is X ==> Y with X, Y disjoint unordered itemsets;
# confidence is kept exact as the integer pair (sup, sup_x) so the canonical
# text is float-free (byte-identical across platforms).  Top-k is defined
# tie-inclusively: every rule with conf >= minconf and sup >= s_k (the k-th
# highest qualifying support) is returned — deterministic, unlike SPMF's
# insertion-order tie-breaking.
# ---------------------------------------------------------------------------

RuleResult = Tuple[Tuple[int, ...], Tuple[int, ...], int, int]  # X, Y, sup, sup_x


def sort_rules(rules: Iterable[RuleResult]) -> List[RuleResult]:
    # conf descending compared exactly: s1/x1 > s2/x2  <=>  s1*x2 > s2*x1
    import functools

    def cmp(a: RuleResult, b: RuleResult) -> int:
        if a[2] != b[2]:
            return -1 if a[2] > b[2] else 1
        lhs, rhs = a[2] * b[3], b[2] * a[3]
        if lhs != rhs:
            return -1 if lhs > rhs else 1
        return -1 if (a[0], a[1]) < (b[0], b[1]) else (1 if (a[0], a[1]) > (b[0], b[1]) else 0)

    return sorted(rules, key=functools.cmp_to_key(cmp))


def rule_line(rule: RuleResult) -> str:
    x, y, sup, supx = rule
    return (f"{' '.join(map(str, x))} ==> {' '.join(map(str, y))} "
            f"#SUP: {sup} #CONF: {sup}/{supx}")


def rules_text(rules: Iterable[RuleResult]) -> str:
    return "\n".join(rule_line(r) for r in sort_rules(rules)) + "\n"


def diff_patterns(a: Iterable[PatternResult], b: Iterable[PatternResult], limit: int = 10) -> str:
    """Human-readable diff for parity failures (missing / extra / support mismatches)."""
    da: Dict[Pattern, int] = dict(a)
    db: Dict[Pattern, int] = dict(b)
    msgs: List[str] = []
    for p in sorted(set(da) - set(db), key=lambda p: (len(p), p))[:limit]:
        msgs.append(f"only in A: {pattern_line(p, da[p])}")
    for p in sorted(set(db) - set(da), key=lambda p: (len(p), p))[:limit]:
        msgs.append(f"only in B: {pattern_line(p, db[p])}")
    for p in sorted(set(da) & set(db), key=lambda p: (len(p), p)):
        if da[p] != db[p]:
            msgs.append(f"support mismatch {p}: A={da[p]} B={db[p]}")
            if len(msgs) >= 2 * limit:
                break
    return "\n".join(msgs) if msgs else "identical"
