"""Dispatch watchdog: bound a blocking device readback with a deadline.

A hung XLA dispatch (driver wedge, tunnel drop, collective deadlock)
used to block a Miner worker FOREVER — the job never reached a failure
status and the worker was lost to the pool.  The watchdog runs the
blocking readback on a helper thread and waits at most a deadline
derived from the ragged planner's own cost model (the KERNELS.json-
anchored lane-time estimate in ops/ragged_batch.estimate_seconds, times
a configurable slack): past it, the launch FAILS with
:class:`WatchdogTimeout` — the engines' existing fault handling turns
that into a jnp downgrade or a supervised job retry — instead of
hanging.  The abandoned reader thread is daemon and counted
(``leaked_threads``, surfaced by ``/admin/health``): Python cannot kill
a thread stuck in a C extension, so leaking-loudly is the honest
contract (the same one Miner.shutdown uses for overrunning jobs).

Disabled by default (``slack = None``): the happy path stays a direct
call with zero thread overhead.  Enable via the boot config
(``[engine] watchdog_slack``) or :func:`configure`.  The estimate is
anchored on TPU kernel walls — on slower backends pick a generous slack
(the CPU test backend runs orders of magnitude off the anchor, which is
why the default is off rather than a guessed floor).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from spark_fsm_tpu.utils import obs
from spark_fsm_tpu.utils.obs import log_event


class WatchdogTimeout(TimeoutError):
    """A guarded dispatch/readback outran its deadline."""


_lock = threading.Lock()
_cfg = {"slack": None, "floor_s": 2.0}
_stats = {"guarded": 0, "timeouts": 0, "leaked_threads": 0}


def _collect_metrics():
    """Canonical fsm_watchdog_* names for the unified registry — the
    /admin/health ``watchdog`` block keys are aliases of these
    (docs/OPERATIONS.md tables the mapping)."""
    with _lock:
        st = dict(_stats)
        slack = _cfg["slack"]
    fams = [(f"fsm_watchdog_{k}_total", "counter", "", [({}, v)])
            for k, v in st.items()]
    fams.append(("fsm_watchdog_slack", "gauge",
                 "configured deadline slack (0 = watchdog disabled)",
                 [({}, 0.0 if slack is None else slack)]))
    return fams


obs.REGISTRY.register_collector("watchdog", _collect_metrics)


def configure(slack: Optional[float] = None, floor_s: float = 2.0) -> None:
    """Set the process-wide watchdog policy.  ``slack`` multiplies the
    cost-model estimate (None disables the watchdog entirely);
    ``floor_s`` is the minimum deadline, so tiny estimates (small-S
    mines, where one OS scheduling hiccup exceeds the modeled wall)
    don't produce hair-trigger timeouts."""
    with _lock:
        _cfg["slack"] = None if slack is None else float(slack)
        _cfg["floor_s"] = float(floor_s)


def configured_slack() -> Optional[float]:
    with _lock:
        return _cfg["slack"]


def deadline_s(estimate_s: float) -> Optional[float]:
    """Deadline for a dispatch whose cost model predicts ``estimate_s``
    of device time; None when the watchdog is disabled."""
    with _lock:
        slack = _cfg["slack"]
        if slack is None:
            return None
        return max(_cfg["floor_s"], float(estimate_s) * slack)


def stats() -> dict:
    with _lock:
        return dict(_stats)


def reset_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = 0


def run_with_deadline(fn: Callable, deadline: Optional[float],
                      site: str = "device.dispatch"):
    """Run ``fn()`` bounded by ``deadline`` seconds (None = direct call,
    no thread).  On timeout the reader thread is abandoned (daemon,
    counted) and :class:`WatchdogTimeout` raises in the caller."""
    if deadline is None:
        return fn()
    with _lock:
        _stats["guarded"] += 1
    box: list = []

    def worker():
        try:
            box.append((True, fn()))
        except BaseException as exc:  # noqa: BLE001 - relayed to caller
            box.append((False, exc))

    t = threading.Thread(target=worker, name=f"fsm-watchdog-{site}",
                         daemon=True)
    t.start()
    t.join(deadline)
    if t.is_alive():
        with _lock:
            _stats["timeouts"] += 1
            _stats["leaked_threads"] += 1
        log_event("watchdog_timeout", site=site, deadline_s=deadline)
        obs.trace_event("watchdog_timeout", site=site,
                        deadline_s=round(deadline, 4))
        raise WatchdogTimeout(
            f"dispatch at {site!r} outran its {deadline:.3f}s watchdog "
            f"deadline (reader thread abandoned)")
    ok, value = box[0]
    if not ok:
        raise value
    return value
