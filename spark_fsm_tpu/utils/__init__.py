"""Utilities: canonical pattern serialization, profiling, logging."""
