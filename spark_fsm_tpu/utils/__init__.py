"""Utilities: canonical pattern/rule ordering (utils.canonical) and
observability — structured JSON-line logs + jax.profiler capture
(utils.obs)."""
