"""Shape-key registry: compiled device geometry, enumerable and observed.

Every engine stamps its job stats with a ``shape_key`` — a string that
identifies the COMPILED geometry of its device programs (two mines with
equal keys reuse every compiled program).  Until now each engine built
that string inline, which made the set of keys a runtime observation
only: an operator could count distinct keys after the fact, but nothing
could say, for a given config, which keys a deployment WILL compile —
so a fresh deployment learned its cold-start bill (41.7 s per
cache-missed geometry, BASELINE.json ``cold_start``) by paying it on a
live ``/train``.

This module closes that loop:

- **one definition per key format** (``key_*``): the engines call these
  when stamping stats, so the enumerator and the engines cannot drift
  on spelling;
- **a runtime registry** (:func:`record` / :func:`recorded`): engines
  record their key at construction time — the moment that decides which
  programs compile — so ``/admin/shapes`` can diff what actually ran
  against what was enumerated (:func:`drift`);
- **an enumerator** (:func:`enumerate_shapes`): given a
  :class:`WorkloadSpec` (the data geometry an operator expects) and the
  boot engine knobs, compute the finite set of shape keys the
  service-default paths will compile — WITHOUT mining — by calling the
  same geometry functions the engines' constructors use
  (``classic_geometry`` et al.).  ``service/prewarm.py`` walks this set
  at boot and compiles every entry against tiny synthetic stores.

Key formats (the geometry axes that decide compiled shapes):

  ``classic:s{S}w{W}r{R}nb{NB}c{C}``        models/spade_tpu.py
  ``queue:s{S}w{W}ni{NI}nb{NB}r{RING}``     models/spade_queue.py
  ``fused:s{S}w{W}ni{NI}f{FCAP}``           models/spade_fused.py
  ``cspade:s{S}w{W}i{I}p{P}nb{NB}c{C}g{G}x{X}d{BITS}``
                                            models/spade_constrained.py
                                            (g/x: maxgap/maxwindow — they
                                            select DIFFERENT compiled
                                            kernels; d: state dtype bits)
  ``tsr:s{S}w{W}``                          models/tsr.py (static part;
                                            per-round top-m varies by
                                            design)
  ``tsr-eval:s{S}w{W}km{K}c{C}``            models/tsr.py eval launches —
                                            one per super-batch geometry
                                            (km bucket x pow2 width, the
                                            ops/ragged_batch.py ladder);
                                            recorded per launch at
                                            dispatch time
  ``tsr-fused:s{S}w{W}m{M}km{K}c{C}``       service/fusion.py cross-job
                                            fused eval launches — item
                                            axis = concat of the fused
                                            jobs' prep stores padded to
                                            the pow2 bucket M
  ``tsr-resident:s{S}w{W}m{M}km{K}nb{NB}r{RING}``
                                            ops/resident_frontier.py
                                            whole-ladder resident
                                            program — one key per wave
                                            width (wide + late-wave
                                            narrow), ring/record caps
                                            derived from the eval
                                            budget by caps_for
  ``sweep:s{S}w{W}r{R}i{NI}``               streaming/incremental.py
                                            batch-store geometry (the
                                            config-5 mid-stream compile)
  ``predict:f{F}d{D}w{W}m{M}``              ops/rule_trie.py batched
                                            prefix->consequent scoring —
                                            F pow2 rule-lane axis, D pow2
                                            antecedent/prefix token
                                            depth, W wave width (fused
                                            request rows), M top-m pad;
                                            recorded per launch by
                                            score_wave
  ``tsr-part:p{P}s{S}w{W}``                 models/tsr.py TsrPartitioned
                                            (parallel/partition.py): the
                                            2-D parts x seq arrangement —
                                            S is the INNER (per-row)
                                            padded seq axis; the per-part
                                            engines additionally record
                                            the inner ``tsr:*`` /
                                            ``tsr-eval:*`` keys, which
                                            the enumerator lists at the
                                            inner geometry
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Iterable, List, Optional, Tuple

# ----------------------------------------------------------------- formats


def key_classic(n_seq: int, n_words: int, rows: int, node_batch: int,
                chunk: int) -> str:
    return f"classic:s{n_seq}w{n_words}r{rows}nb{node_batch}c{chunk}"


def key_queue(n_seq: int, n_words: int, ni_pad: int, nb: int,
              ring: int) -> str:
    return f"queue:s{n_seq}w{n_words}ni{ni_pad}nb{nb}r{ring}"


def key_fused(n_seq: int, n_words: int, ni_pad: int, f_cap: int) -> str:
    return f"fused:s{n_seq}w{n_words}ni{ni_pad}f{f_cap}"


def key_cspade(n_seq: int, n_words: int, item_rows: int, pool_slots: int,
               node_batch: int, chunk: int, maxgap: Optional[int],
               maxwindow: Optional[int], state_bits: int) -> str:
    g = "n" if maxgap is None else int(maxgap)
    x = "n" if maxwindow is None else int(maxwindow)
    return (f"cspade:s{n_seq}w{n_words}i{item_rows}p{pool_slots}"
            f"nb{node_batch}c{chunk}g{g}x{x}d{state_bits}")


def key_tsr(n_seq: int, n_words: int) -> str:
    return f"tsr:s{n_seq}w{n_words}"


def key_tsr_eval(n_seq: int, n_words: int, km: int, width: int) -> str:
    """One TSR eval-launch geometry: the (km side bucket, pow2 candidate
    width) super-batch the ragged packer emitted (ops/ragged_batch.py).
    The engine records one per launch; the enumerator lists the full
    ladder so prewarm can compile every launch program a live mine can
    dispatch."""
    return f"tsr-eval:s{n_seq}w{n_words}km{km}c{width}"


def key_tsr_fused(n_seq: int, n_words: int, m_pad: int, km: int,
                  width: int) -> str:
    """One CROSS-JOB fused eval-launch geometry (service/fusion.py):
    the broker concatenates the participating jobs' prep stores along
    the item axis and pads it to the pow2 bucket ``m_pad``, so the
    fused launch program compiles per (m bucket, km, width) — a finite
    ladder the enumerator lists (``fusion_jobs`` on the WorkloadSpec)
    and prewarm walks, keeping the zero-fresh-compile guarantee across
    fusion."""
    return f"tsr-fused:s{n_seq}w{n_words}m{m_pad}km{km}c{width}"


def key_tsr_resident(n_seq: int, n_words: int, m: int, km: int, nb: int,
                     ring: int) -> str:
    """One resident-frontier program geometry (ops/resident_frontier.py):
    the whole-km-ladder ``lax.while_loop`` compiled per (prep item rows
    m, km-ladder depth, wave width, ring capacity).  The engine records
    the wide key at resident-round start and the narrow key when the
    late-wave switch first compiles it; record/topk caps derive from
    (ring, K_PAD) so they add no axis."""
    return f"tsr-resident:s{n_seq}w{n_words}m{m}km{km}nb{nb}r{ring}"


def key_spam(n_seq: int, n_words: int, rows: int, node_batch: int,
             ni_pad: int) -> str:
    """One SPAM wave-engine geometry (models/spam_bitmap.py): the
    fixed-shape all-items support pass compiles per (seq axis, words,
    store rows, node batch, padded item axis) — ONE key per dataset
    geometry because the wave shape is candidate-raggedness-independent
    by construction (that independence is the engine's point)."""
    return f"spam:s{n_seq}w{n_words}r{rows}nb{node_batch}i{ni_pad}"


def key_spam_hybrid(n_seq: int, n_words: int, rows: int, node_batch: int,
                    ni_pad: int, nd_pad: int) -> str:
    """One HYBRID-store SPAM geometry (ISSUE 16): the planner's density
    crossover routed some items to id-lists, so the fused wave runs over
    a gathered dense block of ``nd_pad`` rows instead of the full item
    axis — a different compiled wave program per dense pad, hence the
    extra ``d`` axis.  Keeps the ``spam:`` prefix (the pure-bitmap plan
    is the ``d``-less spelling, byte-compatible with pre-hybrid keys).
    ``nd_pad`` walks the item tile ladder 0..ni_pad; 0 = every item
    id-list-routed, no wave program at all (pair launches only)."""
    return (f"spam:s{n_seq}w{n_words}r{rows}nb{node_batch}i{ni_pad}"
            f"d{nd_pad}")


def key_spam_pair(n_seq: int, n_words: int, width: int) -> str:
    """One sparse-candidate pair-launch geometry (hybrid SPAM store):
    candidates over id-list-routed items dispatch as explicit
    (parent row, item) pairs at pow2 widths 64..chunk — one compiled
    prune program per width, recorded at dispatch time like the
    ``tsr-eval`` ladder."""
    return f"spam-pair:s{n_seq}w{n_words}c{width}"


def key_predict(lanes: int, depth: int, wave: int, m_pad: int) -> str:
    """One batched rule-trie scoring geometry (ops/rule_trie.py): the
    pow2 rule-lane axis F, the pow2 antecedent/observed-prefix token
    depth D, the wave width W (concurrent request rows fused into one
    launch by service/predictor.py), and the pow2 top-m pad M.  The
    artifact compiler pads live rule sets UP to the declared envelope
    floors so live predicts land on prewarmed keys."""
    return f"predict:f{lanes}d{depth}w{wave}m{m_pad}"


def key_sweep(n_seq: int, n_words: int, n_rows: int, ni_rows: int) -> str:
    return f"sweep:s{n_seq}w{n_words}r{n_rows}i{ni_rows}"


def key_tsr_part(n_parts: int, n_seq_inner: int, n_words: int) -> str:
    """The partitioned-TSR umbrella key (models/tsr.py TsrPartitioned):
    the 2-D ``parts x seq`` arrangement over the inner per-row padded
    sequence axis.  The per-part engines record the inner ``tsr:*`` and
    per-launch ``tsr-eval:*`` keys themselves; this key identifies the
    orchestration geometry so /admin/shapes can see that a partitioned
    ladder was (or was not) enumerated and warmed."""
    return f"tsr-part:p{n_parts}s{n_seq_inner}w{n_words}"


_PARTITION_SKIP = object()  # sentinel: invalid partition override


# ---------------------------------------------------------------- registry

_lock = threading.Lock()
_recorded: Dict[str, int] = {}


def record(key: str) -> None:
    """Note a compiled-geometry key at engine-construction time (the
    moment that fixes which device programs compile)."""
    with _lock:
        _recorded[key] = _recorded.get(key, 0) + 1


def recorded() -> Dict[str, int]:
    """Every shape key observed this process, with construction counts."""
    with _lock:
        return dict(_recorded)


def reset_recorded() -> None:
    with _lock:
        _recorded.clear()


def drift(enumerated: Iterable[str]) -> List[str]:
    """Runtime-observed keys absent from an enumerated set — each one is
    a geometry a prewarmed deployment would still compile on a live
    request (registry drift; surfaced by ``/admin/shapes``)."""
    known = set(enumerated)
    return sorted(k for k in recorded() if k not in known)


# -------------------------------------------------------------- enumerator


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """The data geometry an operator expects to serve — everything the
    enumerator needs to list the compiled shapes without mining.

    ``n_sequences``/``n_items``/``n_words``: the batch ``/train``
    envelope (sequence count, FREQUENT-projection width at the service
    support, bitmap word count — ``build_vertical`` computes the latter
    two from a data sample for free, no mining involved).
    ``constraints``: (maxgap, maxwindow) pairs cSPADE requests will
    carry — each pair selects different compiled kernels.
    ``tsr``: also enumerate the TSR engine's static geometry.
    ``stream_batch_sequences``/``stream_items``: the incremental
    streaming envelope — per-push micro-batch size and window frequent-
    item width; ``sweep_row_buckets`` successive pow2 work-row buckets
    are enumerated per sweep geometry (the tracked tree's level width
    decides the bucket at runtime — levels run far wider than the
    alphabet because tracked nodes share items, so the default covers
    trees up to 8x the item-row bucket).
    ``checkpointed``: prewarm also compiles the segmented (resumable)
    queue programs.
    ``fusion_jobs``: cross-job launch fusion envelope (service/fusion.py)
    — enumerate the ``tsr-fused`` eval ladder for groups of up to this
    many concurrent TSR jobs (their first-round prep stores concatenate
    along the item axis, pow2-padded; 0 = fusion not served).  The boot
    spec sets it from ``[fusion] max_jobs`` when fusion is enabled.
    ``partition_parts``: equivalence-class partitioned mining envelope
    (parallel/partition.py; >= 2 = enumerate the ``tsr-part`` 2-D
    arrangement plus the per-part INNER ``tsr``/``tsr-eval`` ladder at
    the submesh-row geometry).  The boot spec sets it from
    ``[partition] parts`` when partitioning is enabled.
    ``predict_lanes``/``predict_depth``/``predict_wave``/
    ``predict_topm``: the prediction-serving envelope (ops/rule_trie.py
    + service/predictor.py) — rule-lane floor, antecedent/prefix token
    depth floor, max fused wave width, and default top-m.  When
    ``predict_wave > 0`` the enumerator lists one ``predict:*`` key per
    pow2 wave bucket 1..next_pow2(predict_wave) at the floored
    lane/depth/top-m geometry (the artifact compiler pads live rule
    sets up to the same floors, so live predicts land on these keys).
    The boot spec sets them from ``[predict]``.
    """

    n_sequences: int
    n_items: int
    n_words: int = 1
    constraints: Tuple[Tuple[Optional[int], Optional[int]], ...] = ()
    tsr: bool = False
    fusion_jobs: int = 0
    partition_parts: int = 0
    stream_batch_sequences: int = 0
    stream_items: int = 0
    stream_seq_floor: int = 0  # must mirror [prewarm] stream_seq_floor:
    # live batch stores bucket at bucket_seq(max(push, floor)), so an
    # enumeration without the floor would list the WRONG seq bucket
    sweep_row_buckets: int = 4
    checkpointed: bool = False
    predict_lanes: int = 0
    predict_depth: int = 0
    predict_wave: int = 0
    predict_topm: int = 0
    # token-table size bound for store-build warming: token-array LENGTH
    # is a traced shape of the scatter build (pow2-bucketed by
    # _common.scatter_build_store), so prewarm compiles the builder for
    # every pow2 bucket up to this bound.  0 = 8 x n_sequences.
    max_tokens: int = 0


def enumerate_shapes(spec: WorkloadSpec, *, mesh=None,
                     engine_kwargs: Optional[dict] = None
                     ) -> Dict[str, dict]:
    """The finite set of service-default shape keys for ``spec`` under
    the given boot knobs — a superset of what the router will actually
    run (queue AND its classic fallback AND the dense engine where
    eligible are all listed; compiling a fallback at boot is cheap
    insurance, missing one is a 40 s live stall).

    Returns ``{shape_key: target}`` where ``target`` carries the kind
    and geometry parameters ``service/prewarm.py`` needs to compile the
    entry.  Uses the SAME geometry functions the engine constructors
    use, so enumeration cannot drift from construction (and the drift
    test pins it).
    """
    import jax

    from spark_fsm_tpu.models import spade_constrained, spade_fused
    from spark_fsm_tpu.models import spade_queue, spade_tpu, tsr

    ekw = dict(engine_kwargs or {})
    use_pallas = jax.default_backend() == "tpu"
    out: Dict[str, dict] = {}

    def add(key: str, **target) -> None:
        out.setdefault(key, target)

    ns, ni, nw = int(spec.n_sequences), int(spec.n_items), int(spec.n_words)
    max_tokens = int(spec.max_tokens) or 8 * ns
    if ns > 0 and ni > 0:
        ckw = {k: v for k, v in ekw.items()
               if k in ("chunk", "node_batch", "pipeline_depth",
                        "recompute_chunk", "pool_bytes")}
        g = spade_tpu.classic_geometry(ns, ni, nw, mesh=mesh,
                                       use_pallas=use_pallas, **ckw)
        add(g["shape_key"], kind="classic", n_sequences=ns, n_items=ni,
            n_words=nw, max_tokens=max_tokens)
        q = spade_queue.queue_geometry(ns, ni, nw, mesh=mesh,
                                       use_pallas=use_pallas)
        add(q["shape_key"], kind="queue", n_sequences=ns, n_items=ni,
            n_words=nw, max_tokens=max_tokens,
            checkpointed=bool(spec.checkpointed))
        f = spade_fused.fused_geometry(ns, ni, nw, mesh=mesh,
                                       use_pallas=use_pallas)
        add(f["shape_key"], kind="fused", n_sequences=ns, n_items=ni,
            n_words=nw, max_tokens=max_tokens)
        # SPAM wave engine + the hybrid-store ladder (ISSUE 16): the
        # planner routes dense patterns mines here, so a prewarmed boot
        # must cover the fused wave at the pure geometry AND every
        # dense-block pad the per-item density split can produce (the
        # item-tile ladder 0..ni_pad), plus the sparse pair-launch pow2
        # widths — the same finite-ladder posture as tsr-eval
        from spark_fsm_tpu.models import spam_bitmap

        skw = {k: v for k, v in ekw.items()
               if k in ("node_batch", "pipeline_depth", "pool_bytes")}
        sg = spam_bitmap.spam_geometry(ns, ni, nw, mesh=mesh,
                                       use_pallas=use_pallas, **skw)
        add(sg["shape_key"], kind="spam", n_sequences=ns, n_items=ni,
            n_words=nw, max_tokens=max_tokens)
        nd = 0
        while nd <= sg["ni_pad"]:
            add(key_spam_hybrid(sg["n_seq"], nw, sg["total_rows"],
                                sg["node_batch"], sg["ni_pad"], nd),
                kind="spam_hybrid", n_words=nw, nd_pad=nd,
                tile=sg["tile"], s_block=sg["s_block"],
                n_seq_pad=sg["n_seq"], node_batch=sg["node_batch"],
                total_rows=sg["total_rows"], ni_pad=sg["ni_pad"])
            nd += sg["tile"]
        w = 64
        while w <= sg["chunk"]:
            add(key_spam_pair(sg["n_seq"], nw, w),
                kind="spam_pair", n_words=nw, width=w,
                n_seq_pad=sg["n_seq"], node_batch=sg["node_batch"],
                total_rows=sg["total_rows"])
            w *= 2
        for maxgap, maxwindow in spec.constraints:
            cg = spade_constrained.cspade_geometry(
                ns, ni, nw, maxgap=maxgap, maxwindow=maxwindow, mesh=mesh,
                **{k: v for k, v in ekw.items()
                   if k in ("chunk", "node_batch", "pipeline_depth",
                            "recompute_chunk", "pool_bytes")})
            add(cg["shape_key"], kind="cspade", n_sequences=ns, n_items=ni,
                n_words=nw, max_tokens=max_tokens,
                maxgap=maxgap, maxwindow=maxwindow)
        if spec.tsr:
            from spark_fsm_tpu.ops import ragged_batch as RB

            tg = tsr.tsr_geometry(ns, nw, mesh=mesh, use_pallas=use_pallas)
            # eval-launch super-batch ladder (ops/ragged_batch.py): the
            # finite (km, pow2 width) set the ragged packer can emit.
            # Lane floor 32 covers the jnp path (the kernel path's
            # >=128-lane launches are a subset); the width ceiling is a
            # pinned tsr_chunk, else the engine's own dispatch quantum
            # at this sequence axis — the same function the engine's
            # width caps resolve through, so the ladder cannot under-
            # enumerate what a live mine dispatches.
            tsr_chunk = int(ekw.get("tsr_chunk") or 0)
            hi = tsr_chunk or RB.dispatch_quantum_lanes(tg["n_seq"], nw)
            ladder = RB.superbatch_geometries(32, hi)
            add(tg["shape_key"], kind="tsr", n_sequences=ns, n_items=ni,
                n_words=nw, superbatch=ladder)
            for km, width in ladder:
                # one key per geometry so /admin/shapes drift names the
                # exact launch program a live mine would still compile;
                # warmed by the single "tsr" entry's ladder walk
                add(key_tsr_eval(tg["n_seq"], nw, km, width),
                    kind="tsr_eval", km=km, width=width)
            if mesh is None:
                # resident-frontier ladder (ops/resident_frontier.py):
                # the planner routes deep (unlimited-max_side) mines to
                # the whole-ladder while_loop program on single-device
                # engines; caps derive from the SAME eval budget the
                # engine's eligibility check probes, so enumeration and
                # construction cannot disagree on the compiled shapes.
                # The m axis walks the ITERATIVE-DEEPENING ladder the
                # engine's mine() walks (item_cap doubling to n_items):
                # every round that still fits the caps compiles its own
                # resident program, and the ladder self-terminates where
                # caps_for returns None — exactly where the engine's
                # round routes host instead.
                from spark_fsm_tpu.models._common import device_hbm_budget
                from spark_fsm_tpu.ops import resident_frontier as RF

                budget = device_hbm_budget(jax.devices()[0])
                m_res = min(int(ekw.get("item_cap")
                                or tsr.ITEM_CAP_DEFAULT), ni)
                while True:
                    caps = RF.caps_for(tg["n_seq"], nw, m_res, budget)
                    if caps is None:
                        break
                    widths = [caps.nb] + ([caps.nb_late]
                                          if caps.nb_late < caps.nb
                                          else [])
                    for nb in widths:
                        add(key_tsr_resident(tg["n_seq"], nw, m_res,
                                             caps.km, nb, caps.ring),
                            kind="tsr_resident", n_sequences=ns,
                            n_items=ni, n_words=nw, m=m_res, nb=nb,
                            ring=caps.ring, km=caps.km,
                            r_cap=caps.r_cap, d_cap=caps.d_cap,
                            n_seq_pad=tg["n_seq"])
                    if m_res >= ni:
                        break
                    m_res = min(m_res * 2, ni)
            if spec.partition_parts >= 2:
                # equivalence-class partitioned ladder (parallel/
                # partition.py + models/tsr.TsrPartitioned): the 2-D
                # parts x seq arrangement re-derives the TSR geometry at
                # the INNER submesh-row axis — per-part engines compile
                # the same programs a solo engine over one row would, so
                # the enumeration is the inner ladder plus the umbrella
                # key the orchestrator records.  Enumerating through
                # partition.submeshes (not arithmetic on device counts)
                # keeps enumeration and construction on one code path.
                from spark_fsm_tpu.parallel import partition as PN

                try:
                    inner = PN.submeshes(mesh, spec.partition_parts)[0]
                except ValueError as exc:
                    # an /admin/prewarm override that cannot split this
                    # topology must not fail the whole prewarm request
                    # — same degrade-loudly posture as the request
                    # router (plugins.resolved_partition_parts)
                    from spark_fsm_tpu.utils.obs import log_event

                    log_event("partition_config_invalid",
                              reason=str(exc), at="enumerate_shapes")
                    inner = _PARTITION_SKIP
                if inner is not _PARTITION_SKIP:
                    tgp = tsr.tsr_geometry(ns, nw, mesh=inner,
                                           use_pallas=use_pallas)
                    hi_p = tsr_chunk or RB.dispatch_quantum_lanes(
                        tgp["n_seq"], nw)
                    ladder_p = RB.superbatch_geometries(32, hi_p)
                    add(key_tsr_part(spec.partition_parts, tgp["n_seq"],
                                     nw),
                        kind="tsr_part", n_sequences=ns, n_items=ni,
                        n_words=nw, parts=int(spec.partition_parts),
                        superbatch=ladder_p)
                    # the inner per-part geometry: dedup'd against the
                    # solo entries when the inner row equals the outer
                    # mesh; the tsr_part walk warms them (every ROW,
                    # not just row 0 — compiled executables bind
                    # device assignments)
                    add(tgp["shape_key"], kind="tsr_inner")
                    for km, width in ladder_p:
                        add(key_tsr_eval(tgp["n_seq"], nw, km, width),
                            kind="tsr_eval", km=km, width=width)
            if spec.fusion_jobs >= 2 and not use_pallas and mesh is None:
                # cross-job fused ladder (service/fusion.py): groups of
                # 2..fusion_jobs first-round prep stores concatenated
                # along the item axis and pow2-padded — the distinct
                # m buckets are few because next_pow2 collapses group
                # sizes.  The (km, width) set is the SAME solo ladder:
                # the broker's fused caps are minima of per-engine caps,
                # so fused widths are a subset of solo widths.  Gated to
                # the broker's own engagement condition (the single-
                # device jnp path, tsr.py): a pallas/mesh boot can never
                # dispatch a fused launch, so enumerating the ladder
                # there would compile phantom programs at boot and list
                # drift keys no live mine can record.
                m1 = min(tsr.ITEM_CAP_DEFAULT, ni)
                fused_m = sorted({RB.next_pow2(j * m1)
                                  for j in range(2, spec.fusion_jobs + 1)})
                out[tg["shape_key"]]["fused_m"] = fused_m
                for m_pad in fused_m:
                    for km, width in ladder:
                        add(key_tsr_fused(tg["n_seq"], nw, m_pad, km,
                                          width),
                            kind="tsr_fused", m_pad=m_pad, km=km,
                            width=width)

    if spec.stream_batch_sequences > 0 and spec.stream_items > 0:
        from spark_fsm_tpu.streaming import incremental

        sg = incremental.sweep_geometry(
            int(spec.stream_batch_sequences), nw, mesh=mesh,
            use_pallas=use_pallas, seq_floor=int(spec.stream_seq_floor))
        from spark_fsm_tpu.models._common import next_pow2
        from spark_fsm_tpu.ops import pallas_support as PS

        ni_rows = -(-max(int(spec.stream_items), 1) // PS.I_TILE) * PS.I_TILE
        rows = next_pow2(ni_rows + 1)
        for _ in range(max(1, int(spec.sweep_row_buckets))):
            add(key_sweep(sg["n_seq"], sg["n_words"], rows, ni_rows),
                kind="sweep",
                batch_sequences=int(spec.stream_batch_sequences),
                n_items=int(spec.stream_items), n_words=nw,
                max_tokens=8 * int(spec.stream_batch_sequences),
                seq_floor=int(spec.stream_seq_floor),
                ni_rows=ni_rows, n_rows=rows)
            rows *= 2

    if spec.predict_wave > 0 and spec.predict_lanes > 0:
        # prediction-serving scoring ladder (ops/rule_trie.py): one
        # compiled program per (F, D, W, M) bucket.  F/D/M come from the
        # declared envelope floors (the artifact compiler pads live rule
        # sets up to the same floors — rule_trie.build_trie), W walks
        # the pow2 wave ladder 1..max wave because the predict broker
        # pads each dispatched group to the next bucket.
        from spark_fsm_tpu.models._common import next_pow2

        f_pad = next_pow2(max(int(spec.predict_lanes), 1))
        d_pad = next_pow2(max(int(spec.predict_depth), 1))
        m_pad = next_pow2(max(int(spec.predict_topm), 1))
        w = 1
        w_hi = next_pow2(max(int(spec.predict_wave), 1))
        while w <= w_hi:
            add(key_predict(f_pad, d_pad, w, m_pad),
                kind="predict", lanes=f_pad, depth=d_pad, wave=w,
                topm=m_pad)
            w *= 2
    return out
