"""Kafka adapter: a ``poll()``-shaped client -> PollConsumer's fetch.

SURVEY.md sec 2.5 names "Kafka micro-batches" as the reference
ecosystem's streaming feed; sec 7 step 9 keeps the client optional
behind the source interface.  No broker (or client library) is reachable
in this sandbox, so the adapter binds to the SHAPE of the de-facto
Python clients instead of importing one:

    consumer.poll(timeout_ms=...) -> {partition: [record, ...], ...}

where each record carries the payload in ``.value`` (kafka-python) —
bytes or str of SPMF sequence lines, one or more per record.  Both
kafka-python's ``KafkaConsumer`` and confluent-kafka wrapped to this
dict shape satisfy it; the contract tests run against a fake, and a
production deployment does::

    from kafka import KafkaConsumer          # external, optional extra
    consumer = KafkaConsumer("clicks", bootstrap_servers=..., ...)
    PollConsumer(KafkaFetch(consumer), miner.push).run()

Semantics (PollConsumer's fetch contract):
- an empty poll returns None (idle — the loop sleeps and re-polls);
- all records of one poll concatenate into ONE micro-batch, preserving
  partition-list order (a micro-batch is "whatever this poll returned",
  the reference's Spark-Streaming batching analog);
- undecodable/unparseable records follow ``on_bad``: "raise" (default)
  surfaces the error to PollConsumer's supervision counters, "skip"
  drops the record and counts it in ``stats["bad_records"]`` — a
  poisoned topic must be a visible choice, never a silent one.
"""

from __future__ import annotations

from typing import Callable, Optional

from spark_fsm_tpu.data.spmf import SequenceDB, parse_spmf
from spark_fsm_tpu.utils import faults, obs

_BAD_RECORDS = obs.REGISTRY.counter(
    "fsm_kafka_bad_records_total",
    "records that failed to decode/parse (both on_bad modes)")

# dead-letter ring: the last N undecodable payloads are kept in stats
# (truncated, with partition/offset when the record exposes one) so a
# poisoned topic is DIAGNOSABLE from /admin or the consumer's stats —
# a bare bad_records count tells an operator something is wrong but not
# what, which producer, or where to replay from
DEAD_LETTER_RING = 16
DEAD_LETTER_PAYLOAD_CHARS = 160


class KafkaFetch:
    """Adapt a kafka-python-shaped consumer to ``PollConsumer`` fetch.

    Args:
      consumer: object with ``poll(timeout_ms=int) -> dict`` mapping
        partitions to record lists; records expose ``.value``.
      timeout_ms: handed to every ``poll`` call.
      decode: bytes -> str for record values (default strict UTF-8).
      parse: text -> SequenceDB (default SPMF parser).
      on_bad: "raise" (default) or "skip" for records that fail to
        decode or parse.
    """

    def __init__(self, consumer, *, timeout_ms: int = 500,
                 decode: Callable[[bytes], str] = None,
                 parse: Callable[[str], SequenceDB] = None,
                 on_bad: str = "raise") -> None:
        if on_bad not in ("raise", "skip"):
            raise ValueError(f"on_bad must be 'raise' or 'skip' "
                             f"(got {on_bad!r})")
        if not hasattr(consumer, "poll"):
            raise TypeError("consumer must expose poll(timeout_ms=...) "
                            f"(got {type(consumer).__name__})")
        self._consumer = consumer
        self.timeout_ms = int(timeout_ms)
        self._decode = decode or (lambda b: b.decode("utf-8"))
        self._parse = parse or parse_spmf
        self.on_bad = on_bad
        self.stats = {"polls": 0, "records": 0, "bad_records": 0,
                      "dead_letters": []}

    def _dead_letter(self, partition, rec, exc: Exception) -> None:
        """Ring-buffer the undecodable record (both on_bad modes: a
        raised poison message is just as worth diagnosing as a skipped
        one).  Payloads are truncated — the ring is for diagnosis, not
        for replaying multi-MB blobs through a stats endpoint."""
        payload = repr(getattr(rec, "value", None))
        if len(payload) > DEAD_LETTER_PAYLOAD_CHARS:
            payload = payload[:DEAD_LETTER_PAYLOAD_CHARS] + "...(truncated)"
        ring = self.stats["dead_letters"]
        ring.append({
            "partition": str(partition),
            "offset": getattr(rec, "offset", None),
            "payload": payload,
            "error": f"{type(exc).__name__}: {exc}",
        })
        del ring[:-DEAD_LETTER_RING]
        _BAD_RECORDS.inc()
        obs.trace_event("kafka_dead_letter", partition=str(partition),
                        offset=getattr(rec, "offset", None),
                        error=f"{type(exc).__name__}: {exc}")

    def __call__(self) -> Optional[SequenceDB]:
        self.stats["polls"] += 1
        faults.fault_site("kafka.poll", timeout_ms=str(self.timeout_ms))
        recs = self._consumer.poll(timeout_ms=self.timeout_ms)
        if not recs:
            return None
        batch: SequenceDB = []
        n_rec = 0
        for partition, records in recs.items():
            for rec in records:
                n_rec += 1
                try:
                    value = rec.value
                    text = (self._decode(value)
                            if isinstance(value, (bytes, bytearray))
                            else value)
                    batch.extend(self._parse(text))
                except Exception as exc:
                    self._dead_letter(partition, rec, exc)
                    if self.on_bad == "raise":
                        raise
                    self.stats["bad_records"] += 1
        self.stats["records"] += n_rec
        return batch or None
