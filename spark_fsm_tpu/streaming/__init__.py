"""Streaming / incremental mining (SURVEY.md sec 2.5, eval config #5).

The reference ecosystem feeds micro-batches (Kafka) into a sliding-window
sequence database and keeps the mined pattern set current.  This package
provides the TPU-native equivalent: a window of sequence micro-batches with
count-based eviction, re-mined per push (re-mining the window is the
survey-sanctioned baseline; windows are small relative to the batch path).
"""

from spark_fsm_tpu.streaming.consumer import PollConsumer, StopConsumer
from spark_fsm_tpu.streaming.window import SlidingWindow, WindowMiner

__all__ = ["PollConsumer", "SlidingWindow", "StopConsumer", "WindowMiner"]
