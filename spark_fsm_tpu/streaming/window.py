"""Sliding-window sequence DB + incremental miner (eval config #5).

SURVEY.md sec 2.5: "a sliding-window vertical DB where a micro-batch
appends new sequence-id columns to the bitmaps and expired ones are
evicted, then re-mining (or incremental frontier repair) runs on the
updated DB".  This module implements exactly that contract:

- ``SlidingWindow`` holds the live micro-batches (append at the head,
  evict at the tail by batch count and/or total-sequence cap).
- ``WindowMiner`` re-mines the window after each push.  Re-mining is the
  sanctioned baseline (SURVEY.md sec 7 "Streaming eviction ... acceptable
  fallback: re-mine the window (windows are small)"); the vertical build
  is vectorized numpy over the window's sequences and the mine runs on
  the configured engine (TPU bitmap DFS by default, CPU oracle as the
  parity anchor).

Determinism contract (tested): after every push, the mined pattern set is
byte-identical to a fresh mine of exactly the window's sequences — the
stream never changes WHAT is mined, only WHEN.
"""

from __future__ import annotations

import itertools
import threading
from collections import Counter, deque
from typing import Callable, Deque, List, Optional, Tuple

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import abs_minsup
from spark_fsm_tpu.utils.canonical import PatternResult


class SlidingWindow:
    """Count-based sliding window of sequence micro-batches.

    Args:
      max_batches: keep at most this many micro-batches (None = unbounded).
      max_sequences: evict oldest batches while the window holds more than
        this many sequences (None = unbounded).  Eviction granularity is a
        whole micro-batch — the reference's micro-batch semantics.
    """

    def __init__(self, max_batches: Optional[int] = None,
                 max_sequences: Optional[int] = None) -> None:
        if max_batches is None and max_sequences is None:
            max_batches = 1  # degenerate default: mine each batch alone
        if max_batches is not None and max_batches < 1:
            raise ValueError(f"max_batches must be >= 1 (got {max_batches}); "
                             "use None for an unbounded window")
        if max_sequences is not None and max_sequences < 1:
            raise ValueError(f"max_sequences must be >= 1 (got {max_sequences}); "
                             "use None for an unbounded window")
        self.max_batches = max_batches
        self.max_sequences = max_sequences
        self._batches: Deque[SequenceDB] = deque()
        self._n_sequences = 0
        self.pushed_batches = 0
        self.evicted_batches = 0

    # -- window state -----------------------------------------------------

    @property
    def n_batches(self) -> int:
        return len(self._batches)

    @property
    def n_sequences(self) -> int:
        return self._n_sequences

    def batches(self) -> List[SequenceDB]:
        """The live micro-batches, oldest first (a fresh list) — the
        authoritative window content for persistence mirrors."""
        return list(self._batches)

    def sequences(self) -> SequenceDB:
        """The window's sequence DB, oldest batch first (a fresh list —
        the canonical input for both the engine mine and the parity
        oracle)."""
        out: List = []
        for b in self._batches:
            out.extend(b)
        return out

    def item_supports(self) -> Counter:
        """Window-wide sequence-support per item (introspection helper;
        the mining path recomputes its own projection in build_vertical)."""
        total: Counter = Counter()
        for batch in self._batches:
            for seq in batch:
                for it in set(itertools.chain.from_iterable(seq)):
                    total[it] += 1
        return total

    # -- mutation ---------------------------------------------------------

    def push(self, batch: SequenceDB) -> int:
        """Append a micro-batch, evict expired ones; returns #evicted."""
        batch = list(batch)
        if not batch:
            raise ValueError("empty micro-batch: a push must carry at least "
                             "one sequence (it would evict real data while "
                             "adding none)")
        self._batches.append(batch)
        self._n_sequences += len(batch)
        self.pushed_batches += 1
        evicted = 0
        while (self.max_batches is not None
               and len(self._batches) > self.max_batches):
            evicted += self._evict_oldest()
        while (self.max_sequences is not None and len(self._batches) > 1
               and self._n_sequences > self.max_sequences):
            evicted += self._evict_oldest()
        self.evicted_batches += evicted
        return evicted

    def _evict_oldest(self) -> int:
        old = self._batches.popleft()
        self._n_sequences -= len(old)
        return 1


MineFn = Callable[[SequenceDB, int], List[PatternResult]]


def _default_mine(db: SequenceDB, minsup: int) -> List[PatternResult]:
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu

    # shape_buckets: window sizes drift every push; pow2-bucketed device
    # shapes let consecutive re-mines reuse compiled kernels instead of
    # recompiling per window geometry (the dominant streaming cost).
    return mine_spade_tpu(db, minsup, shape_buckets=True)


class WindowMiner:
    """Keeps a sliding window's pattern set current across micro-batches.

    ``push(batch)`` updates the window and re-mines it, returning the new
    pattern set (also kept in ``.patterns``).  ``min_support`` < 1 is
    relative to the *current* window size (recomputed per push), >= 1 is an
    absolute sequence count — the same contract as the train request's
    ``support`` param (service/plugins.py).
    """

    def __init__(self, min_support: float, *,
                 max_batches: Optional[int] = None,
                 max_sequences: Optional[int] = None,
                 mine: MineFn = _default_mine) -> None:
        self.min_support = float(min_support)
        self.window = SlidingWindow(max_batches=max_batches,
                                    max_sequences=max_sequences)
        self._mine = mine
        self._lock = threading.Lock()
        self.patterns: List[PatternResult] = []
        # route mirrors IncrementalWindowMiner's stats key so /status and
        # the bench artifacts always say which streaming path ran
        self.stats = {"pushes": 0, "mines": 0, "evicted_batches": 0,
                      "window_sequences": 0, "patterns": 0,
                      "route": "re-mine"}

    def minsup_abs(self) -> int:
        if self.min_support >= 1.0:
            return int(self.min_support)
        return abs_minsup(self.min_support, max(1, self.window.n_sequences))

    def push(self, batch: SequenceDB) -> List[PatternResult]:
        """Append a micro-batch; evict expired sequences; re-mine."""
        with self._lock:
            self.window.push(batch)
            seqs = self.window.sequences()
            self.patterns = self._mine(seqs, self.minsup_abs()) if seqs else []
            self.stats["pushes"] += 1
            self.stats["mines"] += 1
            self.stats["evicted_batches"] = self.window.evicted_batches
            self.stats["window_sequences"] = self.window.n_sequences
            self.stats["patterns"] = len(self.patterns)
            return self.patterns
