"""Incremental sliding-window SPADE — push cost scales with the BATCH.

Eval config #5 is named "Streaming *incremental* SPADE" (BASELINE.md);
SURVEY.md sec 7 lists "incremental frontier repair" among the hard parts
and sanctions re-mine only as a fallback.  streaming/window.py is that
fallback: every push re-mines the whole window, so the steady-state push
wall scales with the WINDOW (measured ~4.2 s at 495k sequences,
BENCH_SCALE config 5).  This module is the real thing.

The key algebra: SPADE supports are ADDITIVE over the sequence axis —
``support_window(P) = sum over live batches of support_batch(P)`` (each
sequence lives in exactly one micro-batch).  So the miner tracks, on
host, a pattern tree T = the frequent set F plus its negative border
(every candidate an exact mine would have evaluated), with PER-BATCH
support counts per node.  A push then costs:

- **count the arriving batch only** (device): one level-order sweep of T
  over the new batch's bitmap store — the classic engine's
  prep/pair-support/materialize kernels (models/spade_tpu._spade_fns),
  driven by T's known structure instead of by pruning decisions, so the
  whole sweep needs ZERO intermediate readbacks (one fetch of the
  concatenated support vector at the end);
- **evict by subtraction** (host): an expired batch's stored partial
  supports leave each node's running total — no device work at all;
- **border repair** (device, only when a pattern crosses minsup in
  either direction): candidate lists are recomputed top-down from the
  new frequent sets, and candidates T has never evaluated are counted on
  every live batch by a ``lax.scan`` join-fold over that batch's
  device-resident token scatter (steady-state pushes repair nothing).

Downward closure makes the bookkeeping exact: every item of a tracked
node is window-frequent, a node whose ancestor falls below minsup falls
with it, and candidate lists derive from sibling survival exactly as in
the classic engine's ``_resolve`` — so after every push the frequent set
and its supports are **byte-identical to a fresh mine of the window**
(the determinism contract of streaming/window.py, tested per push).

Scope: plain SPADE (no maxgap/maxwindow, no max_pattern_itemsets — the
service routes those to the re-mine path).  With a ``mesh``, every batch
store's sequence axis shards over the devices exactly like the batch
engines' (``shard_map`` sweep/fold kernels, ``psum`` partial supports
over ICI before the host prune — SURVEY.md sec 2.2), so streaming and
partitioning compose the way the reference's Spark streaming does.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import abs_minsup, build_vertical
from spark_fsm_tpu.models._common import bucket_seq, next_pow2
from spark_fsm_tpu.models.spade_tpu import _spade_fns
from spark_fsm_tpu.ops import bitops_jax as B
from spark_fsm_tpu.ops import pallas_support as PS
from spark_fsm_tpu.parallel import multihost as MH
from spark_fsm_tpu.parallel.mesh import SEQ_AXIS, pad_to_multiple, shard_map
from spark_fsm_tpu.streaming.window import SlidingWindow
from spark_fsm_tpu.utils import shapes
from spark_fsm_tpu.utils.canonical import PatternResult, sort_patterns

Key = Tuple[int, bool]  # (GLOBAL item id, is_s_extension)


def sweep_geometry(batch_sequences: int, n_words_raw: int, *,
                   mesh: Optional[Mesh] = None, use_pallas: bool = False,
                   seq_floor: int = 0) -> dict:
    """Device geometry of a batch token store (:class:`_BatchTokens`) —
    shared with the shape-key enumerator (utils/shapes.py), so the sweep
    shapes a stream will compile (the config-5 mid-stream stall) are
    listable at boot.  ``seq_floor`` pins small batches up to a declared
    steady-state bucket so the first pushes land on the prewarmed shapes
    instead of compiling throwaway small-bucket programs."""
    n_words = next_pow2(max(1, n_words_raw))
    n_shards = 1 if mesh is None else mesh.devices.size
    seq_bucket = bucket_seq(max(int(batch_sequences), int(seq_floor or 0)))
    s_block = (min(PS.seq_block(n_words),
                   pad_to_multiple(-(-seq_bucket // n_shards), 128))
               if use_pallas else 1)
    n_seq = pad_to_multiple(seq_bucket, max(1, n_shards * s_block))
    return {"n_seq": n_seq, "n_words": n_words, "s_block": s_block}


class _TNode:
    """Tracked pattern: frequent node or border leaf.  ``steps`` holds
    GLOBAL item ids (the projection drifts across pushes, so dense
    indices would go stale); ``sup`` maps live batch id -> exact batch
    support; ``total`` is kept equal to ``sum(sup.values())`` over live
    batches incrementally."""

    __slots__ = ("steps", "children", "sup", "total")

    def __init__(self, steps: Tuple[Key, ...]):
        self.steps = steps
        self.children: Dict[Key, "_TNode"] = {}
        self.sup: Dict[int, int] = {}
        self.total = 0


def _block_collectives_on_cpu(arr, mesh):
    """XLA's CPU backend can DEADLOCK when two collective (psum)
    programs are in flight at once: each 8-way rendezvous needs all
    eight per-device threads simultaneously, and two concurrent
    programs starve each other on the shared pool (observed as a
    permanent 'waiting for all participants' stall on the 8-virtual-
    device test mesh).  Real accelerators order collective launches in
    hardware streams, so blocking here serializes ONLY the CPU
    emulation substrate — the async-dispatch design (the point of the
    pend lists) is unchanged on TPU."""
    if arr is not None and mesh is not None \
            and jax.default_backend() == "cpu":
        arr.block_until_ready()
    return arr


def _inc_store_builder(n_rows: int, n_seq: int, n_words: int,
                       mesh: Optional[Mesh] = None):
    """Batch bitmap store scatter from device-resident tokens — the
    engines' shared ``_store_builder`` in its flat + remap form: the
    fifth input maps the batch's dense item index -> store row for items
    the current frequent projection needs (unneeded items drop), so one
    cached program serves every push's drifting projection; with a mesh
    each device scatters only its sequence-axis shard."""
    from spark_fsm_tpu.models._common import _store_builder

    return _store_builder(n_rows, n_seq, n_words, mesh, flat=True,
                          remap=True)


@functools.lru_cache(maxsize=32)
def _fold_supports_fn(n_words: int, mesh: Optional[Mesh] = None):
    """Border-repair evaluator: fold a candidate pattern's join chain
    from the item rows (the classic engine's recompute_body without the
    store write — repair needs supports, not bitmaps) and popcount.
    ``items/iss/valid`` are [K, M]: M candidates, K pow2-bucketed steps;
    padded rows carry valid=False and leave the carry untouched.  With a
    mesh, per-shard partial supports ``psum`` over ICI."""
    W = n_words

    def run(store, items, iss, valid):
        m = items.shape[1]
        b = store[items[0]].reshape(m, -1, W)

        def body(carry, xs):
            it, s, v = xs
            nb = B.join(carry, store[it].reshape(carry.shape), s)
            return jnp.where(v[:, None, None], nb, carry), None

        b, _ = jax.lax.scan(body, b, (items[1:], iss[1:], valid[1:]))
        part = B.support(b)
        if mesh is not None:
            part = jax.lax.psum(part, SEQ_AXIS)
        return part

    if mesh is None:
        return jax.jit(run)
    st = P(None, SEQ_AXIS)
    rep = P()
    return jax.jit(shard_map(
        run, mesh=mesh, in_specs=(st, rep, rep, rep), out_specs=rep))


class _BatchTokens:
    """Per-live-batch device state: the token table (uploaded once when
    the batch arrives, ~1000x smaller than the dense store) plus the
    batch's item census.  Bitmap stores are rebuilt from these tokens on
    demand (one on-device scatter) — the dense store never crosses the
    link and old batches hold no HBM beyond their tokens."""

    def __init__(self, bid: int, db: SequenceDB, use_pallas: bool,
                 mesh: Optional[Mesh] = None, put=jnp.asarray,
                 seq_floor: int = 0):
        self.bid = bid
        self.db = db
        self.mesh = mesh
        self._put = put
        vdb = build_vertical(db, min_item_support=1)
        self.item_ids = vdb.item_ids                      # ascending
        self.item_counts: Dict[int, int] = {
            int(i): int(s)
            for i, s in zip(vdb.item_ids, vdb.item_supports)}
        self.n_local = vdb.n_items
        # pow2-bucket both device axes so drifting batch geometry lands
        # on a handful of compiled programs (the shape_buckets policy);
        # under a mesh the bucketed axis must also split evenly across
        # devices (and per-shard stay a Pallas s_block multiple).  The
        # sizing lives in sweep_geometry, shared with the shape-key
        # enumerator; seq_floor pins early small batches onto the
        # declared (prewarmed) steady-state bucket.
        g = sweep_geometry(vdb.n_sequences, vdb.n_words, mesh=mesh,
                           use_pallas=use_pallas, seq_floor=seq_floor)
        self.n_words = g["n_words"]
        self.s_block = g["s_block"]
        self.n_seq = g["n_seq"]
        self.last_shape_key: Optional[str] = None
        # pow2-pad the token arrays (mask-0 pads scatter nothing): token
        # length is a traced shape of the store scatter, so unpadded
        # uploads would recompile it for every distinct batch content —
        # exactly the kind of unenumerable mid-stream compile the shape
        # registry exists to eliminate
        from spark_fsm_tpu.models._common import pad_tokens_pow2

        ti, ts, tw, tm = pad_tokens_pow2(
            vdb.tok_item, vdb.tok_seq, vdb.tok_word, vdb.tok_mask)
        self.ti = put(ti)
        self.ts = put(ts)
        self.tw = put(tw)
        self.tm = put(tm)
        # projection-dependent state, set by _project and CACHED across
        # pushes while the frequent projection holds still (steady-state
        # repair then skips every store rebuild):
        self.row_of: Dict[int, int] = {}
        self.ni_rows = 0
        self.store = None
        self.items_t = None
        self._proj_key = None
        self._n_rows = 0

    def _project(self, needed: List[int], extra_rows: int):
        """Build (or reuse) this batch's store for the given GLOBAL item
        set + ``extra_rows`` work rows; items absent from the batch
        simply get no row (their patterns are zero-support here)."""
        present = [g for g in needed if g in self.item_counts]
        ni_rows = pad_to_multiple(max(len(present), 1), PS.I_TILE)
        n_rows = next_pow2(ni_rows + extra_rows + 1)
        key = (tuple(present), ni_rows)
        if (self.store is not None and self._proj_key == key
                and self._n_rows >= n_rows):
            return self._n_rows
        self.row_of = {g: r for r, g in enumerate(present)}
        self.ni_rows = ni_rows
        # remap length is a traced shape of the scatter build — pow2-pad
        # it (pad entries point out of bounds and are never indexed) so
        # batches with drifting local alphabets land on bucketed builder
        # programs instead of recompiling per batch content
        remap = np.full(next_pow2(max(self.n_local, 1)), n_rows + 1,
                        np.int32)
        idx = np.searchsorted(self.item_ids, present)
        remap[idx] = np.arange(len(present), dtype=np.int32)
        self.store = _inc_store_builder(
            n_rows, self.n_seq, self.n_words, self.mesh)(
            self.ti, self.ts, self.tw, self.tm, self._put(remap))
        self.items_t = None
        self._proj_key = key
        self._n_rows = n_rows
        # a store (re)build is the moment new sweep programs compile:
        # stamp + record the geometry so /admin/shapes and the bench
        # artifacts can attribute mid-stream compile stalls to a key
        self.last_shape_key = shapes.key_sweep(
            self.n_seq, self.n_words, n_rows, ni_rows)
        shapes.record(self.last_shape_key)
        return n_rows

    def store_bytes(self) -> int:
        return (0 if self.store is None
                else self._n_rows * self.n_seq * self.n_words * 4)

    def drop_store(self):
        self.store = None
        self.items_t = None
        self._proj_key = None
        self._n_rows = 0


class IncrementalWindowMiner:
    """WindowMiner-compatible incremental miner (same push/stats/window
    surface, so the service Streamer and the bench harness can swap it in
    for the re-mine path).

    ``min_support`` < 1 is relative to the current window size, >= 1 an
    absolute count — the train-request contract.
    """

    def __init__(self, min_support: float, *,
                 max_batches: Optional[int] = None,
                 max_sequences: Optional[int] = None,
                 mesh: Optional[Mesh] = None,
                 use_pallas="auto",
                 repair_chunk: int = 256,
                 support_chunk: int = 2048,
                 seq_floor: int = 0) -> None:
        self.min_support = float(min_support)
        # pin small early batches to a declared steady-state seq bucket
        # so they ride prewarmed shapes (see sweep_geometry)
        self.seq_floor = int(seq_floor or 0)
        self.window = SlidingWindow(max_batches=max_batches,
                                    max_sequences=max_sequences)
        self.mesh = mesh
        self._put = functools.partial(MH.host_to_device, mesh)
        if use_pallas == "auto":
            self.use_pallas = jax.default_backend() == "tpu"
        else:
            self.use_pallas = bool(use_pallas)
        self._interpret = jax.default_backend() != "tpu"
        self.repair_chunk = int(repair_chunk)
        self.support_chunk = int(support_chunk)
        self._lock = threading.Lock()
        self._next_bid = 0
        # keyed by id() of the window's PRIVATE copy of each batch —
        # push() shallow-copies every arriving batch, so each live window
        # entry is a distinct object and the ids cannot collide even when
        # a caller pushes the same list twice (the duplicate-push guard)
        self._states: Dict[int, _BatchTokens] = {}
        self._item_totals: Dict[int, int] = {}       # window item census
        self._root: Dict[Key, _TNode] = {}           # tracked F1 subtrees
        self.patterns: List[PatternResult] = []
        self.stats = {"pushes": 0, "mines": 0, "evicted_batches": 0,
                      "window_sequences": 0, "patterns": 0,
                      "route": "incremental", "tracked_nodes": 0,
                      "border_nodes": 0, "repaired_nodes": 0,
                      "swept_batches": 0, "sweep_candidates": 0,
                      "repair_rounds": 0, "kernel_launches": 0}

    # ------------------------------------------------------------- util

    def minsup_abs(self) -> int:
        if self.min_support >= 1.0:
            return int(self.min_support)
        return abs_minsup(self.min_support, max(1, self.window.n_sequences))

    def _zero_subtree(self, node: _TNode, bid: int) -> None:
        node.sup[bid] = 0
        for child in node.children.values():
            self._zero_subtree(child, bid)

    # ------------------------------------------------------------- push

    def push(self, batch: SequenceDB) -> List[PatternResult]:
        with self._lock:
            t0 = time.monotonic()
            # the per-batch state below is keyed by object identity, and
            # each _BatchTokens pins its batch (no id reuse while live) —
            # but a caller pushing the SAME list object twice would
            # collapse two window entries onto one state and undercount
            # supports.  A shallow copy makes every window entry a
            # distinct object (and freezes the content this push counted
            # against later caller mutation).
            batch = list(batch)
            self.window.push(batch)
            live = self.window.batches()
            live_ids = {id(b) for b in live}

            # --- evict by subtraction (host only) ---
            evicted = [st for key, st in self._states.items()
                       if key not in live_ids]
            for key in [k for k in self._states if k not in live_ids]:
                del self._states[key]
            if evicted:
                ev_bids = {st.bid for st in evicted}
                for st in evicted:
                    for g, c in st.item_counts.items():
                        left = self._item_totals.get(g, 0) - c
                        if left:
                            self._item_totals[g] = left
                        else:
                            # drop zeroed entries: a rotating item
                            # universe must not grow the census (and the
                            # per-push f1 scan) without bound
                            self._item_totals.pop(g, None)
                self._subtract_evicted(ev_bids)

            # --- register unseen batches (the pushed one; after a
            # service restart, every restored batch) ---
            fresh: List[_BatchTokens] = []
            for b in live:
                if id(b) not in self._states:
                    st = _BatchTokens(self._next_bid, b, self.use_pallas,
                                      mesh=self.mesh, put=self._put,
                                      seq_floor=self.seq_floor)
                    self._next_bid += 1
                    self._states[id(b)] = st
                    fresh.append(st)
                    for g, c in st.item_counts.items():
                        self._item_totals[g] = self._item_totals.get(g, 0) + c
            t_tok = time.monotonic()

            minsup = self.minsup_abs()
            f1 = sorted(g for g, c in self._item_totals.items()
                        if c >= minsup)

            # --- count the arriving batch(es): sweep T (pre-repair
            # structure) over each fresh batch ---
            for st in fresh:
                self._sweep(st, f1)
                self.stats["swept_batches"] += 1
            t_sweep = time.monotonic()

            # --- border repair + result collection ---
            self._repair(minsup, f1)
            t_rep = time.monotonic()
            self.patterns = self._collect_and_prune(minsup, f1)
            self.stats["phase_s"] = {
                "tokens": round(t_tok - t0, 3),
                "sweep": round(t_sweep - t_tok, 3),
                "repair": round(t_rep - t_sweep, 3),
                "prune": round(time.monotonic() - t_rep, 3),
            }

            # sweep-shape export: the freshest batch's current store
            # geometry (what this push compiled against, if anything),
            # plus every distinct live sweep key — bench_scale and
            # /status surface these so mid-stream compile stalls are
            # attributable to a shape key (VERDICT round 5, Weak #2)
            live_keys = sorted({st.last_shape_key
                                for st in self._states.values()
                                if st.last_shape_key})
            if fresh and fresh[-1].last_shape_key:
                self.stats["shape_key"] = fresh[-1].last_shape_key
            if live_keys:
                self.stats["sweep_shape_keys"] = live_keys
            self.stats["pushes"] += 1
            self.stats["mines"] += 1
            self.stats["evicted_batches"] = self.window.evicted_batches
            self.stats["window_sequences"] = self.window.n_sequences
            self.stats["patterns"] = len(self.patterns)
            n_nodes = sum(1 for _ in self._iter_nodes())
            self.stats["tracked_nodes"] = n_nodes
            self.stats["border_nodes"] = n_nodes - len(self.patterns)
            self.stats["push_wall_s"] = round(time.monotonic() - t0, 4)
            # keep projected stores warm across pushes (steady-state
            # repair skips every rebuild) under a fraction of device
            # memory; beyond it, drop oldest-batch stores first
            from spark_fsm_tpu.models._common import device_hbm_budget
            dev = (self.mesh.devices.flat[0] if self.mesh is not None
                   else jax.devices()[0])
            budget = 0.2 * device_hbm_budget(dev)
            # stores shard over the mesh's sequence axis, so the budget
            # (per-device HBM) compares against PER-DEVICE bytes — the
            # global figure would evict n_shards times too eagerly
            n_sh = 1 if self.mesh is None else self.mesh.devices.size
            total = sum(st.store_bytes() for st in self._states.values()
                        ) // n_sh
            for b in live:  # oldest first
                if total <= budget:
                    break
                st = self._states[id(b)]
                total -= st.store_bytes() // n_sh
                st.drop_store()
            self.stats["store_cache_bytes"] = int(
                sum(st.store_bytes() for st in self._states.values()))
            return self.patterns

    def _iter_nodes(self):
        stack = list(self._root.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    def _subtract_evicted(self, ev_bids) -> None:
        for node in self._iter_nodes():
            for bid in ev_bids:
                node.total -= node.sup.pop(bid, 0)

    # ------------------------------------------------------------ sweep

    def _sweep(self, st: _BatchTokens, f1: List[int]) -> None:
        """Fill ``node.sup[st.bid]`` for every tracked node by walking
        T's levels over the batch store.  No pruning happens here, so no
        level needs the previous level's supports — every kernel is
        dispatched back-to-back and ONE readback at the end resolves the
        whole batch."""
        bid = st.bid
        # depth-1 supports come from the batch census (host)
        for (g, _), node in self._root.items():
            c = st.item_counts.get(g, 0)
            node.sup[bid] = c
            node.total += c

        # parents per level = tracked nodes with tracked children
        cur: List[Tuple[_TNode, int]] = []
        lcap = 0
        lvl_nodes = [n for n in self._root.values() if n.children]
        probe = lvl_nodes
        while probe:
            lcap = max(lcap, len(probe))
            probe = [c for n in probe for c in n.children.values()
                     if c.children]
        n_rows = st._project(f1, 2 * max(lcap, 1))
        region = [st.ni_rows, st.ni_rows + max(lcap, 1)]
        scratch = n_rows - 1
        fns = _spade_fns(self.mesh, st.n_words)
        if self.use_pallas and st.n_words > 1 and st.items_t is None:
            from spark_fsm_tpu.models.spade_tpu import _items_transpose
            st.items_t = _items_transpose(self.mesh, st.ni_rows,
                                          st.n_words)(st.store)

        for node in lvl_nodes:
            g = node.steps[0][0]
            row = st.row_of.get(g)
            if row is None:  # item absent from this batch: subtree is 0
                for c in node.children.values():
                    self._zero_subtree(c, bid)
            else:
                cur.append((node, row))

        pend: List[Tuple[jax.Array, List[_TNode]]] = []
        depth = 0
        while cur:
            slots = np.full(next_pow2(max(len(cur), 8)), scratch, np.int32)
            for i, (_, slot) in enumerate(cur):
                slots[i] = slot
            pt = fns["prep"](st.store, self._put(slots))
            self.stats["kernel_launches"] += 1

            refs: List[int] = []
            items: List[int] = []
            iss: List[bool] = []
            meta: List[_TNode] = []
            mat: List[Tuple[int, int, bool, int]] = []
            nxt: List[Tuple[_TNode, int]] = []
            out_base = region[depth % 2]
            for b, (node, _) in enumerate(cur):
                for (g, s), child in node.children.items():
                    jrow = st.row_of.get(g)
                    if jrow is None:
                        self._zero_subtree(child, bid)
                        continue
                    refs.append(b)
                    items.append(jrow)
                    iss.append(s)
                    meta.append(child)
                    if child.children:
                        out = out_base + len(nxt)
                        mat.append((b, jrow, s, out))
                        nxt.append((child, out))
            if refs:
                # each dispatch stays pow2-padded on device — slicing to
                # the live count or concatenating varying shapes would
                # compile a fresh program per candidate count (a multi-
                # second remote AOT on the tunneled backend, per PUSH)
                for sup_dev, n, sub in self._supports_dispatch(
                        st, fns, pt, np.asarray(refs, np.int32),
                        np.asarray(items, np.int32),
                        np.asarray(iss, bool), meta):
                    pend.append((sup_dev, n, sub))
                self.stats["sweep_candidates"] += len(refs)
            if mat:
                c = self.support_chunk
                mr = np.asarray([m[0] for m in mat], np.int32)
                mi = np.asarray([m[1] for m in mat], np.int32)
                ms = np.asarray([m[2] for m in mat], bool)
                mo = np.asarray([m[3] for m in mat], np.int32)
                for lo in range(0, len(mat), c):
                    hi = min(lo + c, len(mat))
                    pad = next_pow2(max(hi - lo, 8)) - (hi - lo)
                    # donates the store; the item rows (and st.items_t,
                    # which mirrors only them) are untouched — writes land
                    # in the work regions
                    st.store = fns["materialize"](
                        pt, st.store,
                        self._put(np.pad(mr[lo:hi], (0, pad))),
                        self._put(np.pad(mi[lo:hi], (0, pad))),
                        self._put(np.pad(ms[lo:hi], (0, pad))),
                        self._put(np.pad(mo[lo:hi], (0, pad),
                                         constant_values=scratch)))
                    self.stats["kernel_launches"] += 1
            cur = nxt
            depth += 1

        # resolve: start every host copy first (they overlap on the
        # link), then block — total wall ~ one roundtrip + transfers
        for dev, _, _ in pend:
            try:
                dev.copy_to_host_async()
            except (AttributeError, NotImplementedError):
                pass  # method unavailable on this backend
        for dev, n, meta in pend:
            sups = np.asarray(dev)
            for i, child in enumerate(meta):
                s = int(sups[i])
                child.sup[bid] = s
                child.total += s

    def _supports_dispatch(self, st: _BatchTokens, fns, pt,
                           refs: np.ndarray, items: np.ndarray,
                           iss: np.ndarray, meta):
        """Support vectors for a candidate list (classic engine's dual
        path: Pallas pair matrix + on-device extraction on TPU, chunked
        gather joins elsewhere).  Yields ``(padded device array, live
        count, meta slice)`` triples — arrays keep their pow2 padding
        (device-side trimming would compile per live count) and the
        caller slices on host after the readback."""
        n = len(refs)
        if self.use_pallas:
            cap = max(1024, next_pow2(n))
            pref = np.zeros(cap, np.int32)
            itm = np.zeros(cap, np.int32)
            pref[:n] = 2 * refs + iss
            itm[:n] = items
            items_arr = st.items_t if st.items_t is not None else st.store
            if self.mesh is not None:
                # the classic engine's cached shard_map launcher: per-
                # shard Pallas pair kernel + psum of extracted supports
                from spark_fsm_tpu.models.spade_tpu import (
                    _pallas_supports_fn)
                sup = _pallas_supports_fn(
                    self.mesh, st.ni_rows, st.s_block, st.n_words,
                    self._interpret)(
                    pt, items_arr, self._put(pref), self._put(itm))
            else:
                sup = PS.batch_supports(
                    pt, items_arr, st.ni_rows,
                    jnp.asarray(pref), jnp.asarray(itm),
                    items_kernel_layout=st.items_t is not None,
                    s_block=st.s_block, interpret=self._interpret,
                    n_words=st.n_words)
            self.stats["kernel_launches"] += 1
            _block_collectives_on_cpu(sup, self.mesh)
            return [(sup, n, meta)]
        out = []
        c = self.support_chunk
        for lo in range(0, n, c):
            hi = min(lo + c, n)
            pad = next_pow2(max(hi - lo, 8)) - (hi - lo)
            sup = fns["supports"](
                pt, st.store,
                self._put(np.pad(refs[lo:hi], (0, pad))),
                self._put(np.pad(items[lo:hi], (0, pad))),
                self._put(np.pad(iss[lo:hi], (0, pad))))
            _block_collectives_on_cpu(sup, self.mesh)
            out.append((sup, hi - lo, meta[lo:hi]))
            self.stats["kernel_launches"] += 1
        return out

    # ----------------------------------------------------------- repair

    def _walk_candidates(self, minsup: int, f1: List[int], missing):
        """Top-down recompute of candidate lists from CURRENT frequent
        sets (the classic _resolve rules); collect candidates T has
        never evaluated.  Returns False if any were found (tree not yet
        at fixpoint)."""

        def walk(node: _TNode, s_list: List[int], i_list: List[int]):
            for j in s_list:
                if (j, True) not in node.children:
                    missing.append((node, (j, True)))
            for j in i_list:
                if (j, False) not in node.children:
                    missing.append((node, (j, False)))
            s_items = [j for j in s_list
                       if node.children.get((j, True)) is not None
                       and node.children[(j, True)].total >= minsup]
            i_items = [j for j in i_list
                       if node.children.get((j, False)) is not None
                       and node.children[(j, False)].total >= minsup]
            for j in s_items:
                walk(node.children[(j, True)], s_items,
                     [x for x in s_items if x > j])
            for j in i_items:
                walk(node.children[(j, False)], s_items,
                     [x for x in i_items if x > j])

        for i in f1:
            node = self._root.get((i, True))
            if node is None:
                # newly frequent item: materialize its root node from the
                # batch censuses (host data, no device work)
                node = _TNode(((i, True),))
                for st in self._states.values():
                    node.sup[st.bid] = st.item_counts.get(i, 0)
                node.total = self._item_totals.get(i, 0)
                self._root[(i, True)] = node
            walk(node, f1, [x for x in f1 if x > i])

    def _repair(self, minsup: int, f1: List[int]) -> None:
        rounds = 0
        while True:
            missing: List[Tuple[_TNode, Key]] = []
            self._walk_candidates(minsup, f1, missing)
            if not missing:
                break
            rounds += 1
            self._evaluate_missing(missing, f1)
            self.stats["repaired_nodes"] += len(missing)
        self.stats["repair_rounds"] += rounds

    def _evaluate_missing(self, missing, f1: List[int]) -> None:
        """Count never-evaluated candidates on EVERY live batch (the fold
        evaluator); insert them as tracked children."""
        children: List[_TNode] = []
        for parent, key in missing:
            child = _TNode(parent.steps + (key,))
            parent.children[key] = child
            children.append(child)

        # dispatch every (batch, chunk) fold back-to-back, THEN resolve —
        # blocking per batch would serialize one tunnel roundtrip per
        # live batch into every repair round
        pend = []
        for st in self._states.values():
            # every candidate/step item is window-frequent (downward
            # closure), so the f1 projection serves all repair rounds.
            # _project reuses the cached store only when its key matches
            # THIS f1 — a cached store from an older projection must
            # never serve stale rows.
            st._project(f1, 0)
            fold = _fold_supports_fn(st.n_words, self.mesh)
            todo: List[Tuple[int, List[Tuple[int, bool]]]] = []
            for ci, child in enumerate(children):
                rows = [(st.row_of.get(g), s) for g, s in child.steps]
                if any(r is None for r, _ in rows):
                    child.sup[st.bid] = 0  # an item absent from batch
                    continue
                todo.append((ci, rows))
            m = self.repair_chunk
            for lo in range(0, len(todo), m):
                grp = todo[lo:lo + m]
                width = next_pow2(max(len(grp), 8))
                k = next_pow2(max(max(len(r) for _, r in grp), 2))
                it = np.zeros((k, width), np.int32)
                ss = np.zeros((k, width), bool)
                va = np.zeros((k, width), bool)
                for col, (_, rows) in enumerate(grp):
                    for row_i, (r, s) in enumerate(rows):
                        it[row_i, col] = r
                        ss[row_i, col] = s
                        va[row_i, col] = True
                sup = fold(st.store, self._put(it), self._put(ss),
                           self._put(va))
                _block_collectives_on_cpu(sup, self.mesh)
                self.stats["kernel_launches"] += 1
                pend.append((sup, st.bid, grp))
        for sup_dev, _, _ in pend:
            try:
                sup_dev.copy_to_host_async()
            except (AttributeError, NotImplementedError):
                pass  # method unavailable on this backend
        for sup_dev, bid, grp in pend:
            sups = np.asarray(sup_dev)
            for col, (ci, _) in enumerate(grp):
                children[ci].sup[bid] = int(sups[col])
        for child in children:
            child.total = sum(child.sup.values())

    # ---------------------------------------------------- prune/collect

    def _collect_and_prune(self, minsup: int,
                           f1: List[int]) -> List[PatternResult]:
        """Final walk: collect the frequent set (byte-identical contract)
        and prune T down to F plus its CURRENT negative border, so
        tracked state cannot grow monotonically."""
        results: List[PatternResult] = []

        def pattern_of(steps: Tuple[Key, ...]):
            pat: List[List[int]] = []
            for g, s in steps:
                if s:
                    pat.append([g])
                else:
                    pat[-1].append(g)
            return tuple(tuple(p) for p in pat)

        def walk(node: _TNode, s_list: List[int], i_list: List[int]):
            keep: Dict[Key, _TNode] = {}
            s_items = [j for j in s_list
                       if (c := node.children.get((j, True))) is not None
                       and c.total >= minsup]
            i_items = [j for j in i_list
                       if (c := node.children.get((j, False))) is not None
                       and c.total >= minsup]
            for j in s_list:
                c = node.children.get((j, True))
                if c is not None:
                    keep[(j, True)] = c
            for j in i_list:
                c = node.children.get((j, False))
                if c is not None:
                    keep[(j, False)] = c
            # drop stale children outside the current candidate lists
            # AND the whole subtree of any non-frequent child (border
            # nodes are leaves)
            node.children = keep
            for key, c in keep.items():
                if c.total < minsup:
                    c.children = {}
            for j in s_items:
                c = node.children[(j, True)]
                results.append((pattern_of(c.steps), c.total))
                walk(c, s_items, [x for x in s_items if x > j])
            for j in i_items:
                c = node.children[(j, False)]
                results.append((pattern_of(c.steps), c.total))
                walk(c, s_items, [x for x in i_items if x > j])

        f1_set = set(f1)
        for key in list(self._root):
            if key[0] not in f1_set:
                del self._root[key]  # item fell below minsup: whole
                # subtree is infrequent by downward closure
        for i in f1:
            node = self._root[(i, True)]
            results.append((pattern_of(node.steps), node.total))
            walk(node, f1, [x for x in f1 if x > i])
        return sort_patterns(results)
