"""Pull-based micro-batch consumer driver (the Kafka-consumer shape).

SURVEY.md sec 2.5 names "Kafka micro-batches" as the reference ecosystem's
streaming feed and sec 7 step 9 makes the consumer "optional behind the
source interface".  No broker is reachable in this sandbox (zero egress),
so what the framework ships is the consumer SHAPE, not a Kafka client: a
user-supplied ``fetch() -> Optional[SequenceDB]`` callable — poll one
micro-batch, return None when the broker has nothing right now — driven
by a poll loop that feeds every batch to a sink (``WindowMiner.push``, a
service Streamer topic, or any callable).  A production deployment plugs
a real client in without touching the framework::

    consumer = kafka.KafkaConsumer(...)          # external library
    def fetch():
        recs = consumer.poll(timeout_ms=500)
        batch = [parse_spmf_line(r.value) for rs in recs.values() for r in rs]
        return batch or None
    PollConsumer(fetch, miner.push).run()

Semantics:

- ``None`` from fetch = idle: sleep ``poll_interval_s`` and poll again
  (a blocking fetch can always return batches back-to-back; the interval
  then never applies).
- An EMPTY batch from fetch is treated as idle too — the window layer
  rejects empty pushes (they would evict real data while adding none).
- ``StopConsumer`` raised by fetch ends the loop cleanly (the
  end-of-partition signal); ``stop()`` ends it from another thread.
- fetch/sink exceptions do NOT kill the loop by default: they are
  counted, reported through ``on_error``, and polling continues after a
  BOUNDED EXPONENTIAL BACKOFF with seeded jitter (the shared
  utils/retry.py policy: ``poll_interval_s`` doubling per consecutive
  error up to ``max_backoff_s``) — a flaky broker must not tear down
  the mining service (the reference's supervision contract, SURVEY.md
  sec 5 failure row) and must not be hammered at full poll rate either.
  ``max_consecutive_errors`` bounds that patience; crossing it stops
  the loop with ``stats["stopped"] = "errors"``.
- ``stop()`` that fails to join its worker thread counts the leak
  (``stats["leaked_threads"]`` + the module-wide :func:`consumer_health`
  counter ``/admin/health`` reports) and logs it, instead of returning
  silently with a zombie poll loop still attached to the broker.
- BACKPRESSURE (ISSUE 5): with ``queue_depth_fn``/``pause_at``/
  ``resume_at`` set, the consumer PAUSES polling when the downstream
  queue (e.g. ``Miner.queue_size``) reaches the high watermark and
  resumes once it drains to the low one — windows wait at the broker
  (which retains them) instead of being submitted into an admission
  queue that would shed them with 429.  Pause/resume transitions are
  counted per instance (``stats``) and process-wide
  (:func:`consumer_health` / ``fsm_consumer_backpressure_pauses_total``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.utils import obs
from spark_fsm_tpu.utils.obs import log_event
from spark_fsm_tpu.utils.retry import RetryPolicy

FetchFn = Callable[[], Optional[SequenceDB]]

_health_lock = threading.Lock()
_health = {"leaked_threads": 0, "backpressure_pauses": 0}
# consume-side freshness: wall clock of the last poll and the last
# NON-IDLE poll across every consumer in the process.  The scrape-time
# gauge fsm_consumer_poll_lag_seconds = now - last consumed batch — the
# pull-loop notion of consumer lag (a healthy idle topic grows it too,
# so read it next to fsm_consumer_batches_total; a growing lag WITH
# busy polls means the sink, not the broker, is behind).
_last_poll_ts: Optional[float] = None
_last_batch_ts: Optional[float] = None

_POLL_SECONDS = obs.REGISTRY.histogram(
    "fsm_consumer_poll_seconds", "fetch() wall per poll")
_POLLS_TOTAL = obs.REGISTRY.counter("fsm_consumer_polls_total")
_BATCHES_TOTAL = obs.REGISTRY.counter("fsm_consumer_batches_total")
_ERRORS_TOTAL = obs.REGISTRY.counter("fsm_consumer_errors_total")


def _collect_metrics():
    health = consumer_health()
    fams = [("fsm_consumer_leaked_threads_total", "counter",
             "poll threads that outran stop()'s join deadline",
             [({}, health["leaked_threads"])]),
            ("fsm_consumer_backpressure_pauses_total", "counter",
             "poll loops paused at the downstream-queue high watermark",
             [({}, health["backpressure_pauses"])])]
    now = time.monotonic()
    for name, ts in (("fsm_consumer_poll_age_seconds", _last_poll_ts),
                     ("fsm_consumer_poll_lag_seconds", _last_batch_ts)):
        if ts is not None:
            fams.append((name, "gauge",
                         "seconds since the last poll / consumed batch",
                         [({}, round(now - ts, 3))]))
    return fams


obs.REGISTRY.register_collector("consumer", _collect_metrics)


def consumer_health() -> dict:
    """Process-wide consumer counters for ``/admin/health`` (consumers
    are free-standing objects, so per-instance stats alone would be
    invisible to the service's health surface)."""
    with _health_lock:
        return dict(_health)


def _count_leak() -> None:
    with _health_lock:
        _health["leaked_threads"] += 1


def _count_pause() -> None:
    with _health_lock:
        _health["backpressure_pauses"] += 1


class StopConsumer(Exception):
    """Raised by a fetch callable to end the poll loop cleanly."""


class PollConsumer:
    """Drives a pull-based micro-batch source into a push-based sink.

    Args:
      fetch: poll one micro-batch; ``None``/empty = nothing available.
      sink: called with each non-empty batch (e.g. ``WindowMiner.push``).
        Its return value is handed to ``on_result`` when given.
      poll_interval_s: sleep between polls after an idle poll or an error.
      max_consecutive_errors: stop after this many back-to-back
        fetch/sink failures (None = keep retrying forever).
      on_result: optional callback with the sink's return value (e.g. the
        window's new pattern set) after every consumed batch.
      on_error: optional callback with the exception; exceptions raised
        BY this callback are swallowed (reporting must not kill the loop).
    """

    def __init__(self, fetch: FetchFn, sink: Callable, *,
                 poll_interval_s: float = 1.0,
                 max_consecutive_errors: Optional[int] = None,
                 max_backoff_s: float = 30.0,
                 on_result: Optional[Callable] = None,
                 on_error: Optional[Callable] = None,
                 queue_depth_fn: Optional[Callable[[], int]] = None,
                 pause_at: Optional[int] = None,
                 resume_at: Optional[int] = None) -> None:
        if poll_interval_s < 0:
            raise ValueError(f"poll_interval_s must be >= 0 "
                             f"(got {poll_interval_s})")
        if max_consecutive_errors is not None and max_consecutive_errors < 1:
            raise ValueError(f"max_consecutive_errors must be >= 1 or None "
                             f"(got {max_consecutive_errors})")
        if queue_depth_fn is not None:
            if pause_at is None or pause_at < 1:
                raise ValueError("queue_depth_fn needs pause_at >= 1 "
                                 f"(got {pause_at})")
            if resume_at is None:
                resume_at = pause_at // 2
            if not 0 <= resume_at < pause_at:
                raise ValueError(f"resume_at must satisfy 0 <= resume_at < "
                                 f"pause_at (got {resume_at} vs {pause_at})")
        elif pause_at is not None or resume_at is not None:
            raise ValueError("pause_at/resume_at need queue_depth_fn")
        self._fetch = fetch
        self._sink = sink
        self._depth_fn = queue_depth_fn
        self.pause_at = pause_at
        self.resume_at = resume_at
        self._paused = False
        self.poll_interval_s = float(poll_interval_s)
        self.max_consecutive_errors = max_consecutive_errors
        self.max_backoff_s = float(max_backoff_s)
        # the shared I/O backoff policy, used only for its seeded
        # delay_s schedule — the retry LOOP here is the poll loop itself
        self._backoff = RetryPolicy(base_s=self.poll_interval_s,
                                    max_s=self.max_backoff_s, seed=0)
        self._on_result = on_result
        self._on_error = on_error
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._leak_counted: Optional[threading.Thread] = None
        self._consecutive_errors = 0
        self.stats = {"polls": 0, "idle_polls": 0, "batches": 0,
                      "sequences": 0, "errors": 0, "backoff_waits": 0,
                      "leaked_threads": 0, "stopped": None,
                      "backpressure_pauses": 0, "backpressure_resumes": 0,
                      "paused_polls": 0}

    # ------------------------------------------------------------- polling

    def poll_once(self) -> bool:
        """One fetch->sink cycle; True when a batch was consumed.

        Raises StopConsumer through (the run loop turns it into a clean
        stop); other exceptions are absorbed into the error counters.
        """
        global _last_poll_ts, _last_batch_ts
        self.stats["polls"] += 1
        _POLLS_TOTAL.inc()
        t0 = time.monotonic()
        try:
            try:
                batch = self._fetch()
            finally:
                # poll latency covers the FETCH only (the broker seam);
                # sink time is the window miner's own story
                _POLL_SECONDS.observe(time.monotonic() - t0)
                _last_poll_ts = time.monotonic()
            if not batch:
                self.stats["idle_polls"] += 1
                return False
            result = self._sink(batch)
        except StopConsumer:
            raise
        except Exception as exc:
            self._report_error(exc)
            self._consecutive_errors += 1
            return False
        self._consecutive_errors = 0
        self.stats["batches"] += 1
        self.stats["sequences"] += len(batch)
        _BATCHES_TOTAL.inc()
        _last_batch_ts = time.monotonic()
        if self._on_result is not None:
            try:
                self._on_result(result)
            except Exception as exc:
                # the batch WAS consumed (the sink advanced), so this is a
                # reporting failure, not a consume failure: count + surface
                # it, never kill the loop (the supervision contract), and
                # leave the consecutive-error streak reset by the consume
                self._report_error(exc)
        return True

    def _backpressure_hold(self) -> bool:
        """True when this loop iteration was spent paused at the
        downstream high watermark instead of polling.  The depth probe
        failing is reported but FAILS OPEN (polling continues): a broken
        gauge must not silently starve the topic forever."""
        if self._depth_fn is None:
            return False
        try:
            depth = int(self._depth_fn())
        except Exception as exc:
            self._report_error(exc)
            if self._paused:
                # failing open FROM a pause is a resume transition: count
                # + log it, or pause/resume stats diverge and the fail-
                # open is invisible to an operator pairing them
                self._paused = False
                self.stats["backpressure_resumes"] += 1
                log_event("consumer_resumed", depth=None,
                          reason="depth probe failed (fail open)")
            return False
        if self._paused:
            if depth <= self.resume_at:
                self._paused = False
                self.stats["backpressure_resumes"] += 1
                log_event("consumer_resumed", depth=depth,
                          resume_at=self.resume_at)
                return False
        elif depth >= self.pause_at:
            self._paused = True
            self.stats["backpressure_pauses"] += 1
            _count_pause()
            obs.trace_event("consumer_paused", depth=depth,
                            pause_at=self.pause_at)
            log_event("consumer_paused", depth=depth, pause_at=self.pause_at)
        if self._paused:
            self.stats["paused_polls"] += 1
            # wake immediately on stop(); poll the gauge at the idle
            # cadence (floored so interval 0 cannot spin on the gauge)
            self._stop.wait(self.poll_interval_s or 0.05)
        return self._paused

    def _report_error(self, exc: Exception) -> None:
        """Count + surface an error; the reporting callback itself must
        never kill the loop."""
        self.stats["errors"] += 1
        _ERRORS_TOTAL.inc()
        obs.trace_event("consumer_error",
                        error=f"{type(exc).__name__}: {exc}")
        if self._on_error is not None:
            try:
                self._on_error(exc)
            except Exception:
                pass  # reporting must not kill the loop

    def run(self, max_polls: Optional[int] = None) -> dict:
        """Poll until stopped; returns the stats dict.

        ``max_polls`` bounds the loop for tests/drains (None = until
        ``stop()``, ``StopConsumer``, or the error bound).

        The stop event is NOT cleared here: ``start()`` clears it before
        launching the thread, so a ``stop()`` racing a fresh ``start()``
        can never be erased by the new thread entering this loop (it
        would spin unstoppably).  A direct ``run()`` call after a
        ``stop()`` therefore returns immediately with
        ``stopped="stop"`` — restart via ``start()``.
        """
        self._consecutive_errors = 0
        polls = 0
        while not self._stop.is_set():
            if max_polls is not None and polls >= max_polls:
                self.stats["stopped"] = "max_polls"
                break
            polls += 1
            # backpressure: a paused iteration burns a poll slot (so
            # bounded runs stay bounded) but never touches the broker
            if self._backpressure_hold():
                continue
            try:
                consumed = self.poll_once()
            except StopConsumer:
                self.stats["stopped"] = "end_of_stream"
                break
            if (self.max_consecutive_errors is not None
                    and self._consecutive_errors
                    >= self.max_consecutive_errors):
                self.stats["stopped"] = "errors"
                break
            if not consumed and self.poll_interval_s:
                # idle: wait out the interval; errored: exponential
                # backoff (interval doubling per consecutive error, up
                # to max_backoff_s, seeded jitter) — either way waking
                # immediately on stop()
                wait = self.poll_interval_s
                if self._consecutive_errors:
                    wait = self._backoff.delay_s(self._consecutive_errors)
                    self.stats["backoff_waits"] += 1
                self._stop.wait(wait)
        else:
            self.stats["stopped"] = "stop"
        return self.stats

    # ----------------------------------------------------- thread wrapper

    def start(self, max_polls: Optional[int] = None) -> "PollConsumer":
        """Run the poll loop in a daemon thread (idempotent while live)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()  # before the spawn: see run()'s docstring
        self._thread = threading.Thread(
            target=self.run, kwargs={"max_polls": max_polls},
            name="fsm-poll-consumer", daemon=True)
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 10.0) -> None:
        """Signal the loop to end; joins the thread when one is running.

        A worker that outruns the join deadline (a sink wedged in a
        device call, a fetch stuck in a socket) is counted and logged as
        a LEAKED thread — the zombie keeps its broker connection and
        must show up in ``/admin/health``, not vanish silently."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(join_timeout_s)
            # count each wedged worker ONCE: a second stop() on the same
            # still-alive thread must not inflate the zombie count
            if t.is_alive() and t is not self._leak_counted:
                self._leak_counted = t
                self.stats["leaked_threads"] += 1
                _count_leak()
                log_event("consumer_thread_leaked", thread=t.name,
                          join_timeout_s=join_timeout_s)
