"""Cluster observability plane (ISSUE 9) — the flight recorder's
durable spine, the cross-replica metrics view, and SLO accounting.

PR 8 made the service multi-replica, but every observability substrate
stayed process-local: the trace ring and /metrics die with the replica,
which is exactly when the lease protocol's failovers need evidence.
This module is the cluster-side counterpart of utils/obs.py:

- **Trace spine** (:class:`TraceSpine`): completed spans flush from the
  flight recorder's per-trace buffers (obs.set_spine) into
  ``fsm:trace:{uid}`` — an append-only list of JSON chunks, each tagged
  with the writing replica's id and fencing token.  The write rides the
  SAME fenced path as results/checkpoints: a holder whose lease was
  superseded has its spine appends REFUSED (counted in
  ``fsm_lease_fence_rejections_total`` next to the prevented result
  double-commits) and is tombstoned so even post-settle stragglers stay
  off the adopter's timeline.  A refused or failed spine write never
  fails the job — observability must not alter control flow.
- **Merged timeline** (:func:`merged_timeline`): the spine chunks plus
  the serving replica's local ring, de-duplicated by
  ``(replica, span_id)`` and ordered by wall-clock ``ts`` (monotonic
  clocks are per-process) — so after a kill -9 the SURVIVOR can show
  admission-on-A → adoption-on-B in one response.
- **Cluster metrics plane**: a scrape-time collector aggregating the
  lease heartbeat records' piggybacked metric snapshots into
  ``fsm_cluster_*`` gauges (total depth, in-flight, free capacity,
  leases held, sheds, lease churn, live replicas) — served identically
  from ANY replica, from the heartbeat-cadence peer cache (a scrape
  must never turn into a store scan storm).
- **SLO layer**: per-priority end-to-end latency (submit → durable
  result) split into queue-wait and execution components, observed into
  fixed-bucket ``fsm_job_*_seconds`` histograms (alertable rates) AND
  sliding-window quantiles (:class:`~spark_fsm_tpu.utils.obs.
  SlidingQuantiles`) behind ``/admin/slo`` — the service-side
  counterpart of bench_throughput's offline p50/p99.

Disabled cost: with ``[cluster]`` off nothing here is installed and the
flight recorder's spine probe is one module-global read; with tracing
off no spans exist to flush.  The SLO histograms are always-on metrics
(per finished JOB, not per dispatch — the bench_smoke dispatch counters
cannot see them).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from spark_fsm_tpu.service import storeguard
from spark_fsm_tpu.utils import envelope, jobctl, obs
from spark_fsm_tpu.utils.obs import log_event

# THE priority vocabulary: admission classes AND the SLO label seeding.
# Spelled here (the lowest service layer that needs it) and aliased by
# service/actors.PRIORITIES, so there is exactly one copy to extend.
PRIORITIES = ("high", "normal", "low")

_SPINE_WRITES = (obs.REGISTRY.counter(
    "fsm_trace_spine_writes_total",
    "durable trace-spine chunk appends, by outcome (fenced = a stale "
    "holder's spans refused — the observability analog of a prevented "
    "double-commit; spooled = deferred into the storeguard write-behind "
    "spool during a store outage)")
    .seed(outcome="ok").seed(outcome="fenced").seed(outcome="error")
    .seed(outcome="spooled"))
# the SAME counter service/lease.py registers — get-or-create returns
# the shared object, so spine refusals land next to the refused
# result/checkpoint writes they are the trace-plane analog of
_FENCE_REJECTED = obs.REGISTRY.counter("fsm_lease_fence_rejections_total")

_ADOPTION_S = obs.REGISTRY.histogram(
    "fsm_job_time_to_adoption_seconds",
    "failover latency: last durable activity of the dead owner (spine "
    "chunk ts, journal ts fallback) to a survivor's adoption — bounded "
    "by lease_ttl_s + recover_every_s when the cluster is healthy"
).seed()
_STEAL_LATENCY_S = obs.REGISTRY.histogram(
    "fsm_job_steal_latency_seconds",
    "work-steal latency: victim's admission (journal ts) to the "
    "thief's successful claim + resubmit").seed()

# the tenant label (ISSUE 14 satellite): bounded vocabulary — "default"
# from boot, fairness-registered tenants via seed_tenant — so per-tenant
# SLO quantiles exist and the scrape never shows no-data for a tenant
# that simply has not finished a job yet
DEFAULT_TENANT = "default"
_tenant_lock = threading.Lock()
_tenants = {DEFAULT_TENANT}

_E2E_S = obs.REGISTRY.histogram(
    "fsm_job_e2e_seconds",
    "end-to-end job latency, submit to durable result, per priority "
    "and tenant")
_QUEUE_WAIT_S = obs.REGISTRY.histogram(
    "fsm_job_queue_wait_seconds",
    "admission-queue wait, submit to first worker pickup, per priority "
    "and tenant")
_EXEC_S = obs.REGISTRY.histogram(
    "fsm_job_exec_seconds",
    "execution component of the end-to-end latency, per priority "
    "and tenant")
for _p in PRIORITIES:
    _E2E_S.seed(priority=_p, tenant=DEFAULT_TENANT)
    _QUEUE_WAIT_S.seed(priority=_p, tenant=DEFAULT_TENANT)
    _EXEC_S.seed(priority=_p, tenant=DEFAULT_TENANT)

# the read-path signal class (ISSUE 17): /predict latencies are ms-scale
# where mining jobs are seconds-scale, so they get their own histogram
# families (sub-ms buckets) and their own sliding-quantile block in
# /admin/slo — a flood of fast predicts must not drown the mining p99,
# and a mining stall must not hide a read-path regression
_PREDICT_E2E_S = obs.REGISTRY.histogram(
    "fsm_predict_e2e_seconds",
    "end-to-end /predict latency (request in -> predictions out), per "
    "priority", buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                         0.05, 0.1, 0.25, 1.0, 5.0))
_PREDICT_WINDOW_S = obs.REGISTRY.histogram(
    "fsm_predict_window_wait_seconds",
    "micro-batch window wait component (submit -> wave dispatch), per "
    "priority", buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                         0.05, 0.1, 0.25, 1.0, 5.0))
_PREDICT_EXEC_S = obs.REGISTRY.histogram(
    "fsm_predict_exec_seconds",
    "scoring-wave execution component (device launch + demux), per "
    "priority", buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                         0.05, 0.1, 0.25, 1.0, 5.0))
for _p in PRIORITIES:
    _PREDICT_E2E_S.seed(priority=_p, tenant=DEFAULT_TENANT)
    _PREDICT_WINDOW_S.seed(priority=_p, tenant=DEFAULT_TENANT)
    _PREDICT_EXEC_S.seed(priority=_p, tenant=DEFAULT_TENANT)


def seed_tenant(tenant: str) -> None:
    """Zero-seed the fsm_job_*_seconds, fsm_predict_*_seconds and
    fsm_usage_*_total series for a (fairness-registered, bounded)
    tenant across every priority class — the obs_smoke no-orphan check
    covers the result."""
    from spark_fsm_tpu.service import usage as _usage

    with _tenant_lock:
        if tenant in _tenants:
            return
        _tenants.add(tenant)
    for p in PRIORITIES:
        _E2E_S.seed(priority=p, tenant=tenant)
        _QUEUE_WAIT_S.seed(priority=p, tenant=tenant)
        _EXEC_S.seed(priority=p, tenant=tenant)
        _PREDICT_E2E_S.seed(priority=p, tenant=tenant)
        _PREDICT_WINDOW_S.seed(priority=p, tenant=tenant)
        _PREDICT_EXEC_S.seed(priority=p, tenant=tenant)
    _usage.seed_tenant(tenant)


def known_tenants() -> List[str]:
    with _tenant_lock:
        return sorted(_tenants)


# sliding-window twins of the three histograms — the /admin/slo p50/p95/
# p99 source ([observability] slo_window_s); the per-priority windows
# keep their label shape, the per-tenant e2e window serves the tenant
# SLO block
_slo = {
    "e2e": obs.SlidingQuantiles(),
    "queue_wait": obs.SlidingQuantiles(),
    "exec": obs.SlidingQuantiles(),
}
_slo_tenant_e2e = obs.SlidingQuantiles()
# the read path's own sliding windows — same window knob, separate
# samples (see the fsm_predict_* histogram comment above)
_slo_predict = {
    "e2e": obs.SlidingQuantiles(),
    "window_wait": obs.SlidingQuantiles(),
    "exec": obs.SlidingQuantiles(),
}
# per-tenant read-path e2e window (ISSUE 19 satellite) — the tenant
# twin of _slo_tenant_e2e for the /admin/slo predict block
_slo_predict_tenant = obs.SlidingQuantiles()

_lock = threading.Lock()
_plane: Optional["TraceSpine"] = None
_max_chunks = 256  # [observability] spine_max_chunks (0 = unbounded)


def spine_key(uid: str) -> str:
    return f"fsm:trace:{uid}"


class TraceSpine:
    """One replica's writer/reader of the durable trace spine.

    ``flush(uid, spans)`` is the obs.set_spine sink: it proves lease
    ownership the same way the result sink does (one local dict read on
    the fast path, a store verification once the local TTL lapses),
    wraps the batch in a chunk tagged ``{replica, token, ts}`` and
    appends it to ``fsm:trace:{uid}``.  Refusal rules, in order:

    1. this replica holds a LIVE lease on the uid → fence() and write
       under its token (the normal mid-job flush);
    2. the lease is marked LOST, or the uid is tombstoned from an
       earlier fencing → REFUSE (counted; the stale-epoch spans must
       never reach the adopter's timeline — the satellite test pins it);
    3. the uid was never leased here and is not tombstoned → write with
       ``token: null`` (stream pushes, solo deployments, and the final
       root-span flush that lands after a terminal release — the uid
       was settled BY US then, so the append is rightful).

    The residual race (fence passes, lease lapses before the rpush
    lands) is the same bounded CAD caveat the lease release documents:
    at worst a few stale SPANS — never results — land, tagged with the
    superseded token the merge exposes.
    """

    def __init__(self, store, lease_mgr=None,
                 max_chunks: Optional[int] = None):
        self._store = store
        self._mgr = lease_mgr
        self._max_chunks = max_chunks  # None = follow the module knob
        self._fenced: set = set()
        self.replica_id = (lease_mgr.replica_id if lease_mgr is not None
                           else "solo")
        # per-BOOT nonce: span_ids restart at 1 in every process, so a
        # crash-restarted replica with a config-pinned replica_id would
        # otherwise collide with its pre-crash chunks' span_ids and the
        # merge's dedup would silently drop the resumed incarnation's
        # spans — the exact post-mortem spans that matter
        self.boot_id = uuid.uuid4().hex[:8]

    def mark_fenced(self, uid: str) -> None:
        """Tombstone a uid whose lease this replica lost: later flushes
        (including the post-settle root-span flush) are refused until a
        fresh lease on the uid is proven."""
        self._fenced.add(uid)

    def flush(self, uid: str, spans: List[dict]) -> str:
        """Append one chunk; returns the outcome ("ok"/"fenced"/
        "error") — the obs sink ignores it, tests read it."""
        if not spans:
            return "ok"
        mgr = self._mgr
        token = None
        guard = storeguard.get()
        outage = guard is not None and guard.is_down()
        try:
            if mgr is not None:
                token = mgr.token_of(uid)
                if mgr.is_lost(uid) or (token is None
                                        and uid in self._fenced):
                    self._fenced.add(uid)
                    _FENCE_REJECTED.inc()
                    _SPINE_WRITES.inc(outcome="fenced")
                    return "fenced"
                if token is not None and not outage:
                    # during a proven outage the fence is deferred to
                    # the spool's replay gate (the journal-gated NX
                    # reacquire under the same token)
                    mgr.fence(uid)  # raises JobLeaseLost when superseded
                    self._fenced.discard(uid)
        except jobctl.JobLeaseLost:
            # fence() already counted the rejection
            self._fenced.add(uid)
            _SPINE_WRITES.inc(outcome="fenced")
            return "fenced"
        except Exception as exc:
            _SPINE_WRITES.inc(outcome="error")
            log_event("trace_spine_fence_error", uid=uid, error=str(exc))
            return "error"
        chunk = envelope.wrap(json.dumps(
            {"replica": self.replica_id, "boot": self.boot_id,
             "token": token, "ts": round(time.time(), 3), "spans": spans}))
        cap = self._max_chunks if self._max_chunks is not None \
            else _max_chunks
        try:
            if guard is not None:
                spooled = guard.spine(
                    uid, chunk, gate=("none" if token is None else None))
                if spooled:
                    _SPINE_WRITES.inc(outcome="spooled")
                    return "spooled"
            else:
                self._store.spine_append(uid, chunk)
            if cap:
                self._store.spine_trim(uid, cap)
            _SPINE_WRITES.inc(outcome="ok")
            return "ok"
        except Exception as exc:
            _SPINE_WRITES.inc(outcome="error")
            log_event("trace_spine_write_failed", uid=uid, error=str(exc))
            return "error"


def install(store, lease_mgr, flush_spans: Optional[int] = None) -> TraceSpine:
    """Build and activate this process's plane: spine sink into the
    flight recorder + the fsm_cluster_* collector.  The LAST install
    wins (tests build many Miners), same posture as the jobs
    collector."""
    global _plane
    plane = TraceSpine(store, lease_mgr)
    with _lock:
        _plane = plane
    obs.set_spine(plane.flush, flush_spans=flush_spans)
    if lease_mgr is not None:
        obs.REGISTRY.register_collector(
            "cluster", _cluster_collector(lease_mgr))
    return plane


def uninstall() -> None:
    """Remove the plane (test isolation): no spine sink, inert cluster
    collector."""
    global _plane
    with _lock:
        _plane = None
    obs.set_spine(None)
    obs.REGISTRY.register_collector("cluster", lambda: [])


def plane() -> Optional[TraceSpine]:
    return _plane


def mark_fenced(uid: str) -> None:
    """Module-level tombstone hook (lease._mark_lost and the fenced
    settle path call this; the hermetic tests use plane instances)."""
    p = _plane
    if p is not None:
        p.mark_fenced(uid)


def configure(ocfg) -> None:
    """Apply the boot ``[observability]`` knobs owned by this plane
    (config.set_config calls it alongside the tracing/watchdog/fusion
    wiring)."""
    global _max_chunks
    _max_chunks = int(ocfg.spine_max_chunks)
    obs.set_spine_flush(int(ocfg.spine_flush_spans))
    for sq in _slo.values():
        sq.set_window(float(ocfg.slo_window_s))
    _slo_tenant_e2e.set_window(float(ocfg.slo_window_s))
    for sq in _slo_predict.values():
        sq.set_window(float(ocfg.slo_window_s))
    _slo_predict_tenant.set_window(float(ocfg.slo_window_s))


# ---------------------------------------------------------------- timeline

def spine_chunks_verified(store, uid: str) -> "Tuple[List[dict], int]":
    """The uid's verified spine chunks + how many were dropped as
    corrupt.  Each chunk rides a checksum envelope (legacy bare-JSON
    chunks still parse); a chunk that fails the envelope OR json.loads
    OR isn't a dict is skipped and counted — one rotten chunk must
    never abort a timeline dump (ISSUE 18)."""
    from spark_fsm_tpu.service import integrity

    try:
        raws = store.spine_chunks(uid)
    except Exception:
        return [], 0
    out: List[dict] = []
    corrupt = 0
    for raw in raws:
        payload, verdict = envelope.unwrap(raw)
        c = None
        if verdict != "corrupt":
            try:
                c = json.loads(payload)
            except (ValueError, TypeError):
                c = None
            if not isinstance(c, dict):
                c, verdict = None, "corrupt"
        integrity.note_read("spine", verdict)
        if c is None:
            corrupt += 1
            continue
        out.append(c)
    return out, corrupt


def spine_chunks(store, uid: str) -> List[dict]:
    """The uid's parsed spine chunks (malformed entries skipped)."""
    return spine_chunks_verified(store, uid)[0]


def last_activity_ts(store, uid: str) -> Optional[float]:
    """Wall timestamp of the uid's most recent spine chunk — the
    adopter's reference point for time-to-adoption (the dead owner's
    last durable flush is its last provable sign of life)."""
    ts = []
    for c in spine_chunks(store, uid):
        try:
            ts.append(float(c.get("ts") or 0))
        except (TypeError, ValueError):
            pass
    ts = [t for t in ts if t > 0]
    return max(ts) if ts else None


def merged_timeline(store, uid: str, local_dump: Optional[dict] = None,
                    replica_id: Optional[str] = None,
                    boot_id: Optional[str] = None) -> Optional[dict]:
    """One monotonic cross-replica timeline: spine chunks + the local
    ring, de-duplicated by ``(replica, boot, span_id)`` (the local
    ring's spans were themselves flushed to the spine, but span_ids
    restart per process — the boot nonce keeps a crash-restarted
    replica's resumed spans distinct from its pre-crash ones), ordered
    by wall ``ts``.  ``boot_id`` is the serving replica's current boot
    nonce (its local ring was flushed under it); None when neither
    source knows the uid."""
    chunks, corrupt_chunks = spine_chunks_verified(store, uid)
    spans: List[dict] = []
    seen = set()
    replicas = set()
    for c in chunks:
        rid = c.get("replica") or "?"
        boot = c.get("boot")
        for s in c.get("spans", ()):
            if not isinstance(s, dict):
                continue
            key = (rid, boot, s.get("span_id"))
            if key in seen:
                continue
            seen.add(key)
            s = dict(s)
            s["replica"] = rid
            if c.get("token") is not None:
                s["token"] = c["token"]
            spans.append(s)
            replicas.add(rid)
    if local_dump:
        rid = replica_id or "local"
        for s in local_dump.get("spans", ()):
            key = (rid, boot_id, s.get("span_id"))
            if key in seen:
                continue
            seen.add(key)
            s = dict(s)
            s["replica"] = rid
            spans.append(s)
            replicas.add(rid)
    if not spans and local_dump is None and not corrupt_chunks:
        return None

    def _order(s: dict):
        # damaged chunks can smuggle mixed-type ts/span_id values past
        # json.loads; the sort must not TypeError on them
        try:
            ts = float(s.get("ts") or 0.0)
        except (TypeError, ValueError):
            ts = 0.0
        sid = s.get("span_id")
        if isinstance(sid, (int, float)):
            return (ts, 0, sid, "")
        return (ts, 1, 0, str(sid))

    spans.sort(key=_order)
    return {"trace_id": uid, "merged": True,
            "replicas": sorted(replicas),
            "n_spans": len(spans), "spine_chunks": len(chunks),
            "corrupt_chunks": corrupt_chunks,
            "attrs": dict((local_dump or {}).get("attrs", {})),
            "dropped_spans": (local_dump or {}).get("dropped_spans", 0),
            "spans": spans}


# ------------------------------------------------------- failover metrics

def observe_adoption(seconds: float) -> None:
    _ADOPTION_S.observe(max(0.0, float(seconds)))


def observe_steal_latency(seconds: float) -> None:
    _STEAL_LATENCY_S.observe(max(0.0, float(seconds)))


# ---------------------------------------------------------------- SLO layer

def observe_job(priority: str, e2e_s: float, queue_wait_s: float,
                exec_s: float, tenant: str = DEFAULT_TENANT) -> None:
    """One finished job's latency decomposition (submit → durable
    result = queue wait + execution), into both the fixed-bucket
    histograms (labelled by priority AND tenant) and the sliding SLO
    windows.  An unregistered tenant folds into "default" — the label
    vocabulary stays bounded by the fairness registry."""
    if priority not in PRIORITIES:
        priority = "normal"
    with _tenant_lock:
        if tenant not in _tenants:
            tenant = DEFAULT_TENANT
    _E2E_S.observe(e2e_s, priority=priority, tenant=tenant)
    _QUEUE_WAIT_S.observe(queue_wait_s, priority=priority, tenant=tenant)
    _EXEC_S.observe(exec_s, priority=priority, tenant=tenant)
    _slo["e2e"].observe(e2e_s, priority=priority)
    _slo["queue_wait"].observe(queue_wait_s, priority=priority)
    _slo["exec"].observe(exec_s, priority=priority)
    _slo_tenant_e2e.observe(e2e_s, tenant=tenant)


def observe_predict(priority: str, e2e_s: float, window_wait_s: float,
                    exec_s: float,
                    tenant: str = DEFAULT_TENANT) -> None:
    """One served /predict's latency decomposition (request in ->
    predictions out = window wait + wave execution) into the read-path
    histogram families and sliding SLO windows — the second signal
    class next to observe_job's mining-path one.  An unregistered
    tenant folds into "default", same bounded-vocabulary rule as
    observe_job."""
    if priority not in PRIORITIES:
        priority = "normal"
    with _tenant_lock:
        if tenant not in _tenants:
            tenant = DEFAULT_TENANT
    _PREDICT_E2E_S.observe(e2e_s, priority=priority, tenant=tenant)
    _PREDICT_WINDOW_S.observe(window_wait_s, priority=priority,
                              tenant=tenant)
    _PREDICT_EXEC_S.observe(exec_s, priority=priority, tenant=tenant)
    _slo_predict["e2e"].observe(e2e_s, priority=priority)
    _slo_predict["window_wait"].observe(window_wait_s, priority=priority)
    _slo_predict["exec"].observe(exec_s, priority=priority)
    _slo_predict_tenant.observe(e2e_s, tenant=tenant)


def slo_snapshot() -> dict:
    """The /admin/slo body: per-priority p50/p95/p99 (+count/max) of
    each latency component over the sliding window."""
    out: Dict[str, object] = {
        "window_s": _slo["e2e"].window_s,
        "ts": round(time.time(), 3),
        "priorities": {},
    }
    for p in PRIORITIES:
        out["priorities"][p] = {
            kind: sq.stats(priority=p) for kind, sq in _slo.items()}
    # per-tenant e2e quantiles (ISSUE 14 satellite): every registered
    # tenant gets a row — {"count": 0} until it finishes a job
    out["tenants"] = {t: _slo_tenant_e2e.stats(tenant=t)
                      for t in known_tenants()}
    # read-path quantiles (ISSUE 17): /predict's own per-priority block
    # so a dashboard can alert on serving p99 independently of mining
    out["predict"] = {
        p: {kind: sq.stats(priority=p)
            for kind, sq in _slo_predict.items()}
        for p in PRIORITIES}
    # per-tenant read-path e2e quantiles (ISSUE 19 satellite): every
    # registered tenant gets a row — {"count": 0} until it predicts
    out["predict_tenants"] = {t: _slo_predict_tenant.stats(tenant=t)
                              for t in known_tenants()}
    return out


def slo_digest() -> dict:
    """COMPACT per-replica SLO digest piggybacked on the lease
    heartbeat (the fleet-wide up_p99 merge): the worst per-priority e2e
    p99 over the local sliding window plus the sample count behind it.
    The autoscale leader scales on the FLEET max of these, so an idle
    leader is no longer blind to a saturating peer."""
    worst, n = None, 0
    for p in PRIORITIES:
        st = _slo["e2e"].stats(priority=p)
        c = int(st.get("count") or 0)
        n += c
        p99 = st.get("p99")
        if c and p99 is not None:
            worst = p99 if worst is None else max(worst, p99)
    return {"p99": (None if worst is None else round(float(worst), 4)),
            "n": n}


def clear_slo() -> None:
    """Drop the sliding windows (test isolation)."""
    for sq in _slo.values():
        sq.clear()
    _slo_tenant_e2e.clear()
    for sq in _slo_predict.values():
        sq.clear()
    _slo_predict_tenant.clear()


# ------------------------------------------------------ cluster collector

def _cluster_collector(mgr):
    """Scrape-time fsm_cluster_* gauges from the heartbeat-cadence peer
    cache (never a fresh store scan — a scrape storm must not become a
    SCAN storm)."""

    def collect():
        view = mgr.cluster_view()
        t = view["totals"]

        def g(name, help, value):
            return (name, "gauge", help, [({}, float(value))])

        return [
            g("fsm_cluster_replicas",
              "live replicas (self + un-expired heartbeat records)",
              t["replicas"]),
            g("fsm_cluster_queue_depth",
              "queued train jobs across live replicas", t["queued"]),
            g("fsm_cluster_in_flight",
              "running train jobs across live replicas", t["running"]),
            g("fsm_cluster_free_capacity",
              "advertised idle worker slots across live replicas",
              t["free"]),
            g("fsm_cluster_leases_held",
              "job leases held across live replicas", t["held"]),
            g("fsm_cluster_sheds",
              "429 sheds across live replicas (sum of advertised "
              "lifetime counters)", t["sheds"]),
            g("fsm_cluster_lease_churn",
              "lease acquisitions + losses across live replicas — "
              "rising churn at stable job volume means flapping "
              "ownership (TTL too tight)", t["lease_churn"]),
        ]

    return collect
