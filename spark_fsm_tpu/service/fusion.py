"""Cross-job launch fusion: co-schedule candidate waves from CONCURRENT
mines into shared super-batched device launches.

The north star is heavy traffic — thousands of small concurrent mines,
not one big one — yet before this layer each job serially owned the
device: the Miner could run several jobs at once, but every engine
dispatched its own launches, so a small mine's candidate wave paid a
full per-launch dispatch cost while leaving the device mostly idle.
The ragged packer (ops/ragged_batch.py) already solved this problem one
level down (candidate pools *within* a job merge into shared launches
under a cost model); this module lifts the same policy one level up, to
candidate waves *across* jobs — ROADMAP open item 3.

Architecture — the unit of device work becomes the WAVE, not the job:

- **eval waves** (models/tsr.py): an engine on the single-device jnp
  path hands its whole per-dispatch candidate set to the broker instead
  of planning and launching itself.  The broker holds it in a **bounded
  fusion window** (``[fusion] window_ms``, width- and job-capped) keyed
  by device geometry ``(n_seq, n_words)``; waves from different jobs
  that share the key are FUSED: their prep stores concatenate along the
  item axis (padded to a pow2 bucket, so the compiled-program set stays
  enumerable — ``tsr-fused`` keys in utils/shapes.py, walked by
  prewarm), their candidates' item indices shift by each job's offset,
  and one ragged super-batch plan covers all of them with per-lane JOB
  tags (``Launch.jobs``) so the single readback demuxes each lane's
  (sup, supx) back to the job that owns it.  Correctness is positional:
  a candidate's gather touches only its own job's rows, so fused counts
  are bit-identical to solo counts (docs/DESIGN.md).
- **a cost model, not a flag**: fusion is taken iff the packer's own
  arithmetic — with the per-launch overhead recalibrated from the live
  ``fsm_costmodel_drift_ratio`` EWMA — predicts the fused plan beats
  the per-job plans by more than the prep-concat cost (priced in the
  same lane-traffic units).  Groups the model declines dispatch per-job
  (still inside the broker, counted ``rejected``).
- **priority-aware window**: a ``high``-priority job's wave NEVER waits
  out the window behind low fill — it launches immediately, fused with
  whatever is already pending.  Normal/low waves wait at most
  ``window_ms``; the window also closes when pending lanes reach
  ``max_width`` or pending waves reach ``max_jobs``.
- **queue waves** (models/spade_queue.py): the queue engine's unit of
  device work is a whole-mine (or segment) program with per-job carry
  state — unfusable by construction — but it routes through the broker
  too (:func:`dispatch_wave`), so every device wave shares one
  accounting/fault surface and the ``fusion.dispatch`` chaos site
  covers both engines.
- **failure posture**: ANY broker failure — the ``fusion.dispatch``
  fault site, a fused-launch error, a cost-model bug — degrades to
  unfused per-job dispatch; a wave is never lost (counted
  ``fsm_fusion_degraded_total``, swept by tests/test_chaos.py).

Disabled (`[fusion] enabled = false`, the default) every probe is one
module-global read — the same pin as the fault registry and the flight
recorder (scripts/bench_smoke.sh's byte-identical counters hold).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from spark_fsm_tpu.ops import ragged_batch as RB
from spark_fsm_tpu.service import meshguard, usage
from spark_fsm_tpu.utils import faults, jobctl, obs, shapes, watchdog
from spark_fsm_tpu.utils.obs import log_event

_WAVES_TOTAL = obs.REGISTRY.counter(
    "fsm_fusion_waves_total",
    "device waves entering the fusion broker, by engine and outcome")
_LAUNCHES_TOTAL = obs.REGISTRY.counter(
    "fsm_fusion_launches_total",
    "device launches the broker dispatched (cross_job=true when lanes "
    "from more than one job shared the launch)")
_JOBS_PER_LAUNCH = obs.REGISTRY.histogram(
    "fsm_fusion_jobs_per_launch",
    "distinct jobs sharing one broker-dispatched launch",
    buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 16.0))
_WINDOW_WAIT = obs.REGISTRY.histogram(
    "fsm_fusion_window_wait_seconds",
    "how long a wave group sat in the fusion window before launching",
    buckets=(0.0005, 0.001, 0.002, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0))
_DEGRADED_TOTAL = obs.REGISTRY.counter(
    "fsm_fusion_degraded_total",
    "broker failures degraded to unfused per-job dispatch (no wave lost)")
_REJECTED_TOTAL = obs.REGISTRY.counter(
    "fsm_fusion_rejected_total",
    "window groups the cost model declined to fuse (dispatched per-job)")
_PENDING = obs.REGISTRY.gauge(
    "fsm_fusion_pending_waves", "waves currently held in fusion windows")

# Fast-path flag: every engine probe (eval_enabled / dispatch_wave)
# returns after ONE module-global read when the broker is off — the
# contract utils/faults._active and obs._trace_on pin.
_on = False

_lock = threading.Lock()
_broker: Optional["FusionBroker"] = None


def configure(cfg) -> None:
    """Set the process-wide fusion policy (config.set_config owns it,
    like the watchdog and the flight recorder; tests may call directly
    with a config.FusionConfig)."""
    global _on, _broker
    with _lock:
        if cfg is not None and cfg.enabled:
            if _broker is None:
                _broker = FusionBroker()
            _broker.reconfigure(
                window_s=float(cfg.window_ms) / 1000.0,
                max_jobs=int(cfg.max_jobs),
                max_width=int(cfg.max_width),
                dispatch_workers=int(getattr(cfg, "dispatch_workers", 2)))
            _on = True
        else:
            _on = False
            # pending waves drain on the broker thread regardless — a
            # disable can never strand a ticket an engine is waiting on


def eval_enabled() -> bool:
    return _on


def broker() -> Optional["FusionBroker"]:
    return _broker


class EvalWave:
    """One engine dispatch's whole candidate set, handed to the broker.

    Also the engine-side ticket: :meth:`result` blocks until the broker
    resolved it (fused or solo) and returns ``(sups, supxs, report)``
    in the wave's own candidate order, or raises the launch failure.
    """

    __slots__ = ("uid", "priority", "cands", "pools", "p1", "s1",
                 "eval_fn", "put", "cap", "lane", "n_seq", "n_words",
                 "t_submit", "topology_epoch", "_event", "_sups",
                 "_supxs", "_report", "_error")

    def __init__(self, *, uid: str, priority: str, cands, pools,
                 p1, s1, eval_fn, put, cap, lane: int, n_seq: int,
                 n_words: int):
        self.uid = uid
        self.priority = priority
        self.cands = cands
        self.pools = pools
        self.p1 = p1
        self.s1 = s1
        self.eval_fn = eval_fn
        self.put = put
        self.cap = cap
        self.lane = int(lane)
        self.n_seq = int(n_seq)
        self.n_words = int(n_words)
        self.t_submit = time.monotonic()
        # topology epoch at submit (service/meshguard.py, None when the
        # plane is off): the broker re-checks at launch time — a row
        # death between submit and dispatch refuses the wave instead of
        # executing it on dead silicon
        self.topology_epoch = meshguard.current_epoch()
        self._event = threading.Event()
        self._sups = self._supxs = None
        self._report: dict = {}
        self._error: Optional[BaseException] = None

    @property
    def key(self) -> Tuple[int, int]:
        """Fusion key: waves fuse only when the compiled sequence-axis
        geometry matches (the item axis concatenates freely)."""
        return (self.n_seq, self.n_words)

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def resolve(self, sups, supxs, report: dict) -> None:
        self._sups, self._supxs, self._report = sups, supxs, report
        self._event.set()

    def fail(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def result(self):
        """Block until resolved.  Polls the job-control safe point while
        waiting, so a cancel/deadline that lands mid-window aborts the
        job exactly like the engines' own launch-boundary checks."""
        while not self._event.wait(0.05):
            jobctl.check()
        if self._error is not None:
            raise self._error
        return self._sups, self._supxs, self._report


def _mark(uid: str, event: str, **attrs) -> None:
    """Land a point event in a job's trace from a dispatcher thread.
    ``obs.trace_event`` binds to the calling thread's CURRENT span —
    which the fsm-fusion-* threads don't carry outside explicit span
    blocks — so the marker opens a zero-length span on the wave's own
    trace to host it (the ``fusion.joined`` idiom)."""
    with obs.span("fusion.mark", trace_id=uid):
        obs.trace_event(event, **attrs)


class _Group:
    __slots__ = ("waves", "t0")

    def __init__(self):
        self.waves: List[EvalWave] = []
        self.t0 = time.monotonic()


class FusionBroker:
    """The dispatcher: one daemon thread owning the fusion windows.

    Engine threads :meth:`submit` waves and block in
    ``EvalWave.result``; the dispatcher groups same-key waves inside
    the bounded window, decides fuse-vs-separate with the calibrated
    cost model, executes the launches, and demuxes the readback per
    job.  Test hooks: :meth:`hold` / :meth:`release` freeze the window
    so a test can line up a deterministic group; :meth:`drain` blocks
    until nothing is pending or in flight.
    """

    _PREP_CACHE_CAP = 32  # fused-prep LRU entries (device arrays)
    # hard byte budget for the same LRU: entries strong-ref device
    # arrays the engines' eval-width budgets know nothing about, so an
    # entry bound alone could pin many GB of HBM at production prep
    # scale (one 8-job fused pair at the default prewarm envelope is
    # ~1.3 GB); evictions trip on whichever bound is hit first
    _PREP_CACHE_BYTES = 2 << 30

    def __init__(self, window_s: float = 0.004, max_jobs: int = 8,
                 max_width: int = 16384, dispatch_workers: int = 2):
        self.window_s = float(window_s)
        self.max_jobs = int(max_jobs)
        self.max_width = int(max_width)
        self.dispatch_workers = max(1, int(dispatch_workers))
        self._cond = threading.Condition()
        self._groups: Dict[Tuple[int, int], _Group] = {}
        self._busy = 0
        self._held = False
        self._threads: List[threading.Thread] = []
        # one stager per dispatcher thread: XYStager's free lists are
        # not safe under concurrent take(), and per-thread pools cost
        # only a few staging buffers each
        self._tls = threading.local()
        # fused-prep LRU: recurring job groups re-fuse every round, and
        # re-concatenating the same prep stores per round was measured
        # as the broker's dominant overhead.  Entries hold STRONG refs
        # to the source arrays, so an id() key can never be recycled
        # while its entry lives; the LRU bound caps the device memory
        # the cache pins.
        self._prep_cache: "Dict[tuple, tuple]" = {}
        self._prep_order: List[tuple] = []
        self._prep_sizes: Dict[tuple, int] = {}
        self._prep_bytes = 0
        self._prep_lock = threading.Lock()
        self._slock = threading.Lock()  # stats: bumped from dispatcher
        # AND engine threads concurrently; bare dict += would lose counts
        # alongside the actual launch/traffic tally, the broker keeps
        # the SOLO-ALTERNATIVE tally: what the same waves would have
        # dispatched unfused (for fused groups, the per-job plans the
        # cost model compared; for solo waves, identical to the actual).
        # actual vs alternative × the committed cost model is the
        # device-dispatch saving the bench reports — a modeled number
        # on CPU, the real bill on hardware where the device serializes
        # launches.
        self.stats = {"waves": 0, "fused_waves": 0, "solo_waves": 0,
                      "launches": 0, "cross_job_launches": 0,
                      "fused_groups": 0, "rejected_groups": 0,
                      "degraded": 0, "traffic_units": 0,
                      "alt_solo_launches": 0, "alt_solo_units": 0}

    # ------------------------------------------------------------- control

    def reconfigure(self, *, window_s: float, max_jobs: int,
                    max_width: int, dispatch_workers: int = 2) -> None:
        with self._cond:
            self.window_s = window_s
            self.max_jobs = max(1, max_jobs)
            self.max_width = max(32, max_width)
            self.dispatch_workers = max(1, dispatch_workers)
            self._cond.notify_all()

    def _bump(self, **adds) -> None:
        with self._slock:
            for k, v in adds.items():
                self.stats[k] += v

    def hold(self) -> None:
        """Freeze the window (tests): waves accumulate, nothing launches
        until :meth:`release`."""
        with self._cond:
            self._held = True

    def release(self) -> None:
        with self._cond:
            self._held = False
            self._cond.notify_all()

    def _stager(self) -> RB.XYStager:
        st = getattr(self._tls, "stager", None)
        if st is None:
            st = self._tls.stager = RB.XYStager()
        return st

    def pending(self) -> int:
        with self._cond:
            return sum(len(g.waves) for g in self._groups.values())

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait until no wave is pending or in flight (tests)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._cond:
                if not self._held and self._busy == 0 and not any(
                        g.waves for g in self._groups.values()):
                    return True
            time.sleep(0.005)
        return False

    # -------------------------------------------------------------- submit

    def submit(self, wave: EvalWave) -> None:
        with self._cond:
            # dispatcher POOL, not a single thread: groups with
            # different membership are independent device work, and one
            # serialized dispatcher was measured to forfeit exactly the
            # concurrency the Miner's worker pool feeds it (a group
            # blocked in readback must not stall the next matured
            # window).  Threads are spawned lazily up to the configured
            # count; the shared pick loop hands each matured group to
            # exactly one of them.
            while len(self._threads) < self.dispatch_workers:
                t = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"fsm-fusion-{len(self._threads)}")
                self._threads.append(t)
                t.start()
            g = self._groups.get(wave.key)
            if g is None or not g.waves:
                g = self._groups[wave.key] = _Group()
            g.waves.append(wave)
            self._bump(waves=1)
            _PENDING.set(sum(len(x.waves) for x in self._groups.values()))
            self._cond.notify_all()

    # ---------------------------------------------------------- dispatcher

    def _ready_key(self, now: float):
        """(key, deadline_hint): the first window due to launch, else
        (None, soonest expiry).  A high-priority wave makes its group
        due IMMEDIATELY — it fuses with whatever is already pending but
        never waits for more fill."""
        soonest: Optional[float] = None
        for key, g in self._groups.items():
            if not g.waves:
                continue
            if any(w.priority == "high" for w in g.waves):
                return key, None
            if len(g.waves) >= self.max_jobs:
                return key, None
            if sum(len(w.cands) for w in g.waves) >= self.max_width:
                return key, None
            expiry = g.t0 + self.window_s
            if now >= expiry:
                return key, None
            soonest = expiry if soonest is None else min(soonest, expiry)
        return None, soonest

    def _loop(self) -> None:
        while True:
            with self._cond:
                group = None
                while group is None:
                    if self._held:
                        self._cond.wait()
                        continue
                    now = time.monotonic()
                    key, soonest = self._ready_key(now)
                    if key is not None:
                        group = self._groups.pop(key)
                        self._busy += 1
                        _PENDING.set(sum(len(x.waves)
                                         for x in self._groups.values()))
                        break
                    self._cond.wait(None if soonest is None
                                    else max(0.0, soonest - now))
            try:
                self._run_group(group)
            finally:
                with self._cond:
                    self._busy -= 1
                    self._cond.notify_all()

    # ------------------------------------------------------------ execution

    def _run_group(self, group: _Group) -> None:
        waves = group.waves
        wait_s = time.monotonic() - group.t0
        _WINDOW_WAIT.observe(wait_s)
        # topology-epoch fence (service/meshguard.py): a wave planned
        # against a mesh a row death has since invalidated is REFUSED
        # here — failed upward so the orchestrator re-plans onto the
        # survivors, never degraded to a solo launch on dead silicon
        live = []
        for w in waves:
            try:
                meshguard.check_epoch(w.topology_epoch)
            except meshguard.StaleTopology as exc:
                _mark(w.uid, "fusion_stale_epoch", error=str(exc))
                w.fail(exc)
                continue
            live.append(w)
        waves = live
        if not waves:
            return
        try:
            faults.fault_site("fusion.dispatch", point="window",
                              jobs=str(len(waves)))
            if len(waves) >= 2:
                fused_plan, fpools, job_of, slices, offsets = \
                    self._fused_plan(waves)
                alt = self._solo_alternative(waves)
                if self._fusion_wins(waves, fused_plan, offsets, alt):
                    fcands = self._fused_cands(waves, offsets[0])
                    self._launch_fused(waves, fused_plan, fcands,
                                       slices, offsets, wait_s)
                    # alt tally lands only once the fused launch did:
                    # a degraded group re-dispatches through
                    # _launch_solo, which tallies its own alternative —
                    # pre-bumping here would double it and overstate
                    # the modeled saving
                    self._bump(alt_solo_launches=alt[0],
                               alt_solo_units=alt[1])
                    return
                self._bump(rejected_groups=1)
                _REJECTED_TOTAL.inc()
            for w in waves:
                self._launch_solo(w, wait_s)
        except BaseException as exc:
            if isinstance(exc, watchdog.WatchdogTimeout):
                # a watchdog timeout is not a broker fault: the DEVICE
                # is suspect, and re-dispatching every wave solo would
                # run N more unguarded-dispatch launches on a possibly
                # wedged backend, each blocking a dispatcher for its
                # own full deadline.  Fail every unresolved wave upward
                # instead — job supervision owns the re-run (same
                # invariant as TsrTPU._resolve_eval's direct path).
                log_event("fusion_watchdog_timeout", jobs=len(waves),
                          error=str(exc))
                for w in waves:
                    if not w.done:
                        _mark(w.uid, "fusion_watchdog_timeout",
                              jobs=len(waves), error=str(exc))
                        w.fail(exc)
                return
            # DEGRADE, never lose a wave: whatever failed — the chaos
            # site, a fused concat, a launch — every unresolved wave is
            # re-dispatched per-job; a wave whose own solo dispatch
            # also fails gets the failure on its ticket (job
            # supervision owns the retry from there).
            self._bump(degraded=1)
            _DEGRADED_TOTAL.inc()
            log_event("fusion_degraded", jobs=len(waves),
                      error=f"{type(exc).__name__}: {exc}")
            for wi, w in enumerate(waves):
                if w.done:
                    continue
                _mark(w.uid, "fusion_degraded", jobs=len(waves),
                      error=f"{type(exc).__name__}: {exc}")
                try:
                    self._launch_solo(w, wait_s)
                except watchdog.WatchdogTimeout as solo_exc:
                    # same posture as the pre-degrade handler above: a
                    # timeout mid-degrade means the device is suspect,
                    # so the REMAINING waves fail upward too instead of
                    # each blocking a dispatcher for its own deadline
                    log_event("fusion_watchdog_timeout",
                              jobs=len(waves) - wi, error=str(solo_exc))
                    for rest in waves[wi:]:
                        if not rest.done:
                            _mark(rest.uid, "fusion_watchdog_timeout",
                                  jobs=len(waves) - wi,
                                  error=str(solo_exc))
                            rest.fail(solo_exc)
                    return
                except BaseException as solo_exc:
                    w.fail(solo_exc)

    def _fused_plan(self, waves: List[EvalWave]):
        """Merge the group's pools into one fused candidate space.

        Returns (plan, fused pools, job_of, per-wave row slices, prep
        offsets).  Prep stores dedup by identity — a job's pipelined
        waves share one prep, so fusing them costs no extra item rows.
        The shifted candidate tuples are NOT built here — see
        :meth:`_fused_cands`."""
        offsets: Dict[int, int] = {}
        uniq: List[Tuple[object, object]] = []
        off = 0
        for w in waves:
            k = id(w.p1)
            if k not in offsets:
                offsets[k] = off
                uniq.append((w.p1, w.s1))
                off += int(w.p1.shape[0])
        fpools: Dict[int, List[int]] = {}
        jobs: List[int] = []
        uid_ix: Dict[str, int] = {}  # lane tags carry JOB identity, not
        # wave identity: one job's pipelined waves fusing together is
        # intra-job batching, and must not read as a cross-job launch
        slices: List[Tuple[int, int]] = []
        base = 0
        for w in waves:
            for km, rows in w.pools.items():
                fpools.setdefault(int(km), []).extend(
                    r + base for r in rows)
            jid = uid_ix.setdefault(w.uid, len(uid_ix))
            jobs.extend([jid] * len(w.cands))
            slices.append((base, base + len(w.cands)))
            base += len(w.cands)
        lane = max(w.lane for w in waves)
        cap = lambda km: min(self.max_width,
                             min(int(w.cap(km)) for w in waves))
        w0 = waves[0]
        overhead = RB.overhead_units(w0.n_seq, w0.n_words)
        plan = RB.plan_launches(fpools, cap=cap, lane=lane,
                                overhead=overhead,
                                job_of=jobs.__getitem__, record=False)
        return plan, fpools, jobs.__getitem__, slices, \
            (offsets, uniq, off)

    @staticmethod
    def _fused_cands(waves, prep_offsets):
        """Index-shift every wave's candidate tuples into the fused
        prep's row space.  Deferred until the cost model has chosen
        fusion: this is the only per-candidate Python work in the
        group path, and a rejected group must not pay it."""
        fcands: List[Tuple[Tuple[int, ...], Tuple[int, ...]]] = []
        for w in waves:
            o = prep_offsets[id(w.p1)]
            for x, y in w.cands:
                fcands.append((tuple(i + o for i in x),
                               tuple(j + o for j in y)))
        return fcands

    def _solo_alternative(self, waves) -> Tuple[int, int]:
        """(launches, traffic units) the group's waves would dispatch
        UNFUSED — the cost model's comparison branch, also tallied in
        ``alt_solo_*`` so actual-vs-alternative × the committed cost
        model gives the broker's device-dispatch saving."""
        w0 = waves[0]
        overhead = RB.overhead_units(w0.n_seq, w0.n_words)
        solo_units = solo_launches = 0
        for w in waves:
            plan = RB.plan_launches(w.pools, cap=w.cap, lane=w.lane,
                                    overhead=overhead, record=False)
            solo_launches += len(plan)
            solo_units += sum(L.traffic_units for L in plan)
        return solo_launches, solo_units

    def _fusion_wins(self, waves, fused_plan, offsets, alt) -> bool:
        """The fusion decision: fused plan + prep-concat cost vs the
        per-job plans (``alt``, computed once by the caller), all in
        the packer's own calibrated units."""
        w0 = waves[0]
        overhead = RB.overhead_units(w0.n_seq, w0.n_words)
        solo_launches, solo_units = alt
        fused_units = sum(L.traffic_units for L in fused_plan)
        # the prep concat streams total_m item rows once — priced as
        # total_m lane-units, the same currency as pad and dispatch
        _, uniq, total_m = offsets
        concat_units = total_m if len(uniq) > 1 else 0
        return (fused_units + len(fused_plan) * overhead + concat_units
                <= solo_units + solo_launches * overhead)

    def _launch_fused(self, waves, plan, fcands, slices, offsets,
                      wait_s: float) -> None:
        prep_offsets, uniq, total_m = offsets
        w0 = waves[0]
        m_pad = RB.next_pow2(max(1, total_m))
        p1f, s1f = self._fused_preps(uniq, m_pad, total_m)
        # span host for record_plan's plan_launches trace event — a
        # dispatcher thread has no current span for it to bind to
        with obs.span("fusion.plan", trace_id=w0.uid, jobs=len(waves)):
            RB.record_plan(plan)
        arr, cols, est_s, measured_s = self._execute(
            plan, fcands, p1f, s1f, w0, trace_uid=w0.uid,
            fused=True, m_pad=m_pad)
        self._bump(fused_groups=1,
                   traffic_units=sum(L.traffic_units for L in plan))
        self._attribute_fused(waves, plan, est_s, measured_s)
        cross = sum(1 for L in plan if L.cross_job)
        report_base = {
            "fused_jobs": len(waves), "launches": len(plan),
            "cross_job_launches": cross,
            "traffic_units": sum(L.traffic_units for L in plan),
            "window_wait_s": round(wait_s, 6), "m_pad": m_pad,
        }
        for wi, w in enumerate(waves):
            lo, hi = slices[wi]
            idx = cols[lo:hi]
            w.resolve(arr[0, idx].astype(np.int64),
                      arr[1, idx].astype(np.int64), dict(report_base))
            self._bump(fused_waves=1)
            _WAVES_TOTAL.inc(engine="tsr", fused="true")
            if wi > 0:
                # a zero-length marker span in every rider's own trace:
                # the fused launch spans live on the leader's
                with obs.span("fusion.joined", trace_id=w.uid,
                              leader=w0.uid, jobs=len(waves),
                              launches=len(plan)):
                    pass

    @staticmethod
    def _attribute_fused(waves, plan, est_s: float,
                         measured_s: float) -> None:
        """Demux a fused plan's device cost back to the jobs that
        occupied it, by LANE SHARE (the per-lane ``Launch.jobs`` tags
        the planner packed with), under the conservation invariant:
        per-job launches sum to ``len(plan)`` and per-job traffic units
        sum to the plan's total, EXACTLY (largest-remainder integer
        apportionment; pad lanes are charged proportionally).  Seconds
        split proportional to each job's traffic share — floats carry
        no exactness guarantee and none is claimed."""
        if usage.get() is None:
            return
        # rebuild the jid -> uid map: _fused_plan assigns jids by FIRST
        # APPEARANCE of each uid in wave order (uid_ix.setdefault)
        uid_of: Dict[int, str] = {}
        order: Dict[str, int] = {}
        for w in waves:
            jid = order.setdefault(w.uid, len(order))
            uid_of.setdefault(jid, w.uid)
        per: Dict[str, List[int]] = {}  # uid -> [launches, traffic]
        total_traffic = 0
        for L in plan:
            total_traffic += L.traffic_units
            if not L.jobs:
                tally = per.setdefault(waves[0].uid, [0, 0])
                tally[0] += 1
                tally[1] += L.traffic_units
                continue
            counts: Dict[int, int] = {}
            for j in L.jobs:
                counts[j] = counts.get(j, 0) + 1
            jids = sorted(counts)
            weights = [counts[j] for j in jids]
            one = usage.split_integral(1, weights)
            traffic = usage.split_integral(L.traffic_units, weights)
            for i, jid in enumerate(jids):
                tally = per.setdefault(uid_of.get(jid, waves[0].uid),
                                       [0, 0])
                tally[0] += one[i]
                tally[1] += traffic[i]
        for uid, (n_launch, n_traffic) in per.items():
            share = (n_traffic / total_traffic if total_traffic > 0
                     else 1.0 / max(1, len(per)))
            usage.deposit(uid, launches=n_launch,
                          traffic_units=n_traffic,
                          seconds_est=est_s * share,
                          seconds_measured=measured_s * share)

    def _fused_preps(self, uniq, m_pad: int, total_m: int):
        """LRU-cached :func:`_fuse_preps`: a group of pipelining jobs
        re-forms every candidate round, and re-concatenating the same
        prep stores per round was the broker's single largest measured
        overhead.  The key is the (ordered) source identities + the pad
        bucket; each entry strong-refs its sources so a cached id can
        never be a recycled pointer."""
        key = (m_pad,) + tuple(id(p) for p, _ in uniq)
        with self._prep_lock:
            hit = self._prep_cache.get(key)
            if hit is not None:
                self._prep_order.remove(key)
                self._prep_order.append(key)
                return hit[1], hit[2]
        fused = _fuse_preps(uniq, m_pad, total_m)
        # BYTE-bounded, not just entry-bounded: at production prep
        # scale one fused pair is hundreds of MB of HBM the engines'
        # eval budgets know nothing about, so the cache must never pin
        # more than its budget (an entry bigger than half of it is not
        # cached at all — recurring giants would just thrash the rest).
        # An entry's pin is the fused pair PLUS the source preps it
        # strong-refs for key safety — once the owning jobs finish, the
        # cache is what keeps those alive, so they bill against the
        # budget too.
        nbytes = (int(getattr(fused[0], "nbytes", 0))
                  + int(getattr(fused[1], "nbytes", 0))
                  + sum(int(getattr(a, "nbytes", 0))
                        for pair in uniq for a in pair))
        with self._prep_lock:
            if (key not in self._prep_cache
                    and nbytes <= self._PREP_CACHE_BYTES // 2):
                self._prep_cache[key] = (list(uniq),) + fused
                self._prep_order.append(key)
                self._prep_sizes[key] = nbytes
                self._prep_bytes += nbytes
                while (self._prep_order
                       and (len(self._prep_order) > self._PREP_CACHE_CAP
                            or self._prep_bytes > self._PREP_CACHE_BYTES)):
                    old = self._prep_order.pop(0)
                    del self._prep_cache[old]
                    self._prep_bytes -= self._prep_sizes.pop(old)
        return fused

    def _launch_solo(self, w: EvalWave, wait_s: float) -> None:
        overhead = RB.overhead_units(w.n_seq, w.n_words)
        # span host for the plan's plan_launches trace event (see
        # _launch_fused) — solo planning records itself
        with obs.span("fusion.plan", trace_id=w.uid, jobs=1):
            plan = RB.plan_launches(w.pools, cap=w.cap, lane=w.lane,
                                    overhead=overhead)
        units = sum(L.traffic_units for L in plan)
        self._bump(traffic_units=units, alt_solo_launches=len(plan),
                   alt_solo_units=units)
        arr, cols, est_s, measured_s = self._execute(
            plan, w.cands, w.p1, w.s1, w, trace_uid=w.uid, fused=False)
        # whole-plan attribution: a solo dispatch (window of one, or a
        # degraded re-dispatch) has exactly one owning job
        usage.deposit(w.uid, launches=len(plan), traffic_units=units,
                      seconds_est=est_s, seconds_measured=measured_s)
        w.resolve(arr[0, cols].astype(np.int64),
                  arr[1, cols].astype(np.int64),
                  {"fused_jobs": 1, "launches": len(plan),
                   "cross_job_launches": 0, "traffic_units": units,
                   "window_wait_s": round(wait_s, 6)})
        self._bump(solo_waves=1)
        _WAVES_TOTAL.inc(engine="tsr", fused="false")

    def _execute(self, plan, cands, p1, s1, w0: EvalWave, *,
                 trace_uid: str, fused: bool,
                 m_pad: Optional[int] = None):
        """Dispatch a plan against one prep pair and read it back —
        the broker-side twin of TsrTPU._dispatch_eval_inner's jnp
        branch, shared by the fused and solo paths so they cannot
        drift."""
        parts: List[object] = []
        cols = np.empty(len(cands), np.int64)
        bufs: List[np.ndarray] = []
        base = 0
        for L in plan:
            with obs.span("fusion.launch", trace_id=trace_uid, km=L.km,
                          width=L.width, jobs=L.n_jobs, fused=fused,
                          predicted_s=round(RB.estimate_seconds(
                              L.traffic_units, 1, w0.n_seq, w0.n_words),
                              6)):
                # same guard the direct jnp path wears (tsr.py): with
                # fusion on this IS the real dispatch call site, and a
                # device.dispatch drill must fire here, not vacuously
                faults.fault_site("device.dispatch", point="jnp",
                                  km=str(L.km), width=str(L.width))
                fn = w0.eval_fn(L.km)
                xy = self._stager().take(L, cands)
                bufs.append(xy)
                cols[L.rows] = base + np.arange(len(L.rows))
                base += L.width
                parts.append(fn(p1, s1, w0.put(xy)))
            self._bump(launches=1,
                       cross_job_launches=1 if L.cross_job else 0)
            _LAUNCHES_TOTAL.inc(cross_job=str(L.cross_job).lower())
            _JOBS_PER_LAUNCH.observe(L.n_jobs)
            if fused and m_pad is not None:
                shapes.record(shapes.key_tsr_fused(
                    w0.n_seq, w0.n_words, m_pad, L.km, L.width))
            else:
                shapes.record(shapes.key_tsr_eval(
                    w0.n_seq, w0.n_words, L.km, L.width))
        if len(parts) == 1:
            out = parts[0]
        else:
            import jax.numpy as jnp

            out = jnp.concatenate(parts, axis=1)
        try:
            out.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass
        est_s = RB.estimate_seconds(
            sum(L.traffic_units for L in plan), len(plan), w0.n_seq,
            w0.n_words)
        t0 = time.monotonic()
        def read():
            faults.fault_site("device.dispatch", point="readback")
            return np.asarray(out)

        with obs.span("fusion.readback", trace_id=trace_uid,
                      predicted_s=round(est_s, 6)) as sp:
            arr = watchdog.run_with_deadline(
                read, watchdog.deadline_s(est_s),
                site="fusion.readback")
            measured_s = time.monotonic() - t0
            sp.set(measured_s=round(measured_s, 6))
            obs.observe_costmodel(
                est_s, measured_s,
                family=("tsr-fused" if fused and m_pad is not None
                        else "tsr-eval"))
        self._stager().release(bufs)
        return arr, cols, est_s, measured_s


def _fuse_preps(uniq, m_pad: int, total_m: int):
    """Concatenate the group's distinct prep pairs along the item axis
    and zero-pad to the pow2 bucket.  Zero rows support nothing and no
    fused candidate ever indexes them, so padding is semantically
    inert; the pow2 bucket is what keeps the fused eval programs a
    finite, prewarm-enumerable ladder (``tsr-fused`` keys)."""
    import jax.numpy as jnp

    p_parts = [p for p, _ in uniq]
    s_parts = [s for _, s in uniq]
    if m_pad > total_m:
        shape = (m_pad - total_m,) + tuple(p_parts[0].shape[1:])
        pad = jnp.zeros(shape, jnp.uint32)
        p_parts = p_parts + [pad]
        s_parts = s_parts + [pad]
    if len(p_parts) == 1:
        return p_parts[0], s_parts[0]
    return (jnp.concatenate(p_parts, axis=0),
            jnp.concatenate(s_parts, axis=0))


# ---------------------------------------------------------------------------
# Engine entry points
# ---------------------------------------------------------------------------


def submit_eval(*, cands, pools, p1, s1, eval_fn, put, cap, lane: int,
                n_seq: int, n_words: int,
                priority: Optional[str] = None,
                uid: Optional[str] = None) -> Optional[EvalWave]:
    """Hand one dispatch's candidate set to the fusion broker.  Returns
    the wave ticket, or None when the broker is off (the engine then
    dispatches directly — one global read on that path).  Job identity
    and admission class default to the job-control context the Miner
    binds around each run."""
    if not _on:
        return None
    b = _broker
    if b is None:  # configure race: treat as off
        return None
    if priority is None or uid is None:
        ctl = jobctl.current()
        if priority is None:
            priority = ctl.priority if ctl is not None else "normal"
        if uid is None:
            if ctl is not None:
                uid = ctl.uid
            else:
                # ENGINE identity, not wave identity: outside a jobctl
                # context (library use) one mine's pipelined waves must
                # still share a job tag, or their fusion would read as
                # cross-job in every stat and lane label
                anchor = getattr(eval_fn, "__self__", None)
                uid = f"eng-{id(anchor if anchor is not None else p1):x}"
    wave = EvalWave(uid=uid, priority=priority, cands=cands, pools=pools,
                    p1=p1, s1=s1, eval_fn=eval_fn, put=put, cap=cap,
                    lane=lane, n_seq=n_seq, n_words=n_words)
    b.submit(wave)
    return wave


def dispatch_wave(engine: str, fn: Callable, **ctx):
    """Route an unfusable device wave (the queue engine's whole-mine or
    segment dispatch) through the broker's accounting/fault surface.
    One global read when the broker is off.  An armed
    ``fusion.dispatch`` fault DEGRADES to a direct dispatch — broker
    failure must never lose a wave.  A ``topology_epoch`` in ``ctx``
    is the meshguard fence: a wave planned against a stale mesh is
    REFUSED (StaleTopology) — that one failure mode must never degrade
    to a direct dispatch on dead silicon."""
    meshguard.check_epoch(ctx.pop("topology_epoch", None))
    if not _on:
        return fn()
    _WAVES_TOTAL.inc(engine=engine, fused="false")
    if _broker is not None:
        _broker._bump(waves=1, solo_waves=1)
    try:
        faults.fault_site("fusion.dispatch", engine=engine, **ctx)
    except faults.FaultInjected as exc:
        _DEGRADED_TOTAL.inc()
        if _broker is not None:
            _broker._bump(degraded=1)
        log_event("fusion_degraded", engine=engine, error=str(exc))
        obs.trace_event("fusion_degraded", engine=engine, error=str(exc))
        return fn()
    with obs.span("fusion.wave", engine=engine, **ctx):
        return fn()
