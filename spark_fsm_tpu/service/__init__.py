"""Service shell — the reference's orchestration layers, TPU-native host side.

Recreates the observable contracts of the reference's L5/L6 stack
(SURVEY.md sec 1: Spray REST API over an Akka actor system) without
translating it: a stdlib HTTP front end over thread-based actor workers,
an in-process Redis-compatible result/status store, pluggable sequence
sources, and an algorithm plugin registry selected by the request's
``algorithm`` parameter (the ``FSMActor``/``AlgorithmPlugin`` boundary
named in BASELINE.json's north star).
"""

from spark_fsm_tpu.service.model import (  # noqa: F401
    ServiceRequest,
    ServiceResponse,
    Status,
)
from spark_fsm_tpu.service.plugins import ALGORITHMS, AlgorithmPlugin  # noqa: F401
from spark_fsm_tpu.service.store import ResultStore  # noqa: F401
