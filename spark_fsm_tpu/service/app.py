"""HTTP front end — the reference's REST surface (SURVEY.md sec 1 L6).

Endpoints (POST, form- or JSON-encoded parameters):

  /train              — start a mining job; returns uid + 'started'.
                        Admission control: a full [service] queue_depth
                        sheds with 429 + Retry-After (cost-model
                        estimate of the queued work); resubmitting a
                        LIVE uid is 409; 'priority' (high/normal/low)
                        classes the queue; 'deadline_s' stamps an abort
                        budget spent by queue wait + mining
  /status/{uid}       — job lifecycle status (also /status?uid=...)
  /get/patterns       — mined patterns for uid (when finished)
  /get/rules          — mined rules, optional antecedent/consequent filter
  /get/prediction     — ranked next-item candidates from mined rules
                        (items=observed ids; best rule per candidate)
  /track/{topic}      — ingest one event for later TRACKED-source mining
  /stream/{topic}     — push an SPMF micro-batch into the topic's sliding
                        window; the window is re-mined and results served
                        under uid "stream:{topic}" (eval config #5)
  /register/{topic}   — register a field spec
  /index/{topic}      — alias of register (reference keeps both)
  /admin/ping         — liveness; /admin/algorithms — plugin listing;
  /admin/stats        — service metrics (job counters, backend, devices,
                        per-cache counters, last prewarm walls);
  /admin/config       — the active boot config;
  /admin/prewarm      — AOT-compile the declared workload envelope NOW
                        (params override the boot [prewarm] section);
  /admin/shapes       — enumerated vs runtime-recorded shape keys + drift;
  /admin/faults       — chaos lab: arm/disarm/list fault-injection sites
                        (REFUSED unless the boot config sets
                        ``fault_injection = true``);
  /admin/health       — per-subsystem recovery counters: armed faults,
                        I/O retry/backoff, dispatch watchdog, devcache
                        circuit breakers, consumer leaked threads;
  /metrics            — the unified registry in Prometheus text
                        exposition format (GET; utils/obs.REGISTRY —
                        point a scrape job here);
  /admin/trace/{job}  — flight-recorder span dump for a job uid (JSON;
                        requires [observability] trace = true).  In
                        cluster mode the response is the MERGED
                        cross-replica timeline: the durable trace spine
                        (fsm:trace:{uid}, written through the fenced
                        path) plus this replica's local ring, ordered
                        by wall time — after a failover the survivor
                        serves admission-on-A → adoption-on-B end to
                        end (service/obsplane.py);
  /admin/trace/last   — the most recently touched trace;
  /admin/cluster      — aggregated cluster view from the lease
                        heartbeats' piggybacked metric snapshots:
                        per-replica rows + totals (queued, in-flight,
                        free, leases held, sheds, lease churn) — same
                        answer from ANY replica;
  /admin/slo          — per-priority p50/p95/p99 of end-to-end job
                        latency (submit → durable result) with
                        queue-wait/execution split, over a sliding
                        window ([observability] slo_window_s) — the
                        service-side counterpart of bench_throughput;
  /admin/rescache     — result-reuse tier stats (service/resultcache.py):
                        hit/coalesce/dominated-serve counters, resident
                        cache bytes, in-flight coalescing registry;
  /admin/autoscale    — elastic control plane (service/autoscale.py):
                        leader, last evaluation signals, the published
                        desired-replica record and decision log;
                        {"enabled": false} when [autoscale] is off;
  /admin/integrity    — durable-state integrity plane (service/
                        integrity.py): verify-on-read counters per
                        surface, background scrubber stats, and the
                        current quarantine listing (fsm:quarantine:*)
                        — the bitrot runbook's one-stop read;
  /admin/usage        — resource attribution plane (service/usage.py):
                        per-tenant device-cost rollups (estimated +
                        measured device-seconds, launches, traffic
                        units, readback bytes), avoided-cost credits
                        from result-cache serves, top-N jobs by cost,
                        and the durable fsm:usage:{tenant} ledger rows;
                        {"enabled": false} when [usage] is off;
  /admin/quarantine   — crash-loop quarantine ledger (service/
                        meshguard.py): lists every fsm:quarantine:*
                        record (poison AND integrity surfaces);
                        ``action=release&uid=...`` deletes a poison
                        record so the uid may be resubmitted (404 when
                        no record exists) — the operator end of the
                        [cluster] max_adoptions POISON: terminal;
  /admin/drain        — drive the scale-down drain protocol NOW (stop
                        admitting → peers steal the queue → leases
                        released); ``exit=1`` also stops the server
                        once the drain completes — the forced-scale-
                        down lever the autoscale smoke uses;
  /admin/cancel/{uid} — abort a live (queued or running) train job at
                        its next safe point; 404 when no live job owns
                        the uid

At boot, main() runs the crash-restart recovery pass BEFORE accepting
traffic: journal intent records left by a dead incarnation are healed —
checkpointed jobs resubmitted (they resume from their persisted
frontier), everything else marked with a durable "interrupted by
restart" failure (service/actors.recover_orphans).

Runs on the stdlib ThreadingHTTPServer: the service layer is deliberately
dependency-free; heavy lifting happens in the engines (device) behind the
Miner worker thread.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qsl, urlsplit

from spark_fsm_tpu import config as cfgmod
from spark_fsm_tpu.service import plugins, usage
from spark_fsm_tpu.utils import obs
from spark_fsm_tpu.service.actors import Master
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.service.store import RedisResultStore, ResultStore


def _parse_body(handler: BaseHTTPRequestHandler) -> dict:
    length = int(handler.headers.get("Content-Length") or 0)
    raw = handler.rfile.read(length) if length else b""
    ctype = (handler.headers.get("Content-Type") or "").split(";")[0].strip()
    if ctype == "application/json" and raw:
        obj = json.loads(raw.decode("utf-8"))
        if not isinstance(obj, dict):
            raise ValueError("JSON body must be an object")
        return {str(k): str(v) for k, v in obj.items()}
    return {k: v for k, v in parse_qsl(raw.decode("utf-8"))}


def _route(path: str) -> Tuple[str, str]:
    parts = [p for p in path.split("/") if p]
    head = parts[0] if parts else ""
    tail = "/".join(parts[1:]) if len(parts) > 1 else ""
    return head, tail


class FsmHandler(BaseHTTPRequestHandler):
    master: Master  # set by make_server

    def log_message(self, fmt: str, *args) -> None:  # quiet by default
        pass

    def _send(self, code: int, payload: str,
              content_type: str = "application/json",
              headers: Optional[dict] = None) -> None:
        body = payload.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _metrics(self) -> None:
        # Prometheus text exposition of the whole registry (metrics are
        # ALWAYS on — a scrape must work whether or not tracing is)
        try:
            self._send(200, obs.REGISTRY.render_prometheus(),
                       content_type="text/plain; version=0.0.4; "
                                    "charset=utf-8")
        except Exception as exc:
            self._send(500, json.dumps({"status": "failure",
                                        "error": str(exc)}))

    def do_POST(self) -> None:  # noqa: N802 (stdlib naming)
        try:
            url = urlsplit(self.path)
            head, tail = _route(url.path)
            data = {k: v for k, v in parse_qsl(url.query)}
            data.update(_parse_body(self))
        except Exception as exc:
            self._send(400, json.dumps({"status": "failure", "error": str(exc)}))
            return

        if head == "metrics":
            self._metrics()
            return
        if head == "admin":
            self._admin(tail, data)
            return
        if head not in ("train", "status", "get", "track", "register",
                        "index", "stream", "predict"):
            self._send(404, json.dumps({"status": "failure",
                                        "error": f"unknown endpoint /{head}"}))
            return
        if head == "status" and tail and "uid" not in data:
            data["uid"] = tail  # /status/{uid}
        if head == "predict" and tail and "uid" not in data:
            data["uid"] = tail  # /predict/{uid}
        task = head if head in ("train", "status", "predict") \
            else f"{head}:{tail}"
        req = ServiceRequest(service="fsm", task=task, data=data)
        try:
            resp = self.master.handle(req)
        except Exception as exc:  # worker bug -> failure envelope, not a
            self._send(400, json.dumps({       # dropped connection
                "service": "fsm", "task": task,
                "data": {"uid": req.uid, "error": str(exc)},
                "status": "failure"}))
            return
        # overload/conflict mapping: the Master stamps the HTTP status it
        # wants (429 shed / 409 live-uid conflict) into the envelope —
        # popped here so the JSON body stays protocol-neutral; a 429
        # carries Retry-After from the cost-model estimate of queued work
        code = int(resp.data.pop("http_status", 200))
        headers = None
        if code == 429 and resp.data.get("retry_after_s"):
            headers = {"Retry-After": resp.data["retry_after_s"]}
        self._send(code, resp.to_json(), headers=headers)

    def do_GET(self) -> None:  # noqa: N802
        # GET convenience mirrors POST for read-only endpoints.
        url = urlsplit(self.path)
        head, _ = _route(url.path)
        if head in ("status", "get", "admin", "metrics"):
            self.do_POST()
        else:
            self._send(405, json.dumps({"status": "failure",
                                        "error": "use POST"}))

    def _admin(self, task: str, data: Optional[dict] = None) -> None:
        try:
            if task == "ping":
                self._send(200, json.dumps({"status": "up"}))
            elif task == "algorithms":
                self._send(200, json.dumps(sorted(plugins.ALGORITHMS)))
            elif task == "stats":
                self._send(200, json.dumps(service_stats(self.master)))
            elif task == "config":
                self._send(200, json.dumps(
                    dataclasses.asdict(cfgmod.get_config())))
            elif task == "prewarm":
                # AOT-compile the declared workload envelope NOW (request
                # params override the boot [prewarm] section field-by-
                # field) — synchronous on purpose: the caller is an
                # operator/boot hook who wants the compiles PAID before
                # traffic lands, and the report is per-key compile walls
                from spark_fsm_tpu.service import prewarm

                spec = prewarm.spec_from_params(
                    data or {}, cfgmod.get_config().prewarm)
                report = prewarm.run(
                    spec, mesh=cfgmod.get_mesh(),
                    engine_kwargs=cfgmod.engine_kwargs(
                        "pool_bytes", "node_batch", "pipeline_depth",
                        "chunk", "recompute_chunk"))
                self._send(200, json.dumps(report))
            elif task == "faults":
                # chaos lab: gated on the BOOT config (not a request
                # param) so a production deployment cannot be armed by
                # anyone who can reach the admin port
                from spark_fsm_tpu.utils import faults

                if not cfgmod.get_config().fault_injection:
                    self._send(403, json.dumps({
                        "status": "failure",
                        "error": "fault injection disabled (set "
                                 "fault_injection = true in the boot "
                                 "config to open the chaos lab)"}))
                    return
                d = data or {}
                action = d.get("action", "list")
                if action == "arm":
                    kw = {}
                    for name, conv in (("nth", int), ("every", int),
                                       ("p", float), ("seed", int),
                                       ("times", int), ("delay_s", float)):
                        if d.get(name) not in (None, ""):
                            kw[name] = conv(d[name])
                    if d.get("exc"):
                        kw["exc"] = d["exc"]
                    if d.get("match"):
                        kw["match"] = d["match"]
                    faults.arm(d["site"], **kw)
                elif action == "disarm":
                    faults.disarm(d.get("site"))
                elif action != "list":
                    raise ValueError(f"unknown faults action {action!r} "
                                     "(arm/disarm/list)")
                self._send(200, json.dumps({
                    "armed": faults.armed(),
                    "counters": faults.counters()}))
            elif task == "health":
                self._send(200, json.dumps(health_report(self.master)))
            elif task == "cancel" or task.startswith("cancel/"):
                # /admin/cancel/{uid} (uid may contain slashes — keep the
                # whole tail; /admin/cancel?uid=... works too): flag a
                # live job for abort at its next safe point
                _, _, uid = task.partition("/")
                uid = uid or (data or {}).get("uid", "")
                if not uid:
                    self._send(400, json.dumps({
                        "status": "failure",
                        "error": "cancel needs a uid: /admin/cancel/{uid}"}))
                    return
                was = self.master.cancel(uid)
                if was is None:
                    self._send(404, json.dumps({
                        "status": "failure",
                        "error": f"no live (queued or running) job owns "
                                 f"uid {uid!r}"}))
                    return
                self._send(200, json.dumps(
                    {"status": "cancelling", "uid": uid, "was": was}))
            elif task == "trace" or task.startswith("trace/"):
                # read-only flight-recorder dumps: /admin/trace/{job_id}
                # (uid may itself contain slashes — keep the whole tail),
                # /admin/trace/last, bare /admin/trace lists trace ids
                from spark_fsm_tpu.service import obsplane

                _, _, tid = task.partition("/")
                if not tid:
                    self._send(200, json.dumps({
                        "enabled": obs.tracing_enabled(),
                        "traces": obs.trace_ids(),
                        "last": obs.last_trace_id(),
                        **obs.recorder_stats()}))
                    return
                if tid == "last":
                    tid = obs.last_trace_id() or ""
                dump = obs.trace_dump(tid) if tid else None
                mgr = self.master.miner._lease
                if mgr is not None and tid:
                    # cluster mode: merge the durable spine with the
                    # local ring — after a failover THIS replica can
                    # serve the dead owner's spans too
                    p = obsplane.plane()
                    merged = obsplane.merged_timeline(
                        self.master.store, tid, dump,
                        replica_id=mgr.replica_id,
                        boot_id=p.boot_id if p is not None else None)
                    if merged is not None and (merged["spans"] or dump):
                        dump = merged
                if dump is None:
                    self._send(404, json.dumps({
                        "status": "failure",
                        "error": (f"no trace for {tid!r}"
                                  if obs.tracing_enabled() else
                                  "tracing disabled (set [observability] "
                                  "trace = true in the boot config)")}))
                    return
                self._send(200, json.dumps(dump))
            elif task == "cluster":
                # aggregated cluster view from the heartbeat records'
                # piggybacked snapshots (served from the heartbeat-
                # cadence peer cache — polling this cannot become a
                # store scan storm)
                mgr = self.master.miner._lease
                if mgr is None:
                    self._send(200, json.dumps({"enabled": False}))
                else:
                    self._send(200, json.dumps(
                        {"enabled": True, **mgr.cluster_view()}))
            elif task == "slo":
                from spark_fsm_tpu.service import obsplane

                self._send(200, json.dumps(obsplane.slo_snapshot()))
            elif task == "rescache":
                # result-reuse tier stats (service/resultcache.py):
                # counters, resident entries/bytes, in-flight
                # coalescing registry — {"enabled": false} when the
                # boot config leaves the tier off
                rc = self.master.miner._rescache
                self._send(200, json.dumps(
                    {"enabled": False} if rc is None else rc.stats()))
            elif task == "autoscale":
                a = self.master.autoscaler
                self._send(200, json.dumps(
                    {"enabled": False} if a is None else a.stats()))
            elif task == "integrity":
                # durable-state integrity plane (service/integrity.py):
                # verify-on-read counters, scrubber state, quarantine
                # listing — the bitrot runbook's one-stop read
                from spark_fsm_tpu.service import integrity

                self._send(200, json.dumps(
                    integrity.report(self.master.store)))
            elif task == "usage":
                # resource attribution / usage metering plane
                # (service/usage.py): per-tenant device-cost rollups
                # (est + measured seconds, launches, traffic units,
                # readback bytes), avoided-cost credits, top-N jobs,
                # durable-ledger rows — flushes pending settlements
                # first so the response is read-your-writes
                from spark_fsm_tpu.service import usage

                self._send(200, json.dumps(
                    usage.report(self.master.store)))
            elif task == "quarantine":
                # crash-loop quarantine ledger (service/meshguard.py):
                # list every preserved fsm:quarantine:* record, or
                # release one (action=release&uid=...) so a poisoned
                # uid may be resubmitted — the operator end of the
                # [cluster] max_adoptions POISON: terminal
                from spark_fsm_tpu.service import meshguard

                d = data or {}
                action = d.get("action", "list")
                if action == "release":
                    uid = d.get("uid", "")
                    if not uid:
                        self._send(400, json.dumps({
                            "status": "failure",
                            "error": "release needs a uid: /admin/"
                                     "quarantine?action=release&uid=..."}))
                        return
                    if not meshguard.quarantine_release(
                            self.master.store, uid):
                        self._send(404, json.dumps({
                            "status": "failure",
                            "error": f"no quarantine record for uid "
                                     f"{uid!r}"}))
                        return
                    self._send(200, json.dumps(
                        {"status": "released", "uid": uid}))
                    return
                if action != "list":
                    raise ValueError(f"unknown quarantine action "
                                     f"{action!r} (list/release)")
                g = meshguard.get()
                self._send(200, json.dumps({
                    "records": meshguard.quarantine_list(
                        self.master.store),
                    "mesh": None if g is None else g.stats()}))
            elif task == "predictor":
                # prediction serving plane (service/predictor.py):
                # request/wave counters, resident artifact inventory
                # (digest + geometry + bytes per entry — the audit
                # surface for cache keys), live [predict] config
                self._send(200, json.dumps(self.master.predictor.stats()))
            elif task == "drain":
                # forced scale-down (operator lever / autoscale smoke):
                # run the drain protocol on a background thread and
                # return immediately — poll /admin/autoscale (or the
                # heartbeat's draining flag via /admin/cluster) for
                # progress.  exit=1 stops the HTTP server after the
                # drain, handing control to main()'s teardown.
                miner = self.master.miner
                if miner.draining:
                    self._send(200, json.dumps(
                        {"status": "already-draining"}))
                    return
                want_exit = (data or {}).get("exit", "0").lower() \
                    not in ("", "0", "false", "no", "off")
                server = self.server

                def _drain():
                    miner.drain(reason="/admin/drain")
                    if want_exit:
                        threading.Thread(target=server.shutdown,
                                         daemon=True).start()

                threading.Thread(target=_drain, daemon=True,
                                 name="fsm-admin-drain").start()
                self._send(200, json.dumps(
                    {"status": "draining",
                     "queued": miner.queue_size(),
                     "running": miner.running_count(),
                     "exit": want_exit}))
            elif task == "shapes":
                # enumerated (last prewarm) vs runtime-recorded shape
                # keys; "drift" lists observed geometries prewarm missed
                from spark_fsm_tpu.service import prewarm
                from spark_fsm_tpu.utils import shapes as shapereg

                report = prewarm.last_report()
                enumerated = report["enumerated"] if report else []
                self._send(200, json.dumps({
                    "enumerated": enumerated,
                    "recorded": shapereg.recorded(),
                    "drift": (shapereg.drift(enumerated)
                              if report else None),
                }))
            else:
                self._send(404, json.dumps(
                    {"status": "failure",
                     "error": f"unknown admin task {task!r}"}))
        except Exception as exc:  # e.g. store backend down: JSON envelope,
            self._send(500, json.dumps({       # not a dropped connection
                "status": "failure", "error": str(exc)}))


def _fusion_stats() -> dict:
    """The /admin/stats ``fusion`` block: enabled flag + window policy,
    and the broker's counters once one exists (it is lazily built on
    the first enabled configure)."""
    from spark_fsm_tpu.service import fusion

    cfg = cfgmod.get_config().fusion
    out = {"enabled": fusion.eval_enabled(),
           "window_ms": cfg.window_ms, "max_jobs": cfg.max_jobs,
           "max_width": cfg.max_width,
           "dispatch_workers": cfg.dispatch_workers}
    b = fusion.broker()
    if b is not None:
        out.update(b.stats)
        out["pending"] = b.pending()
    return out


def service_stats(master: Master) -> dict:
    """Service-wide metrics for /admin/stats (SURVEY.md sec 5 metrics row):
    job counters from the store plus the device/backend the engines see."""
    import jax

    store = master.store
    counters = {
        name: int(store.get(f"fsm:metric:{name}") or 0)
        for name in ("jobs_submitted", "jobs_finished", "jobs_failed",
                     "stream_pushes", "stream_failures")
    }
    mesh_devices = cfgmod.get_config().engine.mesh_devices
    from spark_fsm_tpu.service import prewarm
    from spark_fsm_tpu.service.devcache import (
        cspade_engine_cache, spade_engine_cache, tsr_engine_cache)
    from spark_fsm_tpu.utils import shapes as shapereg

    report = prewarm.last_report()
    return {
        "jobs": counters,
        # admission-control view: live queue occupancy vs its bound
        # (canonical series: fsm_service_queue_depth / fsm_service_
        # sheds_total in the metrics block below)
        "admission": {"queued": master.miner.queue_size(),
                      "queue_depth": master.miner.queue_depth},
        # multi-replica lease layer (service/lease.py): replica id, held
        # leases, live peers (None = single-replica deployment)
        "cluster": (None if master.miner._lease is None
                    else master.miner._lease.stats()),
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "mesh_devices": mesh_devices,
        "algorithms": sorted(plugins.ALGORITHMS),
        # repeat-/train device-store reuse (service/devcache.py); one
        # counter block per cache so a cSPADE hit is visible as such
        "store_cache": dict(spade_engine_cache.stats),
        "cspade_cache": dict(cspade_engine_cache.stats),
        "tsr_cache": dict(tsr_engine_cache.stats),
        # cross-job launch fusion (service/fusion.py): broker counters
        # plus the live window policy (canonical series: fsm_fusion_*)
        "fusion": _fusion_stats(),
        # result-reuse tier (service/resultcache.py): hit/coalesce/
        # dominated-serve counters + resident bytes (canonical series:
        # fsm_rescache_*); None when [rescache] is off
        "rescache": (None if master.miner._rescache is None
                     else master.miner._rescache.stats()),
        # weighted-fair multi-tenant admission (service/fairness.py):
        # tenant vocabulary, weights, live per-tenant queue depths
        # (canonical series: fsm_tenant_*); None when [fairness] is off
        "fairness": (None if master.miner._fair is None
                     else {**master.miner._fair.stats(),
                           "queued": master.miner.tenant_depths()}),
        # elastic control plane (service/autoscale.py): leader, last
        # evaluation, desired record (canonical series:
        # fsm_autoscale_*); None when [autoscale] is off
        "autoscale": (None if master.autoscaler is None
                      else master.autoscaler.stats()),
        # prediction serving plane (service/predictor.py): request/wave
        # counters + artifact-cache inventory (canonical series:
        # fsm_predict_*)
        "predictor": master.predictor.stats(),
        # store-outage guard (service/storeguard.py): health state +
        # spool/stall depth (canonical series: fsm_store_health_state /
        # fsm_storeguard_*); None when [storeguard] is off
        "storeguard": (None if master.miner._guard is None
                       else master.miner._guard.stats()),
        # resource attribution / usage metering plane (service/
        # usage.py): live jobs, deposits/settles, flush counters
        # (canonical series: fsm_usage_*); None when [usage] is off —
        # the per-tenant rollup tables live on /admin/usage
        "usage": (usage.stats() if usage.get() is not None else None),
        # warm-path observability: distinct compiled geometries seen,
        # plus the last prewarm's per-key compile walls (if any ran)
        "shape_keys_recorded": len(shapereg.recorded()),
        "prewarm": (None if report is None else
                    {"keys": report["keys"],
                     "total_wall_s": report["total_wall_s"],
                     "ts": report["ts"]}),
        # the canonical registry view (utils/obs.REGISTRY — what
        # GET /metrics exposes): the blocks above are documented ALIASES
        # of these fsm_* names for one release (docs/OPERATIONS.md
        # tables the mapping)
        "metrics": obs.REGISTRY.snapshot(),
    }


def _integrity_health() -> dict:
    """Compact /admin/health integrity block: config + counters, no
    store walk (the quarantine listing lives on /admin/integrity)."""
    from spark_fsm_tpu.service import integrity

    try:
        return integrity.report()
    except Exception as exc:
        return {"error": str(exc)}


def health_report(master: Master) -> dict:
    """Per-subsystem recovery counters for ``/admin/health`` — the
    runbook's one-stop read when a deployment misbehaves: what is armed
    (should be NOTHING outside a chaos run), what retried, what timed
    out, which breakers are open, and which stop paths leaked threads."""
    from spark_fsm_tpu.service.devcache import (
        cspade_engine_cache, spade_engine_cache, tsr_engine_cache)
    from spark_fsm_tpu.streaming.consumer import consumer_health
    from spark_fsm_tpu.utils import faults, watchdog
    from spark_fsm_tpu.utils.retry import retry_counters

    store = master.store
    jobs = {}
    for name in ("jobs_submitted", "jobs_finished", "jobs_failed",
                 "jobs_retried", "stream_pushes", "stream_failures"):
        try:
            jobs[name] = int(store.get(f"fsm:metric:{name}") or 0)
        except Exception:
            # health must stay readable DURING a chaos drill: an armed
            # store.get fault (or a down store) blanks the counter, it
            # does not take down the one endpoint diagnosing it
            jobs[name] = None
    from spark_fsm_tpu.utils import jobctl

    return {
        "faults": {
            "enabled": cfgmod.get_config().fault_injection,
            "armed": faults.armed(),
            "counters": faults.counters(),
        },
        "admission": {
            "queued": master.miner.queue_size(),
            "queue_depth": master.miner.queue_depth,
            "live_jobs": jobctl.live_count(),
        },
        "cluster": (None if master.miner._lease is None
                    else master.miner._lease.stats()),
        # store-outage guard (service/storeguard.py): health state,
        # spool depth, stalled jobs; None when [storeguard] is off
        "storeguard": (None if master.miner._guard is None
                       else master.miner._guard.stats()),
        "retry": retry_counters(),
        "watchdog": {**watchdog.stats(),
                     "slack": watchdog.configured_slack()},
        "breakers": {
            "store_cache": spade_engine_cache.breaker.snapshot(),
            "cspade_cache": cspade_engine_cache.breaker.snapshot(),
            "tsr_cache": tsr_engine_cache.breaker.snapshot(),
        },
        "consumers": consumer_health(),
        # durable-state integrity plane (service/integrity.py): verify-
        # on-read + scrub counters (no quarantine listing — that walk
        # belongs to /admin/integrity, health must stay scan-free)
        "integrity": _integrity_health(),
        "jobs": jobs,
        "tracing": {"enabled": obs.tracing_enabled(),
                    **obs.recorder_stats()},
        # canonical fsm_* registry names; the blocks above stay as
        # aliases for one release (docs/OPERATIONS.md "Metric names").
        # The jobs counters are deliberately read twice per response
        # (direct from THIS master's store above, via the registered
        # collector here): the collector is process-global and may be
        # bound to another master's store in multi-master test setups,
        # so the alias block must not be derived from it — six extra
        # guard-free peeks per health poll is the price of that
        # correctness.
        "metrics": obs.REGISTRY.snapshot(),
    }


def make_store(cfg: Optional[cfgmod.Config] = None) -> ResultStore:
    cfg = cfg if cfg is not None else cfgmod.get_config()
    if cfg.store.backend == "redis":
        return RedisResultStore(cfg.store.host, cfg.store.port,
                                timeout_s=cfg.store.timeout_s)
    return ResultStore()


def make_server(port: int = 0, host: str = "127.0.0.1",
                master: Optional[Master] = None,
                miner_workers: int = 1) -> ThreadingHTTPServer:
    if master is not None:
        m = master
    else:
        m = Master(store=make_store(), miner_workers=miner_workers)
    handler = type("BoundFsmHandler", (FsmHandler,), {"master": m})
    server = ThreadingHTTPServer((host, port), handler)
    server.master = m  # type: ignore[attr-defined]
    return server


def serve_background(port: int = 0) -> ThreadingHTTPServer:
    """Start a server on a daemon thread; returns it (``server_port`` set)."""
    server = make_server(port)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="fsm-http").start()
    return server


def main() -> None:
    parser = argparse.ArgumentParser(description="spark_fsm_tpu service")
    parser.add_argument("--config", default=None,
                        help="boot config file (.toml or .json); flags "
                             "below override its [service] section")
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--host", default=None)
    parser.add_argument("--miner-workers", type=int, default=None)
    parser.add_argument("--remote-port", type=int, default=None,
                        help="actor-protocol TCP port (0 disables)")
    args = parser.parse_args()
    cfg = cfgmod.load_config(args.config) if args.config else cfgmod.Config()
    if args.port is not None:
        cfg.service.port = args.port
    if args.host is not None:
        cfg.service.host = args.host
    if args.miner_workers is not None:
        cfg.service.miner_workers = args.miner_workers
    if args.remote_port is not None:
        cfg.service.remote_port = args.remote_port
    cfgmod.set_config(cfg)
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    from spark_fsm_tpu.utils.jitcache import enable_compile_cache

    enable_compile_cache()  # persistent XLA cache across service restarts
    if cfg.distributed.enabled:
        # Must run before anything touches the XLA backend: wires this
        # process into the multi-host runtime (SURVEY.md sec 2.2 DCN row).
        from spark_fsm_tpu.parallel.multihost import init_distributed

        init_distributed(
            coordinator_address=cfg.distributed.coordinator_address or None,
            num_processes=cfg.distributed.num_processes or None,
            process_id=cfg.distributed.process_id)
    if cfg.prewarm.enabled:
        # Boot-time AOT prewarm: compile the declared workload envelope
        # BEFORE accepting traffic, so the first live /train or /stream
        # push deserializes from warm caches instead of paying a ~40 s
        # Mosaic compile (BASELINE.json cold_start).  Synchronous by
        # design — a not-yet-listening service is the honest signal that
        # the deployment is still paying its compile bill.
        from spark_fsm_tpu.service import prewarm

        spec = prewarm.spec_from_config(cfg.prewarm)
        if spec is None:
            print("prewarm enabled but the [prewarm] envelope is empty "
                  "(set sequences/items or stream_batch_sequences)",
                  flush=True)
        else:
            report = prewarm.run(
                spec, mesh=cfgmod.get_mesh(),
                engine_kwargs=cfgmod.engine_kwargs(
                    "pool_bytes", "node_batch", "pipeline_depth",
                    "chunk", "recompute_chunk"))
            print(f"prewarm: {len(report['keys'])} shape keys in "
                  f"{report['total_wall_s']}s", flush=True)
    server = make_server(cfg.service.port, cfg.service.host,
                         miner_workers=cfg.service.miner_workers)
    # crash-restart recovery BEFORE accepting traffic: journal intents
    # from a dead incarnation are resubmitted (checkpointed — they
    # resume from the persisted frontier) or failed durably, so no
    # client polls a forever-pending uid from before the crash
    from spark_fsm_tpu.service.actors import recover_orphans

    report = recover_orphans(server.master)  # type: ignore[attr-defined]
    if any(report.values()):
        print(f"restart recovery: {len(report['resumed'])} resumed, "
              f"{len(report['failed'])} failed durably, "
              f"{len(report['cleared'])} journal entries cleared, "
              f"{len(report.get('quarantined', ()))} quarantined",
              flush=True)
    scaler = server.master.autoscaler  # type: ignore[attr-defined]
    if scaler is not None:
        # a drain directive (scale-down victim) exits this process once
        # the queue has been stolen/adopted: stopping the serve loop
        # hands control to the teardown below, same as SIGTERM
        scaler.on_drained = lambda report: threading.Thread(
            target=server.shutdown, daemon=True).start()
        print(f"autoscale controller on (bounds "
              f"[{scaler.min_replicas}, {scaler.max_replicas}], "
              f"cadence {round(scaler.decide_every_s, 3)}s)", flush=True)
    guard = server.master.miner._guard  # type: ignore[attr-defined]
    if guard is not None:
        print(f"storeguard on (probe {guard.probe_every_s}s, "
              f"spool {guard.spool_max_entries}/job, "
              f"stall_max {guard.stall_max_s}s, "
              f"ephemeral_admission "
              f"{'on' if guard.ephemeral_admission else 'off'})",
              flush=True)
    mgr = server.master.miner._lease  # type: ignore[attr-defined]
    if mgr is not None:
        # multi-replica mode: peers identify this instance by replica id
        # in lease/heartbeat keys and /admin/stats
        print(f"cluster replica {mgr.replica_id} "
              f"(lease ttl {mgr.lease_ttl_s}s, "
              f"heartbeat {round(mgr.heartbeat_s, 3)}s, "
              f"steal {'on' if mgr.steal_enabled else 'off'})", flush=True)
    from spark_fsm_tpu.service import integrity

    scr = integrity.get()
    if scr is not None and cfg.integrity.scrub_every_s > 0:
        if mgr is None:
            # solo boot: no heartbeat tick to ride — own daemon thread
            scr.start()
        print(f"integrity scrubber on "
              f"(every {round(cfg.integrity.scrub_every_s, 3)}s, "
              f"batch {cfg.integrity.scrub_batch}, "
              f"{'heartbeat' if mgr is not None else 'thread'} cadence)",
              flush=True)
    print(f"spark_fsm_tpu service on http://{cfg.service.host}:"
          f"{server.server_port}", flush=True)
    remote = None
    if cfg.service.remote_port:
        # Second protocol entry (the reference's Akka-remote analog):
        # actor-vocabulary JSON lines over TCP, same Master.
        from spark_fsm_tpu.service.remote import serve_remote_background

        remote = serve_remote_background(
            server.master, cfg.service.host,  # type: ignore[attr-defined]
            cfg.service.remote_port)
        print(f"spark_fsm_tpu actor protocol on {cfg.service.host}:"
              f"{remote.port}", flush=True)

    def _term(signum, frame):
        # SIGTERM (k8s / systemd stop) drains exactly like Ctrl-C: the
        # serve loop exits, miners finish their CURRENT job and reach a
        # durable status, both protocol servers close — instead of the
        # default hard kill mid-mine.
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _term)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # cleanup can block on the miner drain (up to its join timeout):
        # a second TERM/Ctrl-C must not raise inside this block and skip
        # the remaining teardown
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        # close the listening sockets BEFORE draining so clients get
        # connection-refused instead of hanging in the accept backlog of
        # a server whose loop has already exited
        server.server_close()
        if remote is not None:
            remote.shutdown()
            remote.server_close()
        server.master.shutdown()  # type: ignore[attr-defined]
        print("spark_fsm_tpu service stopped", flush=True)


if __name__ == "__main__":
    main()
