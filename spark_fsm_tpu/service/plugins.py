"""Algorithm plugin registry — the reference's L4 boundary.

The reference selects the miner by the request's ``algorithm`` param
through top-level plugin objects (``SPADE.extract``, ``TSR.extract`` —
SURVEY.md sec 1 L4, sec 3.1).  The rebuild keeps exactly that seam (the
``AlgorithmPlugin`` boundary named in BASELINE.json: ``algorithm=
SPADE_TPU``) over the TPU engines and the CPU oracles:

  SPADE      — CPU oracle miner (numpy bitmap DFS).
  SPADE_TPU  — device engine (models/spade_tpu.py); honors maxgap /
               maxwindow by switching to the constrained engine.
  SPAM       — CPU SPAM wave miner (models/spam_bitmap.py, popcount
               support formulation; unconstrained patterns only).
  SPAM_TPU   — device SPAM fixed-shape wave engine (same module).
  TSR        — CPU top-k rule miner (models/tsr.py TsrCPU: same best-first
               search, NumPy bitmap evaluation on host).
  TSR_TPU    — device TSR engine (models/tsr.py TsrTPU).
  AUTO       — dataset-shape-aware routing to one of the above by the
               engine planner (service/planner.py; ISSUE 15).

Each plugin returns (kind, results) where kind is "patterns" or "rules".
An unknown name raises :class:`UnknownAlgorithm`, whose ``supported``
listing is derived from ``ALGORITHMS`` itself — the HTTP layer maps it
to a structured 400.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple, Union

from spark_fsm_tpu import config
from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import abs_minsup
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.utils.canonical import PatternResult, RuleResult

Results = Union[List[PatternResult], List[RuleResult]]


class UnknownAlgorithm(ValueError):
    """An ``algorithm`` name outside the registry.  Carries the
    registry-derived ``supported`` listing so the HTTP layer can shed a
    structured 400 naming what IS supported (the listing comes from
    ``ALGORITHMS`` itself, never a docstring — satellite contract of
    ISSUE 15)."""

    def __init__(self, name: str, supported):
        self.name = name
        self.supported = sorted(supported)
        super().__init__(
            f"unknown algorithm {name!r} (supported: "
            f"{', '.join(self.supported)})")


@dataclasses.dataclass
class AlgorithmPlugin:
    """``extract(req, db, stats=None, checkpoint=None)``; a provided
    ``stats`` dict receives the engine's observability counters (SURVEY.md
    sec 5 metrics row); ``checkpoint`` (load/save/every_s) enables frontier
    resume where the engine supports it — SPADE_TPU (constrained or not:
    DFS stack) and TSR/TSR_TPU (best-first queue + current top-k); only
    the CPU-oracle SPADE plugin drops it (flagged in stats)."""

    name: str
    kind: str  # "patterns" | "rules"
    extract: Callable[..., Results]


def _minsup(req: ServiceRequest, db: SequenceDB) -> int:
    support = req.param("support")
    if support is None:
        raise ValueError("train request needs a 'support' parameter")
    rel = float(support)
    if rel >= 1.0:  # absolute count given directly
        return int(rel)
    return abs_minsup(rel, len(db))


def _constraints(req: ServiceRequest) -> Tuple[Optional[int], Optional[int]]:
    mg = req.param("maxgap")
    mw = req.param("maxwindow")
    return (int(mg) if mg is not None else None,
            int(mw) if mw is not None else None)


def resolved_partition_parts() -> int:
    """The partition count the boot config implies — ONE resolver
    shared by request routing and the prewarm envelope so the warmed
    and served 2-D layouts cannot drift.  0 = partitioning off.

    ``[partition] parts = 0`` auto-resolves: one partition per process
    in a multi-controller run (the hosts x seq contract), else 2 when
    the boot mesh splits evenly into two rows, else off (a single local
    device has no outer axis to scale over, an odd mesh no even split).
    An explicit parts that cannot split the topology degrades LOUDLY to
    unpartitioned (``partition_config_invalid`` log) instead of failing
    every train request at ``submeshes``."""
    pc = config.get_config().partition
    if not pc.enabled:
        return 0
    import jax

    n_procs = jax.process_count()
    mesh = config.get_mesh()
    if pc.parts:
        # an explicit parts that cannot split the boot topology must
        # not 500 every train request (or abort boot inside prewarm's
        # enumerate): degrade to unpartitioned, loudly — the log line +
        # fsm_partition_plans_total flatlining at 0 are the operator
        # signals (OPERATIONS.md)
        parts = int(pc.parts)
        bad = None
        if n_procs > 1 and parts != n_procs:
            bad = (f"parts={parts} != process_count={n_procs} "
                   "(multi-controller needs one partition per process)")
        elif n_procs == 1 and mesh is not None and parts > 1 \
                and mesh.devices.size % parts:
            bad = (f"parts={parts} does not divide the "
                   f"{mesh.devices.size}-device mesh")
        if bad:
            from spark_fsm_tpu.utils.obs import log_event

            log_event("partition_config_invalid", reason=bad)
            return 0
        return parts if _classes_cover(parts, pc.classes) else 0
    if n_procs > 1:
        return n_procs if _classes_cover(n_procs, pc.classes) else 0
    if mesh is not None and mesh.devices.size >= 2 \
            and mesh.devices.size % 2 == 0:
        return 2 if _classes_cover(2, pc.classes) else 0
    return 0


def _classes_cover(parts: int, classes: int) -> bool:
    """classes >= parts or the LPT plan cannot give every partition a
    class; config validation only covers EXPLICIT parts, so the
    auto-resolved count (the process count on a big pod) must re-check
    here — and degrade loudly rather than let plan_partitions raise on
    every train request."""
    if classes >= parts:
        return True
    from spark_fsm_tpu.utils.obs import log_event

    log_event("partition_config_invalid",
              reason=f"classes={classes} < resolved parts={parts}")
    return False


def _partition_kwargs() -> dict:
    parts = resolved_partition_parts()
    if parts < 2:
        return {}
    return {"partition_parts": parts,
            "partition_classes": config.get_config().partition.classes}


def _checkpoint_unsupported(checkpoint, name: str,
                            stats: Optional[dict]) -> None:
    """A requested checkpoint the selected engine cannot honor must be
    visible (job stats + log), not silently dropped."""
    if checkpoint is None:
        return
    from spark_fsm_tpu.utils.obs import log_event

    log_event("checkpoint_unsupported", algorithm=name)
    if stats is not None:
        stats["checkpoint_unsupported"] = True


def _spade_cpu(req: ServiceRequest, db: SequenceDB,
               stats: Optional[dict] = None, checkpoint=None) -> Results:
    from spark_fsm_tpu.models.oracle import mine_cspade, mine_spade

    _checkpoint_unsupported(checkpoint, "SPADE", stats)

    minsup = _minsup(req, db)
    maxgap, maxwindow = _constraints(req)
    if maxgap is None and maxwindow is None:
        results = mine_spade(db, minsup)
    else:
        results = mine_cspade(db, minsup, maxgap=maxgap, maxwindow=maxwindow)
    if stats is not None:
        stats["patterns"] = len(results)
    return results


def _spade_tpu(req: ServiceRequest, db: SequenceDB,
               stats: Optional[dict] = None, checkpoint=None) -> Results:
    from spark_fsm_tpu.models.spade_constrained import mine_cspade_tpu
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu

    minsup = _minsup(req, db)
    maxgap, maxwindow = _constraints(req)
    kwargs = config.engine_kwargs("pool_bytes", "node_batch",
                                  "pipeline_depth", "chunk", "recompute_chunk")
    mesh = config.get_mesh()
    # Streaming pushes (task == "stream") re-mine a window whose geometry
    # drifts every micro-batch: pow2-bucket the device shapes (both
    # engines support the knob) so consecutive pushes reuse compiled
    # programs instead of recompiling per window size — same knob
    # WindowMiner's default mine uses.
    if req.task == "stream":
        kwargs["shape_buckets"] = True
    if maxgap is None and maxwindow is None:
        # fused routing is a plain-SPADE knob (the constrained engine has
        # no fused counterpart), so it must not reach mine_cspade_tpu
        fused_kw = config.engine_kwargs("fused")
        part_kw = _partition_kwargs()
        if part_kw and req.task != "stream":
            # partitioned mines bypass the engine cache: the route
            # builds one engine per partition row, which the single-
            # engine cache cannot hold (streaming pushes keep the plain
            # route — their windows re-mine batch-sized slices)
            return mine_spade_tpu(db, minsup, mesh=mesh, stats_out=stats,
                                  checkpoint=checkpoint, **part_kw,
                                  **fused_kw, **kwargs)
        if req.task != "stream":
            # repeat mines over identical data reuse the HBM store +
            # compiled engine (service/devcache.py) — checkpointed jobs
            # included: the cached engine holds only the immutable
            # store, and a resume seeds it from the snapshot (the
            # frontier fingerprint is validated first).  Stream
            # re-mines skip the cache (a sliding window's data changes
            # every push, so every push would insert a dead entry).
            from spark_fsm_tpu.service.devcache import spade_engine_cache
            return spade_engine_cache.mine(db, minsup, mesh=mesh,
                                           stats_out=stats,
                                           checkpoint=checkpoint,
                                           **fused_kw, **kwargs)
        return mine_spade_tpu(db, minsup, mesh=mesh, stats_out=stats,
                              checkpoint=checkpoint,
                              **fused_kw, **kwargs)
    part_kw = _partition_kwargs()
    if part_kw and req.task != "stream":
        return mine_cspade_tpu(db, minsup, maxgap=maxgap,
                               maxwindow=maxwindow, mesh=mesh,
                               stats_out=stats, checkpoint=checkpoint,
                               **part_kw, **kwargs)
    if checkpoint is None and req.task != "stream":
        # repeat cSPADE mines reuse the constrained engine (item store +
        # max-start pool); the cache key folds maxgap/maxwindow — they
        # select different kernels AND different enumerations
        from spark_fsm_tpu.service.devcache import cspade_engine_cache
        return cspade_engine_cache.mine(db, minsup, maxgap=maxgap,
                                        maxwindow=maxwindow, mesh=mesh,
                                        stats_out=stats, **kwargs)
    return mine_cspade_tpu(db, minsup, maxgap=maxgap, maxwindow=maxwindow,
                           mesh=mesh, stats_out=stats, checkpoint=checkpoint,
                           **kwargs)


def _spam_constraints_check(req: ServiceRequest) -> None:
    maxgap, maxwindow = _constraints(req)
    if maxgap is not None or maxwindow is not None:
        raise ValueError(
            "the SPAM engine serves unconstrained patterns only "
            "(maxgap/maxwindow unsupported — use SPADE_TPU, or "
            "algorithm=AUTO to let the planner route)")


def _spam_cpu(req: ServiceRequest, db: SequenceDB,
              stats: Optional[dict] = None, checkpoint=None) -> Results:
    from spark_fsm_tpu.models.spam_bitmap import mine_spam_cpu

    _spam_constraints_check(req)
    _checkpoint_unsupported(checkpoint, "SPAM", stats)
    minsup = _minsup(req, db)
    return mine_spam_cpu(db, minsup, stats_out=stats)


def _spam_tpu(req: ServiceRequest, db: SequenceDB,
              stats: Optional[dict] = None, checkpoint=None) -> Results:
    from spark_fsm_tpu.models.spam_bitmap import mine_spam_tpu

    _spam_constraints_check(req)
    minsup = _minsup(req, db)
    kwargs = config.engine_kwargs("pool_bytes", "node_batch",
                                  "pipeline_depth")
    if req.task == "stream":  # see _spade_tpu: bucket drifting windows
        kwargs["shape_buckets"] = True
        part_kw = {}
    else:
        part_kw = _partition_kwargs()
    return mine_spam_tpu(db, minsup, mesh=config.get_mesh(),
                         stats_out=stats, checkpoint=checkpoint,
                         **part_kw, **kwargs)


def _auto(req: ServiceRequest, db: SequenceDB,
          stats: Optional[dict] = None, checkpoint=None) -> Results:
    from spark_fsm_tpu.service import planner

    return planner.extract_auto(req, db, stats, checkpoint=checkpoint)


def _tsr_params(req: ServiceRequest):
    k = int(req.param("k", "100"))
    minconf = float(req.param("minconf", "0.5"))
    max_side = req.param("max_side")
    return k, minconf, int(max_side) if max_side else None


def _tsr_kwargs() -> dict:
    # TSR's batch width is a separate boot knob from SPADE's (tsr_chunk):
    # SPADE's is a fixed dispatch width, TSR's defaults to an HBM-budget-
    # adaptive size — they must not be tuned together.
    kwargs = config.engine_kwargs("item_cap")
    tsr_chunk = config.engine_kwargs("tsr_chunk").get("tsr_chunk")
    if tsr_chunk is not None:
        kwargs["chunk"] = tsr_chunk
    return kwargs


def _tsr_cpu(req: ServiceRequest, db: SequenceDB,
             stats: Optional[dict] = None, checkpoint=None) -> Results:
    from spark_fsm_tpu.models.tsr import mine_tsr_cpu

    k, minconf, max_side = _tsr_params(req)
    return mine_tsr_cpu(db, k, minconf, max_side=max_side, stats_out=stats,
                        checkpoint=checkpoint, **_tsr_kwargs())


def _tsr_tpu(req: ServiceRequest, db: SequenceDB,
             stats: Optional[dict] = None, checkpoint=None) -> Results:
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu

    k, minconf, max_side = _tsr_params(req)
    kwargs = _tsr_kwargs()
    # use_pallas: "auto" (default, engine probes the backend) / truthy
    # (force the kernel path — interpret mode off-TPU; how a chaos
    # drill exercises the OOM degradation ladder over HTTP on any
    # backend) / falsy (pin the jnp evaluator)
    up = (req.param("use_pallas") or "").lower()
    if up and up != "auto":
        kwargs["use_pallas"] = up not in ("0", "false", "no", "off")
    # resident: "auto" (default, the planner's launch-bound heuristic) /
    # "always" (pin the resident-frontier route where structurally
    # eligible — chaos drills and benches) / "never" (pin the classic
    # host loop).  Folded into the devcache key via kwargs like every
    # other engine knob.
    rp = (req.param("resident") or "").lower()
    if rp and rp != "auto":
        kwargs["resident"] = ("always" if rp in ("always", "1", "true",
                                                 "yes", "on")
                              else "never")
    if req.task == "stream":  # see _spade_tpu: bucket drifting windows
        kwargs["shape_buckets"] = True
    part_kw = _partition_kwargs()
    if part_kw and req.task != "stream":
        # the partitioned orchestrator builds one engine per submesh
        # row — bypass the single-engine devcache (same reasoning as
        # the SPADE route above)
        return mine_tsr_tpu(db, k, minconf, max_side=max_side,
                            mesh=config.get_mesh(), stats_out=stats,
                            checkpoint=checkpoint, **part_kw, **kwargs)
    if checkpoint is None and req.task != "stream":
        # repeat TSR mines over identical data reuse the built engine
        # (vertical build + token indexing are the fixed ~7s cost of the
        # framework's longest jobs); checkpointed jobs stay uncached
        # (resume binds its own fingerprint) and stream windows change
        # every push (see _spade_tpu's identical reasoning)
        from spark_fsm_tpu.service.devcache import tsr_engine_cache
        return tsr_engine_cache.mine(db, k, minconf, max_side=max_side,
                                     mesh=config.get_mesh(),
                                     stats_out=stats, **kwargs)
    return mine_tsr_tpu(db, k, minconf, max_side=max_side, mesh=config.get_mesh(),
                        stats_out=stats, checkpoint=checkpoint, **kwargs)


ALGORITHMS: Dict[str, AlgorithmPlugin] = {
    "SPADE": AlgorithmPlugin("SPADE", "patterns", _spade_cpu),
    "SPADE_TPU": AlgorithmPlugin("SPADE_TPU", "patterns", _spade_tpu),
    "SPAM": AlgorithmPlugin("SPAM", "patterns", _spam_cpu),
    "SPAM_TPU": AlgorithmPlugin("SPAM_TPU", "patterns", _spam_tpu),
    "TSR": AlgorithmPlugin("TSR", "rules", _tsr_cpu),
    "TSR_TPU": AlgorithmPlugin("TSR_TPU", "rules", _tsr_tpu),
    # AUTO's registry entry exists so listings ("/admin/algorithms",
    # the 400 body) include it; get_plugin builds the per-request
    # plugin below because AUTO's result KIND depends on the params
    "AUTO": AlgorithmPlugin("AUTO", "patterns", _auto),
}

# the result-identity FAMILY behind each engine name: engines inside a
# family are byte-identical by the parity contract, so the result-reuse
# tier keys cache entries/coalescing on the family — a request hits
# regardless of which engine route produced the entry (ISSUE 15
# composition invariant).  Family names are the historical device-
# engine names so pre-existing cache keys stay valid.
FAMILIES: Dict[str, str] = {
    "SPADE": "SPADE_TPU", "SPADE_TPU": "SPADE_TPU",
    "SPAM": "SPADE_TPU", "SPAM_TPU": "SPADE_TPU",
    "TSR": "TSR_TPU", "TSR_TPU": "TSR_TPU",
}


def get_plugin(req: ServiceRequest) -> AlgorithmPlugin:
    name = (req.param("algorithm") or "SPADE_TPU").upper()
    if name == "AUTO":
        from spark_fsm_tpu.service import planner

        return AlgorithmPlugin("AUTO", planner.infer_kind(req), _auto)
    if name not in ALGORITHMS:
        raise UnknownAlgorithm(name, ALGORITHMS)
    return ALGORITHMS[name]


def effective_params(req: ServiceRequest,
                     n_sequences: Optional[int] = None) -> dict:
    """The request's RESULT-AFFECTING parameters, normalized — the one
    vocabulary the result-reuse tier (service/resultcache.py) keys
    coalescing identity and dominance predicates on.  Two requests with
    equal dicts here (and equal dataset fingerprints) provably mine the
    same result set; engine-routing knobs (fused/resident/use_pallas),
    supervision knobs (retries/deadline_s/priority/checkpoint) and the
    uid are deliberately EXCLUDED — they change scheduling, never
    output (the engines' parity contract).

    ``algo`` is the result-identity FAMILY (``FAMILIES``), not the
    routed engine: SPADE/SPADE_TPU/SPAM/SPAM_TPU (and patterns-AUTO)
    all normalize to one key because their outputs are byte-identical
    by the parity contract — a cache entry produced under one engine
    route serves every other route for the same dataset + params
    (ISSUE 15).  Engine choice is scheduling, never output, exactly
    like the fused/resident knobs already excluded below.

    Pattern algorithms: ``support`` as given (float), plus
    ``minsup_abs`` resolved to the absolute count when the value is
    already absolute (>= 1) or ``n_sequences`` is known — the
    comparable form dominance needs.  Rule algorithms: ``k``,
    ``minconf`` (float; compared exactly via Fraction at serve time),
    ``max_side``.  Raises ValueError on malformed params, same as the
    plugins themselves would.
    """
    plugin = get_plugin(req)
    family = FAMILIES.get(
        plugin.name,
        "TSR_TPU" if plugin.kind == "rules" else "SPADE_TPU")
    if plugin.kind == "rules":
        k, minconf, max_side = _tsr_params(req)
        if k < 1:
            raise ValueError(f"k must be >= 1 (got {k})")
        return {"algo": family, "kind": plugin.kind, "k": k,
                "minconf": minconf, "max_side": max_side}
    support = req.param("support")
    if support is None:
        raise ValueError("train request needs a 'support' parameter")
    rel = float(support)
    minsup_abs: Optional[int] = None
    if rel >= 1.0:
        minsup_abs = int(rel)
    elif n_sequences is not None:
        minsup_abs = abs_minsup(rel, n_sequences)
    maxgap, maxwindow = _constraints(req)
    if plugin.name in ("SPAM", "SPAM_TPU"):
        _spam_constraints_check(req)  # same error as the plugin would raise
    return {"algo": family, "kind": plugin.kind, "support": rel,
            "minsup_abs": minsup_abs, "maxgap": maxgap,
            "maxwindow": maxwindow}
