"""Algorithm plugin registry — the reference's L4 boundary.

The reference selects the miner by the request's ``algorithm`` param
through top-level plugin objects (``SPADE.extract``, ``TSR.extract`` —
SURVEY.md sec 1 L4, sec 3.1).  The rebuild keeps exactly that seam (the
``AlgorithmPlugin`` boundary named in BASELINE.json: ``algorithm=
SPADE_TPU``) over the TPU engines and the CPU oracles:

  SPADE      — CPU oracle miner (numpy bitmap DFS).
  SPADE_TPU  — device engine (models/spade_tpu.py); honors maxgap /
               maxwindow by switching to the constrained engine.
  TSR        — CPU top-k rule miner (models/tsr.py TsrCPU: same best-first
               search, NumPy bitmap evaluation on host).
  TSR_TPU    — device TSR engine (models/tsr.py TsrTPU).

Each plugin returns (kind, results) where kind is "patterns" or "rules".
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple, Union

from spark_fsm_tpu.data.spmf import SequenceDB
from spark_fsm_tpu.data.vertical import abs_minsup
from spark_fsm_tpu.service.model import ServiceRequest
from spark_fsm_tpu.utils.canonical import PatternResult, RuleResult

Results = Union[List[PatternResult], List[RuleResult]]


@dataclasses.dataclass
class AlgorithmPlugin:
    name: str
    kind: str  # "patterns" | "rules"
    extract: Callable[[ServiceRequest, SequenceDB], Results]


def _minsup(req: ServiceRequest, db: SequenceDB) -> int:
    support = req.param("support")
    if support is None:
        raise ValueError("train request needs a 'support' parameter")
    rel = float(support)
    if rel >= 1.0:  # absolute count given directly
        return int(rel)
    return abs_minsup(rel, len(db))


def _constraints(req: ServiceRequest) -> Tuple[Optional[int], Optional[int]]:
    mg = req.param("maxgap")
    mw = req.param("maxwindow")
    return (int(mg) if mg is not None else None,
            int(mw) if mw is not None else None)


def _spade_cpu(req: ServiceRequest, db: SequenceDB) -> Results:
    from spark_fsm_tpu.models.oracle import mine_cspade, mine_spade

    minsup = _minsup(req, db)
    maxgap, maxwindow = _constraints(req)
    if maxgap is None and maxwindow is None:
        return mine_spade(db, minsup)
    return mine_cspade(db, minsup, maxgap=maxgap, maxwindow=maxwindow)


def _spade_tpu(req: ServiceRequest, db: SequenceDB) -> Results:
    from spark_fsm_tpu.models.spade_constrained import mine_cspade_tpu
    from spark_fsm_tpu.models.spade_tpu import mine_spade_tpu

    minsup = _minsup(req, db)
    maxgap, maxwindow = _constraints(req)
    if maxgap is None and maxwindow is None:
        return mine_spade_tpu(db, minsup)
    return mine_cspade_tpu(db, minsup, maxgap=maxgap, maxwindow=maxwindow)


def _tsr_params(req: ServiceRequest):
    k = int(req.param("k", "100"))
    minconf = float(req.param("minconf", "0.5"))
    max_side = req.param("max_side")
    return k, minconf, int(max_side) if max_side else None


def _tsr_cpu(req: ServiceRequest, db: SequenceDB) -> Results:
    from spark_fsm_tpu.models.tsr import mine_tsr_cpu

    k, minconf, max_side = _tsr_params(req)
    return mine_tsr_cpu(db, k, minconf, max_side=max_side)


def _tsr_tpu(req: ServiceRequest, db: SequenceDB) -> Results:
    from spark_fsm_tpu.models.tsr import mine_tsr_tpu

    k, minconf, max_side = _tsr_params(req)
    return mine_tsr_tpu(db, k, minconf, max_side=max_side)


ALGORITHMS: Dict[str, AlgorithmPlugin] = {
    "SPADE": AlgorithmPlugin("SPADE", "patterns", _spade_cpu),
    "SPADE_TPU": AlgorithmPlugin("SPADE_TPU", "patterns", _spade_tpu),
    "TSR": AlgorithmPlugin("TSR", "rules", _tsr_cpu),
    "TSR_TPU": AlgorithmPlugin("TSR_TPU", "rules", _tsr_tpu),
}


def get_plugin(req: ServiceRequest) -> AlgorithmPlugin:
    name = (req.param("algorithm") or "SPADE_TPU").upper()
    if name not in ALGORITHMS:
        raise ValueError(f"unknown algorithm {name!r} "
                         f"(have {sorted(ALGORITHMS)})")
    return ALGORITHMS[name]
