"""Weighted-fair multi-tenant admission (ISSUE 13) — per-tenant token
buckets layered UNDER the strict priority classes.

PR 5's admission queue solved overload (a full queue sheds 429s) but
not FAIRNESS: classes are strict and FIFO within, so one flooding
client occupies every admission slot on every replica and a well-
behaved tenant's submits either shed or queue behind the whole flood.
This module adds the missing dimension without touching the class
semantics the fusion broker and SLO layer already key on:

- **Tenant identity**: requests gain a ``tenant`` param (default
  ``"default"``).  The live vocabulary is BOUNDED (``[fairness]
  max_tenants``) because tenant names label the ``fsm_tenant_*``
  metric families — an attacker minting tenant names must not mint
  unbounded series; a new tenant past the bound is refused with a
  clean failure envelope, never silently remapped.

- **Token buckets (occupancy)**: each tenant's QUEUED jobs are capped
  at ``tenant_depth`` — the bucket: a token is consumed when a submit
  reserves a queue slot and returned when the job is dequeued (or the
  submit aborts).  A tenant out of tokens sheds with 429 even while
  the global queue has room, which is exactly what keeps the flood
  from occupying every slot.  The bucket's REFILL rate is the
  tenant's weight-fair share of the measured service rate, and the
  shed's ``Retry-After`` is derived from it (how long until this
  tenant's own backlog drains at its share), not from the global EWMA
  — a flooding tenant is told the truth about its own queue, not the
  fleet's.

- **Deficit-weighted round-robin**: within each priority class, queued
  jobs are served DRR across tenants — every round, each backlogged
  tenant earns a quantum proportional to its weight and spends one
  deficit per job served.  Weights come from ``[fairness.weights]``
  (unlisted tenants get ``default_weight``).  Priority classes stay
  STRICT above fairness: a ``high`` job from any tenant still beats
  every ``normal`` job — fairness layers UNDER the classes, never
  beside them (docs/DESIGN.md "Fairness under priority classes").

Disabled (``[fairness] enabled = false``, the default) the admission
queue holds no scheduler and every queue operation takes its original
plain-deque path — bench_smoke's dispatch counters stay byte-identical.
"""

from __future__ import annotations

import collections
import math
import re
import threading
from typing import Deque, Dict, Iterable, List, Optional, Tuple

from spark_fsm_tpu import config
from spark_fsm_tpu.utils import obs

DEFAULT_TENANT = "default"

# tenant names become metric label values and store-key components:
# bounded charset, bounded length
_NAME_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

_TENANT_DEPTH = obs.REGISTRY.gauge(
    "fsm_tenant_queue_depth",
    "queued train jobs per tenant (fairness scheduler view)")
_TENANT_DEPTH.set(0, tenant=DEFAULT_TENANT)
_TENANT_ADMITTED = obs.REGISTRY.counter(
    "fsm_tenant_admitted_total",
    "train jobs admitted per tenant").seed(tenant=DEFAULT_TENANT)
_TENANT_SHEDS = obs.REGISTRY.counter(
    "fsm_tenant_sheds_total",
    "train submits shed per tenant (429): the tenant's own queue cap, "
    "or the global bound while the tenant was over its fair share"
).seed(tenant=DEFAULT_TENANT)
_TENANT_SERVED = obs.REGISTRY.counter(
    "fsm_tenant_dequeued_total",
    "train jobs handed to a worker per tenant — the DRR service "
    "order's observable").seed(tenant=DEFAULT_TENANT)


def build_scheduler() -> Optional["TenantScheduler"]:
    """The Miner's constructor hook: a scheduler when the boot config
    enables fairness, else None (the admission queue keeps its plain
    deques and the disabled path costs nothing)."""
    fcfg = config.get_config().fairness
    if not fcfg.enabled:
        return None
    return TenantScheduler(fcfg)


class TenantScheduler:
    """Process-wide tenant registry: weights, the bounded vocabulary,
    and the per-tenant Retry-After estimator.  Queue-side state (the
    per-class DRR lists, the occupancy buckets) lives in
    :class:`FairClass` / the AdmissionQueue, which call back into this
    for weights."""

    def __init__(self, fcfg=None) -> None:
        fcfg = fcfg if fcfg is not None else config.get_config().fairness
        self.tenant_depth = int(fcfg.tenant_depth)
        self.max_tenants = int(fcfg.max_tenants)
        self.default_weight = float(fcfg.default_weight)
        self._weights: Dict[str, float] = {
            str(k): float(v) for k, v in dict(fcfg.weights).items()}
        self._lock = threading.Lock()
        self._known = {DEFAULT_TENANT} | set(self._weights)
        for t in sorted(self._known):
            self._seed_tenant(t)

    @staticmethod
    def _seed_tenant(tenant: str) -> None:
        # zero-seed the tenant's label series so a fresh scrape shows
        # every registered tenant (the PR 9 no-orphan hygiene)
        _TENANT_DEPTH.set(0, tenant=tenant)
        _TENANT_ADMITTED.seed(tenant=tenant)
        _TENANT_SHEDS.seed(tenant=tenant)
        _TENANT_SERVED.seed(tenant=tenant)
        # and the SLO vocabulary (ISSUE 14 satellite): per-tenant
        # fsm_job_*_seconds series + /admin/slo tenant quantiles exist
        # from registration, not from the first finished job
        from spark_fsm_tpu.service import obsplane

        obsplane.seed_tenant(tenant)

    def resolve(self, raw: Optional[str]) -> str:
        """Validate + register a request's tenant.  Raises ValueError
        for malformed names and for NEW tenants past the bounded
        vocabulary (the metric-cardinality guard) — the submit fails
        with a clean envelope, nothing is silently remapped."""
        if raw is None or raw == "":
            return DEFAULT_TENANT
        if not _NAME_RE.match(raw):
            raise ValueError(
                f"invalid tenant {raw!r} (letters, digits, '.', '_', "
                f"'-', max 64 chars)")
        with self._lock:
            if raw not in self._known:
                if len(self._known) >= self.max_tenants:
                    raise ValueError(
                        f"tenant vocabulary full ({self.max_tenants} "
                        f"live tenants); new tenant {raw!r} refused — "
                        f"raise [fairness] max_tenants or reuse an "
                        f"existing tenant")
                self._known.add(raw)
                self._seed_tenant(raw)
        return raw

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, self.default_weight)

    def share(self, tenant: str,
              active: Optional[Iterable[str]] = None) -> float:
        """The tenant's weight-fair share of service capacity among
        ``active`` tenants (all known ones when None)."""
        with self._lock:
            pool = list(active) if active is not None \
                else sorted(self._known)
        if tenant not in pool:
            pool = pool + [tenant]
        total = sum(self.weight(t) for t in pool)
        return self.weight(tenant) / total if total > 0 else 1.0

    def retry_after_s(self, tenant: str, tenant_queued: int,
                      per_job_s: float, workers: int,
                      active: Optional[Iterable[str]] = None) -> int:
        """Seconds until a shed tenant's submit plausibly fits: its OWN
        backlog divided by its bucket's refill rate — the weight-fair
        share of the measured service rate (``workers / per_job_s``).
        This replaces the global-EWMA estimate for tenant sheds: a
        flooding tenant must be told how long ITS queue takes at ITS
        share, not how long the fleet's next free slot takes."""
        refill_per_s = (max(1, workers) / max(1e-6, per_job_s)) \
            * self.share(tenant, active)
        est = (tenant_queued + 1) / max(1e-9, refill_per_s)
        return max(1, min(3600, math.ceil(est)))

    def known_tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._known)

    def stats(self) -> dict:
        with self._lock:
            known = sorted(self._known)
        return {"enabled": True,
                "tenant_depth": self.tenant_depth,
                "max_tenants": self.max_tenants,
                "tenants": known,
                "weights": {t: self.weight(t) for t in known}}


class FairClass:
    """One priority class's queued jobs, served deficit-weighted
    round-robin across tenants.  NOT thread-safe on its own — every
    method runs under the owning AdmissionQueue's condition lock,
    exactly like the plain deques it replaces.

    DRR with unit job cost: ``_active`` is the round-robin ring of
    backlogged tenants; a visit to the tenant at the head serves jobs
    while its deficit lasts, then grants the next quantum (weight
    normalized so every round adds >= 1 somewhere) and rotates.  A
    tenant whose queue drains leaves the ring and forfeits its deficit
    (standard DRR — banked credit must not let an idle-then-bursty
    tenant starve the ring later)."""

    def __init__(self, sched: TenantScheduler):
        self._sched = sched
        self._qs: Dict[str, Deque] = {}
        self._active: Deque[str] = collections.deque()
        self._deficit: Dict[str, float] = {}

    def __len__(self) -> int:
        return sum(len(q) for q in self._qs.values())

    def append(self, req, tenant: str) -> None:
        q = self._qs.get(tenant)
        if q is None:
            q = self._qs[tenant] = collections.deque()
        if not q:
            if tenant not in self._active:
                self._active.append(tenant)
            self._deficit[tenant] = 0.0
        q.append(req)

    def _quantum(self, tenant: str) -> float:
        # normalize by the smallest ACTIVE weight so one full rotation
        # always grants at least one whole job's deficit somewhere —
        # the loop in popleft() provably terminates
        wmin = min(self._sched.weight(t) for t in self._active)
        return self._sched.weight(tenant) / max(1e-9, wmin)

    def popleft(self) -> Tuple[object, str]:
        """(request, tenant) per DRR order.  Caller guarantees the
        class is non-empty (same contract as deque.popleft)."""
        while True:
            t = self._active[0]
            if self._deficit[t] >= 1.0:
                self._deficit[t] -= 1.0
                q = self._qs[t]
                req = q.popleft()
                if not q:
                    self._active.popleft()
                    self._deficit[t] = 0.0
                return req, t
            self._deficit[t] += self._quantum(t)
            self._active.rotate(-1)

    def remove_uid(self, uid: str):
        """(request, tenant) pulled out by uid (the cancel-while-queued
        path), or None."""
        for t, q in self._qs.items():
            for req in q:
                if req.uid == uid:
                    q.remove(req)
                    if not q and t in self._active:
                        self._active.remove(t)
                        self._deficit[t] = 0.0
                    return req, t
        return None

    def uids(self) -> List[str]:
        return [req.uid for q in self._qs.values() for req in q]

    def pop_all(self) -> List[Tuple[object, str]]:
        out = []
        for t, q in self._qs.items():
            out.extend((req, t) for req in q)
            q.clear()
        self._active.clear()
        self._deficit.clear()
        return out

    def tenant_depths(self) -> Dict[str, int]:
        return {t: len(q) for t, q in self._qs.items() if q}

    def backlogged(self) -> List[str]:
        return [t for t, q in self._qs.items() if q]


# ------------------------------------------------------------------ metrics

def note_admitted(tenant: str) -> None:
    _TENANT_ADMITTED.inc(tenant=tenant)


def note_shed(tenant: str) -> None:
    _TENANT_SHEDS.inc(tenant=tenant)


def note_dequeued(tenant: str) -> None:
    _TENANT_SERVED.inc(tenant=tenant)


def set_depth(tenant: str, depth: int) -> None:
    _TENANT_DEPTH.set(depth, tenant=tenant)
