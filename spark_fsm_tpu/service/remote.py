"""Actor-protocol TCP entry — the reference's second (Akka-remote) API.

The reference exposes its actor system two ways: the Spray REST surface and
an Akka-remoting entry that speaks ``ServiceRequest``/``ServiceResponse``
messages directly (SURVEY.md sec 1 L6 "AkkaApi", sec 2 "Akka remote API").
The rebuild's analog is a persistent-connection TCP protocol with one JSON
envelope per line:

    -> {"service": "fsm", "task": "train", "data": {"algorithm": ...}}
    <- {"service": "fsm", "task": "train", "data": {...}, "status": "started"}

Tasks use the actor vocabulary directly (``train``, ``status``,
``get:patterns``, ``get:rules``, ``track:{topic}``, ``stream:{topic}``,
``register:{topic}``) — the same strings the Master routes on — so a remote
client is one socket away from everything the HTTP surface offers, without
HTTP framing.  Errors come back as ``status: failure`` envelopes on the
same line framing; the connection survives malformed requests.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Optional

from spark_fsm_tpu.service.actors import Master
from spark_fsm_tpu.service.model import ServiceRequest, ServiceResponse
from spark_fsm_tpu.utils.obs import log_event

MAX_LINE = 64 << 20  # 64 MiB — streamed micro-batches ride this protocol too


class _Handler(socketserver.StreamRequestHandler):
    server: "RemoteServer"

    def handle(self) -> None:
        while True:
            try:
                line = self.rfile.readline(MAX_LINE + 1)
            except OSError:
                return
            if not line:
                return  # client closed
            if len(line) > MAX_LINE and not line.endswith(b"\n"):
                # Oversized request: drain to the next newline so the
                # one-reply-per-line framing stays in sync, then refuse it.
                while True:
                    try:
                        rest = self.rfile.readline(MAX_LINE)
                    except OSError:
                        return
                    if not rest or rest.endswith(b"\n"):
                        break
                reply = ServiceResponse(
                    "fsm", "", {"error": "request line exceeds "
                                         f"{MAX_LINE} bytes"},
                    "failure").to_json()
            else:
                line = line.strip()
                if not line:
                    continue
                reply = self._reply(line)
            self.wfile.write(reply.encode("utf-8") + b"\n")
            self.wfile.flush()

    def _reply(self, line: bytes) -> str:
        try:
            req = ServiceRequest.from_json(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError, AttributeError,
                TypeError) as exc:  # non-object JSON lands here too
            return ServiceResponse(
                "fsm", "", {"error": f"malformed request: {exc}"},
                "failure").to_json()
        try:
            return self.server.master.handle(req).to_json()
        except Exception as exc:  # worker bug -> failure envelope,
            log_event("remote_request_failed", task=req.task, error=str(exc))
            return ServiceResponse(  # not a dropped connection
                req.service, req.task,
                {"uid": req.uid, "error": str(exc)}, "failure").to_json()


class RemoteServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, master: Master, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.master = master
        super().__init__((host, port), _Handler)

    @property
    def port(self) -> int:
        return self.server_address[1]


def serve_remote_background(master: Master, host: str = "127.0.0.1",
                            port: int = 0) -> RemoteServer:
    """Start the actor-protocol server on a daemon thread."""
    server = RemoteServer(master, host, port)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="fsm-remote").start()
    log_event("remote_api_up", host=host, port=server.port)
    return server


class RemoteClient:
    """Blocking client for the actor protocol (one request per call).

    The protocol is symmetric enough that this is all a remote peer needs;
    it doubles as the reference client for tests and examples.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 9999,
                 timeout: float = 30.0) -> None:
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._file = self._sock.makefile("rwb")

    def close(self) -> None:
        try:
            self._file.close()
            self._sock.close()
        except OSError:
            pass

    def request(self, task: str, data: Optional[dict] = None,
                service: str = "fsm") -> dict:
        req = ServiceRequest(service=service, task=task,
                             data={str(k): str(v)
                                   for k, v in (data or {}).items()})
        self._file.write(req.to_json().encode("utf-8") + b"\n")
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("remote API closed the connection")
        obj = json.loads(line.decode("utf-8"))
        if not isinstance(obj, dict):
            raise ValueError(f"malformed response: {obj!r}")
        return obj
