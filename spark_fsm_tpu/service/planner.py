"""Dataset-shape-aware engine planner (ISSUE 15).

Sits between the algorithm registry and the engines: ``algorithm=AUTO``
requests are routed to a concrete engine by a calibrated crossover
model over the dataset's density/length stats
(``data/vertical.dataset_stats`` — computed once when the dataset is
admitted into the job, before the mine), explicit engine names are
always honored, and unknown names shed a structured 400 listing the
supported registry (service/model.py maps the exception).

The crossover model (docs/DESIGN.md "Engine planner" has the measured
table behind the default):

- **rules** requests (``k``/``minconf`` present) route to ``TSR_TPU``
  — SPAM serves the patterns family only.
- **patterns** requests route to ``SPAM_TPU`` when the dataset is
  DENSE enough that the fixed-shape all-items wave beats ragged
  candidate-list packing: ``density >= [planner] density_crossover``
  AND ``alphabet <= [planner] max_alphabet`` AND no maxgap/maxwindow
  constraints (the SPAM engine does not implement them).  Everything
  else routes to ``SPADE_TPU``.

``[planner] mode = "pinned"`` routes every AUTO to ``[planner]
pinned`` unconditionally — the operator lever for soaking one engine
or excluding a suspect one without touching clients.

Every decision lands in the trace spine as a zero-length
``planner.route`` span (attrs: engine, density, alphabet, reason), so
``/admin/trace/{uid}`` answers *why* an engine was picked, and bumps
``fsm_engine_selected_total{engine=...}`` (explicit routes bump it too,
from the Miner's run path — the counter is "which engine actually
mined", AUTO or not).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from spark_fsm_tpu import config
from spark_fsm_tpu.utils import obs
from spark_fsm_tpu.utils.obs import log_event

# the concrete (routable) engines — the fsm_engine_selected_total label
# vocabulary, zero-seeded so a scrape shows every engine at 0 instead
# of no-data (the obs_smoke no-orphan contract)
CONCRETE_ENGINES = ("SPADE", "SPADE_TPU", "SPAM", "SPAM_TPU",
                    "TSR", "TSR_TPU")

_SELECTED = obs.REGISTRY.counter(
    "fsm_engine_selected_total",
    "train mines dispatched, by the engine that actually ran "
    "(AUTO requests count under the planner-resolved engine)")
for _e in CONCRETE_ENGINES:
    _SELECTED.seed(engine=_e)


def count_selected(engine: str) -> None:
    if engine in CONCRETE_ENGINES:
        _SELECTED.inc(engine=engine)


def infer_kind(req) -> str:
    """AUTO's result kind is a pure function of the request params —
    rules when any TSR parameter is present, patterns otherwise — so
    coalescing identity (plugins.effective_params) is well-defined
    before any routing happens."""
    return ("rules" if (req.param("k") is not None
                        or req.param("minconf") is not None
                        or req.param("max_side") is not None)
            else "patterns")


@dataclasses.dataclass(frozen=True)
class PlannerDecision:
    engine: str
    kind: str
    mode: str           # "auto" | "pinned"
    reason: str
    density: Optional[float] = None
    alphabet: Optional[int] = None
    crossover: Optional[float] = None

    def as_attrs(self) -> dict:
        out = {"engine": self.engine, "kind": self.kind,
               "mode": self.mode, "reason": self.reason}
        if self.density is not None:
            out["density"] = self.density
        if self.alphabet is not None:
            out["alphabet"] = self.alphabet
        if self.crossover is not None:
            out["crossover"] = self.crossover
        return out


def choose_patterns_engine(stats, pcfg=None,
                           constrained: bool = False) -> PlannerDecision:
    """The calibrated patterns-family crossover over a DatasetStats —
    pure and deterministic (tests/test_planner.py pins a table of
    stats -> engine rows against it)."""
    pcfg = pcfg if pcfg is not None else config.get_config().planner
    x = float(pcfg.density_crossover)
    if constrained:
        return PlannerDecision(
            "SPADE_TPU", "patterns", "auto",
            "maxgap/maxwindow constraints (SPAM serves unconstrained "
            "patterns only)")
    if stats.alphabet > int(pcfg.max_alphabet):
        return PlannerDecision(
            "SPADE_TPU", "patterns", "auto",
            f"alphabet {stats.alphabet} > max_alphabet "
            f"{pcfg.max_alphabet} (full-item-axis waves would be "
            f"mostly dead lanes)",
            density=stats.density, alphabet=stats.alphabet, crossover=x)
    if stats.density >= x:
        return PlannerDecision(
            "SPAM_TPU", "patterns", "auto",
            f"density {stats.density} >= crossover {x}",
            density=stats.density, alphabet=stats.alphabet, crossover=x)
    return PlannerDecision(
        "SPADE_TPU", "patterns", "auto",
        f"density {stats.density} < crossover {x}",
        density=stats.density, alphabet=stats.alphabet, crossover=x)


def choose(req, db) -> PlannerDecision:
    """Route one AUTO request over a loaded dataset."""
    pcfg = config.get_config().planner
    kind = infer_kind(req)
    constrained = (req.param("maxgap") is not None
                   or req.param("maxwindow") is not None)
    if pcfg.mode == "pinned":
        engine = pcfg.pinned
        from spark_fsm_tpu.service import plugins

        if plugins.ALGORITHMS[engine].kind != kind:
            # a pinned patterns engine cannot serve a rules request
            # (or vice versa): fall back to the kind's device default,
            # loudly — routing must never change the result kind
            fallback = "TSR_TPU" if kind == "rules" else "SPADE_TPU"
            return PlannerDecision(
                fallback, kind, "pinned",
                f"pinned engine {engine} serves "
                f"{plugins.ALGORITHMS[engine].kind}, request is {kind} "
                f"— kind-default fallback")
        if constrained and engine in ("SPAM", "SPAM_TPU"):
            # same capability fallback for constraints: a SPAM soak
            # must not fail every constrained AUTO request — SPAM
            # serves unconstrained patterns only
            return PlannerDecision(
                "SPADE_TPU", kind, "pinned",
                f"pinned engine {engine} cannot serve "
                f"maxgap/maxwindow — constrained fallback to SPADE_TPU")
        return PlannerDecision(engine, kind, "pinned",
                               f"[planner] mode=pinned -> {engine}")
    if kind == "rules":
        return PlannerDecision("TSR_TPU", "rules", "auto",
                               "rules family (k/minconf present)")
    from spark_fsm_tpu.data.vertical import dataset_stats
    from spark_fsm_tpu.service.plugins import _minsup

    # density over the frequent-item projection at THIS request's
    # minsup — the item axis the routed engine will actually build
    stats = dataset_stats(db, min_item_support=_minsup(req, db))
    return choose_patterns_engine(stats, pcfg, constrained=constrained)


def choose_representation(item_supports, n_sequences: int, *,
                          pin: Optional[str] = None,
                          crossover: Optional[float] = None,
                          diffset_depth: Optional[int] = None,
                          engine: str = "spam"):
    """Per-item vertical-representation routing WITHIN a mine (ISSUE 16):
    the same calibrated density crossover that picks the engine picks,
    per item, dense SPAM bitmap vs SPADE id-list, and the pattern depth
    at which supports switch to the dEclat diffset formulation.

    Returns ``(data.vertical.RepPlan, diffset_depth)``.  Explicit
    arguments (engine kwargs, tests, benches) override the ``[planner]``
    config; every call lands a zero-length ``planner.representation``
    span on the trace spine — one record per mine explaining the whole
    per-item split (counts + density extremes + the crossover used), so
    ``/admin/trace/{uid}`` answers *why* each representation was chosen
    the same way ``planner.route`` answers the engine choice."""
    from spark_fsm_tpu.data import vertical

    pcfg = config.get_config().planner
    pin = pcfg.representation if pin is None else pin
    x = pcfg.density_crossover if crossover is None else crossover
    dd = pcfg.diffset_depth if diffset_depth is None else diffset_depth
    plan = vertical.rep_plan(item_supports, n_sequences,
                             crossover=float(x), pin=pin)
    attrs = plan.as_attrs()
    attrs.update(engine=engine, diffset_depth=int(dd))
    with obs.span("planner.representation", **attrs):
        pass
    log_event("planner_representation", **attrs)
    return plan, int(dd)


def extract_auto(req, db, stats: Optional[dict] = None,
                 checkpoint=None):
    """The AUTO plugin body: choose, record the decision (trace spine +
    counter + job stats), delegate to the chosen engine's plugin with
    ``algorithm`` rewritten so every downstream param reader sees the
    concrete engine."""
    from spark_fsm_tpu.service import plugins
    from spark_fsm_tpu.service.model import ServiceRequest

    decision = choose(req, db)
    # the zero-length routing span rides the job's contextvar trace and
    # flushes to the durable spine with it — /admin/trace/{uid} shows
    # WHY the engine was picked even after a failover
    with obs.span("planner.route", **decision.as_attrs()):
        pass
    log_event("planner_route", uid=req.uid, **decision.as_attrs())
    count_selected(decision.engine)
    if stats is not None:
        stats["planner_engine"] = decision.engine
        stats["planner_mode"] = decision.mode
        stats["planner_reason"] = decision.reason
        if decision.density is not None:
            stats["planner_density"] = decision.density
    data = dict(req.data)
    data["algorithm"] = decision.engine
    routed = ServiceRequest(req.service, req.task, data)
    return plugins.ALGORITHMS[decision.engine].extract(
        routed, db, stats, checkpoint=checkpoint)
