"""Actor-style orchestration — the reference's L5 without Akka.

``FSMMaster`` routes ``ServiceRequest``s to workers (SURVEY.md sec 1 L5,
sec 3 call stacks): miner (train), questor (get), tracker (track),
registrar (register/index), status.  Here the master is a plain router;
the miner runs jobs on a worker thread (the mailbox is a queue — the
actor model's useful property, serialized mutation, without a JVM), and
supervision = per-job exception capture into the ``failure`` status, the
reference's error contract.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
import traceback
import uuid
from typing import Dict, List, Optional

from spark_fsm_tpu import config
from spark_fsm_tpu.ops import ragged_batch as RB
from spark_fsm_tpu.service import (autoscale, fairness, integrity, lease,
                                   meshguard, model, obsplane, planner,
                                   plugins, predictor, resultcache, sources,
                                   storeguard, usage)
from spark_fsm_tpu.service.model import ServiceRequest, ServiceResponse, Status
from spark_fsm_tpu.service.store import ResultStore
from spark_fsm_tpu.utils import envelope, faults, jobctl, obs
from spark_fsm_tpu.utils.obs import log_event, profile_trace
from spark_fsm_tpu.utils.retry import RetryPolicy


def _sink_results(store: ResultStore, uid: str, kind: str, results,
                  guard=None, gate=None) -> None:
    """Persist a mine's output under ``uid`` — the single result sink used
    by batch train jobs and stream pushes alike.  With a storeguard the
    write rides the guard (spooled during a store outage, replayed under
    the fencing gate on reconnect)."""
    if kind == "patterns":
        key, payload = f"fsm:pattern:{uid}", model.serialize_patterns(results)
    else:
        key, payload = f"fsm:rule:{uid}", model.serialize_rules(results)
    if guard is None:
        store.set(key, payload)
    else:
        guard.set(uid, key, payload, gate=gate)


def _record_failure(store: ResultStore, uid: str, exc: Exception,
                    metric: str = "jobs_failed",
                    keep_frontier: bool = False,
                    lease_mgr: Optional[lease.LeaseManager] = None,
                    rescache=None, guard=None) -> None:
    """The supervision contract: error text + traceback under the error
    key, status -> failure (SURVEY.md sec 5 failure-detection row).
    ``metric`` keeps batch-job and stream-push failure counters distinct
    (jobs_failed must never exceed jobs_submitted).  ``keep_frontier``
    preserves the checkpoint keys for failures that do NOT implicate the
    mine itself (deadline/cancel aborts, shutdown drain, a recovery
    resubmit that shed): the persisted progress stays resumable by a
    later checkpointed resubmit instead of being destroyed by an abort
    the job never asked for.

    With a lease manager, the durable write is FENCED: a replica whose
    lease on ``uid`` was superseded (the adopting peer owns the uid's
    keys now) records nothing in the store — its failure stays local
    (log + counters) instead of clobbering the adopter's run.  The
    settle check is one atomic NX reacquire when the lease merely
    expired unclaimed, so the no-adopter case still lands its durable
    failure."""
    if lease_mgr is not None and not lease_mgr.settle_for_failure(uid):
        # release OUR control object by identity: the adopter (possibly
        # in this very process, in test topologies) may have
        # re-registered the uid and its live entry must keep its
        # deadline/cancel/fence signals
        ctl = lease_mgr.attached_ctl(uid)
        lease_mgr.forget(uid)
        jobctl.release_entry(ctl)
        # the fenced epoch's buffered spans must not reach the adopter's
        # spine either: tombstone first, then drain the buffer through
        # the (now refusing) flush so the rejection is COUNTED
        obsplane.mark_fenced(uid)
        log_event("job_failed_fenced", uid=uid, error=str(exc))
        with obs.span("job.failed_fenced", trace_id=uid, error=str(exc)):
            pass
        obs.flush_trace(uid)
        if rescache is not None:
            # the adopter finishes the job elsewhere — coalesced
            # followers waiting HERE re-dispatch as cold mines
            rescache.on_leader_terminal(uid)
        # fenced: the adopter owns the uid's attribution from its
        # checkpoint-adopted snapshot — dropping (not settling) our
        # stale accumulator is what keeps the ledger single-billed
        usage.drop(uid)
        return
    try:
        if guard is None:
            store.set(f"fsm:error:{uid}", f"{exc}\n{traceback.format_exc()}")
            store.add_status(uid, Status.FAILURE)
            store.incr(f"fsm:metric:{metric}")
            if not keep_frontier:
                # a job that FAILED mid-mine after its retries leaves a
                # frontier of unknown quality — drop it, don't leak it
                store.delete(f"fsm:frontier:{uid}")
                store.delete(f"fsm:frontier:results:{uid}")
            # failure is TERMINAL: the journal intent is settled (the
            # restart recovery pass must not resurrect a job that
            # failed durably)
            store.journal_clear(uid)
        else:
            # storeguard route: spooled during an outage, replayed
            # under the fencing gate on reconnect — a store blip no
            # longer turns "record the failure" into a dead worker
            guard.set(uid, f"fsm:error:{uid}",
                      f"{exc}\n{traceback.format_exc()}")
            guard.status(uid, Status.FAILURE)
            guard.incr(uid, f"fsm:metric:{metric}")
            if not keep_frontier:
                guard.delete(uid, f"fsm:frontier:{uid}")
                guard.delete(uid, f"fsm:frontier:results:{uid}")
            guard.delete(uid, f"fsm:journal:{uid}")
    except Exception as wexc:
        # the store failed while recording the failure: the journal
        # intent survives, so recovery settles the uid after the store
        # returns — log loudly instead of killing the worker thread
        log_event("job_failure_record_failed", uid=uid, error=str(wexc))
    # failed or not, the device work already happened — settle it into
    # the tenant rollup so the ledger conserves against the dispatch
    # counters (a failure is not a refund)
    usage.settle(uid)
    # the job-control entry is released regardless (stream uids have
    # neither journal nor entry — no-ops)
    jobctl.release(uid)
    log_event("job_failed", uid=uid, error=str(exc))
    # stamp the terminal failure into the job's flight-recorder ring
    # (explicit trace_id: failures land from threads with no active
    # trace context — the drain path, the submit-after-shutdown path),
    # then flush the spine BEFORE releasing the lease so the final
    # chunk still rides the fenced write path
    with obs.span("job.failed", trace_id=uid, error=str(exc)):
        pass
    obs.lifecycle(uid, "settled", outcome="failure",
                  code=getattr(exc, "code", type(exc).__name__))
    obs.flush_trace(uid)
    if lease_mgr is not None:
        lease_mgr.release(uid)
    if rescache is not None:
        # a leader's abort is its client's decision, not the followers':
        # re-dispatch any coalesced followers through normal admission
        rescache.on_leader_terminal(uid)


def _profile_dir(req: ServiceRequest, uid: str) -> str:
    """Trace dir for this job, or "" (no profiling).

    ``profile`` request param: a path = trace there; any other truthy
    value = trace under the boot config's ``profile_dir`` (required then).
    """
    value = req.param("profile")
    if value is None or value.lower() in ("", "0", "false", "no", "off"):
        return ""
    if "/" in value or value.startswith("."):
        return value
    root = config.get_config().profile_dir
    if not root:
        raise ValueError(
            "profile=1 requested but no profile_dir configured at boot "
            "(set profile_dir in the config file, or pass profile=<path>)")
    return os.path.join(root, uid)


class StoreCheckpoint:
    """Frontier checkpoint persisted in the result store — the optional
    long-mine half of SURVEY.md sec 5's checkpoint row (results-at-job-end
    remain the primary contract).  The engine fingerprints each snapshot,
    so a retry against changed data safely restarts fresh instead of
    resuming garbage.

    Two keys: ``fsm:frontier:{uid}`` holds the frontier snapshot,
    ``fsm:frontier:results:{uid}`` is an APPEND-ONLY list of result-delta
    chunks — each save writes only the patterns found since the previous
    one, so checkpoint cost tracks the frontier, not the full output.

    A ``results_done=0`` save (a fresh mine's first snapshot, or EVERY
    snapshot of a full-rewrite engine like TSR, whose accepted set shrinks
    as minsup rises) embeds its results INSIDE the meta value instead: one
    atomic SET.  A delete-list-then-rewrite scheme would reintroduce the
    torn-snapshot hazard the count check cannot catch — consecutive top-k
    rewrites routinely have the SAME length, so an old meta paired with a
    newer list would pass ``results_total`` and resume duplicated rules.

    Failure posture (the chaos-suite contract): every store verb runs
    under the shared bounded-backoff RetryPolicy (utils/retry.py, site
    ``store.checkpoint``), so a transient store hiccup never fails a
    save; ``save`` works on a SHALLOW COPY of the caller's state dict,
    so a save that dies mid-way leaves the engine's state intact and a
    retried save writes the correct ``results_total``; and ``load``
    HEALS a kill between the delta ``rpush`` and the meta ``set`` — the
    meta names the last GOOD snapshot, trailing chunks newer than it
    (including a retried rpush that had actually landed) are trimmed
    away, and only a list that cannot be reconciled at a chunk boundary
    is refused outright."""

    def __init__(self, store: ResultStore, uid: str,
                 every_s: float = 30.0,
                 retry: Optional[RetryPolicy] = None,
                 lease_mgr: Optional[lease.LeaseManager] = None,
                 guard=None) -> None:
        self.store, self.uid, self.every_s = store, uid, every_s
        self._meta_key = f"fsm:frontier:{uid}"
        self._results_key = f"fsm:frontier:results:{uid}"
        self._inline: list = []  # results_done=0 part of the loaded snapshot
        self._retry = retry if retry is not None else RetryPolicy(seed=0)
        # multi-replica fence: every save re-proves lease ownership
        # BEFORE writing — a stale holder's snapshot must never land
        # over the adopting replica's (service/lease.py)
        self._lease = lease_mgr
        # store-outage guard (service/storeguard.py): saves during a
        # proven outage spool instead of failing the job; None = the
        # pre-guard posture at one `is None` read per save
        self._guard = guard

    def _io(self, fn, *args):
        return self._retry.run(fn, *args, site="store.checkpoint")

    def load(self) -> Optional[dict]:
        raw = self._io(self.store.get, self._meta_key)
        if not raw:
            return None
        meta_payload, verdict = integrity.open_value(raw, "checkpoint")
        if verdict == "corrupt":
            # corrupt META: the snapshot's identity itself is
            # unverifiable — quarantine the bytes for the post-mortem
            # and restart the mine fresh, LOUDLY (ISSUE 18 posture)
            integrity.quarantine(self.store, self._meta_key, raw,
                                 "checkpoint", move=True)
            self._io(self.store.delete, self._results_key)
            log_event("frontier_checkpoint_corrupt_meta", uid=self.uid)
            return None
        state = json.loads(meta_payload)
        inline = state.pop("results_inline", [])
        total = state.pop("results_total", -1)
        chunks = self._io(self.store.lrange, self._results_key)
        results = list(inline)
        used = 0
        # (embedded snapshot state, chunks kept, results at that point)
        # for the corrupt-delta heal: every enveloped chunk embeds the
        # frontier state as of its OWN save, so a later chunk's
        # corruption truncates back to here instead of restarting
        last_good = None
        for chunk in chunks:
            if len(results) == total:
                break  # later chunks postdate this meta (torn tail)
            payload, cv = integrity.open_value(chunk, "checkpoint")
            delta, emb = None, None
            if cv != "corrupt":
                try:
                    obj = json.loads(payload)
                except ValueError:
                    obj = None
                if (isinstance(obj, dict)
                        and isinstance(obj.get("delta"), list)):
                    delta, emb = obj["delta"], obj.get("state")
                elif isinstance(obj, list):
                    delta = obj  # legacy chunk: bare delta, no state
            if delta is None:
                return self._heal_corrupt_delta(chunk, inline, results,
                                                used, last_good)
            results.extend(delta)
            used += 1
            if (isinstance(emb, dict)
                    and emb.get("results_total") == len(results)):
                last_good = (emb, used, len(results))
        if len(results) != total:
            return None  # torn snapshot (killed mid-save): refuse to resume
        if used < len(chunks):
            # a save died between its delta rpush and its meta set: the
            # meta is the LAST GOOD snapshot and the trailing chunks are
            # orphans — trim them so resumed append-mode saves stay
            # consistent with results_total (leaving them would corrupt
            # the NEXT load: a fresh delta lands after the orphan)
            self._io(self.store.ltrim, self._results_key, used)
            log_event("frontier_checkpoint_healed", uid=self.uid,
                      trimmed_chunks=len(chunks) - used)
        # append-mode saves after this resume must re-embed the inline part
        # (their meta overwrites the one that carried it)
        self._inline = inline
        state["results"] = results
        return self._adopt_usage(state)

    def _adopt_usage(self, state: Optional[dict]) -> Optional[dict]:
        """Strip the checkpoint's usage snapshot (the engine's resume
        contract knows nothing of it) and hand it to the meter —
        REPLACING any live accumulator for the uid."""
        if state is not None:
            snap = state.pop("usage", None)
            if snap:
                usage.resume(self.uid, snap)
        return state

    def _heal_corrupt_delta(self, bad_chunk, inline, results, used,
                            last_good) -> Optional[dict]:
        """A delta chunk INSIDE the used prefix failed verification: the
        meta's snapshot is unreachable, but every enveloped chunk embeds
        the frontier state as of its own save — so truncate the list to
        the last good embedded snapshot, rewrite the meta to it, and
        RESUME from there: the corruption costs only the work mined
        after that chunk.  With no embedded predecessor (first chunk
        corrupt, or a legacy pre-envelope prefix) the snapshot is
        unreconstructable — quarantine and restart fresh, loudly."""
        integrity.quarantine(self.store, f"{self._results_key}#{used}",
                             bad_chunk, "checkpoint")
        if last_good is None:
            self._io(self.store.delete, self._meta_key)
            self._io(self.store.delete, self._results_key)
            log_event("frontier_checkpoint_corrupt_restart", uid=self.uid)
            return None
        emb, keep, n = last_good
        self._io(self.store.ltrim, self._results_key, keep)
        meta = dict(emb)  # embedded state carries results_total already
        meta["results_inline"] = inline
        self._io(self.store.set, self._meta_key,
                 envelope.wrap(json.dumps(meta)))
        log_event("frontier_checkpoint_corrupt_delta_healed",
                  uid=self.uid, kept_chunks=keep, results=n)
        self._inline = inline
        state = dict(emb)
        state.pop("results_total", None)
        state["results"] = results[:n]
        return self._adopt_usage(state)

    def save(self, state: dict) -> None:
        with obs.span("checkpoint.save", trace_id=self.uid):
            self._save(state)
        # a successful save is a durable milestone: mark it and flush
        # the trace spine so a kill -9 loses at most the spans since
        # the last checkpoint — exactly the window the frontier itself
        # bounds (the replica_smoke failover timeline reads off this)
        obs.lifecycle(self.uid, "checkpointed")
        obs.flush_trace(self.uid)

    def _save(self, state: dict) -> None:
        g = self._guard
        outage = g is not None and g.is_down()
        if self._lease is not None and not outage:
            # during a PROVEN outage the fence is deferred to the
            # spool's replay gate (journal-gated NX reacquire under the
            # same token) — verifying against an unreachable store here
            # would just fence a job the outage semantics say may stall
            self._lease.fence(self.uid)  # raises JobLeaseLost when stale
        faults.fault_site("checkpoint.save", uid=self.uid)
        # NON-DESTRUCTIVE: pop from a shallow copy, never the caller's
        # dict — a store failure mid-save must leave the engine's state
        # whole so a retried save recomputes the same results_total
        state = dict(state)
        delta = state.pop("results")
        done = state.pop("results_done")
        # usage-attribution snapshot (service/usage.py): rides the meta
        # AND every delta chunk's embedded state, so an adopter resumes
        # the job's device-cost accumulator from wherever load() lands —
        # resume REPLACES, so re-mined work never double-bills
        snap = usage.checkpoint_snapshot(self.uid)
        if snap is not None:
            state["usage"] = snap
        if outage:
            self._save_spooled(g, state, delta, done)
            return
        try:
            self._save_direct(state, delta, done)
        except Exception as exc:
            # a transport failure the guard's probe confirms as an
            # outage converts the save into a spool append mid-flight
            # (an ack-lost rpush that actually landed would make the
            # chunk list non-reconcilable — load() REFUSES such a list
            # and the mine restarts fresh: slower, never corrupt)
            if g is None or not g.note_error(exc):
                raise
            self._save_spooled(g, state, delta, done)

    def _save_direct(self, state: dict, delta, done: int) -> None:
        if done == 0:
            # single atomic meta SET; the chunk list (possibly stale from a
            # crashed earlier incarnation) is dropped
            self._io(self.store.delete, self._results_key)
            self._inline = delta
            state["results_total"] = len(delta)
        else:
            if delta:
                # each chunk embeds the frontier state AS OF THIS SAVE
                # (sans the inline part, which the meta re-embeds every
                # save anyway): the corrupt-delta heal resumes from the
                # newest intact chunk's embedded snapshot (ISSUE 18)
                emb = dict(state)
                emb["results_total"] = done + len(delta)
                payload = envelope.wrap(
                    json.dumps({"delta": delta, "state": emb}))
                n0 = self._io(self.store.llen, self._results_key)

                def _push_delta():
                    # idempotent under retry: an append that LANDED but
                    # raised (ack lost) must not land twice — one writer
                    # per uid, so the length check is race-free
                    if self.store.llen(self._results_key) <= n0:
                        self.store.rpush(self._results_key, payload)

                self._io(_push_delta)
            state["results_total"] = done + len(delta)
        state["results_inline"] = self._inline
        # meta written LAST: results_total only matches inline+list once
        # the delta is in, so a kill between writes reads as torn (and
        # load() heals back to THIS meta's snapshot), never as valid
        self._io(self.store.set, self._meta_key,
                 envelope.wrap(json.dumps(state)))
        log_event("frontier_checkpoint", uid=self.uid,
                  stack=len(state["stack"]), results=state["results_total"])

    def _save_spooled(self, g, state: dict, delta, done: int) -> None:
        """The outage-mode save: the same write sequence (delta first,
        meta LAST — so any replayed prefix reads as torn and load()
        heals back to the previous good snapshot, exactly the existing
        contract) appended to the write-behind spool.  No llen
        idempotence check: one writer per uid plus strictly in-order
        replay makes the spooled sequence exact by construction."""
        uid = self.uid
        if done == 0:
            g.delete(uid, self._results_key)
            self._inline = delta
            state["results_total"] = len(delta)
        else:
            if delta:
                emb = dict(state)
                emb["results_total"] = done + len(delta)
                g.rpush(uid, self._results_key, envelope.wrap(
                    json.dumps({"delta": delta, "state": emb})))
            state["results_total"] = done + len(delta)
        state["results_inline"] = self._inline
        g.set(uid, self._meta_key, envelope.wrap(json.dumps(state)))
        log_event("frontier_checkpoint_spooled", uid=uid,
                  stack=len(state["stack"]),
                  results=state["results_total"])

    def clear(self) -> None:
        g = self._guard
        if g is not None:
            g.delete(self.uid, self._meta_key)
            g.delete(self.uid, self._results_key)
            return
        self.store.delete(self._meta_key)
        self.store.delete(self._results_key)


class AdmissionShed(RuntimeError):
    """A submit refused with HTTP 429 + ``Retry-After: retry_after_s``.
    Default message = the global-queue-full case; ``why`` overrides it
    for the other shed scopes (a tenant over its fairness cap, a
    draining replica, a dataset already in flight on a peer)."""

    def __init__(self, uid: str, depth: int, queued: int,
                 retry_after_s: int, why: Optional[str] = None):
        self.retry_after_s = retry_after_s
        super().__init__(
            why or f"admission queue full ({queued}/{depth} jobs "
                   f"queued); retry in ~{retry_after_s}s")


class UidConflict(RuntimeError):
    """A submit naming a uid that is currently queued or running — the
    HTTP layer maps it to 409.  Accepting it would wipe the live job's
    state from under its worker (the old clear-at-submit hazard)."""

    def __init__(self, uid: str):
        super().__init__(
            f"uid {uid!r} is live (queued or running); resubmitting would "
            "wipe its state — wait for a terminal status or use a new uid")


class QuarantinedUid(UidConflict):
    """A submit naming a crash-loop-quarantined uid ([cluster]
    max_adoptions exhausted).  Subclasses :class:`UidConflict` so every
    handler maps it to the same 409 — but the message points the
    operator at the release path instead of at a live job."""

    def __init__(self, uid: str, adoptions: Optional[int] = None):
        tag = "" if adoptions is None else f" after {adoptions} adoptions"
        RuntimeError.__init__(
            self,
            f"uid {uid!r} is quarantined as a poison job{tag}; inspect "
            f"fsm:quarantine:{uid} and release via "
            "/admin/quarantine?action=release before resubmitting")


# the ONE priority vocabulary (admission classes, SLO label seeding)
# lives in obsplane — actors imports it so the two can never drift
PRIORITIES = obsplane.PRIORITIES

_QUEUE_DEPTH = obs.REGISTRY.gauge(
    "fsm_service_queue_depth",
    "train jobs queued for a miner worker (excludes the running ones)")
_SHEDS_TOTAL = obs.REGISTRY.counter(
    "fsm_service_sheds_total",
    "train submits refused with 429 because the admission queue was full")
for _p in PRIORITIES:
    _SHEDS_TOTAL.seed(priority=_p)
_DRAINS_TOTAL = (obs.REGISTRY.counter(
    "fsm_replica_drains_total",
    "scale-down drains of this replica, by outcome (clean = queue fully "
    "stolen/finished before the timeout; timeout = leftovers handed to "
    "the peers' recovery protocol)")
    .seed(outcome="clean").seed(outcome="timeout"))


class AdmissionQueue:
    """Bounded, priority-classed mailbox replacing the unbounded
    ``queue.Queue`` — the admission-control half of the overload story.

    Three strict priority classes (``high`` > ``normal`` > ``low``);
    within a class, FIFO — or, with a fairness scheduler installed
    (``[fairness] enabled``, service/fairness.py), deficit-weighted
    round-robin across tenants with per-tenant occupancy caps; the
    classes stay strict ABOVE fairness either way.  ``depth`` bounds
    the QUEUED jobs (running jobs have already left the queue; 0 =
    unbounded).  Admission is a two-phase reserve/put so the bound is
    exact under concurrent submitters even though the store writes
    between reservation and enqueue take time: ``try_reserve``
    atomically claims a slot (or reports the shed), ``put`` converts
    it, ``abort`` returns it.

    Worker sentinels (shutdown) are counted separately and handed out
    only once every queued job has been drained — backlog jobs always
    reach a worker, which gives them their durable drain failure.
    ``pause`` (the scale-down drain) stops workers from picking up
    QUEUED work while sentinels still surface, so a drained replica's
    backlog is left for peers to steal instead of being started
    locally."""

    def __init__(self, depth: int,
                 fair: Optional[fairness.TenantScheduler] = None):
        self.depth = int(depth)
        self._fair = fair
        self._cond = threading.Condition()
        if fair is None:
            self._qs: Dict[str, object] = {
                p: collections.deque() for p in PRIORITIES}
        else:
            self._qs = {p: fairness.FairClass(fair) for p in PRIORITIES}
        self._reserved = 0
        self._tenant_reserved: Dict[str, int] = {}
        self._tenant_queued: Dict[str, int] = {}
        self._sentinels = 0
        self._paused = False
        _QUEUE_DEPTH.set(0)

    def _n_queued(self) -> int:
        return sum(len(q) for q in self._qs.values())

    def size(self) -> int:
        with self._cond:
            return self._n_queued()

    def _tenant_total(self, tenant: str) -> int:
        return (self._tenant_queued.get(tenant, 0)
                + self._tenant_reserved.get(tenant, 0))

    def try_reserve(self, priority: str = "low",
                    tenant: str = fairness.DEFAULT_TENANT):
        """(admitted, queued_now, queued_ahead, scope): claim a queue
        slot, or report a shed (``admitted=False``) naming what refused
        it — ``"queue"`` (the global depth; ``queued_now``/``ahead``
        are the global counts) or ``"tenant"`` (the tenant's own
        occupancy cap; both counts are the TENANT's).  ``queued_ahead``
        is the shed submit's true queue position — jobs in classes at
        or above its priority, plus in-flight reservations (class
        unknown until ``put``, counted ahead conservatively) — the
        Retry-After estimator's input: a shed ``high`` submit behind
        200 ``low`` jobs waits for the running work, not the whole
        backlog."""
        with self._cond:
            if self._fair is not None and self._fair.tenant_depth > 0:
                # the tenant's token bucket: one token per queued slot,
                # consumed here, returned at dequeue/abort.  Checked
                # BEFORE the global bound so a flooding tenant sheds
                # with ITS OWN counts while the fleet still has room.
                tn = self._tenant_total(tenant)
                if tn >= self._fair.tenant_depth:
                    return False, tn, tn, "tenant"
            n = self._n_queued() + self._reserved
            if self.depth > 0 and n >= self.depth:
                rank = PRIORITIES.index(priority)
                ahead = sum(len(self._qs[p])
                            for p in PRIORITIES[:rank + 1])
                return False, n, ahead + self._reserved, "queue"
            self._reserved += 1
            if self._fair is not None:
                self._tenant_reserved[tenant] = \
                    self._tenant_reserved.get(tenant, 0) + 1
            return True, n, 0, ""

    def abort(self, tenant: str = fairness.DEFAULT_TENANT) -> None:
        with self._cond:
            self._reserved -= 1
            if self._fair is not None:
                self._tenant_reserved[tenant] = max(
                    0, self._tenant_reserved.get(tenant, 0) - 1)

    def _set_tenant_queued(self, tenant: str, delta: int) -> None:
        n = max(0, self._tenant_queued.get(tenant, 0) + delta)
        self._tenant_queued[tenant] = n
        fairness.set_depth(tenant, n)

    def put(self, req: ServiceRequest, priority: str,
            tenant: str = fairness.DEFAULT_TENANT) -> None:
        with self._cond:
            self._reserved -= 1
            if self._fair is not None:
                self._tenant_reserved[tenant] = max(
                    0, self._tenant_reserved.get(tenant, 0) - 1)
                self._qs[priority].append(req, tenant)
                self._set_tenant_queued(tenant, +1)
            else:
                self._qs[priority].append(req)
            _QUEUE_DEPTH.set(self._n_queued())
            self._cond.notify()

    def put_sentinel(self) -> None:
        with self._cond:
            self._sentinels += 1
            self._cond.notify()

    def get(self) -> Optional[ServiceRequest]:
        """Highest-priority queued request, or None (a sentinel) —
        sentinels only surface once the backlog is fully drained.
        While PAUSED (scale-down drain) queued work is invisible but
        sentinels still surface, so shutdown after a drain completes."""
        with self._cond:
            while True:
                if not self._paused:
                    for p in PRIORITIES:
                        if self._qs[p]:
                            if self._fair is not None:
                                req, tenant = self._qs[p].popleft()
                                self._set_tenant_queued(tenant, -1)
                                fairness.note_dequeued(tenant)
                            else:
                                req = self._qs[p].popleft()
                            _QUEUE_DEPTH.set(self._n_queued())
                            return req
                if self._sentinels:
                    self._sentinels -= 1
                    return None
                self._cond.wait()

    def remove(self, uid: str) -> Optional[ServiceRequest]:
        """Pull a QUEUED request out by uid (the cancel-while-queued
        path: its slot must return to the pool NOW, not when a worker
        eventually dequeues the dead work).  None when no queued request
        carries the uid — a worker already took it."""
        with self._cond:
            if self._fair is not None:
                for q in self._qs.values():
                    hit = q.remove_uid(uid)
                    if hit is not None:
                        req, tenant = hit
                        self._set_tenant_queued(tenant, -1)
                        _QUEUE_DEPTH.set(self._n_queued())
                        return req
                return None
            for q in self._qs.values():
                for req in q:
                    if req.uid == uid:
                        q.remove(req)
                        _QUEUE_DEPTH.set(self._n_queued())
                        return req
        return None

    # ------------------------------------------------- scale-down drain

    def pause(self) -> None:
        """Stop handing QUEUED work to workers (they finish their
        current job only) — the drain protocol's first step.  Sentinels
        still surface, so a later shutdown() completes normally."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def queued_uids(self) -> List[str]:
        """Snapshot of the queued uids (the drain loop's steal-reap
        input)."""
        with self._cond:
            if self._fair is not None:
                return [u for q in self._qs.values() for u in q.uids()]
            return [req.uid for q in self._qs.values() for req in q]

    def pop_all(self) -> List[ServiceRequest]:
        """Empty every class (the drain-timeout leftovers: jobs the
        peers did not steal in time, handed to the recovery protocol by
        the caller)."""
        with self._cond:
            out: List[ServiceRequest] = []
            for q in self._qs.values():
                if self._fair is not None:
                    for req, tenant in q.pop_all():
                        self._set_tenant_queued(tenant, -1)
                        out.append(req)
                else:
                    out.extend(q)
                    q.clear()
            _QUEUE_DEPTH.set(0)
            return out

    def tenant_depths(self) -> Dict[str, int]:
        """Per-tenant queued counts (empty without a fairness
        scheduler) — piggybacked on the lease heartbeat snapshot."""
        with self._cond:
            return {t: n for t, n in self._tenant_queued.items() if n > 0}


def _checkpoint_requested(req: ServiceRequest) -> bool:
    """One spelling of the checkpoint-param truthiness (Miner._run_traced
    and the admission layer's keep-frontier decision must agree)."""
    return (req.param("checkpoint") or "").lower() not in (
        "", "0", "false", "no", "off")


class Miner:
    """Train worker: source -> dataset -> plugin -> sink, with statuses.

    Mirrors SURVEY.md sec 3.1: status 'started' -> build dataset ->
    'dataset' -> mine -> sink patterns/rules -> 'trained' -> 'finished';
    failures land in 'failure' with the error recorded (the supervision
    contract of the reference's actor hierarchy).

    Supervision extends to retry: a failed job re-runs up to ``retries``
    times (request param; default from the boot config) before the failure
    status lands — the analog of Spark's task re-execution.  With
    ``checkpoint=1`` a retry resumes the mine from the last persisted
    frontier instead of starting over.

    Overload/restart posture (ISSUE 5): the mailbox is a bounded
    priority-classed :class:`AdmissionQueue` (``[service] queue_depth``;
    ``priority`` request param) — a full queue sheds the submit with
    :class:`AdmissionShed` (HTTP 429 + Retry-After from the cost-model
    estimate of the queued work) BEFORE any store write, so a shed
    leaves zero trace of the uid.  A ``deadline_s`` request param stamps
    a budget at submit (queue wait spends it) enforced at the engines'
    launch-boundary safe points via utils/jobctl; ``/admin/cancel``
    aborts the same way.  Every admitted job writes a journal intent
    record (``fsm:journal:{uid}``) cleared only on terminal status —
    the crash-restart recovery pass (:func:`recover_orphans`) reads it.
    """

    def __init__(self, store: ResultStore, workers: int = 1,
                 queue_depth: Optional[int] = None,
                 lease_mgr: Optional[lease.LeaseManager] = None) -> None:
        self.store = store
        if queue_depth is None:
            queue_depth = config.get_config().service.queue_depth
        # weighted-fair multi-tenant admission (ISSUE 13,
        # service/fairness.py): None (the default) keeps the queue's
        # plain per-class deques and the tenant param ignored
        self._fair = fairness.build_scheduler()
        self._q = AdmissionQueue(queue_depth, fair=self._fair)
        # scale-down drain state (ISSUE 13): set by drain() — submits
        # shed with 429 pointing at the peers, workers stop picking up
        # queued work, and the backlog leaves via the steal/recovery
        # protocol instead of running here
        self._draining = False
        # multi-replica lease layer (ISSUE 8): explicit manager, or
        # built from the boot [cluster] section.  None (the default
        # single-replica deployment) keeps every guard below at one
        # ``is None`` read.
        if lease_mgr is None and config.get_config().cluster.enabled:
            lease_mgr = lease.LeaseManager.from_config(
                store, config.get_config().cluster)
        self._lease = lease_mgr
        # result-reuse tier (ISSUE 12, service/resultcache.py): dataset
        # fingerprints + in-flight coalescing + dominance serving above
        # admission.  None (the default) keeps submit at ONE attribute
        # read — bench_smoke's dispatch counters stay byte-identical.
        self._rescache = resultcache.build_for(self)
        # store-outage survival (ISSUE 14, service/storeguard.py):
        # health state machine + write-behind spool + outage stalls.
        # None (the default) keeps every durable-write guard below at
        # one ``is None`` read — bench_smoke dispatch counters stay
        # byte-identical.
        self._guard = None
        if config.get_config().storeguard.enabled:
            self._guard = storeguard.install(store, lease_mgr=self._lease)
            self._guard.start()
        # this Miner's incarnation id: journal entries carrying it are
        # LIVE (409 on resubmit); entries carrying any other id belong
        # to a dead incarnation and are recovery fodder
        self.incarnation = uuid.uuid4().hex
        self._stopping = False
        # guards the _stopping check-and-enqueue in submit() against
        # shutdown(): without it a submit could pass the check, lose the
        # CPU, and enqueue BEHIND the sentinels after the workers exited
        self._stop_lock = threading.Lock()
        # EWMA of measured job walls — the Retry-After estimator's input
        # once real jobs have run (the cost-model prior seeds it)
        self._wall_lock = threading.Lock()
        self._wall_ewma: Optional[float] = None
        # serializes the conflict-check -> journal-intent window of
        # submit(): without it two concurrent submits of the SAME uid
        # both pass the 409 check and both admit — the state-wipe race
        # the conflict check exists to close
        self._admit_lock = threading.Lock()
        # adoption counters staged by note_adoption() for the NEXT admit
        # of a uid (recovery resubmit / steal): the journal intent the
        # admit writes carries the count, so the crash-loop quarantine
        # budget ([cluster] max_adoptions) survives further crashes
        self._adoptions_pending: Dict[str, int] = {}
        # running-job count (distinct from queue depth): what the lease
        # heartbeat advertises and the steal scan's idle check reads
        self._running = 0
        self._running_lock = threading.Lock()
        # lifetime successful admissions (monotone): heartbeat-
        # piggybacked as "adm" so the autoscale leader can smooth the
        # fleet's admission RATE and its derivative (predictive
        # scale-up, [autoscale] up_rate_derivative)
        self._admitted = 0
        self._threads = [
            threading.Thread(target=self._loop, daemon=True,
                             name=f"fsm-miner-{i}")
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()
        if self._lease is not None:
            # heartbeat starts with the workers; Master re-wires the
            # periodic-recovery callback after it exists (start() is
            # idempotent on the thread, updates the callback)
            self._lease.start(self)
            # cluster observability plane (ISSUE 9): durable trace
            # spine through the fenced write path + fsm_cluster_*
            # collector.  Last Miner wins, like the jobs collector;
            # solo deployments install nothing and the recorder's
            # spine probe stays one module-global read.
            obsplane.install(self.store, self._lease)
        # durable-state integrity plane (ISSUE 18, service/integrity.py):
        # the at-rest scrubber over this store (last Miner wins, like
        # obsplane).  Cluster mode drives it off the lease heartbeat
        # (integrity.tick inside LeaseManager.tick); solo service boots
        # start its cadence thread in app.main.  None when [integrity]
        # enabled = false — verify-on-READ stays unconditional either
        # way (it is a correctness property, not a feature flag).
        self._integrity = integrity.install(self.store)
        # usage metering plane (ISSUE 19, service/usage.py): the
        # per-job/per-tenant device-cost meter over this store (last
        # Miner wins).  Cluster mode flushes the durable ledger off the
        # lease heartbeat (usage.tick inside LeaseManager.tick); solo
        # installs start the meter's private flush timer.  None when
        # [usage] enabled = false — every dispatch-surface deposit
        # probe is then one module-global read.
        self._usage = usage.install(self.store, self._lease)
        # degraded-topology survival plane (ISSUE 20, service/
        # meshguard.py): per-partition-row health state machine +
        # topology epochs + crash-loop quarantine.  Cluster mode
        # gossips/probes off the lease heartbeat (meshguard tick phase
        # inside LeaseManager.tick).  [meshguard] enabled = false is a
        # strict no-op (a test-installed guard survives a Miner boot);
        # uninstalled, every epoch check and row-fault probe costs one
        # module-global read.
        if config.get_config().meshguard.enabled:
            meshguard.install(config.get_config().meshguard)

    # ------------------------------------------------------------ admission

    def queue_size(self) -> int:
        return self._q.size()

    def worker_count(self) -> int:
        return len(self._threads)

    def running_count(self) -> int:
        with self._running_lock:
            return self._running

    def idle_capacity(self) -> int:
        """Worker slots covered by neither running nor queued work — the
        steal scan's budget (and the heartbeat's ``free`` field)."""
        return max(0, self.worker_count() - self.running_count()
                   - self.queue_size())

    def sheds_total(self) -> float:
        """Lifetime 429 sheds (all priorities) — piggybacked on the
        lease heartbeat's metric snapshot."""
        return _SHEDS_TOTAL.total()

    def wall_ewma(self) -> Optional[float]:
        """EWMA of measured job walls (None before the first finish) —
        the heartbeat snapshot's load-cost hint."""
        with self._wall_lock:
            return self._wall_ewma

    def admitted_total(self) -> int:
        """Lifetime successful admissions — the heartbeat snapshot's
        "adm" field (the autoscaler's predictive-rate input)."""
        with self._running_lock:
            return self._admitted

    @property
    def draining(self) -> bool:
        return self._draining

    def tenant_depths(self) -> Dict[str, int]:
        """Per-tenant queued counts (empty without fairness) — the
        heartbeat snapshot's multi-tenant load view."""
        return self._q.tenant_depths() if self._fair is not None else {}

    def inflight_fps(self) -> List[str]:
        """Dataset fingerprints of in-flight coalescing leaders (empty
        without the result-reuse tier) — the heartbeat snapshot's
        cross-replica coalesce hint (ROADMAP 2c)."""
        rc = self._rescache
        return rc.inflight_fps() if rc is not None else []

    def drain(self, timeout_s: Optional[float] = None,
              reason: str = "scale-down") -> dict:
        """The scale-down drain protocol (ISSUE 13), on the substrate
        PR 8 already built:

        1. stop admitting — submits shed with 429 whose Retry-After is
           the steal path (~2 heartbeats);
        2. stop STARTING queued work (workers finish their current job
           only; the queue pauses) and advertise ``draining`` with zero
           free capacity, so idle peers steal the queued backlog off
           our admission namespace exactly as they would off a loaded
           healthy replica;
        3. wait until the queue has been stolen empty and the running
           jobs finished, or ``timeout_s`` elapses;
        4. leftovers (peers too busy to steal in time) keep their
           journal intent + admission marker but have their LEASE
           released, so the survivors' steal scans and periodic
           recovery adopt them immediately — slower than a steal,
           never lost, never run twice.

        The caller (service/autoscale.py directive, /admin/drain, or
        an operator) shuts the process down afterwards; this method
        only guarantees that by its return every job this replica ever
        admitted is finished, stolen, adoptable, or durably settled.
        Lifecycle ``draining``/``drained`` spans land on the durable
        trace spine under ``replica:{id}`` so the fleet timeline shows
        the drain even after the process exits."""
        with self._stop_lock:
            if self._draining:
                return {"state": "already-draining"}
            self._draining = True
        if timeout_s is None:
            timeout_s = config.get_config().autoscale.drain_timeout_s
        rid = (self._lease.replica_id if self._lease is not None
               else "solo")
        trace_id = f"replica:{rid}"
        t0 = time.monotonic()
        queued0, running0 = self.queue_size(), self.running_count()
        log_event("replica_draining", replica=rid, queued=queued0,
                  running=running0, reason=reason)
        with obs.span("lifecycle.draining", trace_id=trace_id,
                      replica=rid, reason=reason, queued=queued0,
                      running=running0):
            pass
        obs.flush_trace(trace_id)
        self._q.pause()
        if self._lease is not None:
            # heartbeat flips to draining/free=0/steal=false and
            # publishes immediately: peers must stop counting on us
            # (and start stealing from us) within one heartbeat
            self._lease.set_draining(True)
        deadline = t0 + max(0.1, float(timeout_s))
        stolen = 0
        while time.monotonic() < deadline:
            stolen += self._reap_stolen()
            if self.queue_size() == 0 and self.running_count() == 0:
                break
            time.sleep(0.02)
        stolen += self._reap_stolen()
        leftovers = self._q.pop_all()
        for req in leftovers:
            if self._lease is not None:
                # journal intent + admission marker stay (the survivors'
                # steal scan or periodic recovery picks each up exactly
                # once); releasing the lease makes adoption IMMEDIATE
                # instead of a TTL wait.  Local control state dies here.
                ctl = self._lease.attached_ctl(req.uid)
                self._lease.release(req.uid)
                jobctl.release_entry(ctl)
                if self._rescache is not None:
                    # local followers cannot wait for a fan-out that
                    # will now happen on the adopting replica
                    self._rescache.on_leader_terminal(req.uid)
            else:
                # solo deployment: nobody can adopt — settle durably,
                # keep_frontier so a checkpointed resubmit resumes
                _record_failure(self.store, req.uid,
                                RuntimeError("replica draining"),
                                keep_frontier=True, lease_mgr=None,
                                rescache=self._rescache,
                                guard=self._guard)
        running_left = self.running_count()
        outcome = ("clean" if not leftovers and running_left == 0
                   else "timeout")
        _DRAINS_TOTAL.inc(outcome=outcome)
        report = {"outcome": outcome, "reason": reason,
                  "replica": rid, "waited_s": round(
                      time.monotonic() - t0, 3),
                  "queued_at_start": queued0,
                  "running_at_start": running0,
                  "stolen_by_peers": stolen,
                  "left_for_recovery": len(leftovers),
                  "running_left": running_left}
        log_event("replica_drained", **report)
        with obs.span("lifecycle.drained", trace_id=trace_id,
                      replica=rid, outcome=outcome,
                      left_for_recovery=len(leftovers)):
            pass
        obs.flush_trace(trace_id)
        return report

    def _reap_stolen(self) -> int:
        """Drain-loop victim bookkeeping: with the queue PAUSED the
        worker-side drop (retract_admission at dequeue) never runs, so
        the drain polls the admission markers itself — a marker a
        thief claimed means the job runs on the thief now and leaves
        our queue here.  Returns how many entries were reaped."""
        if self._lease is None:
            return 0
        reaped = 0
        for uid in self._q.queued_uids():
            try:
                if not self._lease.admission_claimed(uid):
                    continue
            except Exception:
                continue  # store hiccup: the next poll retries
            req = self._q.remove(uid)
            if req is None:
                continue
            ctl = self._lease.attached_ctl(uid)
            self._lease.stolen_from_us(uid)
            jobctl.release_entry(ctl)
            if self._rescache is not None:
                self._rescache.on_leader_terminal(uid)
            reaped += 1
        return reaped

    def settle_cancelled_queued(self, uid: str) -> bool:
        """Settle a job cancelled while still QUEUED: remove it from the
        admission queue (freeing its slot for new submits immediately)
        and record its durable CANCELLED failure here, instead of
        leaving dead work occupying capacity until a worker gets to it.
        False when a worker already dequeued it — the worker's own
        check_entry settles it then (the removal is atomic under the
        queue lock, so exactly one side ever settles)."""
        req = self._q.remove(uid)
        if req is None:
            return False
        if self._lease is not None and not self._lease.retract_admission(uid):
            # a peer stole the job between the cancel request and this
            # settle: it runs there now — local cancel state is moot.
            # Release OUR control object by identity, never the uid (a
            # same-process thief may have re-registered it already).
            ctl = self._lease.attached_ctl(uid)
            self._lease.stolen_from_us(uid)
            jobctl.release_entry(ctl)
            if self._rescache is not None:
                # the thief runs (and fans out) elsewhere: local
                # followers re-dispatch as cold mines
                self._rescache.on_leader_terminal(uid)
            return True
        try:
            # route through check_entry so the cancel counter and trace
            # event fire exactly like a worker-side abort
            jobctl.check_entry(jobctl.get(uid))
            exc: jobctl.JobAborted = jobctl.JobCancelled(
                uid, "cancelled while queued")
        except jobctl.JobAborted as caught:
            exc = caught
        _record_failure(self.store, uid, exc, keep_frontier=True,
                        lease_mgr=self._lease, rescache=self._rescache,
                        guard=self._guard)
        return True

    @property
    def queue_depth(self) -> int:
        return self._q.depth

    def _observe_wall(self, wall_s: float) -> None:
        with self._wall_lock:
            self._wall_ewma = (wall_s if self._wall_ewma is None
                               else 0.3 * wall_s + 0.7 * self._wall_ewma)

    def _per_job_s(self) -> float:
        """One job's estimated wall: the EWMA of measured walls, seeded
        — before any job has finished — by the ragged planner's cost
        model over the declared prewarm envelope (8 full-width launches
        at the configured sequence scale: the same KERNELS.json-
        anchored arithmetic the watchdog deadlines use)."""
        with self._wall_lock:
            per_job = self._wall_ewma
        if per_job is None:
            pw = config.get_config().prewarm
            n_seq = pw.sequences or 100_000
            per_job = RB.estimate_seconds(8 * 8192, 8, n_seq,
                                          max(1, pw.words or 1))
        return per_job

    def _steal_path_retry_s(self) -> int:
        """~Two heartbeats: the time for an idle peer's steal scan to
        pick a queued job up — the Retry-After whenever the fastest
        path to service is a PEER (free capacity advertised, or this
        replica draining)."""
        hb = self._lease.heartbeat_s if self._lease is not None else 1.0
        return max(1, math.ceil(2 * max(hb, 0.5)))

    def _retry_after_s(self, queued_ahead: int) -> int:
        """Seconds until a shed submit plausibly fits: the submit's true
        QUEUE POSITION (jobs queued at or above its priority class —
        work below it would be overtaken, not waited for) divided over
        the workers, priced per job by :meth:`_per_job_s`.

        CLUSTER OVERRIDE: when peers advertise free capacity in their
        heartbeat records, the shed submit's fastest path is the STEAL
        path — an idle peer claims our queued backlog within a
        heartbeat or two, so the local-EWMA pessimum would overstate
        the wait by orders of magnitude.  Point the client at roughly
        two heartbeats instead."""
        if self._lease is not None and self._lease.peer_free_total() > 0:
            return self._steal_path_retry_s()
        est = self._per_job_s() * (queued_ahead + 1) \
            / max(1, len(self._threads))
        return max(1, min(3600, math.ceil(est)))

    def submit(self, req: ServiceRequest) -> Optional[dict]:
        """Admit a train request; returns response extras (e.g. the
        ephemeral-admission flag) or None."""
        faults.fault_site("service.admit", uid=req.uid)
        priority = (req.param("priority") or "normal").lower()
        if priority not in PRIORITIES:
            raise ValueError(f"unknown priority {priority!r} "
                             f"(valid: {'/'.join(PRIORITIES)})")
        # multi-tenant identity (service/fairness.py): validated +
        # registered against the bounded vocabulary when fairness is
        # on; accepted-but-ignored otherwise (the queue stays FIFO)
        tenant = fairness.DEFAULT_TENANT
        if self._fair is not None:
            tenant = self._fair.resolve(req.param("tenant"))
        deadline_s = None
        raw_deadline = req.param("deadline_s")
        if raw_deadline is not None:
            deadline_s = float(raw_deadline)  # ValueError -> failure reply
            # non-finite values pass a naive `<= 0` check: nan compares
            # False to everything, so the "deadline" would silently never
            # expire while pinning every safe-point probe onto the slow
            # path for the job's whole life
            if not math.isfinite(deadline_s) or deadline_s <= 0:
                raise ValueError(f"deadline_s must be a finite value > 0 "
                                 f"(got {raw_deadline!r})")
        if self._draining:
            # scale-down drain: this replica is leaving the fleet — no
            # new work, and the honest Retry-After is the steal path
            # (peers will have adopted our backlog by then too)
            retry = self._steal_path_retry_s()
            _SHEDS_TOTAL.inc(priority=priority)
            if self._fair is not None:
                fairness.note_shed(tenant)
            log_event("job_shed_draining", uid=req.uid, priority=priority)
            raise AdmissionShed(
                req.uid, self._q.depth, self._q.size(), retry,
                why=f"replica is draining for scale-down; peers serve "
                    f"new work — retry in ~{retry}s")
        g = self._guard
        if g is not None and g.is_down():
            # STORE OUTAGE: the submit cannot be journaled, so it
            # cannot be made durable.  Default: shed 429 (the honest
            # Retry-After is the probe cadence — how fast the service
            # can notice the store back).  Opt-in ephemeral admission
            # runs the job loudly flagged NO-JOURNAL instead: results
            # ride the spool, a crash before the store returns loses
            # them, and the response says so.
            if not g.ephemeral_admission:
                retry = g.shed_outage_admission()
                _SHEDS_TOTAL.inc(priority=priority)
                if self._fair is not None:
                    fairness.note_shed(tenant)
                log_event("job_shed_store_outage", uid=req.uid,
                          priority=priority)
                raise AdmissionShed(
                    req.uid, self._q.depth, self._q.size(), retry,
                    why=f"store outage: durable admission is "
                        f"unavailable; retry in ~{retry}s")
            return self._admit_ephemeral(req, priority, deadline_s,
                                         tenant)
        rc = self._rescache
        if rc is not None:
            # result-reuse tier (service/resultcache.py): a request
            # served from a completed cache entry, or coalesced onto an
            # identical in-flight job, never reaches the queue; a miss
            # registers it as a prospective coalescing leader and falls
            # through to normal cold admission
            out = rc.intercept(req, priority, deadline_s)
            if out == "peer-inflight":
                # cross-replica coalesce HINT (ROADMAP 2c): an identical
                # dataset fingerprint is in flight on a peer — point the
                # client at the cache entry that peer is about to
                # publish instead of admitting a duplicate cold mine.
                # Hint only: replica-local coalescing semantics are
                # unchanged, and any error upstream degraded to a miss.
                retry = self._steal_path_retry_s()
                _SHEDS_TOTAL.inc(priority=priority)
                if self._fair is not None:
                    fairness.note_shed(tenant)
                raise AdmissionShed(
                    req.uid, self._q.depth, self._q.size(), retry,
                    why=f"an identical dataset mine is in flight on a "
                        f"peer replica; retry in ~{retry}s to hit the "
                        f"shared result cache")
            if out is not None:
                return
        enqueued = False
        try:
            enqueued = self._admit(req, priority, deadline_s, tenant)
        finally:
            if rc is not None and not enqueued:
                # the prospective-leader registration from intercept()
                # must die with the failed admission, or later identical
                # requests would attach to a uid that never runs
                rc.admit_aborted(req.uid)
        if enqueued:
            return None
        # shutdown() already enqueued the worker sentinels; a request
        # enqueued now would never be dequeued (workers exit on the
        # sentinel) and would sit "started" forever — the exact state
        # the drain exists to prevent.  Record the durable failure
        # here, same status shape as the drained-backlog path.
        if self._lease is not None:
            try:
                self._lease.retract_admission(req.uid)
            except Exception:
                pass
        _record_failure(self.store, req.uid,
                        RuntimeError("service shutting down"),
                        keep_frontier=True, lease_mgr=self._lease,
                        rescache=rc, guard=self._guard)
        return None

    def _admit_ephemeral(self, req: ServiceRequest, priority: str,
                         deadline_s: Optional[float],
                         tenant: str) -> Optional[dict]:
        """Outage-mode admission under ``[storeguard]
        ephemeral_admission``: NO journal intent, NO lease, NO
        admission marker — the job exists only in this process, its
        statuses/results ride the write-behind spool ungated
        (``gate="none"``: no peer can know the uid, so replay cannot
        double-commit), and the submit response carries
        ``ephemeral: "1"`` so the client knows a crash before the
        store returns loses the job.  Every durable-admission
        guarantee (409 conflict vs peers, steal, adoption) is
        explicitly OUT: that is the flag's meaning.  Two duplicate-uid
        defenses remain even here: a uid live IN THIS PROCESS 409s
        (below), and the replay gate refuses a gate="none" spool whose
        uid acquired any durable trace (journal/lease/status) during
        the outage — a client that reused the uid against a healthy
        peer keeps that peer's results."""
        g = self._guard
        if jobctl.get(req.uid) is not None:
            raise UidConflict(req.uid)
        admitted, queued, ahead, scope = self._q.try_reserve(
            priority, tenant)
        if not admitted:
            _SHEDS_TOTAL.inc(priority=priority)
            if self._fair is not None:
                fairness.note_shed(tenant)
            raise AdmissionShed(req.uid, self._q.depth, queued,
                                self._retry_after_s(ahead))
        enqueued = False
        try:
            ctl = jobctl.register(req.uid, deadline_s, priority=priority)
            ctl.tenant = tenant
            ctl.ephemeral = True
            g.note_ephemeral_admission()
            g.status(req.uid, Status.STARTED, gate="none")
            g.incr(req.uid, "fsm:metric:jobs_submitted", gate="none")
            log_event("job_admitted_ephemeral", uid=req.uid,
                      priority=priority)
            obs.trace_begin(req.uid,
                            algorithm=req.param("algorithm", "SPADE_TPU"),
                            source=req.param("source", "FILE"))
            obs.lifecycle(req.uid, "admitted", priority=priority,
                          ephemeral=True)
            with self._stop_lock:
                if not self._stopping:
                    self._q.put(req, priority, tenant)
                    if self._fair is not None:
                        fairness.note_admitted(tenant)
                    enqueued = True
        except BaseException:
            jobctl.release(req.uid)
            raise
        finally:
            if not enqueued:
                self._q.abort(tenant)
        if not enqueued:
            _record_failure(self.store, req.uid,
                            RuntimeError("service shutting down"),
                            keep_frontier=True, lease_mgr=None,
                            rescache=self._rescache, guard=g)
            return None
        with self._running_lock:
            self._admitted += 1
        return {"ephemeral": "1"}

    def note_adoption(self, uid: str, count: int) -> None:
        """Stage adoption number ``count`` for the NEXT admit of
        ``uid``: the journal intent the admit writes carries the
        counter, so the crash-loop budget is durable across the very
        crashes it is counting."""
        self._adoptions_pending[str(uid)] = int(count)

    def adopt_or_poison(self, uid: str, entry: Dict, raw=None) -> bool:
        """Crash-loop quarantine gate, shared by boot/periodic recovery
        and the steal path.  Returns True when ``uid`` may be adopted
        once more (and pre-stamps the bumped counter for the resubmit);
        False when the budget ([cluster] max_adoptions) is exhausted —
        the job is settled instead as a durable ``POISON:`` terminal
        plus an fsm:quarantine:{uid} record, and every resubmit 409s
        until ``/admin/quarantine`` releases it."""
        try:
            n = int(entry.get("adoptions") or 0)
        except (TypeError, ValueError):
            n = 0
        limit = config.get_config().cluster.max_adoptions
        if n < limit:
            self.note_adoption(uid, n + 1)
            return True
        self._settle_poison(uid, n, limit, raw=raw)
        return False

    def _settle_poison(self, uid: str, adoptions: int, limit: int,
                       raw=None) -> None:
        """Durable poison settle: quarantine record first (evidence =
        the dead holders' trace-spine tail, so the operator sees WHERE
        the crash loop bit without replaying it), then the normal
        fenced failure path — no client ever polls a forever-pending
        poison uid."""
        evidence = None
        try:
            evidence = obsplane.spine_chunks(self.store, uid)[-3:]
        except Exception:
            evidence = None
        meshguard.poison_record(
            self.store, uid,
            reason=(f"adoption budget exhausted: {adoptions} adoptions "
                    f">= [cluster] max_adoptions={limit}"),
            adoptions=adoptions, evidence=evidence, raw_intent=raw)
        # keep_frontier: the preserved checkpoint is evidence too, and
        # an operator release + resubmit resumes instead of re-mining
        _record_failure(
            self.store, uid,
            RuntimeError(
                f"POISON: job crashed its holder {adoptions} times "
                f"([cluster] max_adoptions={limit}); quarantined — "
                "release via /admin/quarantine to resubmit"),
            keep_frontier=True, lease_mgr=self._lease,
            rescache=self._rescache, guard=self._guard)

    def _admit(self, req: ServiceRequest, priority: str,
               deadline_s: Optional[float],
               tenant: str = fairness.DEFAULT_TENANT) -> bool:
        """The cold admission path (conflict check → lease → queue slot
        → journal intent → enqueue), split out of :meth:`submit` so the
        result-reuse bookkeeping wraps it in one try/finally.  Returns
        whether the request was enqueued (False only while shutting
        down)."""
        enqueued = False
        with self._admit_lock:
            # crash-loop quarantine gate (meshguard): a poison record
            # refuses the uid outright — 409 until an operator releases
            # it via /admin/quarantine.  Integrity quarantines (other
            # surfaces under the same prefix) do NOT block.
            poison = meshguard.poisoned(self.store, req.uid)
            if poison is not None:
                meshguard.note_refused(req.uid)
                raise QuarantinedUid(req.uid,
                                     adoptions=poison.get("adoptions"))
            # the conflict check and the journal intent that makes the
            # uid LIVE must be one atomic step: two racing submits of
            # the same uid must serialize here so exactly one admits
            # and the other sees the fresh intent and 409s
            entry = self.store.journal_get(req.uid)
            if entry is not None:
                try:
                    live = (json.loads(entry).get("incarnation")
                            == self.incarnation)
                except ValueError:
                    live = False  # corrupt record: treat as a dead orphan
                if live:
                    raise UidConflict(req.uid)
            fresh_lease = False
            if self._lease is not None:
                # cluster-wide liveness: the lease generalizes the
                # incarnation check across replicas.  Held by a peer ->
                # the job is live THERE (409); protocol failure -> 503
                # with zero store trace of the uid (LeaseUnavailable
                # propagates).  Acquisition happens BEFORE the journal
                # intent so a refused submit leaves nothing behind.
                # A PRE-HELD lease (adoption/steal resubmit) is kept on
                # failure paths below: the caller settles the failure
                # under it, journal-first, so no adopt-vs-settle window
                # opens between a release and the durable record.
                fresh_lease = self._lease.token_of(req.uid) is None
                try:
                    self._lease.acquire(req.uid)
                except lease.LeaseHeld as exc:
                    raise UidConflict(req.uid) from exc
            admitted, queued, ahead, scope = self._q.try_reserve(
                priority, tenant)
            if not admitted:
                if self._lease is not None and fresh_lease:
                    self._lease.release(req.uid)
                _SHEDS_TOTAL.inc(priority=priority)
                if self._fair is not None:
                    fairness.note_shed(tenant)
                log_event("job_shed", uid=req.uid, queued=queued,
                          queued_ahead=ahead, depth=self._q.depth,
                          priority=priority, tenant=tenant, scope=scope)
                if scope == "tenant":
                    # the tenant's own bucket refused the slot: the
                    # Retry-After is how long ITS backlog takes at ITS
                    # weight-fair share of the service rate, not the
                    # global estimate (service/fairness.py)
                    cap = self._fair.tenant_depth
                    retry = self._fair.retry_after_s(
                        tenant, queued, self._per_job_s(),
                        len(self._threads))
                    raise AdmissionShed(
                        req.uid, cap, queued, retry,
                        why=f"tenant {tenant!r} queue cap reached "
                            f"({queued}/{cap} jobs queued); retry in "
                            f"~{retry}s")
                raise AdmissionShed(req.uid, self._q.depth, queued,
                                    self._retry_after_s(ahead))
            try:
                # A client-supplied uid may collide with a finished/
                # failed job; clear its stale error and results so
                # /status and /get reflect THIS job.  A checkpointed
                # submit KEEPS the frontier keys: live uids were
                # rejected above, so a surviving frontier belongs to a
                # dead incarnation and resuming it is exactly the
                # crash-recovery contract (a frontier for different
                # data fails the fingerprint check and the mine
                # restarts fresh).
                self.store.clear_job(
                    req.uid, keep_frontier=_checkpoint_requested(req))
                self.store.journal_set(req.uid, json.dumps({
                    "uid": req.uid,
                    "incarnation": self.incarnation,
                    "replica": (self._lease.replica_id
                                if self._lease is not None else None),
                    "ts": round(time.time(), 3),
                    "checkpoint": _checkpoint_requested(req),
                    "priority": priority,
                    "adoptions": self._adoptions_pending.pop(req.uid, 0),
                    "request": dict(req.data),
                }))
                if self._lease is not None:
                    # mirror the queued job into this replica's admission
                    # namespace — the steal scan's menu; retracted (by us
                    # OR a thief, exclusively) at dequeue
                    self._lease.publish_admission(req.uid)
            except BaseException:
                self._q.abort(tenant)  # reservation never became queued
                try:
                    # OUR journal intent may have landed before the
                    # failure (e.g. the admission-marker write died): a
                    # surviving live-looking record would 409 every
                    # future resubmit.  Clear ONLY a record carrying
                    # this incarnation — when journal_set itself failed,
                    # the surviving record is a PREDECESSOR's (a dead
                    # replica's checkpointed orphan, a stolen victim's
                    # intent) and destroying it would destroy the very
                    # recoverability the journal exists for.
                    raw = self.store.journal_get(req.uid)
                    if raw is not None and json.loads(raw).get(
                            "incarnation") == self.incarnation:
                        self.store.journal_clear(req.uid)
                except Exception:
                    pass
                if self._lease is not None and fresh_lease:
                    self._lease.release(req.uid)
                raise
        try:
            # priority rides the control entry so the fusion broker's
            # window rule sees the admission class at dispatch time
            ctl = jobctl.register(req.uid, deadline_s, priority=priority)
            # tenant too: the fsm_job_*_seconds SLO label at finish
            ctl.tenant = tenant
            if self._lease is not None:
                # heartbeat-detected lease loss self-fences the job at
                # its next safe point via this control entry
                self._lease.attach(req.uid, ctl)
            self.store.add_status(req.uid, Status.STARTED)
            self.store.incr("fsm:metric:jobs_submitted")
            log_event("job_submitted", uid=req.uid,
                      algorithm=req.param("algorithm", "SPADE_TPU"),
                      source=req.param("source", "FILE"),
                      priority=priority)
            # the flight-recorder trace opens AT SUBMIT (handler thread):
            # the queue wait before a worker picks the job up is part of
            # the job's story under load.  The admission lifecycle mark
            # flushes to the durable spine immediately — admission is
            # the one event a failover timeline cannot reconstruct from
            # anywhere else once the admitting replica is dead.
            obs.trace_begin(req.uid,
                            algorithm=req.param("algorithm", "SPADE_TPU"),
                            source=req.param("source", "FILE"))
            obs.lifecycle(req.uid, "admitted", priority=priority,
                          replica=(self._lease.replica_id
                                   if self._lease is not None else None))
            obs.flush_trace(req.uid)
            with self._stop_lock:
                if not self._stopping:
                    if self._rescache is not None:
                        # promote the prospective coalescing leader
                        # strictly BEFORE the enqueue: a follower may
                        # attach the instant the key is visible, and
                        # the worker that will run this request is
                        # guaranteed to fan out (or re-dispatch) it
                        self._rescache.leader_admitted(req.uid)
                    # enqueued strictly BEFORE the sentinels (the lock
                    # orders us against shutdown), so a worker will
                    # dequeue it: either it runs, or the drain check
                    # gives it a durable failure
                    self._q.put(req, priority, tenant)
                    if self._fair is not None:
                        fairness.note_admitted(tenant)
                    enqueued = True
        except BaseException:
            # the submit died between its journal intent and its
            # enqueue: settle the intent (a live-looking record would
            # 409 every future resubmit of this uid) and drop the
            # control entry — best-effort, the store may be the thing
            # that just failed
            try:
                self.store.journal_clear(req.uid)
            except Exception:
                pass
            if self._lease is not None:
                try:
                    self._lease.retract_admission(req.uid)
                except Exception:
                    pass
                self._lease.release(req.uid)
            jobctl.release(req.uid)
            raise
        finally:
            if not enqueued:
                self._q.abort(tenant)  # reservation never became queued
        if enqueued:
            # lifetime admission counter (heartbeat-piggybacked as
            # "adm"): the autoscaler's predictive rate-derivative
            # signal differentiates the fleet SUM of these; locked —
            # concurrent submit threads racing a bare += lose counts
            # under exactly the burst load the signal exists to see
            with self._running_lock:
                self._admitted += 1
        return enqueued

    def _loop(self) -> None:
        while True:
            req = self._q.get()
            if req is None:
                return
            try:
                self._loop_one(req)
            except Exception as exc:
                # the worker thread must NEVER die: a dead worker
                # strands the whole queue behind it (jobs pinned at
                # 'started' forever, leases renewed by a heartbeat
                # that thinks they are fine).  Settle the job as a
                # durable failure (best effort — the journal intent
                # survives for recovery if even that fails) and move
                # on to the next dequeue.
                log_event("worker_loop_error", uid=req.uid,
                          error=str(exc))
                try:
                    _record_failure(self.store, req.uid, exc,
                                    keep_frontier=True,
                                    lease_mgr=self._lease,
                                    rescache=self._rescache,
                                    guard=self._guard)
                except Exception as rexc:
                    log_event("worker_loop_settle_failed", uid=req.uid,
                              error=str(rexc))

    def _loop_one(self, req: ServiceRequest) -> None:
        ctl0 = jobctl.get(req.uid)
        if self._lease is not None and not (
                ctl0 is not None and ctl0.ephemeral):
            try:
                claimed = self._lease.retract_admission(req.uid)
            except Exception as exc:
                g = self._guard
                if g is not None and g.note_error(exc):
                    # store outage at dequeue: defer the marker
                    # retraction into the spool and run the job —
                    # a post-heal thief racing the replayed DEL
                    # loses either way: whoever loses the arbiter
                    # is fenced by token, never double-commits
                    self._lease.retract_admission_deferred(req.uid, g)
                    claimed = True
                else:
                    # UNPROVEN blip (store answered the probe, or
                    # no guard): run the job anyway — if a thief
                    # actually won the marker, the fencing token
                    # refuses the loser's commits; wasting one
                    # mine beats stranding the queue
                    log_event("retract_admission_failed",
                              uid=req.uid, error=str(exc))
                    claimed = True
            if not claimed:
                # the admission marker is GONE: an idle peer won
                # the atomic DEL claim and owns the job (lease +
                # journal) now — drop it silently; running it here
                # would be the double-execution the two-phase claim
                # exists to prevent (release OUR control object by
                # identity — the uid may already map to the thief's
                # live entry in-process)
                ctl = self._lease.attached_ctl(req.uid)
                self._lease.stolen_from_us(req.uid)
                jobctl.release_entry(ctl)
                if self._rescache is not None:
                    # the thief runs (and fans out) elsewhere: local
                    # followers re-dispatch as cold mines
                    self._rescache.on_leader_terminal(req.uid)
                return
        if self._stopping:
            # draining: do NOT start queued backlog jobs — give each a
            # durable failure status (visible through /status) instead
            # of leaving it "started" forever or dying with the process
            # (keep_frontier: a drained checkpointed job's persisted
            # progress stays resumable after the restart)
            _record_failure(self.store, req.uid,
                            RuntimeError("service shutting down"),
                            keep_frontier=True, lease_mgr=self._lease,
                            rescache=self._rescache, guard=self._guard)
            return
        ctl = jobctl.get(req.uid)
        try:
            # a deadline spent ENTIRELY on queue wait (or a cancel
            # that landed while queued) aborts before any work
            jobctl.check_entry(ctl)
        except jobctl.JobAborted as exc:
            _record_failure(self.store, req.uid, exc,
                            keep_frontier=True, lease_mgr=self._lease,
                            rescache=self._rescache, guard=self._guard)
            return
        # Clear again at run start: with a reused uid, an EARLIER job
        # with the same uid may have written its error/results after
        # submit()'s clear (it was still queued/running then).  The
        # last job to *start* owns the uid's keys from here on.
        try:
            self.store.clear_job(req.uid, keep_status_log=True,
                                 keep_frontier=_checkpoint_requested(req))
        except Exception as exc:
            g = self._guard
            if g is None or not g.note_error(exc):
                raise
            # store outage: the clear is cosmetic for a FRESH uid
            # (this run's writes overwrite the live keys anyway) —
            # skipping it beats failing the job, and the log line
            # flags the one visible residue (a reused uid's stale
            # error key may shadow through /status until then)
            log_event("job_clear_skipped_outage", uid=req.uid)
        try:
            retries = int(req.param(
                "retries",
                str(config.get_config().service.job_retries)))
        except ValueError as exc:  # malformed param: fail like any
            _record_failure(self.store, req.uid, exc,  # other bad param
                            lease_mgr=self._lease,
                            rescache=self._rescache, guard=self._guard)
            return
        with self._running_lock:
            self._running += 1
        try:
            self._attempts(req, ctl, retries)
        finally:
            with self._running_lock:
                self._running -= 1

    def _attempts(self, req: ServiceRequest, ctl, retries: int) -> None:
        attempt = 0
        while True:
            try:
                # re-checked between attempts too: a deadline that
                # expired during a failed attempt must not buy a
                # retry it can never finish
                jobctl.check_entry(ctl)
                with jobctl.activate(ctl):
                    self._run(req)
                break
            except jobctl.JobAborted as exc:
                # TERMINAL, never retried: durable failure whose error
                # text leads with CANCELLED/DEADLINE_EXCEEDED/
                # LEASE_LOST.  The frontier survives: progress an abort
                # cut short resumes on a later checkpointed resubmit
                # (for LEASE_LOST the adopting replica is already
                # resuming it — the fenced _record_failure writes
                # nothing there)
                _record_failure(self.store, req.uid, exc,
                                keep_frontier=True, lease_mgr=self._lease,
                                rescache=self._rescache, guard=self._guard)
                break
            except ValueError as exc:  # bad params / bad source: the
                # failure is deterministic (SourceError included) — a
                # re-run would just repeat it, so fail immediately
                _record_failure(self.store, req.uid, exc,
                                lease_mgr=self._lease,
                                rescache=self._rescache, guard=self._guard)
                break
            except Exception as exc:  # supervision: retry, then failure
                attempt += 1
                if attempt > max(0, retries):
                    _record_failure(self.store, req.uid, exc,
                                    lease_mgr=self._lease,
                                    rescache=self._rescache,
                                    guard=self._guard)
                    break
                try:
                    self.store.incr("fsm:metric:jobs_retried")
                except Exception:
                    pass  # counter only; a down store must not veto a retry
                log_event("job_retry", uid=req.uid, attempt=attempt,
                          error=str(exc))
                with obs.span("job.retry", trace_id=req.uid,
                              attempt=attempt, error=str(exc)):
                    pass

    def _run(self, req: ServiceRequest) -> None:
        # the job's root flight-recorder span: every engine/planner/IO
        # span below threads under it via the contextvar — no plumbing
        try:
            with obs.trace(req.uid, site="job",
                           algorithm=req.param("algorithm", "SPADE_TPU"),
                           source=req.param("source", "FILE")) as job_sp:
                self._run_traced(req, job_sp)
        finally:
            # the root span closes on trace exit, AFTER the terminal
            # flush inside — push it too, so the spine's last chunk
            # carries the job's whole-wall span (post-release, so it
            # lands unfenced: the uid was settled by this replica)
            obs.flush_trace(req.uid)

    def _run_traced(self, req: ServiceRequest, job_sp) -> None:
        t0 = time.perf_counter()
        ctl = jobctl.current()
        # first-pickup lifecycle mark with the measured queue wait —
        # the observation point the per-priority SLO split reads
        obs.lifecycle(req.uid, "started",
                      queue_wait_s=(
                          None if ctl is None or ctl.started_t is None
                          else round(ctl.started_t - ctl.submitted_t, 6)))
        with obs.span("job.dataset"):
            db = sources.get_db(req, self.store)
        # coarse safe point shared by every algorithm: a cancel/deadline
        # that landed during the dataset build aborts before the mine
        # (the engines' own launch-boundary checks take over from here);
        # the lease fence rides the same boundary — a job whose lease
        # lapsed during a long dataset build self-fences before mining
        jobctl.check()
        g = self._guard
        gate = ("none" if ctl is not None and ctl.ephemeral else None)
        if self._lease is not None and (g is None or not g.is_down()):
            # the fence is skipped only during a PROVEN outage — the
            # spool's replay gate re-proves the token before any
            # deferred write lands (docs/DESIGN.md "Spool replay")
            self._lease.fence(req.uid)
        if self._rescache is not None:
            # content-addressed dataset fingerprint, once per load:
            # stamped on the control entry (the cache-entry key) and
            # learned into the stable-source map (never raises)
            self._rescache.note_dataset(req, db, ctl)
        if g is None:
            self.store.add_status(req.uid, Status.DATASET)
        else:
            g.status(req.uid, Status.DATASET, gate=gate)
        plugin = plugins.get_plugin(req)
        if plugin.name != "AUTO":
            # fsm_engine_selected_total counts the engine that actually
            # mines; AUTO bumps its RESOLVED engine inside the planner
            planner.count_selected(plugin.name)
        stats: Dict[str, object] = {
            "algorithm": plugin.name,
            "sequences": len(db),
            "dataset_s": round(time.perf_counter() - t0, 4),
        }
        job_sp.set(algorithm=plugin.name, sequences=len(db))
        ckpt: Optional[StoreCheckpoint] = None
        if _checkpoint_requested(req):
            ckpt = StoreCheckpoint(
                self.store, req.uid,
                every_s=float(req.param("checkpoint_every_s", "30")),
                lease_mgr=self._lease, guard=self._guard)
        trace_dir = _profile_dir(req, req.uid)
        t1 = time.perf_counter()
        with profile_trace(trace_dir), obs.span("job.mine"):
            results = plugin.extract(req, db, stats, checkpoint=ckpt)
        mine_s = time.perf_counter() - t1
        stats["mine_s"] = round(mine_s, 4)
        stats["results"] = len(results)
        stats["results_per_s"] = round(len(results) / mine_s, 2) if mine_s else 0.0
        if trace_dir:
            stats["profile_trace"] = trace_dir
        # settle the job's device-cost accumulator BEFORE the stats
        # write: the usage block rides fsm:stats:{uid} AND (via
        # rescache.on_finished below) the cache entry, which is what
        # prices a future serve's avoided-cost credit
        u = usage.settle(req.uid)
        if u:
            stats["usage"] = u
        with obs.span("job.sink", results=len(results)):
            outage = g is not None and g.is_down()
            if self._lease is not None and not outage:
                # the split-brain gate: a stale holder that somehow
                # mined to completion (expired mid-run, adopter already
                # re-running) must NOT commit its result set over the
                # adopter's — raises JobLeaseLost, terminal, fenced.
                # During a PROVEN outage the same gate moves to the
                # spool replay (journal-gated NX reacquire under the
                # same token) — refused there, these writes are dropped
                # and counted, never committed over the adopter's
                self._lease.fence(req.uid)
            if g is None:
                self.store.set(f"fsm:stats:{req.uid}", json.dumps(stats))
                _sink_results(self.store, req.uid, plugin.kind, results)
                self.store.add_status(req.uid, Status.TRAINED)
                self.store.add_status(req.uid, Status.FINISHED)
            else:
                g.set(req.uid, f"fsm:stats:{req.uid}", json.dumps(stats),
                      gate=gate)
                _sink_results(self.store, req.uid, plugin.kind, results,
                              guard=g, gate=gate)
                g.status(req.uid, Status.TRAINED, gate=gate)
                g.status(req.uid, Status.FINISHED, gate=gate)
        if self._rescache is not None:
            # result-reuse tier: publish the cache entry and fan the
            # durable result out to coalesced followers — while the
            # leader's lease is STILL HELD, so both ride the fenced
            # write path; never raises (the job is already green)
            self._rescache.on_finished(req, ctl, plugin, results, stats)
        if ckpt is not None:
            # only AFTER the results are durable: a sink failure retried
            # mid-way must resume from the final frontier, not re-mine.
            # Best-effort — the job has already succeeded, and a cleanup
            # hiccup must not fail/re-run it (uid reuse reclaims the keys).
            try:
                ckpt.clear()
            except Exception as exc:
                log_event("frontier_clear_failed", uid=req.uid,
                          error=str(exc))
        # FINISHED is terminal: settle the journal intent and release
        # the job-control entry (order matters — the terminal status is
        # already durable, so a crash right here leaves an orphan whose
        # recovery pass sees 'finished' and just clears the journal).
        # Ephemeral jobs never wrote a journal intent — nothing to clear.
        if ctl is None or not ctl.ephemeral:
            if g is None:
                self.store.journal_clear(req.uid)
            else:
                g.delete(req.uid, f"fsm:journal:{req.uid}", gate=gate)
        jobctl.release(req.uid)
        # SLO accounting (submit -> durable result, per priority and
        # tenant) + the settled lifecycle mark, flushed to the spine
        # while the lease is STILL HELD so the final chunk rides the
        # fenced write path
        if ctl is not None:
            now_m = time.monotonic()
            e2e_s = now_m - ctl.submitted_t
            queue_wait_s = max(0.0, (ctl.started_t or now_m)
                               - ctl.submitted_t)
            obsplane.observe_job(ctl.priority, e2e_s, queue_wait_s,
                                 max(0.0, e2e_s - queue_wait_s),
                                 tenant=ctl.tenant)
        obs.lifecycle(req.uid, "settled", outcome="finished")
        obs.flush_trace(req.uid)
        if self._lease is not None:
            self._lease.release(req.uid)
        if g is None:
            self.store.incr("fsm:metric:jobs_finished")
        else:
            g.incr(req.uid, "fsm:metric:jobs_finished", gate=gate)
        self._observe_wall(time.perf_counter() - t0)
        log_event("job_finished", uid=req.uid, **stats)

    def shutdown(self, join_timeout_s: float = 30.0) -> None:
        """Drain: workers finish their CURRENT job only — queued backlog
        jobs get a durable "service shutting down" failure status instead
        of starting (the ``_stopping`` flag), and the threads are joined
        against ONE shared deadline so shutdown wall time is bounded by
        ``join_timeout_s`` total, not per worker.  A job outrunning the
        deadline is abandoned loudly (logged; daemon threads die with the
        process; a checkpointed job resumes on restart — the
        torn-snapshot-safe StoreCheckpoint contract).  Backlog jobs are
        drained BEFORE the sentinels surface (AdmissionQueue.get), so
        every queued job's durable failure lands and its journal entry
        clears; submits racing the drain still shed with 429 when the
        queue is full, or land the durable failure when it is not."""
        if self._lease is not None:
            # BEFORE the drain: no new work may be pulled in (a steal
            # or periodic adoption landing now would meet the drain and
            # get a bogus durable failure); renewals keep running so
            # the draining jobs stay fenced-safe to their end
            self._lease.quiesce()
        with self._stop_lock:
            self._stopping = True
            for _ in self._threads:
                self._q.put_sentinel()
        deadline = time.monotonic() + join_timeout_s
        for t in self._threads:
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                log_event("shutdown_abandoned_worker", thread=t.name)
        if self._lease is not None:
            # after the drain: every backlog job has settled (and
            # released its lease); stop the heartbeat and retract the
            # replica record so peers adopt anything left promptly
            self._lease.stop()
        if (self._integrity is not None
                and integrity.get() is self._integrity):
            # stop OUR scrubber only — a later Miner's install owns the
            # module-global slot now (last-wins, same as obsplane)
            self._integrity.stop()
        if self._guard is not None:
            self._guard.stop()
            if storeguard.get() is self._guard:
                storeguard.uninstall()


class Questor:
    """Query worker: serve mined patterns/rules from the store.

    Supports the reference's rule-filtering queries for prediction
    (SURVEY.md sec 3.2): 'antecedent'/'consequent' params restrict rules
    to those whose side intersects the given items, and
    ``/get/prediction?items=...`` returns ranked next-item candidates
    (best rule per item, confidence-ordered).
    """

    def __init__(self, store: ResultStore) -> None:
        self.store = store

    def handle(self, req: ServiceRequest, subject: str) -> ServiceResponse:
        uid = req.uid
        status = self.store.status(uid)
        if status is None:
            return model.response(req, Status.FAILURE, error="unknown uid")
        if status != Status.FINISHED:
            return model.response(req, status,
                                  error="job not finished; results pending")
        if subject == "patterns":
            payload = self.store.patterns(uid)
            if payload is None:
                return model.response(req, Status.FAILURE, error="no patterns")
            return model.response(req, Status.FINISHED, patterns=payload)
        if subject == "rules":
            payload = self.store.rules(uid)
            if payload is None:
                return model.response(req, Status.FAILURE, error="no rules")
            rules = model.deserialize_rules(payload)
            ante = req.param("antecedent")
            cons = req.param("consequent")
            if ante:
                want = {int(i) for i in ante.split(",")}
                rules = [r for r in rules if want & set(r[0])]
            if cons:
                want = {int(i) for i in cons.split(",")}
                rules = [r for r in rules if want & set(r[1])]
            return model.response(req, Status.FINISHED,
                                  rules=model.serialize_rules(rules))
        if subject == "prediction":
            # Next-item prediction (SURVEY.md sec 3.2): rules whose
            # antecedent is CONTAINED in the observed item set vote for
            # their consequent items; each candidate keeps its best rule
            # (confidence first, support as tie-break) and items already
            # observed are excluded.  This is the ranked form of the
            # antecedent filter above — the reference ecosystem's use of
            # mined rules.
            payload = self.store.rules(uid)
            if payload is None:
                return model.response(req, Status.FAILURE, error="no rules")
            items_param = req.param("items")
            if not items_param:
                return model.response(
                    req, Status.FAILURE,
                    error="prediction needs 'items' (comma-separated item "
                          "ids observed so far)")
            try:
                have = {int(i) for i in items_param.split(",")}
            except ValueError:
                return model.response(
                    req, Status.FAILURE,
                    error=f"bad 'items' value {items_param!r}")
            best: Dict[int, tuple] = {}
            for x, y, sup, supx in model.deserialize_rules(payload):
                if supx <= 0 or not set(x) <= have:
                    continue
                conf = sup / supx
                for it in y:
                    if it in have:
                        continue
                    cur = best.get(it)
                    if cur is None or (conf, sup) > (cur[0], cur[1]):
                        best[it] = (conf, sup, supx, x, y)
            ranked = sorted(best.items(),
                            key=lambda kv: (-kv[1][0], -kv[1][1], kv[0]))
            # entry shape mirrors serialize_rules (exact sup/supx kept
            # integral, confidence the same float division) so a
            # prediction cross-references its /get/rules entry exactly
            return model.response(
                req, Status.FINISHED, predictions=json.dumps([
                    {"item": it, "confidence": conf, "support": sup,
                     "antecedent_support": supx,
                     "antecedent": list(x), "consequent": list(y)}
                    for it, (conf, sup, supx, x, y) in ranked]))
        return model.response(req, Status.FAILURE,
                              error=f"unknown subject {subject!r}")


class Tracker:
    """Ingest worker: /track events into the store (SURVEY.md sec 3.3).

    Validation honors the topic's registered field spec: the required
    'item' role may live under any event field name the spec maps it to.
    """

    def __init__(self, store: ResultStore) -> None:
        self.store = store

    def handle(self, req: ServiceRequest, topic: str) -> ServiceResponse:
        event = {k: v for k, v in req.data.items() if k != "uid"}
        item_field = sources.field_map(self.store, topic)["item"]
        if item_field not in event:
            return model.response(req, Status.FAILURE,
                                  error=f"missing field {item_field!r} "
                                        f"(the registered 'item' role)")
        self.store.track(topic, json.dumps(event))
        return model.response(req, Status.FINISHED)


class Registrar:
    """Field-spec registration (SURVEY.md sec 3.4)."""

    def __init__(self, store: ResultStore) -> None:
        self.store = store

    def handle(self, req: ServiceRequest, topic: str) -> ServiceResponse:
        spec = {k: v for k, v in req.data.items() if k != "uid"}
        self.store.add_fields(topic, json.dumps(spec))
        return model.response(req, Status.FINISHED)


class Streamer:
    """Streaming micro-batch worker (SURVEY.md sec 2.5, eval config #5).

    Each topic owns a sliding window of sequence micro-batches.  A push
    (``/stream/{topic}`` with an SPMF micro-batch in ``sequences``)
    appends the batch, evicts expired ones, and re-mines the window
    through the SAME AlgorithmPlugin boundary as batch train jobs — so
    SPADE/SPADE_TPU (with or without maxgap/maxwindow) and TSR all work
    incrementally.  Results land in the store under uid
    ``stream:{topic}`` with a ``finished`` status, so ``/get/patterns``
    (or ``/get/rules``) serves the window's current result set exactly
    like a batch job's.

    Window config (``support``, ``algorithm``, ``max_batches``,
    ``max_sequences``, constraints) is fixed by the first push to the
    topic; later pushes may omit it.  Relative ``support`` is recomputed
    against the *current* window size on every push.

    Window state survives restarts (SURVEY.md sec 5 checkpoint row's
    streaming half): the topic config and the window's raw micro-batch
    texts persist in the store (``fsm:stream:cfg/window:{topic}``), and a
    restarted service rebuilds the window on the topic's first touch — so
    the push after a restart mines the true window, not a truncated one.
    Mined results were already durable (``fsm:pattern:stream:{topic}``).
    """

    def __init__(self, store: ResultStore) -> None:
        self.store = store
        self._lock = threading.Lock()
        self._topics: Dict[str, dict] = {}

    def _build_state(self, data: Dict[str, str],
                     mb: Optional[int], ms: Optional[int]) -> dict:
        """Topic state from a (validated-here) config; shared by first-push
        creation and restart restore."""
        from spark_fsm_tpu.streaming.window import WindowMiner

        base = ServiceRequest("fsm", "stream", data)
        # Validate the WHOLE config before caching: a bad first push must
        # not poison the topic forever.
        plugin = plugins.get_plugin(base)
        support = float(data["support"])
        for p in ("maxgap", "maxwindow", "k", "max_side"):
            if base.param(p) is not None:
                int(base.param(p))
        if base.param("minconf") is not None:
            float(base.param("minconf"))

        def plugin_mine(db, minsup_abs, _plugin=plugin, _base=base):
            # WindowMiner computes the window-relative absolute minsup;
            # hand it to the plugin as an absolute count (plugins._minsup
            # treats support >= 1 as absolute).
            d = dict(_base.data)
            d["support"] = str(int(minsup_abs))
            return _plugin.extract(
                ServiceRequest(_base.service, _base.task, d), db)

        # Streaming route: true incremental mining (count the arriving
        # batch + border repair — streaming/incremental.py) is the
        # default for plain SPADE_TPU windows, single-device OR meshed
        # (the incremental miner shards each batch store's sequence
        # axis, SURVEY sec 2.2 x 2.5); everything else (TSR,
        # constraints, CPU oracle) re-mines the window
        # (streaming/window.py, the SURVEY sec 7 fallback).
        # ``incremental=0`` pins the re-mine path.
        algo = (data.get("algorithm") or "SPADE_TPU").upper()
        # same falsy spellings as the checkpoint param (Miner._run)
        # str() first: clients may send a JSON number/boolean and the
        # falsy-spelling contract must hold regardless of value type
        inc_param = str(data.get("incremental", "1") or "").lower()
        use_inc = (plugin.kind == "patterns"
                   and algo == "SPADE_TPU"
                   and base.param("maxgap") is None
                   and base.param("maxwindow") is None
                   and inc_param not in ("", "0", "false", "no", "off"))
        if use_inc:
            from spark_fsm_tpu.streaming.incremental import \
                IncrementalWindowMiner
            # stream_seq_floor (boot [prewarm] section): pin batch-store
            # buckets to the declared steady-state size so the first
            # pushes land on prewarmed shapes instead of compiling
            # throwaway small-bucket programs
            miner = IncrementalWindowMiner(
                support, max_batches=mb, max_sequences=ms,
                mesh=config.get_mesh(),
                seq_floor=config.get_config().prewarm.stream_seq_floor)
        else:
            miner = WindowMiner(support, max_batches=mb, max_sequences=ms,
                                mine=plugin_mine)

        return {
            "miner": miner,
            "kind": plugin.kind,
            "cfg": {"data": data, "max_batches": mb, "max_sequences": ms},
            # held across push + result sink + response-field reads
            # so concurrent pushes cannot sink an older window's
            # results over a newer one's (push alone is serialized
            # inside WindowMiner, but the store write is not)
            "lock": threading.Lock(),
        }

    def _restore(self, topic: str) -> Optional[dict]:
        """Rebuild a topic from its persisted config + window batches."""
        from spark_fsm_tpu.data.spmf import parse_spmf

        raw = self.store.get(f"fsm:stream:cfg:{topic}")
        if not raw:
            return None
        cfg = json.loads(raw)
        state = self._build_state(cfg["data"], cfg["max_batches"],
                                  cfg["max_sequences"])
        window = state["miner"].window
        win_key = f"fsm:stream:window:{topic}"
        try:
            texts = self.store.lrange(win_key)
        except Exception:  # real Redis: WRONGTYPE on a pre-delta-format key
            texts = []
        if not texts:
            raw = None
            try:
                raw = self.store.get(win_key)
            except Exception:
                pass
            if raw:  # migrate the old whole-window-JSON format in place
                try:
                    texts = json.loads(raw)
                except ValueError:
                    texts = []
                if not (isinstance(texts, list)
                        and all(isinstance(t, str) for t in texts)):
                    texts = []  # corrupt old value: start a fresh window
                self.store.delete(win_key)
                for t in texts:
                    self.store.rpush(win_key, t)
        for text in texts:
            # refill WITHOUT re-mining: results are already durable, and
            # the next push re-mines the full window anyway.  Replaying
            # through push() re-applies the eviction caps, so even a
            # persisted list with stale head entries (a crash between the
            # append and its trim) converges to the correct window.
            window.push(parse_spmf(text))
        sraw = self.store.get(f"fsm:stats:stream:{topic}")
        if sraw:
            # cumulative counters survive the restart; the refill pushes
            # above must not inflate them
            prev = json.loads(sraw)
            for key in ("pushes", "mines", "evicted_batches"):
                if key in prev:
                    state["miner"].stats[key] = int(prev[key])
            window.pushed_batches = int(prev.get("pushes",
                                                 window.pushed_batches))
            window.evicted_batches = int(prev.get("evicted_batches",
                                                  window.evicted_batches))
        log_event("stream_topic_restored", topic=topic,
                  batches=window.n_batches, sequences=window.n_sequences)
        return state

    def _topic_state(self, req: ServiceRequest, topic: str) -> dict:
        with self._lock:
            state = self._topics.get(topic)
            if state is None:
                state = self._restore(topic)
            if state is None:
                mb = req.param("max_batches")
                ms = req.param("max_sequences")
                if mb is None and ms is None:
                    mb = "4"
                # the cached base request keeps only mining params — never
                # the first micro-batch's payload
                data = {k: v for k, v in req.data.items()
                        if k not in ("sequences", "uid")}
                data.setdefault("algorithm", "SPADE_TPU")
                data.setdefault("support", "0.1")
                state = self._build_state(
                    data,
                    int(mb) if mb is not None else None,
                    int(ms) if ms is not None else None)
                self.store.set(f"fsm:stream:cfg:{topic}",
                               json.dumps(state["cfg"]))
            self._topics[topic] = state
            return state

    def handle(self, req: ServiceRequest, topic: str) -> ServiceResponse:
        from spark_fsm_tpu.data.spmf import parse_spmf

        if not topic:
            return model.response(req, Status.FAILURE,
                                  error="stream needs a topic: /stream/{topic}")
        text = req.param("sequences")
        if text is None:
            return model.response(req, Status.FAILURE,
                                  error="stream push needs a 'sequences' "
                                        "parameter (SPMF micro-batch)")
        try:
            batch = parse_spmf(text)
            if not batch:
                raise ValueError("empty micro-batch: 'sequences' parsed to "
                                 "zero sequences")
            state = self._topic_state(req, topic)
        except ValueError as exc:
            # config/parse rejections count as stream failures too, so
            # /admin/stats reflects every failed push
            self.store.incr("fsm:metric:stream_failures")
            return model.response(req, Status.FAILURE, error=str(exc))
        uid = f"stream:{topic}"
        miner = state["miner"]
        win_key = f"fsm:stream:window:{topic}"
        # one flight-recorder trace per topic (uid "stream:{topic}"),
        # a root span per push: the window re-mine's engine spans
        # thread under it exactly like a batch job's
        with state["lock"], obs.trace(uid, site="stream.push",
                                      topic=topic, sequences=len(batch)):
            try:
                try:
                    results = miner.push(batch)
                finally:
                    # persist the DELTA (append the batch, trim evictions to
                    # the live batch count) — the window mutates before the
                    # mine runs, so this happens even for a failed mine, or
                    # a restart would restore a window diverged from the
                    # live one.  Cost is O(batch), not O(window).
                    self.store.rpush(win_key, text)
                    while self.store.llen(win_key) > miner.window.n_batches:
                        self.store.lpop(win_key)
                # a prior failed push's error must not shadow this success
                # in /status (the batch path clears via clear_job)
                self.store.delete(f"fsm:error:{uid}")
                _sink_results(self.store, uid, state["kind"], results)
                self.store.set(f"fsm:stats:{uid}", json.dumps(miner.stats))
                self.store.add_status(uid, Status.FINISHED)
                self.store.incr("fsm:metric:stream_pushes")
            except Exception as exc:
                _record_failure(self.store, uid, exc,
                                metric="stream_failures")
                return model.response(req, Status.FAILURE, error=str(exc))
            window = miner.window
            return model.response(
                req, Status.FINISHED, uid=uid,
                window_batches=str(window.n_batches),
                window_sequences=str(window.n_sequences),
                evicted_batches=str(miner.stats["evicted_batches"]),
                results=str(len(results)))


def _jobs_collector(store: ResultStore):
    """Scrape-time bridge from the store's job counters to canonical
    fsm_* names — the /admin/stats ``jobs`` block keys are aliases of
    these.  A store that is down (or chaos-armed) skips its rows: the
    scrape must stay readable during the drill it is diagnosing."""
    names = ("jobs_submitted", "jobs_finished", "jobs_failed",
             "jobs_retried", "stream_pushes", "stream_failures")

    def collect():
        rows = []
        for n in names:
            try:
                # peek, not get: a scrape must never trip (or consume)
                # an armed store.get injection, or a pinned-seed chaos
                # drill goes nondeterministic under concurrent scraping
                v = int(store.peek(f"fsm:metric:{n}") or 0)
            except Exception:
                continue
            rows.append((f"fsm_{n}_total", "counter", "", [({}, v)]))
        return rows

    return collect


class Master:
    """Routes tasks to workers — the reference's FSMMaster."""

    def __init__(self, store: Optional[ResultStore] = None,
                 miner_workers: int = 1,
                 queue_depth: Optional[int] = None,
                 lease_mgr: Optional[lease.LeaseManager] = None) -> None:
        self.store = store if store is not None else ResultStore()
        # the registry keys one "jobs" collector process-wide: the last
        # Master built owns it (tests build many; the service builds one)
        obs.REGISTRY.register_collector("jobs", _jobs_collector(self.store))
        self.miner = Miner(self.store, workers=miner_workers,
                           queue_depth=queue_depth, lease_mgr=lease_mgr)
        if self.miner._lease is not None:
            # upgrade the heartbeat with the PERIODIC recovery pass:
            # a peer's crash is healed within ~one lease TTL without
            # waiting for anyone to reboot (start() is idempotent on
            # the thread; this call only installs the callback)
            self.miner._lease.start(self.miner,
                                    recover=lambda: recover_orphans(self))
        self.questor = Questor(self.store)
        # the read plane (ISSUE 17, service/predictor.py): /predict
        # compiles finished mines into device-resident rule tries and
        # micro-batches concurrent scoring into fused waves
        self.predictor = predictor.Predictor(self.store)
        self.tracker = Tracker(self.store)
        self.registrar = Registrar(self.store)
        self.streamer = Streamer(self.store)
        # elastic control plane (ISSUE 13, service/autoscale.py): one
        # controller per replica, leader-elected over the store; None
        # unless [autoscale] enabled (config requires [cluster] too)
        self.autoscaler = autoscale.build_for(self.miner)
        if self.autoscaler is not None:
            self.autoscaler.start()

    def cancel(self, uid: str) -> Optional[str]:
        """Cancel a live job (``/admin/cancel/{uid}``): returns what it
        was doing ("queued"/"running") or None when no live job owns the
        uid.  A RUNNING job aborts at its next safe point; a QUEUED job
        is settled immediately — its admission slot returns to the pool
        now instead of when a worker reaches the dead work."""
        state = jobctl.cancel(uid)
        if state is not None:
            log_event("job_cancel_requested", uid=uid, was=state)
        if state == "queued":
            self.miner.settle_cancelled_queued(uid)
        return state

    def handle(self, req: ServiceRequest) -> ServiceResponse:
        task, _, subject = req.task.partition(":")
        if task == "train":
            if not req.uid:
                req.data["uid"] = ServiceRequest.fresh_uid()
            try:  # validate algorithm/source names before going async
                plugins.get_plugin(req)
                src = (req.param("source") or "FILE").upper()
                if src not in sources.SOURCES:
                    raise ValueError(f"unknown source {src!r}")
                extras = self.miner.submit(req) or {}
            except plugins.UnknownAlgorithm as exc:
                # structured 400 BEFORE anything went async: the body
                # names the supported registry (derived from the
                # planner's view of plugins.ALGORITHMS, never a
                # docstring), so a client typo is one round trip to fix
                # instead of a failure buried deep in dispatch
                return model.response(
                    req, Status.FAILURE, error=str(exc),
                    http_status="400",
                    supported=json.dumps(exc.supported))
            except AdmissionShed as exc:
                # overload shed: protocol-mapped to 429 + Retry-After by
                # the HTTP layer (remote clients read retry_after_s).
                # In cluster mode the body carries the same cached peer
                # view the Retry-After hint consulted, so the client can
                # see whether the hint means "steal path" or "local
                # EWMA" (docs/OPERATIONS.md).
                extra: Dict[str, str] = {}
                if self.miner._lease is not None:
                    try:
                        extra["cluster"] = json.dumps(
                            self.miner._lease.shed_view())
                    except Exception:
                        pass
                return model.response(req, Status.FAILURE, error=str(exc),
                                      http_status="429",
                                      retry_after_s=str(exc.retry_after_s),
                                      **extra)
            except UidConflict as exc:
                return model.response(req, Status.FAILURE, error=str(exc),
                                      http_status="409")
            except lease.LeaseUnavailable as exc:
                # the lease protocol itself failed (store down, injected
                # lease.acquire fault): the submit cannot be made safe —
                # clean 503 with zero store trace of the uid
                return model.response(req, Status.FAILURE, error=str(exc),
                                      http_status="503")
            except (ValueError, faults.FaultInjected) as exc:
                # bad submit params, or a chaos-armed admission/journal
                # site: a clean synchronous failure envelope either way
                return model.response(req, Status.FAILURE, error=str(exc))
            # extras: e.g. ephemeral="1" — the LOUD no-journal flag a
            # store-outage admission carries ([storeguard])
            return model.response(req, Status.STARTED, **extras)
        if task == "status":
            status = self.store.status(req.uid)
            if status is None:
                return model.response(req, Status.FAILURE, error="unknown uid")
            extra: Dict[str, str] = {}
            error = self.store.get(f"fsm:error:{req.uid}")
            if error:
                extra["error"] = error
            stats = self.store.get(f"fsm:stats:{req.uid}")
            if stats:  # engine + timing counters (SURVEY.md sec 5 metrics)
                extra["stats"] = stats
            return model.response(req, status, **extra)
        if task == "get":
            return self.questor.handle(req, subject or "patterns")
        if task == "predict":
            return self.predictor.handle(req)
        if task == "track":
            return self.tracker.handle(req, subject or "item")
        if task == "stream":
            return self.streamer.handle(req, subject)
        if task in ("register", "index"):
            return self.registrar.handle(req, subject or "item")
        return model.response(req, Status.FAILURE,
                              error=f"unknown task {req.task!r}")

    def shutdown(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.predictor.shutdown()
        self.miner.shutdown()


_RECOVERY_TOTAL = obs.REGISTRY.counter(
    "fsm_recovery_jobs_total",
    "journal orphans handled by the boot recovery pass, by outcome")
# zero-seed the outcome vocabulary (obs_smoke's no-orphan contract):
# "quarantined" is the ISSUE 18 poison-intent report bucket; "corrupt"
# counts the same records once they ALSO settle as durable failures
for _outcome in ("cleared", "resumed", "failed", "quarantined", "corrupt"):
    _RECOVERY_TOTAL.seed(outcome=_outcome)
del _outcome


def recover_orphans(master: Master) -> Dict[str, List[str]]:
    """Boot-time crash-restart recovery (service/app.py runs this before
    accepting traffic): heal every journal intent record left by a DEAD
    incarnation.

    - already-terminal orphan (the crash hit between the terminal status
      write and the journal clear): settle the journal — ``cleared``;
    - checkpointed orphan: resubmit the journaled request through the
      normal admission path; the mine resumes from its persisted
      frontier (zero duplicated results — the fingerprint check restarts
      fresh if the data changed) — ``resumed``;
    - anything else: durable ``failure: interrupted by restart`` so no
      client ever polls a forever-pending uid — ``failed``.

    MULTI-REPLICA (``[cluster] enabled``): liveness is proven by the
    JOB LEASE, not inferred from the incarnation tag — a foreign
    journal entry is an orphan ONLY once its lease has expired, and
    adoption itself is an atomic NX re-acquisition, so N replicas may
    run this pass concurrently (boot + periodic) and each orphan is
    adopted exactly once.  Without the lease layer the PR 5
    single-writer assumption still holds: exactly ONE service instance
    may own a store, because a sibling's live jobs would read as dead
    orphans here (docs/OPERATIONS.md states the same constraint).
    """
    store, miner = master.store, master.miner
    mgr = miner._lease
    report: Dict[str, List[str]] = {"resumed": [], "failed": [],
                                    "cleared": [], "quarantined": []}
    for uid in store.journal_uids():
        raw = store.journal_get(uid)
        if not raw:
            continue  # settled between the scan and this read
        try:
            entry = json.loads(raw)
            if not isinstance(entry, dict):
                raise ValueError("journal intent must be an object")
        except ValueError:
            # poison intent (bitrot or a torn write — journal_get hands
            # back the RAW bytes on a failed envelope so this parse
            # fails): move it to fsm:quarantine:{uid} and keep
            # recovering the REMAINING orphans — one bad record must
            # not wedge boot recovery for every other job (ISSUE 18).
            # An undecodable intent can never be resumed, so the uid
            # ALSO settles as a durable failure (lease-fenced: a live
            # holder elsewhere keeps settling rights) — no client polls
            # a forever-pending uid whose intent rotted.
            integrity.quarantine(store, f"fsm:journal:{uid}", raw,
                                 "journal", move=True)
            if ((mgr is None or mgr.adopt_expired(uid))
                    and store.status(uid) not in (Status.FINISHED,
                                                  Status.FAILURE)):
                _record_failure(
                    store, uid,
                    RuntimeError("journal intent corrupt (quarantined "
                                 f"at fsm:quarantine:{uid}); re-submit "
                                 "to re-mine"),
                    keep_frontier=True, lease_mgr=mgr,
                    rescache=miner._rescache, guard=miner._guard)
            report["quarantined"].append(uid)
            _RECOVERY_TOTAL.inc(outcome="corrupt")
            log_event("restart_recovery_quarantined", uid=uid)
            continue
        if entry.get("incarnation") == miner.incarnation:
            continue  # live in THIS incarnation (a concurrent submit)
        if mgr is not None and not mgr.adopt_expired(uid):
            continue  # lease still live on a replica (the job is merely
            # running/queued elsewhere), or a sibling recovery pass won
            # the adoption race — either way: not ours to touch
        if mgr is not None and entry.get("replica"):
            # reap the dead replica's admission marker for this uid —
            # markers have no TTL (a TTL'd marker would make the
            # victim's dequeue misread an expiry as a steal), so
            # adoption is where a crashed replica's markers get
            # collected instead of leaking forever
            try:
                store.delete(f"fsm:admission:{entry['replica']}:{uid}")
            except Exception:
                pass
        status = store.status(uid)
        if status in (Status.FINISHED, Status.FAILURE):
            store.journal_clear(uid)
            if mgr is not None:
                mgr.release(uid)
            report["cleared"].append(uid)
            _RECOVERY_TOTAL.inc(outcome="cleared")
            continue
        # failover latency candidate, measured BEFORE the resubmit (the
        # resubmit's own spine flush would reset the reference): the
        # dead owner's last provable sign of life (its final spine
        # flush; journal intent ts when it never flushed) to now.
        # Bounded by lease_ttl_s + recover_every_s (+ the owner's flush
        # cadence) on a healthy cluster — replica_smoke asserts it.
        # Observed into the histogram only on a SUCCESSFUL adoption
        # resume below: an orphan settled as a durable failure was not
        # adopted in the sense the metric's alert contract promises.
        adoption_s = None
        if mgr is not None:
            ref_ts = obsplane.last_activity_ts(store, uid)
            if ref_ts is None:
                try:
                    ref_ts = float(entry.get("ts") or 0) or None
                except (TypeError, ValueError):
                    ref_ts = None
            if ref_ts is not None:
                adoption_s = max(0.0, time.time() - ref_ts)
        if entry.get("checkpoint"):
            # crash-loop quarantine gate ([cluster] max_adoptions): a
            # job whose every holder dies would otherwise ping-pong
            # through adoption forever.  Past the budget it settles as
            # a durable POISON: terminal + fsm:quarantine:{uid} record
            # (409 on resubmit until /admin/quarantine releases it).
            if not miner.adopt_or_poison(uid, entry, raw=raw):
                report["failed"].append(uid)
                _RECOVERY_TOTAL.inc(outcome="failed")
                log_event("restart_recovery_poisoned", uid=uid)
                continue
            req = ServiceRequest("fsm", "train", {
                str(k): str(v) for k, v in entry.get("request", {}).items()})
            try:
                miner.submit(req)
                report["resumed"].append(uid)
                _RECOVERY_TOTAL.inc(outcome="resumed")
                log_event("restart_recovery_resumed", uid=uid)
                if mgr is not None:
                    if adoption_s is not None:
                        obsplane.observe_adoption(adoption_s)
                    # the resubmit re-opened the trace ring: stamp the
                    # adoption onto the spine so the merged timeline
                    # shows owner-death -> adoption in one place
                    obs.lifecycle(
                        uid, "adopted", replica=mgr.replica_id,
                        time_to_adoption_s=(
                            None if adoption_s is None
                            else round(adoption_s, 3)))
                    obs.flush_trace(uid)
                continue
            except Exception as exc:  # shed (tiny queue at boot) or a
                # store hiccup: fall through to the durable failure —
                # recovery must never leave the orphan pending (and the
                # staged adoption counter must not leak onto a future
                # fresh submit of the same uid)
                miner._adoptions_pending.pop(uid, None)
                failure = RuntimeError(
                    f"interrupted by restart; recovery resubmit failed: "
                    f"{exc}")
        else:
            failure = RuntimeError(
                "interrupted by restart (job was not checkpointed; "
                "re-submit to re-mine)")
        # keep_frontier: a recovery resubmit that shed (tiny queue at
        # boot) must not destroy the very progress it failed to resume
        _record_failure(store, uid, failure, keep_frontier=True,
                        lease_mgr=mgr, rescache=miner._rescache,
                        guard=miner._guard)
        report["failed"].append(uid)
        _RECOVERY_TOTAL.inc(outcome="failed")
    if any(report.values()):
        log_event("restart_recovery",
                  resumed=len(report["resumed"]),
                  failed=len(report["failed"]),
                  cleared=len(report["cleared"]),
                  quarantined=len(report["quarantined"]))
    return report
