"""AOT prewarm driver: pay every enumerable compile at boot.

A fresh deployment's first mine of a geometry costs ~41.7 s of XLA/Mosaic
compile (BASELINE.json ``cold_start.cache_miss_cold_wall_s``), and a
streaming consumer hits a one-time mid-stream sweep-compile stall when
the tracked tree first outgrows its store bucket (12.85 s at config-5
scale, BENCH_SCALE ``per_push_phase_s[1]``).  Both are *enumerable*
costs: the shape-key registry (utils/shapes.py) lists the finite set of
compiled geometries a declared workload envelope will touch.

This driver walks that set and compiles every entry against a TINY
synthetic store with the DECLARED global geometry: ``build_vertical``'s
``pad_sequences_to``/``word_multiple`` stretch a KB-scale token table to
the full padded device shape, so the store scatter-build and the whole
kernel chain compile at exactly the shapes live requests will hit —
populating the in-process jit caches and the persistent XLA cache
(utils/jitcache.py).  The synthetic content is one single-itemset
sequence per item: every item is a frequent root (one full DFS wave runs
— prep, pair supports, prune), but no two items ever co-occur, so there
are no frequent children and the mine is milliseconds of device work on
top of the compiles it exists to trigger.

Entry points: ``run(spec)`` (the driver), ``POST /admin/prewarm``
(service/app.py; parameters override the boot ``[prewarm]`` config), and
the app boot hook (``[prewarm] enabled = true``).  Per-key compile walls
land in the returned report and in ``/admin/stats``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from spark_fsm_tpu.utils import faults, obs, shapes
from spark_fsm_tpu.utils.jitcache import compile_counts, enable_compile_counter
from spark_fsm_tpu.utils.obs import log_event

_COMPILE_SECONDS = obs.REGISTRY.histogram(
    "fsm_prewarm_compile_seconds",
    "per-shape-key AOT prewarm wall (service/prewarm.run)")
_COMPILE_ERRORS = obs.REGISTRY.counter(
    "fsm_prewarm_errors_total", "prewarm keys that failed to compile")

_lock = threading.Lock()
_last_report: Optional[dict] = None


def _tiny_vdb(n_sequences: int, n_items: int, n_words: int):
    """Vertical DB with the declared GLOBAL geometry but ~KB content:
    one single-itemset sequence per item (all roots frequent at
    minsup=1, no co-occurrence, so no frequent children), padded out to
    ``n_sequences`` all-zero sequences and ``n_words`` bitmap words."""
    from spark_fsm_tpu.data.vertical import build_vertical

    if n_items < 1 or n_sequences < n_items:
        raise ValueError(
            f"prewarm spec needs 1 <= items <= sequences, got "
            f"items={n_items} sequences={n_sequences}")
    db = [[[i]] for i in range(1, n_items + 1)]
    if n_words > 1:
        # one long sequence forces the declared word count's position
        # range too (word_multiple pads the rest)
        db[0] = [[1]] * (32 * (n_words - 1) + 1)
    return build_vertical(db, min_item_support=1,
                          pad_sequences_to=n_sequences,
                          word_multiple=n_words)


def _warm_support_concat(chunk: int) -> None:
    """Batches wider than one support chunk concatenate their per-chunk
    device outputs into one array (for the single async host copy);
    the engines pow2-bucket the arity (spade_tpu._concat_pow2) exactly
    so this ladder is finite — warm arities 2..512 plus the zeros pad
    program (covers batches up to 512*chunk candidates; beyond that a
    live mine pays one ~ms concat compile, not a kernel compile)."""
    import jax.numpy as jnp

    z = jnp.zeros(chunk, jnp.int32)
    jnp.zeros_like(z)
    k = 2
    while k <= 512:
        jnp.concatenate([z] * k)
        k *= 2


def _force_classic_chain(eng) -> None:
    """Compile the chain members a no-children mine never dispatches
    (materialize at chunk width, recompute at a representative step
    depth) — all writes land in the scratch row of a throwaway engine."""
    pt = eng._prep_fn(eng.store, eng._put(np.zeros(eng.node_batch,
                                                   np.int32)))
    c = eng.chunk
    z32 = eng._put(np.zeros(c, np.int32))
    zb = eng._put(np.zeros(c, bool))
    os_ = eng._put(np.full(c, eng.scratch, np.int32))
    eng.store = eng._materialize_fn(pt, eng.store, z32, z32, zb, os_)
    rc = eng.recompute_chunk
    for k in (2, 4, 8, 16):  # pow2-bucketed step depth of live rebuilds
        eng.store = eng._recompute_fn(
            eng.store, eng._put(np.zeros((k, rc), np.int32)),
            eng._put(np.zeros((k, rc), bool)),
            eng._put(np.zeros((k, rc), bool)),
            eng._put(np.full(rc, eng.scratch, np.int32)))
    _warm_support_concat(eng.chunk)


def _force_cspade_chain(eng) -> None:
    """Constrained-engine analog of :func:`_force_classic_chain`."""
    nb = eng.node_batch
    m, pm = eng._prep_fn(eng.pool, eng.items,
                         eng._put(np.zeros(nb, np.int32)),
                         eng._put(np.zeros(nb, np.int32)),
                         eng._put(np.ones(nb, bool)))
    c = eng.chunk
    z32 = eng._put(np.zeros(c, np.int32))
    zb = eng._put(np.zeros(c, bool))
    os_ = eng._put(np.full(c, eng.scratch, np.int32))
    eng.pool = eng._materialize_fn(m, pm, eng.items, eng.pool,
                                   z32, z32, zb, os_)
    rc = eng.recompute_chunk
    for k in (2, 4, 8, 16):  # pow2-bucketed step depth of live rebuilds
        eng.pool = eng._recompute_fn(
            eng.pool, eng.items, eng._put(np.zeros((k, rc), np.int32)),
            eng._put(np.zeros((k, rc), bool)),
            eng._put(np.zeros((k, rc), bool)),
            eng._put(np.full(rc, eng.scratch, np.int32)))
    _warm_support_concat(eng.chunk)


def _token_buckets(n_items: int, max_tokens: int) -> List[int]:
    from spark_fsm_tpu.models._common import next_pow2

    b = next_pow2(max(16, n_items))
    hi = next_pow2(max(b, max_tokens))
    out = []
    while b <= hi:
        out.append(b)
        b *= 2
    return out


def _warm_store_builders(n_rows: int, n_seq: int, n_words: int, mesh,
                         flat: bool, n_items: int, max_tokens: int,
                         put) -> None:
    """Compile the store scatter-build for every pow2 token-count bucket
    up to the declared bound: token-array length is a traced shape
    (pow2-bucketed by scatter_build_store), so real data's token count
    lands on one of these buckets — all-zero dummy tokens scatter
    nothing and the output is discarded."""
    from spark_fsm_tpu.models._common import _store_builder

    fn = _store_builder(n_rows, n_seq, n_words, mesh, flat)
    for nt in _token_buckets(n_items, max_tokens):
        z = np.zeros(nt, np.int32)
        fn(put(z), put(z), put(z), put(np.zeros(nt, np.uint32)))


def _warm_classic(t: dict, mesh, ekw: dict) -> None:
    from spark_fsm_tpu.models.spade_tpu import SpadeTPU

    vdb = _tiny_vdb(t["n_sequences"], t["n_items"], t["n_words"])
    eng = SpadeTPU(vdb, 1, mesh=mesh, **ekw)
    eng.mine()
    _force_classic_chain(eng)
    _warm_store_builders(eng.store.shape[0], eng.n_seq, eng.n_words, mesh,
                         True, t["n_items"], t["max_tokens"], eng._put)


def _warm_queue(t: dict, mesh) -> None:
    from spark_fsm_tpu.models.spade_queue import QueueSpadeTPU, _queue_mine_fn

    vdb = _tiny_vdb(t["n_sequences"], t["n_items"], t["n_words"])
    eng = QueueSpadeTPU(vdb, 1, mesh=mesh)
    eng.mine()  # the whole-mine one-shot program: the 41.7 s item
    _warm_store_builders(eng.store.shape[0], eng.n_seq, eng.n_words, mesh,
                         True, t["n_items"], t["max_tokens"], eng._put)
    if t.get("checkpointed"):
        # the segmented (resumable) variants: four programs now exist —
        # (wide, late-wave narrow) x (first segment, donating
        # continuation) — and a tiny mine reaches at most one of them
        # (its root count picks wide or narrow, and a single-wave mine
        # never runs segment 2), so each is dispatched directly on a
        # fresh root carry.  The donating programs get a THROWAWAY
        # engine each: donation invalidates the carry's store array,
        # and carry[0] is the engine's persistent store.
        eng2 = QueueSpadeTPU(vdb, 1, mesh=mesh)
        eng2.mine(checkpoint_cb=lambda s: None, checkpoint_every_s=1e9)
        cap = eng2.caps
        nbl = eng2._nb_late
        widths = [cap.nb] + ([nbl] if nbl < cap.nb else [])
        for nbw in widths:
            imax = cap.i_max * (max(1, cap.nb // max(1, nbl))
                                if nbw == nbl else 1)
            mkw = (eng2.mesh, eng2.n_words, eng2.ni_pad, eng2.max_its,
                   nbw, cap.ring, cap.c_cap, cap.m_cap, cap.r_cap,
                   imax, eng2.use_pallas, eng2._s_block,
                   eng2._interpret, True)
            _queue_mine_fn(*mkw, False)(
                *eng2._root_carry(eng2._roots()), eng2._put(np.int32(1)))
            eng3 = QueueSpadeTPU(vdb, 1, mesh=mesh)
            _queue_mine_fn(*mkw, True)(
                *eng3._root_carry(eng3._roots()), eng3._put(np.int32(1)))


def _warm_fused(t: dict, mesh) -> None:
    from spark_fsm_tpu.models.spade_fused import FusedSpadeTPU

    vdb = _tiny_vdb(t["n_sequences"], t["n_items"], t["n_words"])
    eng = FusedSpadeTPU(vdb, 1, mesh=mesh)
    eng.mine()
    _warm_store_builders(eng.ni_pad + 2 * eng.caps.f_cap + 1, eng.n_seq,
                         eng.n_words, mesh, True, t["n_items"],
                         t["max_tokens"], eng._put)


def _warm_spam(t: dict, mesh, ekw: dict) -> None:
    """Compile the SPAM engine's pure-bitmap chain: construction (store
    scatter + dense gather seam) plus a tiny mine through the fused
    extension-count-prune wave.  ``representation="bitmap"`` pins the
    pure plan — the prewarm vdb is one-sequence-per-item (density ~0),
    which the planner would otherwise route entirely to id-lists and
    the pure wave program a live DENSE mine runs would stay cold."""
    from spark_fsm_tpu.models.spam_bitmap import SpamBitmapTPU

    vdb = _tiny_vdb(t["n_sequences"], t["n_items"], t["n_words"])
    skw = {k: v for k, v in ekw.items()
           if k in ("node_batch", "pipeline_depth", "pool_bytes")}
    eng = SpamBitmapTPU(vdb, 1, mesh=mesh, representation="bitmap", **skw)
    eng.mine()
    _warm_store_builders(eng.store.shape[0], eng.n_seq, eng.n_words, mesh,
                         True, t["n_items"], t["max_tokens"], eng._put)


def _spam_put(mesh):
    import functools

    from spark_fsm_tpu.parallel import multihost as MH

    return functools.partial(MH.host_to_device, mesh)


def _warm_spam_hybrid(t: dict, mesh) -> None:
    """Compile one hybrid-store wave geometry: the dense-block gather
    plus the fused prune wave at this ``nd_pad`` — all-zero stores have
    the right shapes (the only thing a compile keys on).  The d0 entry
    has no wave program (every item id-list-routed; its launches are
    the spam-pair widths) — recording the key keeps /admin/shapes
    completeness exact."""
    import jax

    from spark_fsm_tpu.ops import spam_bitops as SB

    nd, nw = int(t["nd_pad"]), int(t["n_words"])
    S, nb = int(t["n_seq_pad"]), int(t["node_batch"])
    put = _spam_put(mesh)
    if nd:
        use_pallas = jax.default_backend() == "tpu"
        SB.gather_rows_fn(mesh)(
            put(np.zeros((t["total_rows"], S * nw), np.uint32)),
            put(np.full(nd, -1, np.int32)))
        fn = SB.wave_extend_prune_fn(mesh, nw, nd, t["tile"],
                                     use_pallas=use_pallas,
                                     s_block=int(t["s_block"]),
                                     interpret=False)
        fn(put(np.zeros((2 * nb, S * nw), np.uint32)),
           put(np.zeros((nd, S * nw), np.uint32)),
           put(np.int32(1)), put(np.zeros(2 * nb, bool)))
    shapes.record(shapes.key_spam_hybrid(S, nw, t["total_rows"], nb,
                                         int(t["ni_pad"]), nd))


def _warm_spam_pair(t: dict, mesh) -> None:
    """Compile one sparse pair-launch width: the gather-join-count-prune
    program keys on (pt rows, store rows, width) — dispatched on zero
    stores with all-pad (-1) items, milliseconds on top of the
    compile."""
    from spark_fsm_tpu.ops import spam_bitops as SB

    nw, w = int(t["n_words"]), int(t["width"])
    S, nb = int(t["n_seq_pad"]), int(t["node_batch"])
    put = _spam_put(mesh)
    SB.pair_prune_fn(mesh, nw)(
        put(np.zeros((2 * nb, S * nw), np.uint32)),
        put(np.zeros((t["total_rows"], S * nw), np.uint32)),
        put(np.zeros(w, np.int32)), put(np.full(w, -1, np.int32)),
        put(np.int32(1)), put(np.zeros(w, bool)))
    shapes.record(shapes.key_spam_pair(S, nw, w))


def _warm_cspade(t: dict, mesh, ekw: dict) -> None:
    from spark_fsm_tpu.models.spade_constrained import ConstrainedSpadeTPU

    vdb = _tiny_vdb(t["n_sequences"], t["n_items"], t["n_words"])
    eng = ConstrainedSpadeTPU(vdb, 1, maxgap=t["maxgap"],
                              maxwindow=t["maxwindow"], mesh=mesh, **ekw)
    eng.mine()
    _force_cspade_chain(eng)
    _warm_store_builders(eng.item_rows, eng.n_seq, eng.n_words, mesh,
                         False, t["n_items"], t["max_tokens"], eng._put)


def _walk_eval_ladder(eng, superbatch):
    """Dispatch one launch per (km, width) eval geometry on ``eng`` —
    the ONE warm walk behind the solo AND partitioned TSR ladders (the
    chunk/_round_m/prep setup and the kernel-vs-jnp dispatch must not
    drift between them).  All-(-1) candidate slots resolve to the pad
    rows, so each dispatch is milliseconds of device work on top of the
    compile it triggers.  The jnp program compiles even on
    kernel-capable backends: it is the kernel-failure fallback, plus
    the sub-C_LANES widths only the jnp planner emits — cheap
    insurance, and it keeps every enumerated tsr-eval key recorded on
    every backend.  Returns the engine-layout preps for callers that
    warm further programs at the same geometry."""
    from spark_fsm_tpu.ops import pallas_tsr as PT
    from spark_fsm_tpu.ops import ragged_batch as RB

    m = min(eng.item_cap, eng.vdb.n_items)
    eng.chunk = eng._round_chunk(m)
    eng._round_m = m
    eng._jnp_prep = None
    p1, s1 = eng._prep(m)
    pj, sj = (eng._prep_engine(m) if eng.use_pallas else (p1, s1))
    for km, width in superbatch:
        launch = RB.Launch(km, width, [], [])
        if eng.use_pallas and width >= PT.C_LANES:  # kernel out-tile floor
            eng._dispatch_kernel_launch(
                p1, s1, [], launch, [], np.empty(0, np.int64), 0)
        else:
            xy = eng._stager.take(launch, [])
            eng._eval_fn(km)(pj, sj, eng._put(xy))
            eng._count_launch(launch)
    return pj, sj


def _warm_tsr(t: dict, mesh) -> None:
    from spark_fsm_tpu.models.tsr import TsrTPU
    from spark_fsm_tpu.ops import ragged_batch as RB

    vdb = _tiny_vdb(t["n_sequences"], t["n_items"], t["n_words"])
    eng = TsrTPU(vdb, min(8, t["n_items"]), 0.5, max_side=2, mesh=mesh)
    eng.mine()
    # Eval-launch super-batch ladder (ops/ragged_batch.py + the
    # ``tsr-eval`` keys in utils/shapes.py): compile every (km, width)
    # launch program the ragged packer can emit, at the first deepening
    # round's top-m store — the service envelope's dominant geometry
    # (later rounds' m varies by design and recompiles per round).
    pj, sj = _walk_eval_ladder(eng, t.get("superbatch", ()))
    # Cross-job fused eval ladder (service/fusion.py): the broker's
    # fused launches run the SAME jnp eval programs at a concatenated
    # pow2-padded item axis, so the compiled set is the enumerated
    # ``fused_m`` buckets x the (km, width) ladder.  Zero stores have
    # the right SHAPE — the only thing a compile keys on — so warming
    # costs no store build.  The broker is gated to the single-device
    # jnp path (the folded kernel layout's appended pad row does not
    # survive an item-axis concat), matching the jnp eval fns warmed
    # here.
    import jax.numpy as jnp

    for m_pad in t.get("fused_m", ()):
        zshape = (m_pad,) + tuple(pj.shape[1:])
        pf = jnp.zeros(zshape, jnp.uint32)
        sf = jnp.zeros(zshape, jnp.uint32)
        for km, width in t.get("superbatch", ()):
            launch = RB.Launch(km, width, [], [])
            xy = eng._stager.take(launch, [])
            eng._eval_fn(km)(pf, sf, eng._put(xy))
            shapes.record(shapes.key_tsr_fused(
                eng.n_seq, eng.n_words, m_pad, km, width))


def _warm_tsr_part(t: dict, mesh) -> None:
    """Compile the equivalence-class partitioned TSR ladder
    (parallel/partition.py + models/tsr.TsrPartitioned): a tiny
    partitioned mine covers the orchestrator's own programs, then EVERY
    part engine walks the (km, width) eval ladder at the inner submesh
    geometry.  Every row is walked, not just the first — compiled
    executables bind their device assignment, so row 0's compile does
    not warm row 1's devices even though the shape keys are equal."""
    from spark_fsm_tpu.models.tsr import TsrPartitioned

    vdb = _tiny_vdb(t["n_sequences"], t["n_items"], t["n_words"])
    # record_metrics=False: a boot warm must not make fsm_partition_*
    # report mines that never happened or clobber the imbalance gauge
    orch = TsrPartitioned(vdb, min(8, t["n_items"]), 0.5, mesh=mesh,
                          parts=t["parts"], max_side=2,
                          record_metrics=False)
    orch.mine()
    for eng in orch.engines.values():
        _walk_eval_ladder(eng, t.get("superbatch", ()))


def _warm_resident(t: dict, mesh) -> None:
    """Compile one resident-frontier segment program (wide or narrow —
    one enumerated key per wave width, ops/resident_frontier.py) at the
    declared geometry: a zero-entry carry with wave budget 0 never runs
    a wave, so the dispatch is the while_loop compile plus microseconds
    of cond evaluation.  The resident route is single-device by
    construction (the enumerator emits the ladder only for mesh=None),
    so no shard_map variant exists to warm."""
    import jax.numpy as jnp

    from spark_fsm_tpu.ops import resident_frontier as RF

    S, W, m = t["n_seq_pad"], t["n_words"], t["m"]
    ring, r_cap, d_cap = t["ring"], t["r_cap"], t["d_cap"]
    km, nb = t["km"], t["nb"]
    z = lambda *shape, dt=jnp.int32: jnp.zeros(shape, dt)
    i32 = jnp.int32
    carry = (jnp.full((ring, 2, km), -1, i32), z(ring), z(ring),
             z(ring), z(ring, dt=jnp.bool_), z(ring),
             i32(0), i32(0),
             jnp.full((r_cap, 2, km), -1, i32), z(r_cap), z(r_cap),
             i32(0), z(RF.K_PAD), i32(0), i32(1), jnp.bool_(False),
             i32(0), i32(0), i32(0),
             jnp.full((d_cap, 2, km + 1), -1, i32), z(d_cap),
             z(d_cap), z(d_cap), z(d_cap, dt=jnp.bool_), z(d_cap),
             i32(0))
    RF._resident_fn(nb, km)(
        z(m, S, W, dt=jnp.uint32), z(m, S, W, dt=jnp.uint32), z(m),
        i32(1), i32(2), i32(1), i32(1 << 30), i32(0), *carry)
    shapes.record(shapes.key_tsr_resident(S, W, m, km, nb, ring))


def _warm_sweep(t: dict, mesh) -> None:
    """Compile the incremental sweep chain at one enumerated row bucket:
    rebuild a live batch's store at that bucket, then dispatch the
    prep/supports/materialize kernels (and the repair fold) across the
    pow2 width ladder live sweeps use."""
    import jax.numpy as jnp

    from spark_fsm_tpu.models._common import next_pow2
    from spark_fsm_tpu.models.spade_tpu import _spade_fns
    from spark_fsm_tpu.streaming.incremental import (
        IncrementalWindowMiner, _fold_supports_fn)

    miner = IncrementalWindowMiner(
        1.0, max_batches=4, mesh=mesh,
        # live batch stores bucket at bucket_seq(max(push, floor)); the
        # warm pushes are tiny, so the floor must carry BOTH envelope
        # knobs to land on the live bucket
        seq_floor=max(t["batch_sequences"], t.get("seq_floor", 0)))
    batch = [[[i]] for i in range(1, t["n_items"] + 1)]
    if t["n_words"] > 1:
        batch[0] = [[1]] * (32 * (t["n_words"] - 1) + 1)
    # two pushes: the first compiles the token scatter + repair fold for
    # the fresh tree, the second the sweep over an existing tree — the
    # exact mid-stream pattern behind the config-5 push-2 stall
    miner.push(batch)
    miner.push(list(batch))
    st = next(iter(miner._states.values()))
    f1 = sorted(miner._item_totals)
    target = t["n_rows"]
    if st._n_rows != target:
        st.drop_store()
        st._project(f1, max(0, target - st.ni_rows - 1))
    assert st._n_rows == target, (st._n_rows, target)
    fns = _spade_fns(miner.mesh, st.n_words)
    put = miner._put
    scratch = st._n_rows - 1
    # Live sweep shapes form a 2-D family: prep (pt) width = pow2 bucket
    # of the level's NODE count, candidate width = pow2 bucket of the
    # level's candidate count (chunk-capped at support_chunk), and the
    # two compose into one compiled program per (p, c) pair.  Warm the
    # full pow2 grid — it is bounded (log x log) and each entry is a
    # small XLA program; absorbing it at boot is the whole point.  The
    # tree's level width is bounded by the row bucket it projects into
    # (extra work rows = 2*level width), so the ladders follow n_rows,
    # not the item count — tracked nodes share items, so levels run far
    # wider than the alphabet.
    p_hi = max(8, next_pow2(max(t["n_items"], t["n_rows"] // 2)))
    c_hi = min(miner.support_chunk,
               next_pow2(max(8, t["n_items"] * t["n_items"],
                             t["n_rows"])))
    p = 8
    while p <= p_hi:
        slots = np.full(p, scratch, np.int32)
        pt = fns["prep"](st.store, put(slots))
        c = 8
        while c <= c_hi:
            if not miner.use_pallas:  # TPU routes supports via Pallas
                fns["supports"](pt, st.store,
                                put(np.zeros(c, np.int32)),
                                put(np.zeros(c, np.int32)),
                                put(np.zeros(c, bool)))
            st.store = fns["materialize"](
                pt, st.store, put(np.zeros(c, np.int32)),
                put(np.zeros(c, np.int32)), put(np.zeros(c, bool)),
                put(np.full(c, scratch, np.int32)))
            c *= 2
        if miner.use_pallas:
            # the Pallas pair-matrix path pads candidates to pow2 caps
            # >= 1024 — the dominant per-shape Mosaic compile (this IS
            # the config-5 push-2 stall, paid here instead).  Drive the
            # SAME launcher the live sweep uses (the shard_map'd
            # _pallas_supports_fn under a mesh; a mismatched dummy call
            # would warm a program the stream never runs), across cap
            # buckets up to 16384 — levels with more candidates pay a
            # live recompile of the cheap extraction program, not of
            # the pair kernel (which is keyed per pt width, warmed
            # here).
            from spark_fsm_tpu.ops import pallas_support as PS
            items_arr = st.items_t if st.items_t is not None else st.store
            cap = 1024
            while cap <= 16384:
                pref = np.zeros(cap, np.int32)
                if miner.mesh is not None:
                    from spark_fsm_tpu.models.spade_tpu import (
                        _pallas_supports_fn)
                    _pallas_supports_fn(
                        miner.mesh, st.ni_rows, st.s_block, st.n_words,
                        miner._interpret)(pt, items_arr, put(pref),
                                          put(pref))
                else:
                    PS.batch_supports(
                        pt, items_arr, st.ni_rows, jnp.asarray(pref),
                        jnp.asarray(pref),
                        items_kernel_layout=st.items_t is not None,
                        s_block=st.s_block, interpret=miner._interpret,
                        n_words=st.n_words)
                cap *= 2
        p *= 2
    fold = _fold_supports_fn(st.n_words, miner.mesh)
    for k in (2, 4, 8, 16):  # pow2-bucketed step depth x chunk width
        fw = 8
        while fw <= next_pow2(miner.repair_chunk):
            fold(st.store, put(np.zeros((k, fw), np.int32)),
                 put(np.zeros((k, fw), bool)),
                 put(np.zeros((k, fw), bool)))
            fw *= 2
    # the remap scatter-build: live batches land on pow2 token-count and
    # remap-length buckets (both traced shapes) — warm a small grid
    # around the declared envelope
    from spark_fsm_tpu.streaming.incremental import _inc_store_builder
    fn = _inc_store_builder(target, st.n_seq, st.n_words, miner.mesh)
    rb0 = next_pow2(max(16, t["n_items"]))
    for nt in _token_buckets(t["n_items"], t["max_tokens"]):
        for rb in (rb0, 2 * rb0):
            z = np.zeros(nt, np.int32)
            fn(put(z), put(z), put(z), put(np.zeros(nt, np.uint32)),
               put(np.full(rb, target + 1, np.int32)))


def run(spec: shapes.WorkloadSpec, *, mesh=None,
        engine_kwargs: Optional[dict] = None) -> dict:
    """Walk the enumerated shape set and compile every entry; returns a
    report with per-key walls + fresh-compile counts and stores it for
    ``/admin/stats`` / ``/admin/shapes``."""
    import jax

    enable_compile_counter()
    engine_kwargs = dict(engine_kwargs or {})
    eng_sub = {k: v for k, v in engine_kwargs.items()
               if k in ("chunk", "node_batch", "pipeline_depth",
                        "recompute_chunk", "pool_bytes")}
    targets = shapes.enumerate_shapes(spec, mesh=mesh,
                                      engine_kwargs=engine_kwargs)
    rows: List[dict] = []
    t_all = time.monotonic()
    # prewarm owns a trace of its own (uid "prewarm"): boot/admin
    # compile walls are readable at /admin/trace/prewarm when tracing
    # is on, one span per shape key
    ctx = obs.trace("prewarm", site="prewarm", keys=len(targets))
    with ctx:
        rows.extend(_run_keys(targets, mesh, eng_sub))
    report = {
        "keys": rows,
        "enumerated": sorted(targets),
        "total_wall_s": round(time.monotonic() - t_all, 3),
        "backend": jax.default_backend(),
        "ts": round(time.time(), 3),
    }
    global _last_report
    with _lock:
        _last_report = report
    log_event("prewarm_done", keys=len(rows),
              total_wall_s=report["total_wall_s"])
    return report


def _run_keys(targets, mesh, eng_sub) -> List[dict]:
    rows: List[dict] = []
    for key, t in sorted(targets.items()):
        c0 = compile_counts()
        t0 = time.monotonic()
        err = None
        with obs.span("prewarm.compile", shape_key=key, kind=t["kind"]):
            try:
                # chaos seam: an injected compile failure here proves the
                # per-key isolation below (one bad key must not take down
                # boot or the other keys' warms)
                faults.fault_site("prewarm.compile", shape_key=key,
                                  kind=t["kind"])
                if t["kind"] == "classic":
                    _warm_classic(t, mesh, eng_sub)
                elif t["kind"] == "queue":
                    _warm_queue(t, mesh)
                elif t["kind"] == "fused":
                    _warm_fused(t, mesh)
                elif t["kind"] == "cspade":
                    _warm_cspade(t, mesh, eng_sub)
                elif t["kind"] == "spam":
                    _warm_spam(t, mesh, eng_sub)
                elif t["kind"] == "spam_hybrid":
                    _warm_spam_hybrid(t, mesh)
                elif t["kind"] == "spam_pair":
                    _warm_spam_pair(t, mesh)
                elif t["kind"] == "tsr":
                    _warm_tsr(t, mesh)
                elif t["kind"] in ("tsr_eval", "tsr_fused", "tsr_inner"):
                    pass  # warmed by the "tsr"/"tsr_part" entries'
                    # ladder walks; the separate key exists so
                    # /admin/shapes drift can name the exact launch
                    # geometry a live mine would compile
                elif t["kind"] == "tsr_part":
                    _warm_tsr_part(t, mesh)
                elif t["kind"] == "tsr_resident":
                    _warm_resident(t, mesh)
                elif t["kind"] == "sweep":
                    _warm_sweep(t, mesh)
                elif t["kind"] == "predict":
                    _warm_predict(t)
            except Exception as exc:  # a failed warm must not take down
                err = f"{type(exc).__name__}: {exc}"  # boot
                _COMPILE_ERRORS.inc()
        _COMPILE_SECONDS.observe(time.monotonic() - t0, kind=t["kind"])
        c1 = compile_counts()
        row = {"shape_key": key, "kind": t["kind"],
               "wall_s": round(time.monotonic() - t0, 3),
               "fresh_compiles": c1["count"] - c0["count"],
               "compile_s": round(c1["seconds"] - c0["seconds"], 3)}
        if err:
            row["error"] = err
        rows.append(row)
        log_event("prewarm_key", **row)
    return rows


def _warm_predict(t) -> None:
    """Compile one rung of the /predict scoring ladder: the read plane's
    first post-boot request must land on a cached executable like every
    other subsystem's (ops/rule_trie.py owns the kernel; it warms with
    zero planes at the exact (F, D, W, M) a live wave would trace)."""
    from spark_fsm_tpu.ops import rule_trie

    rule_trie.warm_geometry(int(t["lanes"]), int(t["depth"]),
                            int(t["wave"]), int(t["topm"]))


def last_report() -> Optional[dict]:
    with _lock:
        return _last_report


def spec_from_config(pc) -> Optional[shapes.WorkloadSpec]:
    """WorkloadSpec from a config.PrewarmConfig; None when the envelope
    is empty (nothing to warm)."""
    constraints = ()
    if pc.maxgap is not None or pc.maxwindow is not None:
        constraints = ((pc.maxgap, pc.maxwindow),)
    if pc.sequences <= 0 and pc.stream_batch_sequences <= 0:
        return None
    return shapes.WorkloadSpec(
        n_sequences=int(pc.sequences), n_items=int(pc.items),
        n_words=max(1, int(pc.words)), constraints=constraints,
        tsr=bool(pc.tsr),
        fusion_jobs=_fusion_jobs_default(),
        partition_parts=_partition_parts_default(),
        stream_batch_sequences=int(pc.stream_batch_sequences),
        stream_items=int(pc.stream_items),
        stream_seq_floor=int(pc.stream_seq_floor),
        checkpointed=bool(pc.checkpointed),
        max_tokens=int(pc.max_tokens),
        **_predict_defaults())


def _predict_defaults() -> Dict[str, int]:
    """The /predict scoring-ladder envelope the boot config implies:
    with the prediction plane enabled, prewarm must cover the artifact
    floor geometry across the pow2 wave ladder up to ``max_wave`` or
    the first prewarmed predict pays a live compile.  Floors of 0 mean
    per-artifact geometry (nothing enumerable) — skip."""
    from spark_fsm_tpu import config

    pc = config.get_config().predict
    if not pc.enabled or pc.lanes_floor <= 0 or pc.depth_floor <= 0:
        return {"predict_lanes": 0, "predict_depth": 0,
                "predict_wave": 0, "predict_topm": 0}
    return {"predict_lanes": int(pc.lanes_floor),
            "predict_depth": int(pc.depth_floor),
            "predict_wave": max(1, int(pc.max_wave)),
            "predict_topm": max(1, int(pc.topm))}


def _partition_parts_default() -> int:
    """The partitioned-ladder envelope the boot config implies: with
    equivalence-class partitioning enabled, prewarm must cover the 2-D
    parts x seq ladder or the first partitioned mine pays a live
    compile per submesh row (service/plugins.py resolves the same
    number at request time — ONE resolver so the warmed and served
    layouts cannot drift)."""
    from spark_fsm_tpu.service.plugins import resolved_partition_parts

    return resolved_partition_parts()


def _fusion_jobs_default() -> int:
    """The fused-ladder envelope the boot config implies: with the
    cross-job broker enabled, prewarm must cover groups up to
    ``[fusion] max_jobs`` or the first real fusion pays a live compile
    — the exact stall prewarm exists to prevent."""
    from spark_fsm_tpu import config

    fc = config.get_config().fusion
    return int(fc.max_jobs) if fc.enabled else 0


def spec_from_params(params: Dict[str, str], pc) -> shapes.WorkloadSpec:
    """WorkloadSpec for ``POST /admin/prewarm``: request parameters
    override the boot ``[prewarm]`` envelope field-by-field."""
    def geti(name, default):
        v = params.get(name)
        return int(v) if v not in (None, "") else int(default or 0)

    maxgap = params.get("maxgap", pc.maxgap)
    maxwindow = params.get("maxwindow", pc.maxwindow)
    constraints = ()
    if maxgap not in (None, "") or maxwindow not in (None, ""):
        constraints = ((int(maxgap) if maxgap not in (None, "") else None,
                        int(maxwindow) if maxwindow not in (None, "")
                        else None),)
    truthy = lambda v, d: (str(v).lower() not in ("", "0", "false", "no",
                                                  "off")
                           if v is not None else bool(d))
    return shapes.WorkloadSpec(
        n_sequences=geti("sequences", pc.sequences),
        n_items=geti("items", pc.items),
        n_words=max(1, geti("words", pc.words)),
        constraints=constraints,
        tsr=truthy(params.get("tsr"), pc.tsr),
        fusion_jobs=geti("fusion_jobs", _fusion_jobs_default()),
        partition_parts=geti("partition_parts",
                             _partition_parts_default()),
        stream_batch_sequences=geti("stream_batch_sequences",
                                    pc.stream_batch_sequences),
        stream_items=geti("stream_items", pc.stream_items),
        stream_seq_floor=geti("stream_seq_floor", pc.stream_seq_floor),
        checkpointed=truthy(params.get("checkpointed"), pc.checkpointed),
        max_tokens=geti("max_tokens", pc.max_tokens),
        **{name: geti(name, default)
           for name, default in _predict_defaults().items()})
