"""Durable-state integrity plane (ISSUE 18): per-surface verify-on-read
policy, the quarantine keyspace, and the background scrubber.

utils/envelope.py owns the BYTES (checksummed self-describing envelope
around every durable write); this module owns the POLICY — what each
surface does when a read fails its checksum, how corrupt values are
quarantined for the post-mortem, and the at-rest scrubber that finds
bitrot *before* a read path trips over it.

Per-surface degradation posture (the DESIGN.md table; each surface
degrades by its own blast radius, never by a shared policy):

==========  ========================================================
surface     on corrupt
==========  ========================================================
checkpoint  delta chunk: truncate to the last good snapshot embedded
            in the preceding chunk and RESUME (actors.StoreCheckpoint
            .load); meta: restart the mine fresh, loudly.  The
            scrubber only quarantine-COPIES checkpoint damage — the
            heal itself belongs to load(), the single writer.
journal     intent moved to ``fsm:quarantine:{uid}``; boot recovery
            continues over the remaining orphans
            (actors.recover_orphans).
rescache    entry invalidated + quarantined; the request falls
            through to a cold mine — corrupt bytes are NEVER served.
            A missing/corrupt LRU sidecar beside an intact entry is
            REPAIRED (re-derived from the entry), the one surface a
            live leader can heal in place.
spine       chunk skipped + counted (obsplane.merged_timeline) — the
            timeline is evidence and must never fail a dump.  The
            scrubber counts, it does not quarantine (no per-element
            list surgery).
lease       heartbeat/autoscale record treated as absent — the TTL
            layer already tolerates missing records; a corrupt one
            just ages out.
==========  ========================================================

The scrubber rides the lease heartbeat cadence in cluster mode
(lease.LeaseManager.tick -> :func:`tick`) and a private daemon thread
on solo boots (started by app.main); either way each pass walks at
most ``[integrity] scrub_batch`` keys via cursor-based ``scan_keys``
with the cursor carried ACROSS passes — it can never become a store
scan storm.  Reporting: ``/admin/integrity`` + the zero-seeded
``fsm_integrity_{scans,verified,legacy,corrupt,quarantined,repaired}_total``
families.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Optional

from spark_fsm_tpu.utils import envelope, obs
from spark_fsm_tpu.utils.obs import log_event

#: label vocabulary for every fsm_integrity_* family (zero-seeded so a
#: scrape reads 0, not no-data, for surfaces with no events yet)
SURFACES = ("checkpoint", "journal", "rescache", "spine", "lease")

QUARANTINE_PREFIX = "fsm:quarantine:"

_SCANS = obs.REGISTRY.counter(
    "fsm_integrity_scans_total", "background scrubber passes completed")
_VERIFIED = obs.REGISTRY.counter(
    "fsm_integrity_verified_total",
    "durable values that passed envelope verification, by surface")
_LEGACY = obs.REGISTRY.counter(
    "fsm_integrity_legacy_total",
    "pre-envelope values accepted as verify=legacy, by surface")
_CORRUPT = obs.REGISTRY.counter(
    "fsm_integrity_corrupt_total",
    "durable values that FAILED verification, by surface")
_QUARANTINED = obs.REGISTRY.counter(
    "fsm_integrity_quarantined_total",
    "corrupt values preserved under fsm:quarantine:*, by surface")
_REPAIRED = obs.REGISTRY.counter(
    "fsm_integrity_repaired_total",
    "corrupt/missing values re-derived in place (rescache sidecars), "
    "by surface")
for _s in SURFACES:
    _VERIFIED.seed(surface=_s)
    _LEGACY.seed(surface=_s)
    _CORRUPT.seed(surface=_s)
    _QUARANTINED.seed(surface=_s)
    _REPAIRED.seed(surface=_s)


def note_read(surface: str, verdict: str) -> None:
    """Count one verify-on-read (or at-rest) verdict for ``surface``.
    ``missing`` is a key-absent read, not a verification outcome."""
    if verdict == "ok":
        _VERIFIED.inc(surface=surface)
    elif verdict == "legacy":
        _LEGACY.inc(surface=surface)
    elif verdict == "corrupt":
        _CORRUPT.inc(surface=surface)


def open_value(raw: Optional[str], surface: str):
    """`envelope.unwrap` + verdict counting in one call — the spelling
    most read sites use.  Returns ``(payload, verdict)`` unchanged."""
    payload, verdict = envelope.unwrap(raw)
    note_read(surface, verdict)
    return payload, verdict


def quarantine_key(key: str) -> str:
    """Quarantine address for a damaged key.  Journal intents map to
    the ISSUE-mandated ``fsm:quarantine:{uid}``; everything else keeps
    its post-``fsm:`` tail (e.g. ``fsm:quarantine:rescache:{fp}:{algo}``)
    so one scan of the prefix lists every quarantined surface."""
    if key.startswith("fsm:journal:"):
        return QUARANTINE_PREFIX + key[len("fsm:journal:"):]
    if key.startswith("fsm:"):
        return QUARANTINE_PREFIX + key[len("fsm:"):]
    return QUARANTINE_PREFIX + key


def quarantine(store, key: str, raw: Optional[str], surface: str,
               move: bool = False) -> str:
    """Preserve damaged bytes under the quarantine keyspace (enveloped,
    so the quarantine record itself is verifiable) and count it.  With
    ``move`` the original key is deleted — the journal/rescache posture;
    checkpoint damage is only COPIED (load() owns the heal).  Idempotent
    per key: a scrub pass re-walking known damage neither rewrites nor
    recounts it."""
    qkey = quarantine_key(key)
    if store.peek(qkey) is None:
        rec = json.dumps({"key": key, "surface": surface,
                          "ts": round(time.time(), 3), "value": raw})
        store.set(qkey, envelope.wrap(rec))
        _QUARANTINED.inc(surface=surface)
        log_event("integrity_quarantined", key=key, surface=surface,
                  moved=move)
    if move:
        store.delete(key)
    return qkey


def note_repaired(surface: str) -> None:
    _REPAIRED.inc(surface=surface)


# -- the background scrubber ----------------------------------------------

# (prefix, surface-kind) walked round-robin with a cross-pass cursor.
# fsm:frontier: covers both the meta and the fsm:frontier:results: list.
_WALK = (
    ("fsm:journal:", "journal"),
    ("fsm:rescache:", "rescache_entry"),
    ("fsm:rescache-lru:", "rescache_sidecar"),
    ("fsm:frontier:", "checkpoint"),
    ("fsm:trace:", "spine"),
)


class Scrubber:
    """Batch-bounded at-rest envelope verifier.

    One ``scrub()`` pass examines at most ``batch`` keys, resuming from
    the cursor the previous pass left off — a 10M-key store is scrubbed
    across many passes, never in one scan storm.  kv reads go through
    ``store.peek`` (guard-free: a scrub must not consume an armed chaos
    trigger aimed at the read path it protects); list surfaces ride
    ``lrange``/``spine_chunks``."""

    def __init__(self, store, scrub_every_s: float = 60.0,
                 batch: int = 256) -> None:
        self.store = store
        self.scrub_every_s = float(scrub_every_s)
        self.batch = int(batch)
        self._pi = 0          # index into _WALK
        self._cursor = "0"
        self._next_due = 0.0  # monotonic deadline for maybe_scrub
        self._run_lock = threading.Lock()  # tick thread vs solo thread
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.passes = 0
        self.keys_scanned = 0
        self.last_pass: Optional[dict] = None

    # -- driving ----------------------------------------------------------

    def maybe_scrub(self) -> None:
        """Next-due-gated pass — safe to call from any cadence (lease
        tick AND the solo thread may both drive one scrubber)."""
        if self.scrub_every_s <= 0:
            return
        now = time.monotonic()
        if now < self._next_due:
            return
        if not self._run_lock.acquire(blocking=False):
            return
        try:
            self._next_due = now + self.scrub_every_s
            self.scrub()
        finally:
            self._run_lock.release()

    def start(self) -> None:
        """Solo-boot cadence thread (cluster mode rides the lease
        heartbeat via :func:`tick` instead and never needs this)."""
        if self._thread is not None or self.scrub_every_s <= 0:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.scrub_every_s):
                try:
                    self.maybe_scrub()
                except Exception as exc:  # scrub must never kill the loop
                    log_event("integrity_scrub_failed", error=str(exc))

        self._thread = threading.Thread(
            target=_loop, name="integrity-scrub", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    # -- one pass ---------------------------------------------------------

    def scrub(self, limit: Optional[int] = None) -> dict:
        """One bounded pass; returns its tally (also kept as
        ``last_pass``).  Direct calls (tests, admin) bypass the cadence
        gate but still share the run lock."""
        budget = int(limit) if limit is not None else self.batch
        t0 = time.monotonic()
        tally = {"keys": 0, "corrupt": 0, "quarantined": 0, "repaired": 0}
        advances = 0
        while tally["keys"] < budget and advances <= len(_WALK):
            prefix, kind = _WALK[self._pi]
            step = min(64, budget - tally["keys"])
            nxt, keys = self.store.scan_keys(prefix, self._cursor, step)
            for key in keys:
                try:
                    self._verify_key(key, kind, tally)
                except Exception as exc:
                    # one unreadable key must not wedge the walk
                    log_event("integrity_scrub_key_failed", key=key,
                              error=str(exc))
                tally["keys"] += 1
            if nxt == "0":
                self._pi = (self._pi + 1) % len(_WALK)
                self._cursor = "0"
                advances += 1
            else:
                self._cursor = nxt
        self.passes += 1
        self.keys_scanned += tally["keys"]
        _SCANS.inc()
        tally["duration_ms"] = round((time.monotonic() - t0) * 1000, 3)
        tally["ts"] = round(time.time(), 3)
        self.last_pass = tally
        if tally["corrupt"]:
            log_event("integrity_scrub_found_corruption", **tally)
        return tally

    def _verify_key(self, key: str, kind: str, tally: dict) -> None:
        if kind == "journal":
            payload, verdict = open_value(self.store.peek(key), "journal")
            if verdict != "corrupt":
                return
            tally["corrupt"] += 1
            quarantine(self.store, key, self.store.peek(key), "journal",
                       move=True)
            tally["quarantined"] += 1
        elif kind == "rescache_entry":
            self._verify_rescache_entry(key, tally)
        elif kind == "rescache_sidecar":
            self._verify_rescache_sidecar(key, tally)
        elif kind == "checkpoint":
            self._verify_checkpoint(key, tally)
        elif kind == "spine":
            for chunk in self.store.lrange(key):
                payload, verdict = open_value(chunk, "spine")
                if verdict == "corrupt":
                    tally["corrupt"] += 1

    def _verify_rescache_entry(self, key: str, tally: dict) -> None:
        from spark_fsm_tpu.service import resultcache

        raw = self.store.peek(key)
        if raw is None:
            return
        payload, verdict = envelope.unwrap(raw)
        ent = None
        if verdict != "corrupt":
            ent = resultcache.parse_entry(payload)
            if ent is None:
                verdict = "corrupt"  # decodes but fails its rules_digest
        note_read("rescache", verdict)
        if ent is None:
            tally["corrupt"] += 1
            quarantine(self.store, key, raw, "rescache", move=True)
            self.store.delete(resultcache.sidecar_key_for(key))
            tally["quarantined"] += 1
            return
        # intact entry: re-derive a missing/corrupt LRU sidecar — the
        # repair a live leader can always make (and the heal for a kill
        # between the entry write and the sidecar write)
        side_key = resultcache.sidecar_key_for(key)
        sp, sv = envelope.unwrap(self.store.peek(side_key))
        healthy = False
        if sv != "corrupt" and sp is not None:
            try:
                healthy = isinstance(json.loads(sp), dict)
            except ValueError:
                healthy = False
        if not healthy:
            resultcache.write_sidecar(self.store, key, ent, len(payload))
            note_repaired("rescache")
            tally["repaired"] += 1
            log_event("integrity_sidecar_repaired", key=side_key)

    def _verify_rescache_sidecar(self, key: str, tally: dict) -> None:
        sp, sv = envelope.unwrap(self.store.peek(key))
        bad = sv == "corrupt"
        if not bad and sp is not None:
            try:
                bad = not isinstance(json.loads(sp), dict)
            except ValueError:
                bad = True
        if not bad:
            note_read("rescache", sv)
            return
        note_read("rescache", "corrupt")
        tally["corrupt"] += 1
        # the entry walk rebuilds it next time it passes; here we only
        # clear the damage (an orphan sidecar with no entry just dies)
        self.store.delete(key)
        from spark_fsm_tpu.service import resultcache
        entry_key = resultcache.entry_key_for_sidecar(key)
        if self.store.peek(entry_key) is not None:
            self._verify_rescache_entry(entry_key, tally)

    def _verify_checkpoint(self, key: str, tally: dict) -> None:
        if key.startswith("fsm:frontier:results:"):
            for i, chunk in enumerate(self.store.lrange(key)):
                payload, verdict = open_value(chunk, "checkpoint")
                if verdict == "corrupt":
                    tally["corrupt"] += 1
                    # COPY only — StoreCheckpoint.load owns the heal
                    # (ltrim + meta rewrite under the single writer)
                    quarantine(self.store, f"{key}#{i}", chunk,
                               "checkpoint")
                    tally["quarantined"] += 1
            return
        raw = self.store.peek(key)
        payload, verdict = open_value(raw, "checkpoint")
        if verdict == "corrupt":
            tally["corrupt"] += 1
            quarantine(self.store, key, raw, "checkpoint")
            tally["quarantined"] += 1

    def stats(self) -> dict:
        prefix, _ = _WALK[self._pi]
        return {"scrub_every_s": self.scrub_every_s, "batch": self.batch,
                "passes": self.passes, "keys_scanned": self.keys_scanned,
                "cursor": f"{prefix}@{self._cursor}",
                "last_pass": self.last_pass}


# -- module wiring (the obsplane install pattern) -------------------------

_cfg = None  # IntegrityConfig from the boot config; None = defaults
_scrubber: Optional[Scrubber] = None


def configure(icfg) -> None:
    """Adopt the ``[integrity]`` boot config (config.set_config)."""
    global _cfg
    _cfg = icfg
    s = _scrubber
    if s is not None and icfg is not None:
        s.scrub_every_s = float(icfg.scrub_every_s)
        s.batch = int(icfg.scrub_batch)


def install(store) -> Optional[Scrubber]:
    """Install the process-wide scrubber over ``store`` (Miner init;
    last install wins, mirroring obsplane).  Returns None when the
    integrity plane is disabled — verify-on-read stays unconditional
    either way (it is a correctness property, not a feature flag)."""
    global _scrubber
    if _scrubber is not None:
        _scrubber.stop()
    if _cfg is not None and not _cfg.enabled:
        _scrubber = None
        return None
    _scrubber = Scrubber(
        store,
        scrub_every_s=_cfg.scrub_every_s if _cfg is not None else 60.0,
        batch=_cfg.scrub_batch if _cfg is not None else 256)
    return _scrubber


def uninstall() -> None:
    global _scrubber
    if _scrubber is not None:
        _scrubber.stop()
    _scrubber = None


def get() -> Optional[Scrubber]:
    return _scrubber


def tick() -> None:
    """Heartbeat-cadence hook (lease.LeaseManager.tick): one global
    read when nothing is installed."""
    s = _scrubber
    if s is not None:
        s.maybe_scrub()


def report(store=None) -> dict:
    """The ``/admin/integrity`` body: config, scrubber progress, counter
    totals, and a bounded listing of the quarantine keyspace."""
    s = _scrubber
    cfg = _cfg
    out = {
        "enabled": bool(cfg.enabled) if cfg is not None else True,
        "scrub_every_s": (float(cfg.scrub_every_s) if cfg is not None
                          else 60.0),
        "scrub_batch": int(cfg.scrub_batch) if cfg is not None else 256,
        "scrubber": s.stats() if s is not None else None,
        "counters": {
            "scans": _SCANS.total(),
            "verified": _VERIFIED.total(),
            "legacy": _LEGACY.total(),
            "corrupt": _CORRUPT.total(),
            "quarantined": _QUARANTINED.total(),
            "repaired": _REPAIRED.total(),
        },
        "quarantine": [],
    }
    st = store if store is not None else (s.store if s is not None else None)
    if st is not None:
        for qkey in itertools.islice(
                st.scan_iter(QUARANTINE_PREFIX), 100):
            row = {"key": qkey}
            payload, verdict = envelope.unwrap(st.peek(qkey))
            if verdict != "corrupt" and payload is not None:
                try:
                    rec = json.loads(payload)
                    if isinstance(rec, dict):
                        row.update({k: rec.get(k)
                                    for k in ("key", "surface", "ts")
                                    if rec.get(k) is not None})
                        row["quarantine_key"] = qkey
                except ValueError:
                    pass
            out["quarantine"].append(row)
    return out
